"""Fleet soak harness: component units + the tier-1 smoke scenario.

The smoke scenario is the CI gate the ISSUE's acceptance names: ~50
replicas through a zone loss AND a rolling update on the virtual
clock, with TTFT p95, update error rate, and post-zone-loss
time-to-ready all asserted from the live skytpu_* metrics registry.
Full-scale soaks (1000+ replicas) are `-m slow` and also run via
tests/run_full.sh.
"""
import json
import os
import random
import time

import pytest

from skypilot_tpu.fleetsim import chaos as chaos_lib
from skypilot_tpu.fleetsim import clock as clock_lib
from skypilot_tpu.fleetsim import replicas as replicas_lib
from skypilot_tpu.fleetsim import runner as runner_lib
from skypilot_tpu.fleetsim import slo as slo_lib
from skypilot_tpu.fleetsim import traffic as traffic_lib
from skypilot_tpu.observability import instruments as obs
from skypilot_tpu.resilience import faults
from skypilot_tpu.serve import serve_state

SVC = 'fleetsim-test'


@pytest.fixture(autouse=True)
def clean_sim_state():
    faults.reset()
    serve_state.reset_for_tests()
    yield
    faults.reset()
    serve_state.reset_for_tests()


# --- virtual clock ----------------------------------------------------------

class TestVirtualClock:

    def test_advance_and_sleep_move_time(self):
        clk = clock_lib.VirtualClock()
        assert clk.now() == 0.0
        clk.advance(5.0)
        clk.sleep(2.5)
        assert clk.now() == 7.5

    def test_rewind_rejected(self):
        with pytest.raises(ValueError):
            clock_lib.VirtualClock().advance(-1.0)


# --- traffic ----------------------------------------------------------------

class TestTraffic:

    def test_same_seed_same_arrivals(self):
        curve = traffic_lib.parse({'kind': 'constant', 'qps': 50.0})
        a = [curve.arrivals(random.Random(3), t, t + 5) for t in
             range(0, 50, 5)]
        b = [curve.arrivals(random.Random(3), t, t + 5) for t in
             range(0, 50, 5)]
        assert a == b
        assert sum(a) > 0

    def test_diurnal_stays_within_band(self):
        curve = traffic_lib.DiurnalTraffic(10.0, 50.0, period_s=600.0)
        rates = [curve.rate(t) for t in range(0, 600, 7)]
        assert min(rates) >= 10.0 - 1e-9
        assert max(rates) <= 50.0 + 1e-9

    def test_burst_adds_only_inside_window(self):
        curve = traffic_lib.parse({
            'kind': 'burst', 'inner': {'kind': 'constant', 'qps': 5.0},
            'burst_qps': 20.0, 'at': 100.0, 'duration_s': 50.0})
        assert curve.rate(99.0) == 5.0
        assert curve.rate(100.0) == 25.0
        assert curve.rate(149.9) == 25.0
        assert curve.rate(150.0) == 5.0

    def test_trace_replay_is_a_step_function(self):
        curve = traffic_lib.TraceTraffic([[0, 2.0], [60, 8.0],
                                          [120, 1.0]])
        assert curve.rate(30) == 2.0
        assert curve.rate(60) == 8.0
        assert curve.rate(500) == 1.0

    def test_poisson_zero_rate(self):
        assert traffic_lib.poisson(random.Random(0), 0.0) == 0


# --- chaos schedules --------------------------------------------------------

class TestChaosSchedule:

    def test_events_fire_in_order_once(self):
        sched = chaos_lib.ChaosSchedule.from_config([
            {'at': 30, 'action': 'rolling_update'},
            {'at': 10, 'action': 'zone_loss', 'zone': 'z'},
        ])
        assert [e.action for e in sched.pop_due(10.0)] == ['zone_loss']
        assert sched.pop_due(10.0) == []
        assert [e.action for e in sched.pop_due(99.0)] == \
            ['rolling_update']
        assert sched.remaining() == 0

    def test_unknown_action_rejected(self):
        with pytest.raises(ValueError):
            chaos_lib.ChaosEvent(1.0, 'meteor_strike')


# --- SLO evaluation from the registry ---------------------------------------

class TestSLOEvaluator:

    def test_quantile_from_bucket_deltas(self):
        slos = [slo_lib.HistQuantileBelow('p95', threshold=2.0,
                                          window=('a', 'b'))]
        ev = slo_lib.SLOEvaluator(slos)
        ev.mark('a')
        for _ in range(95):
            obs.FLEETSIM_TTFT_SECONDS.observe(0.3)
        for _ in range(5):
            obs.FLEETSIM_TTFT_SECONDS.observe(9.0)
        ev.mark('b')
        (result,) = ev.evaluate()
        # p95 resolves to the bucket bound holding the 95th sample.
        assert result['ok'] and result['value'] == 0.35

    def test_zero_sample_window_fails(self):
        ev = slo_lib.SLOEvaluator([slo_lib.HistQuantileBelow(
            'p95', threshold=2.0, window=('a', 'b'))])
        ev.mark('a')
        ev.mark('b')
        (result,) = ev.evaluate()
        assert not result['ok'] and 'samples' in result['detail']

    def test_ratio_over_window(self):
        ev = slo_lib.SLOEvaluator([slo_lib.RatioBelow(
            'err', threshold=0.1, window=('a', 'b'))])
        ev.mark('a')
        for _ in range(98):
            obs.FLEETSIM_REQUESTS.labels(outcome='ok').inc()
        for _ in range(2):
            obs.FLEETSIM_REQUESTS.labels(outcome='error').inc()
        ev.mark('b')
        (result,) = ev.evaluate()
        assert result['ok'] and abs(result['value'] - 0.02) < 1e-9

    def test_counter_ratio_across_metrics(self):
        """CounterRatioAbove: a ratio across SEPARATE counters (the
        prefix-cache hit ratio), from window deltas."""
        ev = slo_lib.SLOEvaluator([slo_lib.CounterRatioAbove(
            'hit_ratio', threshold=0.6,
            num_metric='skytpu_prefix_cache_hits_total',
            den_metrics=('skytpu_prefix_cache_hits_total',
                         'skytpu_prefix_cache_misses_total'),
            window=('a', 'b'))])
        ev.mark('a')
        for _ in range(8):
            obs.PREFIX_CACHE_HITS.inc()
        for _ in range(2):
            obs.PREFIX_CACHE_MISSES.inc()
        ev.mark('b')
        (result,) = ev.evaluate()
        assert result['ok'] and abs(result['value'] - 0.8) < 1e-9
        assert result['metric'] == 'skytpu_prefix_cache_hits_total'

    def test_counter_ratio_zero_events_fails(self):
        ev = slo_lib.SLOEvaluator([slo_lib.CounterRatioAbove(
            'hit_ratio', threshold=0.5,
            num_metric='skytpu_prefix_cache_hits_total',
            den_metrics=('skytpu_prefix_cache_hits_total',
                         'skytpu_prefix_cache_misses_total'),
            window=('a', 'b'))])
        ev.mark('a')
        ev.mark('b')
        (result,) = ev.evaluate()
        assert not result['ok'] and 'events' in result['detail']

    def test_never_fired_event_gauge_fails(self):
        """A gauge series that was never written must FAIL, not read
        as 0.0 'recovered instantly' — a retimed/misspelled chaos
        event must not green-light its recovery SLO."""
        ev = slo_lib.SLOEvaluator([slo_lib.GaugeWithin(
            'rec', threshold=60.0,
            labels=(('event', 'never_happened_ev'),))])
        (result,) = ev.evaluate()
        assert not result['ok']
        assert 'never written' in result['detail']

    def test_unrecovered_gauge_fails(self):
        obs.FLEETSIM_RECOVERY_SECONDS.labels(event='test_ev').set(-1.0)
        ev = slo_lib.SLOEvaluator([slo_lib.GaugeWithin(
            'rec', threshold=60.0, labels=(('event', 'test_ev'),))])
        (result,) = ev.evaluate()
        assert not result['ok']
        obs.FLEETSIM_RECOVERY_SECONDS.labels(event='test_ev').set(12.0)
        (result,) = ev.evaluate()
        assert result['ok'] and result['value'] == 12.0

    def test_missing_window_mark_fails(self):
        ev = slo_lib.SLOEvaluator([slo_lib.RatioBelow(
            'err', threshold=0.1, window=('never', 'end'))])
        ev.mark('end')
        (result,) = ev.evaluate()
        assert not result['ok'] and 'never marked' in result['detail']

    def test_report_schema_and_rc(self, tmp_path):
        path, rc = slo_lib.write_report(
            str(tmp_path), 'unit',
            [{'name': 'x', 'metric': 'm', 'ok': True, 'value': 1,
              'threshold': 2, 'detail': ''}])
        data = json.loads(open(path).read())
        assert rc == 0 and data['rc'] == 0
        assert data['scenario'] == 'unit'
        assert isinstance(data['asserts'], list)
        _, rc = slo_lib.write_report(
            str(tmp_path), 'unit', [], rc_override=1)
        assert rc == 1


# --- the simulated fleet ----------------------------------------------------

def _fleet(clk=None, zones=('za', 'zb')):
    serve_state.add_service(SVC, {'run': 'true'}, lb_port=0,
                            controller_port=0)
    clk = clk or clock_lib.VirtualClock()
    profile = replicas_lib.ReplicaProfile(
        startup_median_s=10.0, startup_sigma=0.0)
    fleet = replicas_lib.SimFleet(SVC, clk, random.Random(0), profile,
                                  zones=list(zones))
    return fleet, clk


class TestSimFleet:

    def test_startup_lifecycle_on_virtual_clock(self):
        fleet, clk = _fleet()
        fleet.scale_up(4)
        fleet.probe_all()
        assert fleet.ready_endpoints() == []
        rows = serve_state.get_replicas(SVC)
        assert {r['status'] for r in rows} == \
            {serve_state.ReplicaStatus.PROVISIONING}
        clk.advance(3.0)   # past provision_done (25% of startup)
        fleet.probe_all()
        assert {r['status'] for r in serve_state.get_replicas(SVC)} \
            == {serve_state.ReplicaStatus.STARTING}
        clk.advance(8.0)   # past ready_at
        fleet.probe_all()
        assert len(fleet.ready_endpoints()) == 4
        # Zones balanced between za/zb.
        zones = [r['zone'] for r in serve_state.get_replicas(SVC)]
        assert zones.count('za') == zones.count('zb') == 2

    def test_zone_loss_kills_through_fault_point_and_replaces(self):
        fleet, clk = _fleet()
        fleet.scale_up(4)
        clk.advance(11.0)
        fleet.probe_all()
        before = obs.FAULTS_INJECTED.value(point='fleet.zone_loss')
        faults.arm('fleet.zone_loss', times=None)
        fleet.mark_zone_lost('za')
        fleet.probe_all()
        faults.disarm('fleet.zone_loss')
        # Both za replicas died via the fault point...
        assert obs.FAULTS_INJECTED.value(point='fleet.zone_loss') == \
            before + 2
        # ...and were replaced into the surviving zone.
        rows = serve_state.get_replicas(SVC)
        assert len(rows) == 4
        assert all(r['zone'] == 'zb' for r in rows
                   if r['status'] ==
                   serve_state.ReplicaStatus.PROVISIONING)

    def test_preemption_wave_size_is_the_armed_times_bound(self):
        fleet, clk = _fleet()
        fleet.scale_up(6, use_spot=True)
        clk.advance(11.0)
        fleet.probe_all()
        faults.arm('fleet.preemption_wave', times=2)
        fleet.begin_preemption_wave()
        fleet.probe_all()
        # Exactly 2 of 6 died (times bound), both replaced.
        assert len(fleet.ready_endpoints()) == 4
        assert len(serve_state.get_replicas(SVC)) == 6

    def test_handle_request_latencies_and_dead_endpoint(self):
        fleet, clk = _fleet()
        fleet.scale_up(1)
        clk.advance(11.0)
        fleet.probe_all()
        fleet.begin_tick(5.0)
        (endpoint,) = fleet.ready_endpoints()
        ttft, total = fleet.handle_request(endpoint)
        assert 0 < ttft < total
        assert fleet.handle_request('http://gone.sim:8080') is None
        fleet.end_tick()

    def test_prefix_hit_term_counts_and_speeds_warm_requests(self):
        serve_state.add_service(SVC, {'run': 'true'}, lb_port=0,
                                controller_port=0)
        clk = clock_lib.VirtualClock()
        profile = replicas_lib.ReplicaProfile(
            startup_median_s=10.0, startup_sigma=0.0,
            ttft_median_s=0.5, ttft_sigma=0.0,
            prefix_hit_ratio=0.5, warm_ttft_factor=0.1,
            shared_prefix_tokens=256, concurrency=1000)
        fleet = replicas_lib.SimFleet(SVC, clk, random.Random(0),
                                      profile, zones=['za'])
        fleet.scale_up(1)
        clk.advance(11.0)
        fleet.probe_all()
        (endpoint,) = fleet.ready_endpoints()
        h0 = obs.PREFIX_CACHE_HITS.value()
        m0 = obs.PREFIX_CACHE_MISSES.value()
        r0 = obs.PREFIX_CACHE_REUSED_TOKENS.value()
        fleet.begin_tick(1000.0)
        ttfts = [fleet.handle_request(endpoint)[0]
                 for _ in range(200)]
        fleet.end_tick()
        hits = obs.PREFIX_CACHE_HITS.value() - h0
        misses = obs.PREFIX_CACHE_MISSES.value() - m0
        assert hits + misses == 200
        assert 60 < hits < 140            # ~half, seeded rng
        assert obs.PREFIX_CACHE_REUSED_TOKENS.value() - r0 == \
            hits * 256
        # Warm samples are a tenth of cold (sigma 0: bimodal, up to
        # the tiny within-tick load inflation).
        warm = [t for t in ttfts if t < 0.25]
        assert len(warm) == hits
        assert all(abs(t - 0.05) < 1e-3 for t in warm)

    def test_content_aware_prefix_cache_is_a_routing_outcome(self):
        """ISSUE 15: with prefix_cache_capacity the hit model is
        CONTENT-aware — the same family hitting the same replica
        stays warm, scattering it across replicas re-misses, and LRU
        capacity evicts the coldest family."""
        serve_state.add_service(SVC, {'run': 'true'}, lb_port=0,
                                controller_port=0)
        clk = clock_lib.VirtualClock()
        profile = replicas_lib.ReplicaProfile(
            startup_median_s=10.0, startup_sigma=0.0,
            ttft_median_s=0.5, ttft_sigma=0.0,
            prefix_cache_capacity=2, warm_ttft_factor=0.1,
            concurrency=1000)
        fleet = replicas_lib.SimFleet(SVC, clk, random.Random(0),
                                      profile, zones=['za'])
        fleet.scale_up(2)
        clk.advance(11.0)
        fleet.probe_all()
        e1, e2 = sorted(fleet.ready_endpoints())
        fleet.begin_tick(1000.0)
        h0, m0 = (obs.PREFIX_CACHE_HITS.value(),
                  obs.PREFIX_CACHE_MISSES.value())

        def ctx(fam):
            return {'prefix_key': ('family', fam),
                    'prefix_tokens': 128}

        # Pinned family: first request cold, rest warm on e1...
        assert fleet.handle_request(e1, context=ctx(1))[0] > 0.4
        for _ in range(3):
            assert fleet.handle_request(e1, context=ctx(1))[0] < 0.1
        # ...but the SAME family is cold on e2 (content, not luck).
        assert fleet.handle_request(e2, context=ctx(1))[0] > 0.4
        assert obs.PREFIX_CACHE_HITS.value() - h0 == 3
        assert obs.PREFIX_CACHE_MISSES.value() - m0 == 2
        # Capacity 2: families 2,3 evict family 1 from e1's LRU.
        fleet.handle_request(e1, context=ctx(2))
        fleet.handle_request(e1, context=ctx(3))
        assert fleet.handle_request(e1, context=ctx(1))[0] > 0.4
        # A request with no prefix key is an honest miss.
        m1 = obs.PREFIX_CACHE_MISSES.value()
        fleet.handle_request(e1, context={'prompt_tokens': [1, 2]})
        assert obs.PREFIX_CACHE_MISSES.value() == m1 + 1
        fleet.end_tick()

    def test_pool_profiles_and_pool_gauges(self):
        serve_state.add_service(SVC, {'run': 'true'}, lb_port=0,
                                controller_port=0)
        clk = clock_lib.VirtualClock()
        base = replicas_lib.ReplicaProfile(
            startup_median_s=10.0, startup_sigma=0.0,
            ttft_median_s=0.5, ttft_sigma=0.0)
        prefill = replicas_lib.ReplicaProfile(
            startup_median_s=10.0, startup_sigma=0.0,
            ttft_median_s=2.0, ttft_sigma=0.0, concurrency=4)
        fleet = replicas_lib.SimFleet(
            SVC, clk, random.Random(0), base, zones=['za'],
            pool_profiles={'prefill': prefill})
        fleet.scale_up(1, pool='prefill')
        fleet.scale_up(1, pool='decode')
        clk.advance(11.0)
        fleet.probe_all()
        rows = {r['replica_id']: r['pool']
                for r in serve_state.get_replicas(SVC)}
        assert sorted(rows.values()) == ['decode', 'prefill']
        # Pool profile drives the latency shape.
        by_pool = {r.pool: r.endpoint
                   for r in fleet._replicas.values()}  # noqa: SLF001
        fleet.begin_tick(100.0)
        assert fleet.handle_request(by_pool['prefill'])[0] > 1.5
        assert fleet.handle_request(by_pool['decode'])[0] < 1.0
        fleet.end_tick()
        # Per-pool pressure series written for the pool autoscalers.
        assert obs.POOL_KV_UTILIZATION.value(pool='prefill') > 0
        assert obs.POOL_KV_UTILIZATION.value(pool='decode') > 0

    def test_capacity_profile_rejects_context_sharding(self):
        with pytest.raises(ValueError, match='context'):
            replicas_lib.ReplicaProfile(
                mesh_shape=(('context', 2),), prefix_cache_capacity=4)

    def test_mesh_shape_declares_topology_and_enforces_gate(self):
        """ISSUE 14: mesh_shape declares the replica's sharded
        topology, and the profile enforces the ENGINE's composition
        rule — a context-sharded replica runs dense, so a prefix-hit
        term there would gate on counters the real engine can never
        emit."""
        p = replicas_lib.ReplicaProfile(
            mesh_shape=(('tensor', 4),), prefix_hit_ratio=0.8)
        assert p.mesh_ways('tensor') == 4
        assert p.mesh_ways('context') == 1
        with pytest.raises(ValueError, match='context'):
            replicas_lib.ReplicaProfile(
                mesh_shape=(('tensor', 2), ('context', 2)),
                prefix_hit_ratio=0.8)
        # Context sharding without the prefix term is fine (dense
        # long-context replicas are a real topology).
        replicas_lib.ReplicaProfile(
            mesh_shape=(('tensor', 2), ('context', 2)))


# --- the tier-1 smoke scenario (the CI gate) --------------------------------

class TestSmokeScenario:

    def test_smoke_scenario_passes_slos(self, tmp_path):
        sim = runner_lib.FleetSim(runner_lib.SCENARIOS['smoke'],
                                  seed=0, out_dir=str(tmp_path))
        report = sim.run()
        by_name = {r['name']: r for r in report['asserts']}
        # The acceptance trio, asserted from the live registry (the
        # evaluator reads metric objects, nothing parses logs):
        assert by_name['ttft_p95']['ok'], by_name['ttft_p95']
        assert by_name['update_error_rate']['ok'], \
            by_name['update_error_rate']
        assert by_name['zone_loss_recovery']['ok'], \
            by_name['zone_loss_recovery']
        assert report['rc'] == 0, report['asserts']
        # Real traffic flowed through the real LB dispatch discipline.
        assert report['extra']['requests'] > 1000
        assert report['extra']['replicas_driven'] >= 48
        # The machine-readable evidence artifact, in the shared
        # {rc, scenario, asserts} schema.
        data = json.loads(
            open(os.path.join(str(tmp_path), 'SLO_smoke.json')).read())
        assert data['rc'] == 0
        assert data['scenario'] == 'smoke'
        assert all('threshold' in a for a in data['asserts'])

    def test_fused_decode_scenario_gates_decode_step_signal(
            self, tmp_path):
        """ROADMAP item 5 REMAINING: the fused_decode scenario drives
        replica distributions parameterized by fused-loop host-step
        time and asserts the p95 of the REAL
        skytpu_decode_step_seconds histogram (bucket deltas over the
        warmup..end window) — the engine's new decode-step-latency
        signal has soak coverage."""
        sim = runner_lib.FleetSim(
            runner_lib.SCENARIOS['fused_decode'], seed=0,
            out_dir=str(tmp_path))
        report = sim.run()
        by_name = {r['name']: r for r in report['asserts']}
        assert by_name['decode_step_p95']['ok'], \
            by_name['decode_step_p95']
        assert by_name['decode_step_p95']['metric'] == \
            'skytpu_decode_step_seconds'
        # The p95 resolved from real bucket bounds, not a stub value.
        assert 0 < by_name['decode_step_p95']['value'] <= 0.25
        assert by_name['ttft_p95']['ok'], by_name['ttft_p95']
        assert report['rc'] == 0, report['asserts']
        assert report['extra']['requests'] > 1000

    def test_spec_decode_scenario_gates_acceptance_ratio(
            self, tmp_path):
        """ISSUE 13 satellite: the spec_decode scenario models
        fused draft/verify rounds per host dispatch and gates the
        draft acceptance RATIO from counter deltas of the REAL
        skytpu_spec_* registry series (the ones the engine exports),
        plus the decode-step p95 one fused speculative dispatch must
        hold."""
        sim = runner_lib.FleetSim(
            runner_lib.SCENARIOS['spec_decode'], seed=0,
            out_dir=str(tmp_path))
        report = sim.run()
        by_name = {r['name']: r for r in report['asserts']}
        acc = by_name['spec_acceptance']
        assert acc['ok'], acc
        assert acc['metric'] == 'skytpu_spec_accepted_tokens_total'
        # The ratio resolved from real counter deltas, near the
        # profile's expected ~0.59 (not a stub or an absolute read).
        assert 0.45 <= acc['value'] <= 0.75
        assert by_name['decode_step_p95']['ok'], \
            by_name['decode_step_p95']
        assert by_name['decode_step_p95']['metric'] == \
            'skytpu_decode_step_seconds'
        assert report['rc'] == 0, report['asserts']
        assert report['extra']['requests'] > 1000
        data = json.loads(open(os.path.join(
            str(tmp_path), 'SLO_spec_decode.json')).read())
        assert data['rc'] == 0 and data['scenario'] == 'spec_decode'

    def test_shared_prefix_scenario_gates_hit_ratio(self, tmp_path):
        """ISSUE 11 satellite: the shared_prefix scenario models a
        prefix-hit-ratio replica term and gates the cache hit RATIO
        from counter deltas of the REAL skytpu_prefix_cache_*
        registry series (the ones the engine exports), plus the
        warm-traffic TTFT p95 the cache is supposed to buy."""
        sim = runner_lib.FleetSim(
            runner_lib.SCENARIOS['shared_prefix'], seed=0,
            out_dir=str(tmp_path))
        report = sim.run()
        by_name = {r['name']: r for r in report['asserts']}
        hit = by_name['cache_hit_ratio']
        assert hit['ok'], hit
        assert hit['metric'] == 'skytpu_prefix_cache_hits_total'
        # The ratio resolved from real counter deltas, near the
        # profile's configured 0.87 (not a stub or an absolute read).
        assert 0.75 <= hit['value'] <= 1.0
        assert by_name['ttft_p95']['ok'], by_name['ttft_p95']
        assert report['rc'] == 0, report['asserts']
        assert report['extra']['requests'] > 1000
        data = json.loads(open(os.path.join(
            str(tmp_path), 'SLO_shared_prefix.json')).read())
        assert data['rc'] == 0 and data['scenario'] == 'shared_prefix'

    def test_preemption_migration_scenario_gates_success_ratio(
            self, tmp_path):
        """ISSUE 17 satellite: the preemption_migration scenario
        kills the busiest replicas mid-decode (replica.preempt) and
        gates the snapshot/restore ladder on the REAL
        skytpu_migration_* series: success RATIO >= 0.9 from counter
        deltas and the client-visible interruption-gap p95 from
        bucket deltas. The armed lb.migrate fault forces exactly two
        honest terminations, so both rungs of the ladder are
        exercised in one report."""
        sim = runner_lib.FleetSim(
            runner_lib.SCENARIOS['preemption_migration'], seed=0,
            out_dir=str(tmp_path))
        report = sim.run()
        by_name = {r['name']: r for r in report['asserts']}
        ratio = by_name['migration_success']
        assert ratio['ok'], ratio
        assert ratio['metric'] == 'skytpu_migration_successes_total'
        # >= 0.9 but < 1.0: the two forced lb.migrate failures landed
        # (the failure rung ran), yet the fleet still cleared the bar.
        assert 0.9 <= ratio['value'] < 1.0, ratio
        gap = by_name['migration_interruption_p95']
        assert gap['ok'], gap
        assert gap['metric'] == 'skytpu_migration_interruption_seconds'
        assert 0 < gap['value'] <= 2.0
        assert by_name['ttft_p95']['ok'], by_name['ttft_p95']
        assert report['rc'] == 0, report['asserts']
        assert report['extra']['requests'] > 1000
        data = json.loads(open(os.path.join(
            str(tmp_path), 'SLO_preemption_migration.json')).read())
        assert data['rc'] == 0
        assert data['scenario'] == 'preemption_migration'

    def test_disaggregation_scenario_gates_handoff_ratio(
            self, tmp_path):
        """ISSUE 19 satellite: the disaggregation scenario pushes a
        skewed prompt/gen mix through prefill + decode pools with
        planned KV handoff, kills the busiest DECODE replicas
        mid-wave, and gates the handoff success ratio (>= 0.85 from
        skytpu_handoff_* counter deltas), ZERO failed requests, the
        transfer p95, and the decode-pool TTFT p95 with the
        co-located baseline pass (same seed, handoff off) in the
        same report."""
        sim = runner_lib.FleetSim(
            runner_lib.SCENARIOS['disaggregation'], seed=0,
            out_dir=str(tmp_path))
        report = sim.run()
        by_name = {r['name']: r for r in report['asserts']}
        ratio = by_name['handoff_success']
        assert ratio['ok'], ratio
        assert ratio['metric'] == 'skytpu_handoff_successes_total'
        # >= 0.85 but < 1.0: the armed lb.handoff fault forced a few
        # counted co-located fallbacks — the degradation rung ran —
        # yet the fleet still cleared the bar.
        assert 0.85 <= ratio['value'] < 1.0, ratio
        # A fallback is a degraded SUCCESS: zero hard failures even
        # while chaos kills decode replicas mid-wave.
        failed = by_name['failed_requests']
        assert failed['ok'] and failed['value'] == 0.0, failed
        assert by_name['baseline_failed_requests']['value'] == 0.0
        transfer = by_name['handoff_transfer_p95']
        assert transfer['ok'], transfer
        assert transfer['metric'] == 'skytpu_handoff_transfer_seconds'
        assert 0 < transfer['value'] <= 1.5
        # Both sides of the A/B resolved the decode-pool TTFT series.
        assert by_name['decode_pool_ttft_p95']['ok']
        assert by_name['baseline_decode_pool_ttft_p95'][
            'value'] is not None
        assert report['rc'] == 0, report['asserts']
        assert report['extra']['requests'] > 1000
        assert report['extra']['handoff_enabled'] is True
        assert report['extra']['baseline']['handoff_enabled'] is False
        assert report['extra']['pools'] == ['decode', 'prefill']
        data = json.loads(open(os.path.join(
            str(tmp_path), 'SLO_disaggregation.json')).read())
        assert data['rc'] == 0
        assert data['scenario'] == 'disaggregation'

    def test_sharded_serve_scenario_gates_decode_and_hit_ratio(
            self, tmp_path):
        """ISSUE 14 satellite: the sharded_serve scenario drives
        tensor=4-sharded replicas (paged pool + prefix cache — the
        composition this PR unlocked) and gates BOTH the
        decode-step p95 and the prefix hit ratio from the live
        skytpu_* registry series."""
        sim = runner_lib.FleetSim(
            runner_lib.SCENARIOS['sharded_serve'], seed=0,
            out_dir=str(tmp_path))
        assert runner_lib.SCENARIOS['sharded_serve'].profile \
            .mesh_ways('tensor') == 4
        report = sim.run()
        by_name = {r['name']: r for r in report['asserts']}
        assert by_name['decode_step_p95']['ok'], \
            by_name['decode_step_p95']
        assert by_name['decode_step_p95']['metric'] == \
            'skytpu_decode_step_seconds'
        hit = by_name['prefix_hit_ratio']
        assert hit['ok'], hit
        assert hit['metric'] == 'skytpu_prefix_cache_hits_total'
        # Resolved from real counter deltas near the configured 0.8.
        assert 0.7 <= hit['value'] <= 1.0
        assert by_name['ttft_p95']['ok'], by_name['ttft_p95']
        assert report['rc'] == 0, report['asserts']
        assert report['extra']['requests'] > 1000
        data = json.loads(open(os.path.join(
            str(tmp_path), 'SLO_sharded_serve.json')).read())
        assert data['rc'] == 0 and data['scenario'] == 'sharded_serve'

    def test_prefix_affinity_scenario_gates_hit_ratio_vs_baseline(
            self, tmp_path):
        """ISSUE 15 acceptance: the prefix_affinity scenario drives a
        multi-pool fleet with CONTENT-aware replica caches through
        the real LB dispatch + PrefixAffinityPolicy, and gates (a)
        fleet cache-hit ratio >= 0.6 under affinity routing, (b)
        >= 2x hit-ratio improvement over the least_load baseline
        pass IN THE SAME REPORT, (c) warm TTFT p50/p95."""
        sim = runner_lib.FleetSim(
            runner_lib.SCENARIOS['prefix_affinity'], seed=0,
            out_dir=str(tmp_path))
        report = sim.run()
        by_name = {r['name']: r for r in report['asserts']}
        hit = by_name['cache_hit_ratio']
        assert hit['ok'], hit
        assert hit['metric'] == 'skytpu_prefix_cache_hits_total'
        assert hit['value'] >= 0.6
        base = by_name['baseline_cache_hit_ratio']
        # The baseline pass scattered the same traffic: its ratio is
        # a real counter-delta number, well below affinity's.
        assert 0.0 < base['value'] < hit['value']
        imp = by_name['hit_ratio_improvement']
        assert imp['ok'], imp
        assert imp['value'] >= 2.0
        # Warm-dominated median vs the mixed-workload tail budget.
        assert by_name['ttft_p50']['ok'], by_name['ttft_p50']
        assert by_name['ttft_p95']['ok'], by_name['ttft_p95']
        assert report['rc'] == 0, report['asserts']
        # Both passes pushed real traffic through the real LB.
        assert report['extra']['requests'] > 1000
        assert report['extra']['lb_policy'] == 'prefix_affinity'
        assert report['extra']['baseline']['lb_policy'] == \
            'least_load'
        assert report['extra']['baseline']['requests'] > 1000
        assert report['extra']['pools'] == ['decode', 'prefill']
        data = json.loads(open(os.path.join(
            str(tmp_path), 'SLO_prefix_affinity.json')).read())
        assert data['rc'] == 0
        assert data['scenario'] == 'prefix_affinity'

    def test_controller_stall_and_crash_fault_modes(self, tmp_path):
        """`controller.step` has two chaos modes: latency_only arms a
        STALLED tick (clock advances, no crash), a plain arm a
        CRASHED tick (counted, run continues)."""
        base = runner_lib.SCENARIOS['smoke']
        import dataclasses
        scenario = dataclasses.replace(
            base, name='smoke_stall',
            duration_s=30.0, warmup_s=10.0,
            chaos=(
                {'at': 12.0, 'action': 'arm_fault',
                 'point': 'controller.step', 'times': 1,
                 'latency': 4.0, 'latency_only': True},
                {'at': 18.0, 'action': 'arm_fault',
                 'point': 'controller.step', 'times': 1},
            ),
            slos=(slo_lib.RatioBelow('error_rate', threshold=1.0),))
        before = obs.FAULTS_INJECTED.value(point='controller.step')
        report = runner_lib.FleetSim(scenario, seed=0,
                                     out_dir=str(tmp_path)).run()
        assert obs.FAULTS_INJECTED.value(point='controller.step') == \
            before + 2
        # Only the second arm (no latency_only) crashed the tick.
        assert report['extra']['controller_crashes'] == 1


    def test_crash_writes_failing_report_and_cleans_up(self, tmp_path):
        """A run that dies mid-loop must still write an rc=1 report,
        disarm every fault and drop its service rows — then re-raise
        so the failure is loud. A crashed soak must never look like a
        passing one OR poison the next scenario."""
        import dataclasses
        base = runner_lib.SCENARIOS['smoke']
        scenario = dataclasses.replace(
            base, name='smoke_crash', duration_s=20.0, warmup_s=5.0,
            # Malformed event: zone_loss without a zone -> KeyError
            # AFTER fleet.zone_loss was armed forever.
            chaos=({'at': 4.0, 'action': 'zone_loss'},),
            slos=(slo_lib.RatioBelow('error_rate', threshold=1.0),))
        with pytest.raises(KeyError):
            runner_lib.FleetSim(scenario, seed=0,
                                out_dir=str(tmp_path)).run()
        data = json.loads(open(os.path.join(
            str(tmp_path), 'SLO_smoke_crash.json')).read())
        assert data['rc'] == 1
        assert 'KeyError' in data['extra']['error']
        assert faults.armed_points() == []
        assert serve_state.get_service('fleetsim-smoke_crash') is None


# --- full-scale soaks (slow; also run via tests/run_full.sh) ----------------

@pytest.mark.slow
class TestFullSoaks:

    def _run(self, name, tmp_path):
        sim = runner_lib.FleetSim(runner_lib.SCENARIOS[name], seed=0,
                                  out_dir=str(tmp_path))
        report = sim.run()
        assert report['rc'] == 0, report['asserts']
        return report

    def test_zone_loss_acceptance(self, tmp_path):
        """The ISSUE acceptance bar: >= 1000 replicas through zone
        loss + recovery on the virtual clock in < 60s wall."""
        start = time.monotonic()
        report = self._run('zone_loss', tmp_path)
        wall = time.monotonic() - start
        assert report['extra']['replicas_driven'] >= 1000
        assert wall < 60.0, f'soak took {wall:.1f}s wall'
        assert report['extra']['unrecovered_events'] == []

    def test_rolling_update_soak(self, tmp_path):
        self._run('rolling_update', tmp_path)

    def test_preemption_wave_soak(self, tmp_path):
        """Also the regression harness for the decide_mixed fallback
        runaway: a bounded fleet proves the hold branch no longer
        compounds the spot shortfall."""
        report = self._run('preemption_wave', tmp_path)
        assert report['extra']['replicas_driven'] < 1200, \
            'fallback autoscaler relaunched unboundedly'


# --- CLI --------------------------------------------------------------------

class TestCLI:

    def test_list_and_bad_scenario(self, capsys):
        from skypilot_tpu.fleetsim.__main__ import main
        assert main(['--list']) == 0
        out = capsys.readouterr().out
        for name in runner_lib.SCENARIOS:
            assert name in out
        with pytest.raises(SystemExit):
            main(['--scenario', 'nope'])
