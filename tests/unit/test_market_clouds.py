"""Fluidstack + Vast provisioners against in-memory fake APIs.

Vast's offer-market model gets its own coverage: launches accept the
cheapest matching offer, and an empty offer book is a CapacityError
the failover engine can act on.
"""
import itertools

import pytest

from skypilot_tpu import exceptions
from skypilot_tpu.adaptors import fluidstack as fs_adaptor
from skypilot_tpu.adaptors import vast as vast_adaptor
from skypilot_tpu.provision import common
from skypilot_tpu.provision import fluidstack as fs_provision
from skypilot_tpu.provision import vast as vast_provision


def _config(instance_type, count=1, **node):
    return common.ProvisionConfig(
        provider_config={'region': 'norway'},
        authentication_config={'ssh_user': 'ubuntu',
                               'ssh_public_key_content': 'ssh-ed25519 K'},
        node_config={'instance_type': instance_type, **node},
        count=count)


# ----------------------------------------------------------- fluidstack

class FakeFluidstack:
    def __init__(self):
        self.instances = {}
        self.ssh_keys = []
        self._ids = itertools.count()

    def request(self, method, path, params=None, json_body=None):
        if path == '/ssh_keys' and method == 'GET':
            return {'ssh_keys': list(self.ssh_keys)}
        if path == '/ssh_keys' and method == 'POST':
            self.ssh_keys.append(dict(json_body))
            return dict(json_body)
        if path == '/instances' and method == 'GET':
            return {'instances': list(self.instances.values())}
        if path == '/instances' and method == 'POST':
            iid = f'fs-{next(self._ids)}'
            self.instances[iid] = {
                'id': iid, 'name': json_body['name'],
                'status': 'running', 'ip_address': '185.0.0.4',
                'private_ip': '10.3.0.4', '_spec': json_body}
            return self.instances[iid]
        if method == 'PUT' and path.endswith('/stop'):
            self.instances[path.split('/')[2]]['status'] = 'stopped'
            return {}
        if method == 'PUT' and path.endswith('/start'):
            self.instances[path.split('/')[2]]['status'] = 'running'
            return {}
        if method == 'DELETE':
            del self.instances[path.split('/')[2]]
            return {}
        raise AssertionError(f'unexpected {method} {path}')


@pytest.fixture
def fake_fs():
    api = FakeFluidstack()
    fs_adaptor.set_client_factory(lambda: api)
    yield api
    fs_adaptor.set_client_factory(
        lambda: (_ for _ in ()).throw(AssertionError('no client')))


def test_fluidstack_lifecycle(fake_fs):
    record = fs_provision.run_instances(
        'norway', 'fs1', _config('8x_H100', gpu_type='H100',
                                 gpu_count=8))
    assert record.created_instance_ids == ['fs1-0']
    inst = next(iter(fake_fs.instances.values()))
    assert inst['_spec']['gpu_count'] == 8
    assert len(fake_fs.ssh_keys) == 1
    info = fs_provision.get_cluster_info('norway', 'fs1', {})
    assert info.get_head_instance().hosts[0].external_ip == '185.0.0.4'
    fs_provision.stop_instances('fs1', {})
    assert fs_provision.query_instances('fs1', {}) == {
        'fs1-0': 'stopped'}
    record = fs_provision.run_instances(
        'norway', 'fs1', _config('8x_H100', gpu_type='H100',
                                 gpu_count=8))
    assert record.resumed_instance_ids == ['fs1-0']
    fs_provision.terminate_instances('fs1', {})
    assert fs_provision.query_instances('fs1', {}) == {}


# ----------------------------------------------------------------- vast

class FakeVast:
    def __init__(self):
        self.offers = []
        self.instances = {}
        self._ids = itertools.count(500)
        self.accepted_asks = []

    def request(self, method, path, params=None, json_body=None):
        if path == '/api/v0/bundles/' and method == 'PUT':
            q = json_body['q']
            matching = [o for o in self.offers
                        if o['gpu_name'] == q['gpu_name']['eq']
                        and o['num_gpus'] == q['num_gpus']['eq']]
            return {'offers': sorted(matching,
                                     key=lambda o: o['dph_total'])}
        if path == '/api/v0/instances/' and method == 'GET':
            return {'instances': list(self.instances.values())}
        if method == 'PUT' and path.startswith('/api/v0/asks/'):
            ask_id = int(path.split('/')[4])
            offer = next(o for o in self.offers if o['id'] == ask_id)
            self.accepted_asks.append(ask_id)
            iid = next(self._ids)
            self.instances[iid] = {
                'id': iid, 'label': json_body['label'],
                'actual_status': 'running',
                'public_ipaddr': '72.0.0.9', 'ssh_port': 34022,
                '_offer': offer, '_spec': json_body}
            return {'success': True, 'new_contract': iid}
        if method == 'PUT' and path.startswith('/api/v0/instances/'):
            iid = int(path.split('/')[4])
            self.instances[iid]['actual_status'] = (
                'stopped' if json_body['state'] == 'stopped'
                else 'running')
            return {}
        if method == 'DELETE':
            del self.instances[int(path.split('/')[4])]
            return {}
        raise AssertionError(f'unexpected {method} {path}')


@pytest.fixture
def fake_vast():
    api = FakeVast()
    vast_adaptor.set_client_factory(lambda: api)
    yield api
    vast_adaptor.set_client_factory(
        lambda: (_ for _ in ()).throw(AssertionError('no client')))


def test_vast_accepts_cheapest_offer(fake_vast):
    fake_vast.offers = [
        {'id': 1, 'gpu_name': 'H100 SXM', 'num_gpus': 8,
         'dph_total': 19.0},
        {'id': 2, 'gpu_name': 'H100 SXM', 'num_gpus': 8,
         'dph_total': 14.5},
        {'id': 3, 'gpu_name': 'H100 SXM', 'num_gpus': 1,
         'dph_total': 2.0},
    ]
    record = vast_provision.run_instances(
        'any', 'v1', _config('8x_H100', gpu_type='H100', gpu_count=8))
    assert record.created_instance_ids == ['v1-0']
    assert fake_vast.accepted_asks == [2]  # cheapest 8xH100 offer
    inst = next(iter(fake_vast.instances.values()))
    assert 'ssh-ed25519 K' in inst['_spec']['onstart']
    info = vast_provision.get_cluster_info('any', 'v1', {})
    host = info.get_head_instance().hosts[0]
    assert host.ssh_port == 34022  # market boxes expose mapped ports
    runners = vast_provision.get_command_runners(info)
    assert runners[0].port == 34022


def test_vast_empty_offer_book_is_capacity_error(fake_vast):
    with pytest.raises(exceptions.CapacityError):
        vast_provision.run_instances(
            'any', 'v2', _config('8x_H100', gpu_type='H100',
                                 gpu_count=8))


def test_vast_gpu_name_mapping(fake_vast):
    """Catalog names must translate to Vast's live vocabulary
    ('RTX4090' -> 'RTX 4090'), or no offer would ever match."""
    fake_vast.offers = [
        {'id': 4, 'gpu_name': 'RTX 4090', 'num_gpus': 1,
         'dph_total': 0.38},
        {'id': 5, 'gpu_name': 'A100 SXM4', 'num_gpus': 8,
         'dph_total': 8.9},
    ]
    client = vast_adaptor.client()
    assert [o['id'] for o in vast_provision.search_offers(
        client, 'RTX4090', 1)] == [4]
    assert [o['id'] for o in vast_provision.search_offers(
        client, 'A100-80GB', 8)] == [5]


def test_stopping_state_refuses_duplicate_creation(fake_vast,
                                                   fake_fs):
    """A 'stopping' instance must block relaunch instead of spawning
    a same-name twin that would be orphaned (and billed) forever."""
    fake_vast.offers = [{'id': 9, 'gpu_name': 'H100 SXM',
                         'num_gpus': 1, 'dph_total': 2.0}]
    vast_provision.run_instances(
        'any', 'v1', _config('1x_H100', gpu_type='H100', gpu_count=1))
    iid = next(iter(fake_vast.instances))
    fake_vast.instances[iid]['actual_status'] = 'stopping'
    with pytest.raises(exceptions.ProvisionError, match='stopping'):
        vast_provision.run_instances(
            'any', 'v1', _config('1x_H100', gpu_type='H100',
                                 gpu_count=1))
    assert len(fake_vast.instances) == 1

    fs_provision.run_instances(
        'norway', 'fs1', _config('1x_H100', gpu_type='H100',
                                 gpu_count=1))
    fid = next(iter(fake_fs.instances))
    fake_fs.instances[fid]['status'] = 'stopping'
    with pytest.raises(exceptions.ProvisionError, match='stopping'):
        fs_provision.run_instances(
            'norway', 'fs1', _config('1x_H100', gpu_type='H100',
                                     gpu_count=1))
    assert len(fake_fs.instances) == 1


def test_vast_stop_resume_terminate(fake_vast):
    fake_vast.offers = [
        {'id': 9, 'gpu_name': 'RTX 4090', 'num_gpus': 1,
         'dph_total': 0.4}]
    vast_provision.run_instances(
        'any', 'v1', _config('1x_RTX4090', gpu_type='RTX4090',
                             gpu_count=1))
    vast_provision.stop_instances('v1', {})
    assert vast_provision.query_instances('v1', {}) == {
        'v1-0': 'stopped'}
    record = vast_provision.run_instances(
        'any', 'v1', _config('1x_RTX4090', gpu_type='RTX4090',
                             gpu_count=1))
    assert record.resumed_instance_ids == ['v1-0']
    vast_provision.terminate_instances('v1', {})
    assert vast_provision.query_instances('v1', {}) == {}


def test_twelve_cloud_registry(enable_clouds):
    """All 12 infra targets registered; optimizer ranks across the two
    market clouds (vast's 8xH100 floor $15.60 < fluidstack $23.12)."""
    from skypilot_tpu import Dag, Resources, Task
    from skypilot_tpu.clouds import CLOUD_REGISTRY
    from skypilot_tpu.optimizer import Optimizer
    assert {'gcp', 'aws', 'azure', 'kubernetes', 'ssh', 'local',
            'lambda', 'runpod', 'nebius', 'do', 'fluidstack',
            'vast'} <= set(CLOUD_REGISTRY.names())
    enable_clouds('fluidstack', 'vast')
    with Dag() as dag:
        t = Task('t', run='true')
        t.set_resources(Resources(accelerators='H100:8'))
        dag.add(t)
    Optimizer.optimize(dag, quiet=True)
    assert t.best_resources.cloud == 'vast'
