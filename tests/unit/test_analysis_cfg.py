"""Unit tests for the statement-level CFG the flow checkers stand on:
try/finally duplication, with-statement exception edges, loop
back-edges, and except-dispatch escape semantics.
"""
import ast
import textwrap

from skypilot_tpu.analysis import cfg as cfg_mod
from skypilot_tpu.analysis import dataflow


def _fn(source: str) -> ast.FunctionDef:
    tree = ast.parse(textwrap.dedent(source))
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return node
    raise AssertionError('no function in fixture')


def _stmt_on_line(fn: ast.AST, lineno: int) -> ast.stmt:
    best = None
    for node in ast.walk(fn):
        if isinstance(node, ast.stmt) and node.lineno == lineno:
            best = node
    assert best is not None, f'no statement on line {lineno}'
    return best


def _find(fn: ast.AST, needle: str, source: str) -> ast.stmt:
    lines = textwrap.dedent(source).splitlines()
    for i, line in enumerate(lines, 1):
        if needle in line:
            return _stmt_on_line(fn, i)
    raise AssertionError(f'{needle!r} not in fixture')


def test_try_finally_runs_on_every_continuation():
    """The finalbody is duplicated per continuation: neither the
    normal exit, the raise exit, nor the return path can bypass it."""
    src = """
        def f(x):
            try:
                step(x)
                return x
            finally:
                cleanup()
    """
    fn = _fn(src)
    graph = cfg_mod.build(fn)
    cleanup = _find(fn, 'cleanup()', src)
    # Normal, exception, and return continuations each get their own
    # copy of the finalbody.
    copies = graph.nodes_for(cleanup)
    assert len(copies) >= 2
    exit_node, raise_node = graph.terminals()
    blocked_ids = {n.index for n in copies}
    step = _find(fn, 'step(x)', src)
    for start in graph.nodes_for(step):
        # step() raises -> must pass through a finally copy first.
        hit = dataflow.reach_avoiding(
            start, {exit_node.index, raise_node.index},
            blocked=lambda n: n.index in blocked_ids)
        assert hit is None, 'a path escaped the finally'


def test_with_statement_has_exception_edge_and_body_flow():
    src = """
        def f(res):
            with res.open() as h:
                use(h)
            done()
    """
    fn = _fn(src)
    graph = cfg_mod.build(fn)
    exit_node, raise_node = graph.terminals()
    with_stmt = _find(fn, 'with res.open()', src)
    (wnode,) = graph.nodes_for(with_stmt)
    kinds = {kind for _, kind in wnode.succs}
    # Entering the context can raise; the body is the normal edge.
    assert cfg_mod.EXCEPTION in kinds and cfg_mod.NORMAL in kinds
    use = _find(fn, 'use(h)', src)
    (unode,) = graph.nodes_for(use)
    # The body call can raise out of the function...
    assert any(t.index == raise_node.index for t, k in unode.succs
               if k == cfg_mod.EXCEPTION)
    # ...and normally falls through to the statement after the with.
    done = _find(fn, 'done()', src)
    hit = dataflow.reach_avoiding(
        unode, {graph.nodes_for(done)[0].index}, blocked=lambda n: False)
    assert hit is not None


def test_loop_back_edges_mark_cyclic_nodes():
    src = """
        def f(items):
            total = 0
            for x in items:
                total += use(x)
            while total > 0:
                total = shrink(total)
            return total
    """
    fn = _fn(src)
    graph = cfg_mod.build(fn)
    cyclic = graph.cyclic_nodes()
    for needle in ('total += use(x)', 'total = shrink(total)'):
        stmt = _find(fn, needle, src)
        assert all(n.index in cyclic for n in graph.nodes_for(stmt)), \
            f'{needle!r} not recognized as loop body'
    for needle in ('total = 0', 'return total'):
        stmt = _find(fn, needle, src)
        assert all(n.index not in cyclic
                   for n in graph.nodes_for(stmt)), \
            f'{needle!r} wrongly marked cyclic'


def _escapes_handler(src: str) -> bool:
    """Can the try body's exception reach the raise exit without
    entering the handler body?"""
    fn = _fn(src)
    graph = cfg_mod.build(fn)
    _, raise_node = graph.terminals()
    risky = _find(fn, 'risky()', src)
    handled = _find(fn, 'handled()', src)
    handler_ids = {n.index for n in graph.nodes_for(handled)}
    for start in graph.nodes_for(risky):
        hit = dataflow.reach_avoiding(
            start, {raise_node.index},
            blocked=lambda n: n.index in handler_ids)
        if hit is not None:
            return True
    return False


def test_narrow_except_lets_exceptions_escape():
    assert _escapes_handler("""
        def f():
            try:
                risky()
            except ValueError:
                handled()
    """)


def test_bare_except_catches_everything():
    assert not _escapes_handler("""
        def f():
            try:
                risky()
            except:
                handled()
    """)


def test_base_exception_handler_catches_everything():
    assert not _escapes_handler("""
        def f():
            try:
                risky()
            except BaseException:
                handled()
    """)


def test_safe_builtins_do_not_fork_exception_edges():
    src = """
        def f(xs):
            n = len(xs)
            return n
    """
    fn = _fn(src)
    graph = cfg_mod.build(fn)
    stmt = _find(fn, 'n = len(xs)', src)
    (node,) = graph.nodes_for(stmt)
    assert all(k == cfg_mod.NORMAL for _, k in node.succs)
