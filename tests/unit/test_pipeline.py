"""Pipeline parallelism: GPipe schedule over the `pipe` mesh axis.

No reference equivalent (SkyPilot ships no parallelism machinery;
SURVEY.md §2.11) — correctness oracle is the non-pipelined forward.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.models import llama
from skypilot_tpu.parallel import MeshSpec, make_mesh
from skypilot_tpu.parallel import pipeline


@pytest.fixture(scope='module')
def setup():
    import dataclasses
    # 4 layers so the stack splits across up to 4 stages.
    config = dataclasses.replace(llama.CONFIGS['tiny'], num_layers=4)
    params = llama.init_params(config, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (8, 32), 0,
                                config.vocab_size, jnp.int32)
    reference = llama.forward(params, tokens, config)
    return config, params, tokens, reference


@pytest.mark.parametrize('stages', [2, 4])
def test_pipeline_matches_unpipelined(setup, stages):
    config, params, tokens, reference = setup
    mesh = make_mesh(MeshSpec(data=8 // stages, pipe=stages, fsdp=1))
    out = pipeline.llama_pipeline_forward(params, tokens, config, mesh)
    np.testing.assert_allclose(np.asarray(reference), np.asarray(out),
                               atol=3e-4, rtol=1e-3)


def test_more_microbatches_than_stages(setup):
    config, params, tokens, reference = setup
    mesh = make_mesh(MeshSpec(data=4, pipe=2, fsdp=1))
    out = pipeline.llama_pipeline_forward(params, tokens, config, mesh,
                                          num_microbatches=4)
    np.testing.assert_allclose(np.asarray(reference), np.asarray(out),
                               atol=3e-4, rtol=1e-3)


def test_single_stage_fallback(setup):
    config, params, tokens, reference = setup
    mesh = make_mesh(MeshSpec(data=8, pipe=1, fsdp=1))
    out = pipeline.llama_pipeline_forward(params, tokens, config, mesh)
    np.testing.assert_allclose(np.asarray(reference), np.asarray(out),
                               atol=3e-4, rtol=1e-3)


def test_gradients_flow_through_pipeline():
    """jax.grad reverses the schedule; grads must match the oracle.

    Own 2-layer config (not the module fixture's 4): grad-of-pipeline
    compile time scales with the stacked layer count and dominates the
    whole suite, while 1 layer/stage already exercises every
    microbatch/stage boundary the schedule has."""
    import dataclasses
    config = dataclasses.replace(llama.CONFIGS['tiny'], num_layers=2)
    params = llama.init_params(config, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (4, 16), 0,
                                config.vocab_size, jnp.int32)
    # 4-device submesh: grad-of-pipeline compile scales with SPMD
    # partition count, and 2x2 already exercises microbatch rotation.
    mesh = make_mesh(MeshSpec(data=2, pipe=2, fsdp=1),
                     devices=jax.devices()[:4])

    def ref_loss(p):
        return (llama.forward(p, tokens, config).astype(
            jnp.float32) ** 2).mean()

    def pipe_loss(p):
        return (pipeline.llama_pipeline_forward(
            p, tokens, config, mesh).astype(jnp.float32) ** 2).mean()

    g_ref = jax.grad(ref_loss)(params)
    g_pipe = jax.grad(pipe_loss)(params)
    for a, b in zip(jax.tree.leaves(g_ref), jax.tree.leaves(g_pipe)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=5e-3)


def test_uneven_layers_rejected(setup):
    config, params, tokens, _ = setup  # 4 layers % 8 stages != 0
    mesh = make_mesh(MeshSpec(data=1, pipe=8, fsdp=1))
    with pytest.raises(ValueError, match='layers'):
        pipeline.llama_pipeline_forward(params, tokens, config, mesh)


def test_uneven_microbatches_rejected(setup):
    config, params, tokens, _ = setup
    mesh = make_mesh(MeshSpec(data=4, pipe=2, fsdp=1))
    with pytest.raises(ValueError, match='microbatches'):
        pipeline.llama_pipeline_forward(params, tokens, config, mesh,
                                        num_microbatches=3)
