"""End-to-end on the local cloud: launch -> job runs -> logs -> queue ->
exec -> cancel -> autostop -> down.

This exercises the REAL stack (optimizer, provisioner, skylet job queue,
gang runner, log tailer) with zero credentials — the role moto plays in
the reference (tests/common_test_fixtures.py:414), but with actual
process execution.
"""
import io
import time

import pytest

from skypilot_tpu import Resources, Task, core, exceptions, state
from skypilot_tpu.execution import exec_cmd, launch
from skypilot_tpu.skylet import job_lib


def _local_task(run='echo hello-world', **kw):
    t = Task('e2e', run=run, **kw)
    t.set_resources(Resources(infra='local'))
    return t


def _wait_job(handle, job_id, timeout=30):
    rt = handle.runtime_dir
    deadline = time.time() + timeout
    while time.time() < deadline:
        job = job_lib.get_job(rt, job_id)
        if job and job['status'].is_terminal():
            return job
        time.sleep(0.2)
    raise TimeoutError('job did not finish')


@pytest.fixture
def local_cloud(enable_clouds):
    enable_clouds('local')


class TestLocalEndToEnd:

    def test_launch_runs_job_and_streams_logs(self, local_cloud, capfd):
        job_id, handle = launch(_local_task(), cluster_name='t1')
        assert job_id == 1
        assert handle.cluster_name == 't1'
        job = job_lib.get_job(handle.runtime_dir, job_id)
        assert job['status'] == job_lib.JobStatus.SUCCEEDED
        # launch() tails by default; output must have streamed back.
        out = capfd.readouterr().out
        assert 'hello-world' in out
        # State DB reflects UP.
        rec = state.get_cluster_from_name('t1')
        assert rec['status'] == state.ClusterStatus.UP

    def test_env_injection(self, local_cloud, capfd):
        run = ('echo rank=$SKYTPU_NODE_RANK nodes=$SKYTPU_NUM_NODES '
               'procs=$SKYTPU_NUM_PROCESSES coord=$SKYTPU_COORDINATOR_ADDR '
               'myenv=$MYVAR')
        t = _local_task(run=run, envs={'MYVAR': 'abc'})
        job_id, handle = launch(t, cluster_name='t2')
        out = capfd.readouterr().out
        assert 'rank=0 nodes=1 procs=1' in out
        assert 'coord=127.0.0.1:8476' in out
        assert 'myenv=abc' in out

    def test_multi_node_gang(self, local_cloud, capfd):
        t = _local_task(run='echo node-$SKYTPU_NODE_RANK-of-'
                            '$SKYTPU_NUM_NODES')
        t.num_nodes = 3
        job_id, handle = launch(t, cluster_name='t3')
        out = capfd.readouterr().out
        for i in range(3):
            assert f'node-{i}-of-3' in out

    def test_multiprocess_dcn_bootstrap_psum(self, local_cloud, capfd):
        """The full distributed contract, executed: the gang launches
        2 REAL host processes, each calls jax.distributed.initialize
        from the injected SKYTPU_* coordinates
        (parallel/mesh.py initialize_distributed), and a psum runs
        ACROSS the processes — proving the coordinator address, rank
        injection, and collective path work end-to-end, not just as
        env-var strings."""
        program = (
            "import os\n"
            "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
            "import jax\n"
            "jax.config.update('jax_platforms', 'cpu')\n"
            "from jax.extend import backend as _jexb\n"
            "_jexb.clear_backends()\n"
            "from skypilot_tpu.parallel import mesh as mesh_lib\n"
            "assert mesh_lib.initialize_distributed()\n"
            "import jax.numpy as jnp\n"
            "assert jax.process_count() == 2, jax.process_count()\n"
            "n = jax.local_device_count()\n"
            "x = jnp.full((n,), (jax.process_index() + 1) / n)\n"
            "y = jax.pmap(lambda v: jax.lax.psum(v, 'i'),\n"
            "             axis_name='i')(x)\n"
            "print(f'rank{jax.process_index()} psum={float(y[0]):.1f}')\n"
            # The multislice leg: treat each process as one slice over
            # DCN and psum over the hybrid mesh built by mesh_from_env.
            "from skypilot_tpu.skylet import constants as C\n"
            "os.environ[C.ENV_MEGASCALE_NUM_SLICES] = '2'\n"
            "from skypilot_tpu.parallel import MeshSpec\n"
            "import numpy as np\n"
            "mesh = mesh_lib.mesh_from_env(MeshSpec(data=-1, fsdp=1))\n"
            "from jax.sharding import PartitionSpec as P\n"
            "g = jax.shard_map(lambda a: jax.lax.psum(a, 'data'),\n"
            "                  mesh=mesh, in_specs=P('data'),\n"
            "                  out_specs=P())\n"
            "nd = len(jax.devices())\n"
            "gx = jax.make_array_from_process_local_data(\n"
            "    jax.NamedSharding(mesh, P('data')),\n"
            "    np.ones((n,), np.float32), (nd,))\n"
            "print(f'rank{jax.process_index()} "
            "meshsum={float(g(gx)[0]):.1f} axes={mesh.axis_names}')\n")
        import shlex
        run = f'python3 -c {shlex.quote(program)}'
        t = _local_task(run=run)
        t.num_nodes = 2
        job_id, handle = launch(t, cluster_name='tdcn')
        out = capfd.readouterr().out
        # Each process contributed (rank+1): psum == 1 + 2 == 3 on
        # every rank (global collective, not per-host).
        assert 'rank0 psum=3.0' in out
        assert 'rank1 psum=3.0' in out
        # Multislice: the hybrid mesh's data axis spans both
        # "slices" (processes); psum of ones over all 16 global
        # devices == 16.
        assert 'rank0 meshsum=16.0' in out
        assert 'rank1 meshsum=16.0' in out

    def test_gang_failure_kills_all(self, local_cloud):
        # Node 1 fails fast; node 0 would run 30s. Gang must kill it.
        run = ('if [ "$SKYTPU_NODE_RANK" = "1" ]; then exit 7; '
               'else sleep 30; fi')
        t = _local_task(run=run)
        t.num_nodes = 2
        start = time.time()
        with pytest.raises(exceptions.JobExitNonZeroError):
            launch(t, cluster_name='t4')
        assert time.time() - start < 25, 'gang kill did not happen'
        rec = state.get_cluster_from_name('t4')
        job = job_lib.get_job(rec['handle'].runtime_dir, 1)
        assert job['status'] == job_lib.JobStatus.FAILED
        assert job['exit_code'] == 7

    def test_setup_then_run(self, local_cloud, capfd):
        t = _local_task(run='cat marker.txt')
        t.setup = 'echo from-setup > marker.txt'
        job_id, handle = launch(t, cluster_name='t5')
        out = capfd.readouterr().out
        assert 'from-setup' in out

    def test_failed_setup_status(self, local_cloud):
        t = _local_task(run='echo never')
        t.setup = 'exit 3'
        with pytest.raises(exceptions.JobExitNonZeroError):
            launch(t, cluster_name='t6')
        rec = state.get_cluster_from_name('t6')
        job = job_lib.get_job(rec['handle'].runtime_dir, 1)
        assert job['status'] == job_lib.JobStatus.FAILED_SETUP

    def test_exec_on_existing_and_queue(self, local_cloud):
        _, handle = launch(_local_task(), cluster_name='t7')
        job_id, _ = exec_cmd(_local_task(run='echo second'),
                             cluster_name='t7', detach_run=True)
        assert job_id == 2
        _wait_job(handle, job_id)
        q = core.queue('t7')
        assert len(q) == 2
        assert {j['job_id'] for j in q} == {1, 2}
        assert all(j['status'] == 'SUCCEEDED' for j in q)

    def test_exec_on_missing_cluster_raises(self, local_cloud):
        with pytest.raises(exceptions.ClusterDoesNotExist):
            exec_cmd(_local_task(), cluster_name='nope')

    def test_cancel_running_job(self, local_cloud):
        _, handle = launch(_local_task(run='sleep 60'),
                           cluster_name='t8', detach_run=True)
        # Wait until RUNNING.
        rt = handle.runtime_dir
        deadline = time.time() + 15
        while time.time() < deadline:
            job = job_lib.get_job(rt, 1)
            if job['status'] == job_lib.JobStatus.RUNNING:
                break
            time.sleep(0.2)
        cancelled = core.cancel('t8', job_ids=[1])
        assert cancelled == [1]
        job = job_lib.get_job(rt, 1)
        assert job['status'] == job_lib.JobStatus.CANCELLED

    def test_workdir_sync(self, local_cloud, tmp_path, capfd):
        wd = tmp_path / 'proj'
        wd.mkdir()
        (wd / 'data.txt').write_text('workdir-content')
        t = _local_task(run='cat data.txt', workdir=str(wd))
        launch(t, cluster_name='t9')
        out = capfd.readouterr().out
        assert 'workdir-content' in out

    def test_down_removes_cluster(self, local_cloud):
        launch(_local_task(), cluster_name='t10')
        core.down('t10')
        assert state.get_cluster_from_name('t10') is None
        with pytest.raises(exceptions.ClusterDoesNotExist):
            core.down('t10')

    def test_relaunch_reuses_cluster(self, local_cloud):
        job1, h1 = launch(_local_task(), cluster_name='t11')
        job2, h2 = launch(_local_task(run='echo again'),
                          cluster_name='t11')
        assert job2 == 2  # same job DB == same cluster
        assert h2.cluster_name_on_cloud == h1.cluster_name_on_cloud

    def test_autostop_set_and_execute(self, local_cloud):
        t = _local_task()
        job_id, handle = launch(t, cluster_name='t12')
        core.autostop('t12', idle_minutes=0)
        # idle_minutes=0 -> should autostop immediately on next check.
        from skypilot_tpu.skylet import autostop_lib
        rt = handle.runtime_dir
        deadline = time.time() + 10
        while time.time() < deadline:
            if autostop_lib.should_autostop(rt):
                break
            time.sleep(0.2)
        assert autostop_lib.should_autostop(rt)
        autostop_lib.execute_autostop(rt)
        # Local cloud stop -> instances report stopped.
        from skypilot_tpu import provision
        statuses = provision.query_instances(
            'local', handle.cluster_name_on_cloud, handle.provider_config)
        assert set(statuses.values()) == {'stopped'}

    def test_status_refresh_reconciles(self, local_cloud):
        _, handle = launch(_local_task(), cluster_name='t13')
        # Kill the cluster behind the state DB's back.
        from skypilot_tpu import provision
        provision.terminate_instances(
            'local', handle.cluster_name_on_cloud, handle.provider_config)
        records = core.status(refresh=True)
        assert all(r['name'] != 't13' for r in records)
        assert state.get_cluster_from_name('t13') is None
