"""Docs build gate: the markdown tree stays consistent with the code.

The docs (docs/) are plain CommonMark; "buildable" here means this
suite passes — every internal link resolves, every documented CLI
command exists (and vice versa), documented config keys are in the
schema, documented env vars appear in the source, and referenced
recipe files exist. Reference analog: the Sphinx build of
docs/source/ (a broken ref fails their build; this is our equivalent
gate).
"""
import os
import re

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_DOCS = os.path.join(_REPO, 'docs')


def _pages():
    out = []
    for root, _, files in os.walk(_DOCS):
        for name in files:
            if name.endswith('.md'):
                out.append(os.path.join(root, name))
    return sorted(out)


def _read(path):
    with open(path, encoding='utf-8') as f:
        return f.read()


def test_tree_is_substantive():
    pages = _pages()
    assert len(pages) >= 20, f'only {len(pages)} pages'
    for page in pages:
        assert len(_read(page).split()) > 80, f'{page} is a stub'


def test_internal_links_resolve():
    link = re.compile(r'\]\(([^)#]+?)(?:#[^)]*)?\)')
    broken = []
    for page in _pages():
        for target in link.findall(_read(page)):
            if target.startswith(('http://', 'https://', 'mailto:')):
                continue
            resolved = os.path.normpath(
                os.path.join(os.path.dirname(page), target))
            if not os.path.exists(resolved):
                broken.append(f'{os.path.relpath(page, _REPO)} -> {target}')
    assert not broken, broken


def _cli_commands():
    from skypilot_tpu.client.cli import cli

    found = set()

    def walk(grp, prefix=''):
        for name, cmd in grp.commands.items():
            full = f'{prefix}{name}'
            if hasattr(cmd, 'commands'):
                walk(cmd, full + ' ')
            else:
                found.add(full)
    walk(cli)
    return found


def test_cli_reference_matches_click_app():
    """reference/cli.md documents exactly the commands that exist."""
    text = _read(os.path.join(_DOCS, 'reference', 'cli.md'))
    documented = set(re.findall(r'^### `tsky ([^`]+)`', text,
                                flags=re.MULTILINE))
    actual = _cli_commands()
    assert documented == actual, (
        f'missing from docs: {sorted(actual - documented)}; '
        f'documented but gone: {sorted(documented - actual)}')


def test_all_tsky_invocations_are_real_commands():
    """Any `tsky foo [bar]` in ANY page must be a real command (or
    group) — docs that teach commands that don't exist are worse than
    no docs."""
    actual = _cli_commands()
    prefixes = {c.split()[0] for c in actual}
    bad = []
    for page in _pages():
        for m in re.finditer(
                r'tsky ((?:[a-z][a-z-]+)(?![\w/-])'
                r'(?: [a-z][a-z-]+(?![\w/-]))?)',
                _read(page)):
            words = m.group(1).split()
            if words[0] not in prefixes:
                bad.append(f'{os.path.basename(page)}: tsky {m.group(1)}')
            elif ' '.join(words) not in actual and \
                    words[0] not in {c.split()[0] for c in actual
                                     if ' ' in c}:
                # Two words where the first is a plain command: the
                # second is an argument (e.g. `tsky status`), fine.
                pass
    assert not bad, bad


def test_config_reference_keys_exist():
    from skypilot_tpu.utils import schemas
    text = _read(os.path.join(_DOCS, 'reference', 'config.md'))
    schema_props = schemas.CONFIG_SCHEMA['properties']
    # Every `section` in the per-cloud table must be a schema key.
    for section in re.findall(r'^\| `([a-z_0-9]+)` \|', text,
                              flags=re.MULTILINE):
        assert section in schema_props, \
            f'config.md documents unknown section {section!r}'
    # Every top-level key that exists should be mentioned somewhere.
    for key in schema_props:
        assert key in text, f'config key {key!r} undocumented'


def test_documented_env_vars_exist_in_source():
    import subprocess
    everything = subprocess.run(
        ['grep', '-rhot', r'SKYTPU_[A-Z_]*',
         os.path.join(_REPO, 'skypilot_tpu')],
        capture_output=True, text=True)
    real = set(re.findall(r'SKYTPU_[A-Z_]+',
                          everything.stdout)) or set()
    # Fallback when grep flags differ: scan files directly.
    if not real:
        for root, _, files in os.walk(os.path.join(_REPO,
                                                   'skypilot_tpu')):
            for name in files:
                if name.endswith('.py'):
                    real.update(re.findall(
                        r'SKYTPU_[A-Z_]+',
                        _read(os.path.join(root, name))))
    bad = []
    for page in _pages():
        for var in set(re.findall(r'SKYTPU_[A-Z_]+', _read(page))):
            if var not in real:
                bad.append(f'{os.path.basename(page)}: {var}')
    assert not bad, bad


def test_referenced_recipes_exist():
    bad = []
    for page in _pages():
        for path in re.findall(r'`((?:llm|examples)/[\w.-]+)`',
                               _read(page)):
            if not os.path.exists(os.path.join(_REPO, path)):
                bad.append(f'{os.path.basename(page)}: {path}')
    assert not bad, bad


def test_index_links_every_page():
    """Every page is reachable from the index (no orphan docs)."""
    index = _read(os.path.join(_DOCS, 'index.md'))
    linked = set(re.findall(r'\]\(([^)#]+?\.md)\)', index))
    linked = {os.path.normpath(os.path.join(_DOCS, t)) for t in linked}
    orphans = [os.path.relpath(p, _DOCS) for p in _pages()
               if p not in linked
               and os.path.basename(p) != 'index.md']
    assert not orphans, f'pages not linked from index.md: {orphans}'
