"""CommandRunner rsync direction semantics (shared convention).

Reference: sky/utils/command_runner.py:168 — up means local `source` →
remote `target`; down means remote `source` → local `target`. All
runners must agree so callers can use the interface polymorphically.
"""
import os

from skypilot_tpu.utils import command_runner


def _capture_argv(monkeypatch, runner_cls):
    calls = []

    def fake_run_subprocess(argv, **kwargs):
        calls.append(argv)
        return (0, '', '') if kwargs.get('require_outputs') else 0

    monkeypatch.setattr(runner_cls, '_run_subprocess',
                        staticmethod(fake_run_subprocess))
    return calls


def test_ssh_rsync_up_direction(monkeypatch, tmp_path):
    calls = _capture_argv(monkeypatch, command_runner.SSHCommandRunner)
    r = command_runner.SSHCommandRunner('h1', user='u')
    r.rsync(str(tmp_path), '/remote/dir', up=True)
    argv = calls[-1]
    assert argv[-2] == str(tmp_path)
    assert argv[-1] == 'u@h1:/remote/dir'


def test_ssh_rsync_down_direction(monkeypatch, tmp_path):
    """down: remote `source` → local `target` — source must NOT be
    ignored (the round-1 bug)."""
    calls = _capture_argv(monkeypatch, command_runner.SSHCommandRunner)
    r = command_runner.SSHCommandRunner('h1', user='u')
    local_target = str(tmp_path / 'out')
    r.rsync('/remote/logs/', local_target, up=False)
    argv = calls[-1]
    assert argv[-2] == 'u@h1:/remote/logs/'
    assert argv[-1] == local_target


def test_kubernetes_rsync_down_direction(monkeypatch, tmp_path):
    calls = _capture_argv(monkeypatch,
                          command_runner.KubernetesCommandRunner)
    r = command_runner.KubernetesCommandRunner('pod1', namespace='ns')
    local_target = str(tmp_path / 'job.log')
    r.rsync('/pod/job.log', local_target, up=False)
    argv = calls[-1]
    assert 'ns/pod1:/pod/job.log' in argv
    assert local_target in argv
    # remote source comes before local target (kubectl cp SRC DST)
    assert argv.index('ns/pod1:/pod/job.log') < argv.index(local_target)


def test_local_rsync_roundtrip(tmp_path):
    src = tmp_path / 'src'
    src.mkdir()
    (src / 'a.txt').write_text('hello')
    dst = tmp_path / 'dst'
    r = command_runner.LocalProcessRunner()
    r.rsync(str(src) + '/', str(dst) + '/', up=True)
    assert (dst / 'a.txt').read_text() == 'hello'
