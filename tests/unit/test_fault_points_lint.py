"""Fault-point namespace lint: name drift in the chaos-injection
catalog fails tier-1, not debugging sessions.

Since the static-analysis PR the naming/documentation rules are a thin
wrapper over the migrated `fault-points` checker (skypilot_tpu/
analysis/checkers/fault_points.py) — same contract, same tier-1 test
names, one implementation shared with `python -m
skypilot_tpu.analysis`. The behavioral tests (declare() validation,
injection observability) stay here: they exercise the runtime, not the
catalog contract.
"""
import os

from skypilot_tpu.analysis.checkers import fault_points
from skypilot_tpu.resilience import faults

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _assert_clean(rule: str) -> None:
    findings = fault_points.findings_for_rule(rule, _REPO)
    assert not findings, '\n'.join(f.message for f in findings)


def test_catalog_registered():
    _assert_clean('catalog-present')


def test_every_point_matches_naming_regex():
    _assert_clean('point-name')


def test_every_point_has_description():
    _assert_clean('point-description')


def test_points_documented_in_resilience_guide():
    """Every registered point appears in docs/guides/resilience.md —
    injection points stay discoverable as they spread."""
    _assert_clean('point-documented')


def test_documented_points_exist():
    """No doc rot in the other direction either: every `a.b` code
    literal in the guide's fault-point table is a real point."""
    _assert_clean('doc-ghost')


def test_declare_rejects_bad_names():
    import pytest
    with pytest.raises(ValueError):
        faults.declare('NoDots', 'a description long enough')
    with pytest.raises(ValueError):
        faults.declare('probe.http', 'duplicate of an existing point')


def test_armed_injection_is_observable():
    """An armed point increments skytpu_faults_injected_total — chaos
    runs are visible in the same scrape as everything else."""
    from skypilot_tpu.observability import instruments as obs
    faults.reset()
    try:
        faults.arm('provision.launch', times=1)
        before = obs.FAULTS_INJECTED.value(point='provision.launch')
        try:
            faults.inject('provision.launch')
        except faults.FaultInjected:
            pass
        assert obs.FAULTS_INJECTED.value(
            point='provision.launch') == before + 1
    finally:
        faults.reset()
