"""Fault-point namespace lint (style of test_metrics_lint.py): name
drift in the chaos-injection catalog fails tier-1, not debugging
sessions.

Importing the faults module registers the whole catalog; this pass
asserts the naming/uniqueness/documentation contract over ALL of
them — a typo'd point name would otherwise silently never fire.
"""
import os
import re

from skypilot_tpu.resilience import faults

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_GUIDE = os.path.join(_REPO, 'docs', 'guides', 'resilience.md')


def _points():
    points = faults.registered_points()
    assert len(points) >= 5, 'fault-point catalog went missing'
    return points


def test_every_point_matches_naming_regex():
    for name in _points():
        assert faults.POINT_RE.fullmatch(name), (
            f'{name}: fault points are dotted plane.operation names')


def test_every_point_has_description():
    for name, desc in _points().items():
        assert desc and len(desc.strip()) >= 10, name


def test_points_documented_in_resilience_guide():
    """Every registered point appears in docs/guides/resilience.md —
    injection points stay discoverable as they spread."""
    with open(_GUIDE, encoding='utf-8') as f:
        text = f.read()
    missing = [p for p in _points() if f'`{p}`' not in text]
    assert not missing, (
        f'fault points undocumented in guides/resilience.md: {missing}')


def test_documented_points_exist():
    """No doc rot in the other direction either: every `a.b` code
    literal in the guide's fault-point table is a real point."""
    with open(_GUIDE, encoding='utf-8') as f:
        text = f.read()
    table = re.findall(r'^\| `([a-z][a-z0-9_.]*)` \|', text,
                       flags=re.MULTILINE)
    assert table, 'guide lost its fault-point table'
    registered = set(_points())
    ghosts = [p for p in table if '.' in p and p not in registered]
    assert not ghosts, f'guide documents unknown fault points: {ghosts}'


def test_declare_rejects_bad_names():
    import pytest
    with pytest.raises(ValueError):
        faults.declare('NoDots', 'a description long enough')
    with pytest.raises(ValueError):
        faults.declare('probe.http', 'duplicate of an existing point')


def test_armed_injection_is_observable():
    """An armed point increments skytpu_faults_injected_total — chaos
    runs are visible in the same scrape as everything else."""
    from skypilot_tpu.observability import instruments as obs
    faults.reset()
    try:
        faults.arm('provision.launch', times=1)
        before = obs.FAULTS_INJECTED.value(point='provision.launch')
        try:
            faults.inject('provision.launch')
        except faults.FaultInjected:
            pass
        assert obs.FAULTS_INJECTED.value(
            point='provision.launch') == before + 1
    finally:
        faults.reset()
