"""Cluster liveness heartbeats: skylet event -> API server -> status.

Reference analog: sky/skylet/events.py:94 (UsageHeartbeatReportEvent) —
the reference ships heartbeats to its usage endpoint; ours land in the
API server's state DB so `tsky status` and the dashboard can tell a
live cluster record from a stale one.
"""
import json
import time
import urllib.error
import urllib.request

import pytest

from skypilot_tpu import state
from skypilot_tpu.server import app as app_mod
from skypilot_tpu.server import requests_db
from skypilot_tpu.skylet import constants as skylet_constants
from skypilot_tpu.skylet import events
from skypilot_tpu.utils import log_utils


@pytest.fixture
def server():
    requests_db.reset_for_tests()
    with app_mod.ServerThread() as srv:
        yield srv
    requests_db.reset_for_tests()


def _register_cluster(name='hb-test'):
    state.add_or_update_cluster(name, handle=None,
                                requested_resources_str='local',
                                num_nodes=1, ready=True)
    return name


def _post_heartbeat(url, payload):
    req = urllib.request.Request(
        f'{url}/api/v1/heartbeat', data=json.dumps(payload).encode(),
        headers={'Content-Type': 'application/json'}, method='POST')
    with urllib.request.urlopen(req, timeout=10) as resp:
        return resp.status


class TestHeartbeatEndpoint:

    def test_known_cluster_recorded(self, server):
        name = _register_cluster()
        status = _post_heartbeat(server.url, {
            'cluster_name': name, 'epoch': 'e1',
            'jobs': {'RUNNING': 2}, 'skylet_pid': 1234,
            'time': time.time()})
        assert status == 200
        beats = state.get_heartbeats()
        assert name in beats
        assert beats[name]['age_s'] < 60
        assert beats[name]['epoch'] == 'e1'
        assert beats[name]['payload']['jobs'] == {'RUNNING': 2}

    def test_stale_incarnation_refused(self, server):
        """A leaked skylet from a torn-down incarnation (old epoch)
        must not keep the re-provisioned record looking live."""
        name = 'hb-epoch'
        state.add_or_update_cluster(name, handle=None,
                                    requested_resources_str='local',
                                    num_nodes=1, ready=True,
                                    epoch='current-epoch')
        with pytest.raises(urllib.error.HTTPError) as err:
            _post_heartbeat(server.url, {
                'cluster_name': name, 'epoch': 'old-epoch'})
        assert err.value.code == 404
        assert name not in state.get_heartbeats()
        assert _post_heartbeat(server.url, {
            'cluster_name': name, 'epoch': 'current-epoch'}) == 200
        assert name in state.get_heartbeats()

    def test_unknown_cluster_refused(self, server):
        with pytest.raises(urllib.error.HTTPError) as err:
            _post_heartbeat(server.url, {'cluster_name': 'nope'})
        assert err.value.code == 404
        assert state.get_heartbeats() == {}

    def test_no_auth_required(self, server):
        """Skylets hold no user tokens: the endpoint must stay open
        even when the server has users configured (auth._OPEN_PATHS)."""
        from skypilot_tpu.server import auth
        assert '/api/v1/heartbeat' in auth._OPEN_PATHS  # noqa: SLF001

    def test_oversized_payload_refused(self, server):
        name = _register_cluster()
        with pytest.raises(urllib.error.HTTPError) as err:
            _post_heartbeat(server.url, {
                'cluster_name': name, 'junk': 'x' * 32768})
        assert err.value.code == 413

    def test_non_object_refused(self, server):
        req = urllib.request.Request(
            f'{server.url}/api/v1/heartbeat', data=b'[1,2]',
            headers={'Content-Type': 'application/json'}, method='POST')
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(req, timeout=10)
        assert err.value.code == 400


class TestSkyletHeartbeatEvent:

    def _write_topology(self, tmp_path, name, url):
        rt = tmp_path / 'rt'
        rt.mkdir(exist_ok=True)
        topology = {'cluster_name': name, 'epoch': 'ep-1', 'nodes': [],
                    'heartbeat': {'url': url}}
        with open(skylet_constants.topology_path(str(rt)), 'w',
                  encoding='utf-8') as f:
            json.dump(topology, f)
        return str(rt)

    def test_event_posts_to_server(self, server, tmp_path):
        name = _register_cluster('hb-skylet')
        rt = self._write_topology(tmp_path, name, server.url)
        events.HeartbeatEvent(rt)._run()  # noqa: SLF001
        beats = state.get_heartbeats()
        assert name in beats
        assert beats[name]['epoch'] == 'ep-1'

    def test_event_without_url_is_noop(self, tmp_path):
        rt = tmp_path / 'rt'
        rt.mkdir()
        topology = {'cluster_name': 'c', 'epoch': 'e', 'nodes': []}
        with open(skylet_constants.topology_path(str(rt)), 'w',
                  encoding='utf-8') as f:
            json.dump(topology, f)
        events.HeartbeatEvent(str(rt))._run()  # noqa: SLF001

    def test_event_survives_dead_server(self, tmp_path):
        name = 'hb-dead'
        rt = self._write_topology(tmp_path, name,
                                  'http://127.0.0.1:1/')
        events.HeartbeatEvent(str(rt))._run()  # noqa: SLF001 — no raise


class TestStatusSurfacing:

    def test_core_status_attaches_age(self, server):
        name = _register_cluster('hb-status')
        _post_heartbeat(server.url, {'cluster_name': name})
        from skypilot_tpu import core
        rec = [r for r in core.status() if r['name'] == name][0]
        assert rec['heartbeat_age_s'] is not None
        assert rec['heartbeat_age_s'] < 60
        other = _register_cluster('hb-silent')
        rec = [r for r in core.status() if r['name'] == other][0]
        assert rec['heartbeat_age_s'] is None

    def test_heartbeat_str_rendering(self):
        assert log_utils.heartbeat_str(None) == '-'
        assert log_utils.heartbeat_str(5, 'UP') == '5s ago'
        assert log_utils.heartbeat_str(120, 'UP') == '2m ago'
        assert 'stale' in log_utils.heartbeat_str(600, 'UP')
        # A stopped cluster's silence is expected, not stale.
        assert 'stale' not in log_utils.heartbeat_str(600, 'STOPPED')

    def test_dashboard_summary_includes_heartbeat(self, server):
        name = _register_cluster('hb-dash')
        _post_heartbeat(server.url, {'cluster_name': name})
        from skypilot_tpu.server import dashboard
        row = [c for c in dashboard.summary()['clusters']
               if c['name'] == name][0]
        assert row['heartbeat'].endswith('ago')

    def test_removal_clears_heartbeat(self, server):
        name = _register_cluster('hb-gone')
        _post_heartbeat(server.url, {'cluster_name': name})
        state.remove_cluster(name, terminate=True)
        assert name not in state.get_heartbeats()

    def test_stop_clears_heartbeat(self, server):
        """Both stop paths (teardown + refresh reconciliation) must
        drop the beat: a STOPPED cluster's age must not grow forever."""
        name = _register_cluster('hb-stop')
        _post_heartbeat(server.url, {'cluster_name': name})
        state.remove_cluster(name, terminate=False)
        assert name not in state.get_heartbeats()
        # A skylet outliving the stop by a couple of minutes must not
        # resurrect the beat the stop just dropped.
        with pytest.raises(urllib.error.HTTPError):
            _post_heartbeat(server.url, {'cluster_name': name})
        assert name not in state.get_heartbeats()

    def test_pre_epoch_record_accepts_without_adopting(self, server):
        """Migrated (epoch-less) records accept beats but must NOT
        adopt the first reported epoch — trust-on-first-use would let
        a forger define the epoch and lock out the real skylet."""
        name = _register_cluster('hb-tofu')  # no epoch on the record
        assert _post_heartbeat(server.url, {
            'cluster_name': name, 'epoch': 'forged'}) == 200
        # A different epoch (the real skylet's) still gets through.
        assert _post_heartbeat(server.url, {
            'cluster_name': name, 'epoch': 'genuine'}) == 200
        # The next provision records a genuine epoch; from then on
        # mismatches are refused.
        state.add_or_update_cluster(name, handle=None,
                                    requested_resources_str='local',
                                    num_nodes=1, ready=True,
                                    epoch='genuine')
        with pytest.raises(urllib.error.HTTPError):
            _post_heartbeat(server.url, {
                'cluster_name': name, 'epoch': 'forged'})


class TestTopologyPlumbing:

    def test_build_topology_embeds_url(self, monkeypatch):
        from skypilot_tpu.provision import common as provision_common
        from skypilot_tpu.provision import provisioner
        monkeypatch.setenv('SKYTPU_API_SERVER_URL',
                           'http://127.0.0.1:9999')
        info = provision_common.ClusterInfo(
            instances={}, head_instance_id=None, provider_name='local',
            provider_config={'runtime_dir': '/tmp/x'})
        topo = provisioner.build_topology('c1', info)
        assert topo['heartbeat'] == {'url': 'http://127.0.0.1:9999'}
        monkeypatch.delenv('SKYTPU_API_SERVER_URL')
        topo = provisioner.build_topology('c1', info)
        assert 'heartbeat' not in topo
