"""The continuous SLO watchdog (observability/watchdog.py).

Covers the alerting discipline ISSUE 20 specifies: breach/clear
hysteresis (a boundary-hugging signal must never flap an alert),
fire -> clear lifecycle with the transition counter and evidence
dumps, the EWMA robust-z anomaly detector catching a step change
after warmup, the SKYTPU_WATCHDOG_RULES grammar round trip, and the
ReplicaUp federation rule clearing when membership is pruned.
"""
import glob
import json
import math
import os

import pytest

from skypilot_tpu.observability import instruments as obs
from skypilot_tpu.observability import metrics as metrics_lib
from skypilot_tpu.observability import timeseries as ts_lib
from skypilot_tpu.observability import watchdog as wd_lib


def _store():
    return ts_lib.TimeSeriesStore(registry=metrics_lib.Registry())


class _Clock:

    def __init__(self, t=0.0):
        self.t = t

    def now(self):
        return self.t

    def advance(self, dt=1.0):
        self.t += dt
        return self.t


def _gauge_watchdog(store, clock, *, lo=0.0, hi=10.0,
                    breach_ticks=2, clear_ticks=3, **kw):
    rule = wd_lib.GaugeWithin('depth', 'skytpu_wd_depth',
                              lo=lo, hi=hi, window=30.0)
    return wd_lib.Watchdog(rules=[rule], store=store,
                           now_fn=clock.now,
                           breach_ticks=breach_ticks,
                           clear_ticks=clear_ticks,
                           window=30.0, **kw), rule


class TestHysteresis:

    def test_fire_needs_consecutive_breaches(self):
        store, clock = _store(), _Clock()
        wd, _ = _gauge_watchdog(store, clock, dump_evidence=False)
        # One breach tick: no alert yet.
        store.add_sample('skytpu_wd_depth', {}, 50.0,
                         now=clock.advance())
        assert wd.tick() == []
        assert wd.snapshot()['rules'][0]['breach_streak'] == 1
        # Second consecutive breach: FIRE.
        store.add_sample('skytpu_wd_depth', {}, 50.0,
                         now=clock.advance())
        (event,) = wd.tick()
        assert event['state'] == 'fire'
        assert event['rule'] == 'depth'
        assert wd.snapshot()['rules'][0]['firing'] is True

    def test_boundary_hugging_signal_never_flaps(self):
        """Alternating ok/breach samples with breach_ticks=2 must
        never fire — and an alternating signal against clear_ticks=3
        must never clear a firing alert either. No alert storms from
        a signal that hugs its threshold."""
        store, clock = _store(), _Clock()
        wd, _ = _gauge_watchdog(store, clock, dump_evidence=False)
        for i in range(40):
            value = 50.0 if i % 2 else 5.0
            store.add_sample('skytpu_wd_depth', {}, value,
                             now=clock.advance())
            assert wd.tick() == []
        snap = wd.snapshot()['rules'][0]
        assert snap['fired'] == 0 and snap['firing'] is False

    def test_clear_needs_consecutive_clean_ticks(self):
        store, clock = _store(), _Clock()
        wd, _ = _gauge_watchdog(store, clock, dump_evidence=False)
        for _ in range(2):
            store.add_sample('skytpu_wd_depth', {}, 50.0,
                             now=clock.advance())
            wd.tick()
        assert wd.snapshot()['rules'][0]['firing'] is True
        # Two clean ticks: still firing (clear_ticks=3)...
        for _ in range(2):
            store.add_sample('skytpu_wd_depth', {}, 5.0,
                             now=clock.advance())
            assert wd.tick() == []
        # ...the third clears.
        store.add_sample('skytpu_wd_depth', {}, 5.0,
                         now=clock.advance())
        (event,) = wd.tick()
        assert event['state'] == 'clear'
        snap = wd.snapshot()['rules'][0]
        assert snap['fired'] == 1 and snap['cleared'] == 1

    def test_insufficient_data_holds_state(self):
        """evaluate() -> None (no samples in window) advances NEITHER
        streak: a scrape gap cannot clear a real alert."""
        store, clock = _store(), _Clock()
        wd, _ = _gauge_watchdog(store, clock, dump_evidence=False)
        for _ in range(2):
            store.add_sample('skytpu_wd_depth', {}, 50.0,
                             now=clock.advance())
            wd.tick()
        assert wd.snapshot()['rules'][0]['firing'] is True
        # 100s of silence: the window goes empty; ticks are no-ops.
        for _ in range(10):
            clock.advance(10.0)
            assert wd.tick() == []
        snap = wd.snapshot()['rules'][0]
        assert snap['firing'] is True and snap['clear_streak'] == 0

    def test_transitions_counted_in_registry(self):
        store, clock = _store(), _Clock()
        wd, _ = _gauge_watchdog(store, clock, dump_evidence=False)
        fired = obs.WATCHDOG_ALERTS.labels(rule='depth',
                                           state='fire')
        cleared = obs.WATCHDOG_ALERTS.labels(rule='depth',
                                             state='clear')
        f0, c0 = fired.value(), cleared.value()
        for _ in range(2):
            store.add_sample('skytpu_wd_depth', {}, 50.0,
                             now=clock.advance())
            wd.tick()
        for _ in range(3):
            store.add_sample('skytpu_wd_depth', {}, 5.0,
                             now=clock.advance())
            wd.tick()
        assert fired.value() == f0 + 1
        assert cleared.value() == c0 + 1


class TestEvidenceDump:

    def test_fire_dumps_window_and_trace(self, tmp_path,
                                         monkeypatch):
        monkeypatch.setenv('SKYTPU_TRACE_DUMP_DIR', str(tmp_path))
        store, clock = _store(), _Clock()
        wd, _ = _gauge_watchdog(store, clock)
        for _ in range(2):
            store.add_sample('skytpu_wd_depth', {}, 50.0,
                             now=clock.advance())
            events = wd.tick()
        (event,) = events
        dumps = event['dumps']
        wd_dump = [p for p in dumps if 'WATCHDOG_depth_' in p]
        assert wd_dump, dumps
        doc = json.loads(open(wd_dump[0]).read())
        assert doc['rule'] == 'depth'
        assert doc['value'] == 50.0
        # The offending window rides along: the breached series with
        # its retained samples, directly feedable to `top --file`.
        names = [row['name'] for row in doc['window']['series']]
        assert 'skytpu_wd_depth' in names
        assert glob.glob(os.path.join(str(tmp_path),
                                      'WATCHDOG_depth_*.json'))

    def test_no_dump_dir_means_no_files(self, monkeypatch):
        monkeypatch.delenv('SKYTPU_TRACE_DUMP_DIR', raising=False)
        store, clock = _store(), _Clock()
        wd, _ = _gauge_watchdog(store, clock)
        for _ in range(2):
            store.add_sample('skytpu_wd_depth', {}, 50.0,
                             now=clock.advance())
            events = wd.tick()
        assert events[0].get('dumps') == []


class TestRules:

    def test_hist_quantile_rule(self):
        reg = metrics_lib.Registry()
        store = ts_lib.TimeSeriesStore(registry=reg)
        hist = metrics_lib.Histogram(
            'skytpu_wd_seconds', 'W.', buckets=(0.1, 0.5, 2.0),
            registry=reg)
        rule = wd_lib.HistQuantileBelow('p95', 'skytpu_wd_seconds',
                                        threshold=0.5, window=30.0)
        for _ in range(20):
            hist.observe(0.05)
        store.sample_now(now=0.0)
        for _ in range(20):
            hist.observe(1.5)
        store.sample_now(now=10.0)
        res = rule.evaluate(store, 10.0, 60.0)
        assert res['breached'] and res['value'] == 2.0

    def test_counter_ratio_rule(self):
        store = _store()
        for t in range(3):
            store.add_sample('skytpu_hits_total', {}, 1.0 * t,
                             now=float(t), kind='counter')
            store.add_sample('skytpu_misses_total', {}, 9.0 * t,
                             now=float(t), kind='counter')
        rule = wd_lib.CounterRatioAbove(
            'hit_ratio', 'skytpu_hits_total',
            ('skytpu_hits_total', 'skytpu_misses_total'),
            threshold=0.5, window=30.0)
        res = rule.evaluate(store, 2.0, 60.0)
        assert res['breached'] and res['value'] == pytest.approx(0.1)

    def test_replica_up_fires_and_clears_on_pruning(self):
        """The federation rule: a dead replica's up=0 breaches; the
        rule re-reads membership each tick, so pruning the dead
        replica CLEARS the alert without any new samples."""
        store, clock = _store(), _Clock()
        members = ['http://r1', 'http://r2']
        rule = wd_lib.ReplicaUp('replica_up', lambda: members,
                                window=30.0)
        for url in members:
            store.add_sample('skytpu_replica_up', {'replica': url},
                             1.0, now=clock.advance())
        res = rule.evaluate(store, clock.t, 60.0)
        assert not res['breached']
        store.add_sample('skytpu_replica_up',
                         {'replica': 'http://r2'}, 0.0,
                         now=clock.advance())
        res = rule.evaluate(store, clock.t, 60.0)
        assert res['breached'] and 'http://r2' in res['detail']
        members.remove('http://r2')
        res = rule.evaluate(store, clock.t, 60.0)
        assert not res['breached']

    def test_gauge_on_missing_modes(self):
        store = _store()
        skip = wd_lib.GaugeWithin('g', 'skytpu_absent', hi=1.0,
                                  on_missing='skip')
        breach = wd_lib.GaugeWithin('g', 'skytpu_absent', hi=1.0,
                                    on_missing='breach')
        assert skip.evaluate(store, 0.0, 60.0) is None
        assert breach.evaluate(store, 0.0, 60.0)['breached']


class TestAnomaly:

    def test_step_change_detected_after_warmup(self):
        store, clock = _store(), _Clock()
        rule = wd_lib.AnomalyEWMA('anom', 'skytpu_wd_lat',
                                  z_max=8.0, warmup_ticks=5,
                                  window=30.0)
        # Steady signal with small jitter through warmup + baseline.
        for i in range(12):
            value = 1.0 + 0.01 * (i % 3)
            store.add_sample('skytpu_wd_lat', {}, value,
                             now=clock.advance())
            res = rule.evaluate(store, clock.t, 60.0)
            assert not res['breached'], (i, res)
        # 10x step: robust-z explodes past any sane bound.
        store.add_sample('skytpu_wd_lat', {}, 10.0,
                         now=clock.advance())
        res = rule.evaluate(store, clock.t, 60.0)
        assert res['breached'] and res['value'] > 8.0

    def test_warmup_never_breaches(self):
        store, clock = _store(), _Clock()
        rule = wd_lib.AnomalyEWMA('anom', 'skytpu_wd_lat',
                                  z_max=0.001, warmup_ticks=5,
                                  window=30.0)
        for i in range(5):
            store.add_sample('skytpu_wd_lat', {}, float(i * i),
                             now=clock.advance())
            res = rule.evaluate(store, clock.t, 60.0)
            assert not res['breached']
            assert 'warmup' in res['detail']


class TestRuleGrammar:

    def test_round_trip(self):
        rules = wd_lib.parse_rules(
            'p95(skytpu_decode_step_seconds) < 0.5 @ 120; '
            'ratio(skytpu_hits_total/skytpu_hits_total+'
            'skytpu_misses_total) >= 0.8; '
            'within(skytpu_batch_occupancy, 0, 64) @ 30; '
            'anomaly(skytpu_prefill_seconds)')
        kinds = [type(r).__name__ for r in rules]
        assert kinds == ['HistQuantileBelow', 'CounterRatioAbove',
                         'GaugeWithin', 'AnomalyEWMA']
        p95, ratio, within, anom = rules
        assert p95.q == 0.95 and p95.threshold == 0.5 \
            and p95.window == 120.0
        assert ratio.den_metrics == ('skytpu_hits_total',
                                     'skytpu_misses_total')
        assert within.lo == 0.0 and within.hi == 64.0 \
            and within.window == 30.0
        assert anom.metric == 'skytpu_prefill_seconds'
        assert anom.window is None

    @pytest.mark.parametrize('bad', [
        'p95(m) > 0.5',            # quantile needs an upper bound
        'ratio(a/b) < 0.5',        # ratio needs a lower bound
        'ratio(nodenominator) >= 1',
        'within(m, 1)',            # needs metric, lo, hi
        'anomaly(m) < 3',          # takes no comparator
        'bogus(m) < 1',
        'p95(m) 0.5',              # missing comparator
    ])
    def test_garbage_raises(self, bad):
        with pytest.raises(ValueError):
            wd_lib.parse_rules(bad)

    def test_empty_spec_is_empty(self):
        assert wd_lib.parse_rules('') == []
        assert wd_lib.parse_rules(' ; ; ') == []

    def test_default_rules_from_env(self, monkeypatch):
        monkeypatch.setenv('SKYTPU_WATCHDOG_RULES',
                           'within(skytpu_q, 0, 9)')
        monkeypatch.setenv('SKYTPU_WATCHDOG_ANOMALY_Z', '8')
        rules = wd_lib.default_rules()
        names = [r.name for r in rules]
        assert 'within(skytpu_q,0,9)' in names
        assert 'anomaly(decode_step)' in names
        assert 'anomaly(ttft)' in names
        monkeypatch.setenv('SKYTPU_WATCHDOG_ANOMALY_Z', '0')
        assert len(wd_lib.default_rules()) == 1


class TestEngine:

    def test_pre_tick_runs_and_failure_is_contained(self):
        store, clock = _store(), _Clock()
        calls = []

        def pre(wd):
            calls.append(1)
            raise RuntimeError('scrape down')

        wd, _ = _gauge_watchdog(store, clock, dump_evidence=False,
                                pre_tick=pre)
        store.add_sample('skytpu_wd_depth', {}, 5.0,
                         now=clock.advance())
        wd.tick()  # must not raise
        assert calls == [1]

    def test_evaluate_error_is_contained(self):
        class Broken:
            name = 'broken'

            def evaluate(self, store, now, default_window):
                raise RuntimeError('boom')

        store, clock = _store(), _Clock()
        wd = wd_lib.Watchdog(rules=[Broken()], store=store,
                             now_fn=clock.now, breach_ticks=1,
                             clear_ticks=1, window=30.0)
        assert wd.tick() == []
        assert 'evaluate error' in \
            wd.snapshot()['rules'][0]['detail']

    def test_snapshot_is_json_portable(self):
        store, clock = _store(), _Clock()
        rule = wd_lib.GaugeWithin('inf_g', 'skytpu_wd_depth',
                                  hi=math.inf, window=30.0)
        wd = wd_lib.Watchdog(rules=[rule], store=store,
                             now_fn=clock.now, breach_ticks=1,
                             clear_ticks=1, window=30.0)
        store.add_sample('skytpu_wd_depth', {}, 5.0,
                         now=clock.advance())
        wd.tick()
        json.dumps(wd.snapshot())

    def test_background_thread_disabled_at_zero(self, monkeypatch):
        monkeypatch.setenv('SKYTPU_WATCHDOG_TICK_SECONDS', '0')
        wd = wd_lib.Watchdog(rules=[], store=_store())
        assert wd.start() is False
