"""Disaggregated prefill->decode serving (ISSUE 19): planned KV
handoff with a zero-token-loss degradation ladder.

The acceptance oracle is the same greedy token-for-token identity as
crash migration (PR 17), now for the PLANNED route: a handoff-flagged
request pauses at the prefill->decode boundary (first token emitted,
slot live under a lease), its snapshot restores into a decode engine,
and the combined stream must equal an uninterrupted run — for the
dense cache, the paged pool with the prefix cache on, and the
int8-quantized pool. Every rung of the degradation ladder ends in the
same stream: decode-pool restore, forced co-located resume (armed
`lb.handoff` fault), and lease expiry (which also compiles nothing
new). Around the oracle: the pool invariant (free + cached + private
== total) holds on both replicas after handoff, fallback, and abort;
an abort racing a handoff never double-frees; restore candidates walk
the decode pool (breaker-allowed) before the general fleet; and
handoff eligibility refuses string-estimated prompts and non-streamed
requests outright.
"""
import asyncio
import time

import jax
import pytest

from skypilot_tpu import inference
from skypilot_tpu.inference import engine as eng_lib
from skypilot_tpu.models import llama
from skypilot_tpu.observability import instruments as obs
from skypilot_tpu.resilience import faults
from skypilot_tpu.serve import load_balancer as lb_lib


@pytest.fixture(scope='module')
def tiny():
    config = llama.CONFIGS['tiny']
    params = llama.init_params(config, jax.random.key(7))
    return config, params


@pytest.fixture(autouse=True)
def _disarm():
    yield
    faults.reset()


def _greedy(max_new):
    return inference.SamplingParams(temperature=0.0,
                                    max_new_tokens=max_new)


def _engine(params, config, **kw):
    kw.setdefault('batch_size', 2)
    kw.setdefault('max_seq_len', 64)
    kw.setdefault('prefill_chunk', 16)
    kw.setdefault('kv_quant', 'none')
    kw.setdefault('decode_fuse_steps', 2)
    return inference.InferenceEngine(params, config, **kw)


_PROMPT = [3, 17, 42, 9, 105, 8]
_STEPS = 16


def _ref(params, config, prompt=None, steps=_STEPS, **kw):
    eng = _engine(params, config, **kw)
    rid = eng.submit(list(prompt or _PROMPT), _greedy(steps))
    return eng.run_to_completion()[rid]


def _drive_to_pause(eng, rid, max_steps=200):
    """Step until the request parks at the prefill->decode boundary;
    returns the tokens generated so far (>= 1: the pause only exists
    once the first token does)."""
    for _ in range(max_steps):
        eng.step()
        for s in eng.state.slots:
            if s is not None and s.request_id == rid \
                    and s.handoff_pause:
                assert s.generated, \
                    'paused before the first generated token'
                return list(s.generated)
        assert rid not in eng.finished(), \
            'request finished before pausing at the boundary'
    raise AssertionError('request never paused at the boundary')


class TestHandoffIdentity:
    """The planned two-leg route is invisible in the token stream."""

    def _handoff(self, params, config, **kw):
        ref = _ref(params, config, **kw)
        src = _engine(params, config, **kw)
        dst = _engine(params, config, **kw)
        rid = src.submit(list(_PROMPT), _greedy(_STEPS),
                         handoff=True)
        mid = _drive_to_pause(src, rid)
        assert rid in src.handoff_pending()
        blob = src.snapshot_request(rid)
        # The structural guard: a pause only exists after the first
        # token, so an exported handoff blob ALWAYS carries real KV.
        header, _ = eng_lib._snapshot_unpack(blob)
        assert header['layout'] != 'none'
        src.abort(rid)
        rid2 = dst.restore_request(blob)
        final = dst.run_to_completion()[rid2]
        assert final[:len(mid)] == mid, \
            'restored run rewrote already-streamed tokens'
        assert final == ref
        return src, dst

    def test_paged_prefix_off(self, tiny):
        config, params = tiny
        self._handoff(params, config, prefix_cache=False)

    def test_paged_prefix_on(self, tiny):
        config, params = tiny
        self._handoff(params, config, prefix_cache=True)

    def test_int8_quantized_pool(self, tiny):
        config, params = tiny
        self._handoff(params, config, kv_quant='int8')

    def test_dense(self, tiny):
        config, params = tiny
        self._handoff(params, config, kv_page_size=0)


class TestLeaseSemantics:
    """The lease holds the slot still, resumes it on expiry, and the
    resume is a host-side state transition — zero recompiles."""

    def test_paused_slot_does_not_decode(self, tiny, monkeypatch):
        monkeypatch.setenv('SKYTPU_HANDOFF_LEASE_SECONDS', '30')
        config, params = tiny
        eng = _engine(params, config, prefix_cache=False)
        rid = eng.submit(list(_PROMPT), _greedy(_STEPS),
                         handoff=True)
        mid = _drive_to_pause(eng, rid)
        for _ in range(4):
            eng.step()
        assert eng.active_progress()[rid] == mid, \
            'a lease-paused slot kept decoding'
        assert not eng.has_runnable_work
        # Explicit resume (the co-located fallback rung) is a state
        # transition: the slot rejoins the batch and finishes with
        # the uninterrupted stream.
        assert eng.resume_handoff(rid)
        assert not eng.resume_handoff(rid)  # second call: no-op
        final = eng.run_to_completion()[rid]
        assert final == _ref(params, config, prefix_cache=False)

    def test_lease_expiry_resumes_local_zero_recompiles(
            self, tiny, monkeypatch):
        monkeypatch.setenv('SKYTPU_HANDOFF_LEASE_SECONDS', '0.15')
        config, params = tiny
        eng = _engine(params, config, prefix_cache=False)
        # Warm the engine end to end so the fused-decode cache is
        # settled before the handoff run.
        ref = _ref(params, config, prefix_cache=False)
        warm_rid = eng.submit(list(_PROMPT), _greedy(_STEPS))
        assert eng.run_to_completion()[warm_rid] == ref
        warm_fused = eng_lib.fused_decode_steps._cache_size()
        fb0 = obs.HANDOFF_FALLBACKS.value()
        rid = eng.submit(list(_PROMPT), _greedy(_STEPS),
                         handoff=True)
        mid = _drive_to_pause(eng, rid)
        assert len(mid) < _STEPS
        time.sleep(0.2)  # let the lease lapse
        final = eng.run_to_completion()[rid]
        assert final == ref
        assert obs.HANDOFF_FALLBACKS.value() == fb0 + 1
        assert eng_lib.fused_decode_steps._cache_size() == warm_fused


class TestPoolInvariants:
    """free + cached + private == total on both replicas, whatever
    rung the request took — and aborts racing a handoff never
    double-free."""

    @staticmethod
    def _accounted(eng):
        free = len(eng._page_alloc)
        cached = eng._prefix.num_pages() if eng._prefix else 0
        private = sum(
            len(set(pages) - eng._slot_shared[i])
            for i, pages in enumerate(eng._slot_pages))
        return free + cached + private

    def test_invariant_after_handoff(self, tiny):
        config, params = tiny
        src = _engine(params, config, prefix_cache=True)
        dst = _engine(params, config, prefix_cache=True)
        rid = src.submit(list(_PROMPT), _greedy(_STEPS),
                         handoff=True)
        _drive_to_pause(src, rid)
        blob = src.snapshot_request(rid)
        src.abort(rid)
        assert self._accounted(src) == src._pages_total
        rid2 = dst.restore_request(blob)
        assert self._accounted(dst) == dst._pages_total
        assert rid2 in dst.run_to_completion()
        assert self._accounted(dst) == dst._pages_total

    def test_invariant_after_fallback(self, tiny, monkeypatch):
        monkeypatch.setenv('SKYTPU_HANDOFF_LEASE_SECONDS', '30')
        config, params = tiny
        eng = _engine(params, config, prefix_cache=True)
        rid = eng.submit(list(_PROMPT), _greedy(_STEPS),
                         handoff=True)
        _drive_to_pause(eng, rid)
        assert eng.resume_handoff(rid)
        assert rid in eng.run_to_completion()
        assert self._accounted(eng) == eng._pages_total

    def test_abort_racing_handoff_never_double_frees(
            self, tiny, monkeypatch):
        monkeypatch.setenv('SKYTPU_HANDOFF_LEASE_SECONDS', '30')
        config, params = tiny
        eng = _engine(params, config, prefix_cache=True)
        rid = eng.submit(list(_PROMPT), _greedy(_STEPS),
                         handoff=True)
        _drive_to_pause(eng, rid)
        eng.abort(rid)
        assert self._accounted(eng) == eng._pages_total
        # The abort swept every handoff structure: no stale lease, no
        # export marker, and a late resume is a clean no-op.
        assert not eng._handoff_deadline
        assert rid not in eng.handoff_pending()
        assert not eng.resume_handoff(rid)
        eng.abort(rid)  # double abort: still a no-op
        assert self._accounted(eng) == eng._pages_total
        # The pool is intact: a fresh request runs to completion.
        rid2 = eng.submit(list(_PROMPT), _greedy(_STEPS))
        assert rid2 in eng.run_to_completion()
        assert self._accounted(eng) == eng._pages_total


class TestRestoreCandidateOrder:
    """Restore legs exhaust the decode pool's breaker-allowed
    replicas before any general-pool replica sees the blob."""

    def test_decode_pool_first_breaker_skipped(self):
        lb = lb_lib.LoadBalancer(policy_name='round_robin',
                                 honor_env_policy=False)
        d1, d2 = 'http://d1', 'http://d2'
        g1, g2 = 'http://g1', 'http://g2'
        lb.set_replicas([g1, d1, g2, d2],
                        pools={d1: 'decode', d2: 'decode',
                               g1: 'general', g2: 'general'})
        order = lb._restore_candidates()
        assert order[:2] == [d1, d2], \
            'decode pool must lead the restore order'
        assert set(order) == {d1, d2, g1, g2}
        # The request's own shape must not reorder the restore walk:
        # a long-prompt context classified 'prefill' still restores
        # decode-pool-first (the remainder is decode-only work).
        ctx = {'prompt_tokens': list(range(4096)),
               'max_new_tokens': 4, 'stream': True}
        assert lb._restore_candidates(ctx) == order
        # Open d1's breaker: the ladder's walk skips it and tries the
        # SECOND decode replica before any general-pool replica.
        for _ in range(3):
            lb.breaker.record_failure(d1)
        assert not lb.breaker.allow(d1)
        walk = [c for c in lb._restore_candidates()
                if lb.breaker.allow(c)]
        assert walk[0] == d2
        assert walk.index(d2) < walk.index(g1)
        assert walk.index(d2) < walk.index(g2)


class TestHandoffEligibility:
    """Only streamed requests whose prompt arrived TOKENIZED may take
    the two-leg route; the chars/4 string estimate never gates it."""

    def test_string_prompt_never_eligible(self, monkeypatch):
        monkeypatch.setenv('SKYTPU_LB_POOL_PROMPT_THRESHOLD', '8')
        ctx = {'prompt': 'x' * 4096, 'max_new_tokens': 4,
               'stream': True}
        # The shape classifier still calls it prefill (estimated)...
        assert lb_lib.classify_pool_role(ctx) == 'prefill'
        # ...but an ESTIMATED count must never flag a handoff.
        assert not lb_lib.handoff_eligible(ctx)

    def test_non_streamed_not_eligible(self, monkeypatch):
        monkeypatch.setenv('SKYTPU_LB_POOL_PROMPT_THRESHOLD', '8')
        ctx = {'prompt_tokens': list(range(32)), 'max_new_tokens': 4}
        assert not lb_lib.handoff_eligible(ctx)

    def test_tokenized_streamed_eligible(self, monkeypatch):
        monkeypatch.setenv('SKYTPU_LB_POOL_PROMPT_THRESHOLD', '8')
        ctx = {'prompt_tokens': list(range(32)), 'max_new_tokens': 4,
               'stream': True}
        assert lb_lib.handoff_eligible(ctx)

    def test_decode_shaped_not_eligible(self, monkeypatch):
        monkeypatch.setenv('SKYTPU_LB_POOL_PROMPT_THRESHOLD', '8')
        ctx = {'prompt_tokens': list(range(32)),
               'max_new_tokens': 512, 'stream': True}
        assert not lb_lib.handoff_eligible(ctx)

    def test_request_context_maps_tokenized_openai_prompt(
            self, monkeypatch):
        """An OpenAI-style body carrying the tokenized prompt under
        `prompt` classifies by its REAL token count, not the chars/4
        estimate of its string repr."""
        import json as json_lib
        monkeypatch.setenv('SKYTPU_LB_POOL_PROMPT_THRESHOLD', '8')
        body = json_lib.dumps({'prompt': [5] * 32,
                               'max_new_tokens': 4,
                               'stream': True}).encode()
        ctx = lb_lib.request_context(body, 'application/json',
                                     len(body))
        assert ctx['prompt_tokens'] == [5] * 32
        assert ctx['stream'] is True
        assert lb_lib.classify_pool_role(ctx) == 'prefill'
        assert lb_lib.handoff_eligible(ctx)

    def test_request_context_omits_stream_when_unset(self):
        import json as json_lib
        body = json_lib.dumps({'prompt_tokens': [1, 2, 3],
                               'max_new_tokens': 4}).encode()
        ctx = lb_lib.request_context(body, 'application/json',
                                     len(body))
        assert ctx == {'prompt_tokens': [1, 2, 3],
                       'max_new_tokens': 4}


_LB_PROMPT = list(range(7, 19))
_LB_STEPS = 24


async def _client_stream(session, url, prompt, max_new):
    """POST a streamed generate through the LB; returns (tokens,
    done_tokens). Fails the test if any internal frame (handoff,
    migrate, error) leaks through."""
    import json as json_lib
    async with session.post(url, json={
            'prompt_tokens': prompt, 'max_new_tokens': max_new,
            'temperature': 0.0, 'stream': True}) as resp:
        assert resp.status == 200, await resp.text()
        got, done_tokens = [], None
        buf = b''
        async for chunk in resp.content.iter_any():
            buf += chunk
            while b'\n\n' in buf:
                frame, buf = buf.split(b'\n\n', 1)
                doc = None
                for line in frame.split(b'\n'):
                    if line.startswith(b'data: '):
                        doc = json_lib.loads(line[6:])
                if doc is None:
                    continue
                assert 'handoff' not in doc, \
                    'handoff frame leaked to the client'
                assert 'migrate' not in doc, \
                    'migrate frame leaked to the client'
                assert 'error' not in doc, doc
                if 'token' in doc:
                    got.append(doc['token'])
                else:
                    done_tokens = doc.get('tokens')
        return got, done_tokens


class TestServePlane:
    """The full two-leg route through real HTTP: prefill replica ->
    LB-intercepted handoff frame -> decode-pool restore (or forced
    co-located fallback) — the client stream is identical either
    way."""

    def _serve(self, tiny, monkeypatch, n_decode=1, general=False):
        """Build engines + ref; returns (engines dict, ref)."""
        monkeypatch.setenv('SKYTPU_LB_POOL_PROMPT_THRESHOLD', '8')
        # Only the explicit abandon (or a fallback resume) may free
        # the prefill slot inside the test window — a short lease
        # would mask a broken release path.
        monkeypatch.setenv('SKYTPU_HANDOFF_LEASE_SECONDS', '30')
        config, params = tiny
        ref = _ref(params, config, prompt=_LB_PROMPT,
                   steps=_LB_STEPS, max_seq_len=128,
                   prefix_cache=False)
        assert len(ref) == _LB_STEPS
        def mk():
            return _engine(params, config, max_seq_len=128,
                           prefix_cache=False)

        engines = {'prefill': mk()}
        for i in range(n_decode):
            engines[f'decode{i}'] = mk()
        if general:
            engines['general'] = mk()
        return engines, ref

    def test_planned_handoff_identity_and_pool_order(
            self, tiny, monkeypatch):
        """Happy path plus satellite 1 end to end: the breaker-open
        decode replica is skipped, the second decode replica takes
        the leg, the general pool never sees the blob — and the
        prefill slot frees via the abandon signal long before its
        30 s lease."""
        from aiohttp import ClientSession
        from aiohttp.test_utils import TestServer
        from skypilot_tpu.inference import server as srv

        engines, ref = self._serve(tiny, monkeypatch, n_decode=1,
                                   general=True)
        holders = {name: {'loop': srv.EngineLoop(eng)}
                   for name, eng in engines.items()}
        lb = lb_lib.LoadBalancer(policy_name='round_robin',
                                 honor_env_policy=False)
        c0 = {n: obs.__dict__[c].value() for n, c in [
            ('att', 'HANDOFF_ATTEMPTS'),
            ('succ', 'HANDOFF_SUCCESSES'),
            ('fb', 'HANDOFF_FALLBACKS'),
            ('mig', 'MIGRATION_ATTEMPTS'),
            ('fail', 'LB_MIDSTREAM_FAILURES')]}

        async def go():
            servers = {n: TestServer(srv.create_app(h))
                       for n, h in holders.items()}
            for s in servers.values():
                await s.start_server()
            urls = {n: f'http://127.0.0.1:{s.port}'
                    for n, s in servers.items()}
            dead_decode = 'http://127.0.0.1:9'  # never listening
            lb.set_replicas(
                [urls['prefill'], dead_decode, urls['decode0'],
                 urls['general']],
                pools={urls['prefill']: 'prefill',
                       dead_decode: 'decode',
                       urls['decode0']: 'decode',
                       urls['general']: 'general'})
            for _ in range(3):  # force its breaker open
                lb.breaker.record_failure(dead_decode)
            assert not lb.breaker.allow(dead_decode)
            lb_port = lb.start()
            try:
                async with ClientSession() as session:
                    got, done = await _client_stream(
                        session,
                        f'http://127.0.0.1:{lb_port}/generate',
                        _LB_PROMPT, _LB_STEPS)
                # The abandon signal frees the prefill slot promptly
                # (the lease alone would hold it 30 s).
                deadline = time.time() + 5
                while engines['prefill'].has_work and \
                        time.time() < deadline:
                    await asyncio.sleep(0.05)
                return got, done
            finally:
                lb.stop()
                for s in servers.values():
                    await s.close()

        try:
            got, done = asyncio.new_event_loop().run_until_complete(
                go())
        finally:
            for h in holders.values():
                h['loop'].stop()
        assert got == ref, (
            f'client stream diverged: {len(got)} vs {len(ref)}')
        assert done == ref
        assert obs.HANDOFF_ATTEMPTS.value() == c0['att'] + 1
        assert obs.HANDOFF_SUCCESSES.value() == c0['succ'] + 1
        assert obs.HANDOFF_FALLBACKS.value() == c0['fb']
        # A planned handoff is not a crash migration and never an
        # honest termination.
        assert obs.MIGRATION_ATTEMPTS.value() == c0['mig']
        assert obs.LB_MIDSTREAM_FAILURES.value() == c0['fail']
        # The decode replica took the leg; the general pool was never
        # offered it.
        assert engines['decode0']._next_id >= 1
        assert engines['general']._next_id == 0
        assert not engines['prefill'].has_work, \
            'prefill slot still held after a confirmed handoff'

    def test_forced_fallback_is_co_located_and_identical(
            self, tiny, monkeypatch):
        """Every rung short of the prefill replica chaos-killed: the
        armed `lb.handoff` fault fails the decode-leg restore, the
        ladder resumes the request co-located, the stream is
        identical, and the degradation is COUNTED — never an
        error."""
        from aiohttp import ClientSession
        from aiohttp.test_utils import TestServer
        from skypilot_tpu.inference import server as srv

        engines, ref = self._serve(tiny, monkeypatch, n_decode=1)
        holders = {name: {'loop': srv.EngineLoop(eng)}
                   for name, eng in engines.items()}
        lb = lb_lib.LoadBalancer(policy_name='round_robin',
                                 honor_env_policy=False)
        faults.arm('lb.handoff', times=1, exc=OSError('chaos'))
        att0 = obs.HANDOFF_ATTEMPTS.value()
        succ0 = obs.HANDOFF_SUCCESSES.value()
        fb0 = obs.HANDOFF_FALLBACKS.value()
        fail0 = obs.LB_MIDSTREAM_FAILURES.value()

        async def go():
            servers = {n: TestServer(srv.create_app(h))
                       for n, h in holders.items()}
            for s in servers.values():
                await s.start_server()
            urls = {n: f'http://127.0.0.1:{s.port}'
                    for n, s in servers.items()}
            lb.set_replicas(
                [urls['prefill'], urls['decode0']],
                pools={urls['prefill']: 'prefill',
                       urls['decode0']: 'decode'})
            lb_port = lb.start()
            try:
                async with ClientSession() as session:
                    return await _client_stream(
                        session,
                        f'http://127.0.0.1:{lb_port}/generate',
                        _LB_PROMPT, _LB_STEPS)
            finally:
                lb.stop()
                for s in servers.values():
                    await s.close()

        try:
            got, done = asyncio.new_event_loop().run_until_complete(
                go())
        finally:
            for h in holders.values():
                h['loop'].stop()
        assert got == ref, (
            f'client stream diverged: {len(got)} vs {len(ref)}')
        assert done == ref
        assert obs.HANDOFF_ATTEMPTS.value() == att0 + 1
        assert obs.HANDOFF_SUCCESSES.value() == succ0
        assert obs.HANDOFF_FALLBACKS.value() == fb0 + 1
        assert obs.LB_MIDSTREAM_FAILURES.value() == fail0
        # The decode engine never saw the request.
        assert engines['decode0']._next_id == 0
