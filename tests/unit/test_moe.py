"""MoE model: routing invariants, forward/train, expert-parallel mesh."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.models import moe
from skypilot_tpu.parallel import mesh as mesh_lib
from skypilot_tpu.parallel import sharding


@pytest.fixture(scope='module')
def tiny():
    config = moe.CONFIGS['tiny-moe']
    params = moe.init_params(config, jax.random.key(3))
    return config, params


def test_routing_invariants(tiny):
    config, params = tiny
    g, e = 64, config.hidden_size
    h = jax.random.normal(jax.random.key(0), (g, e), jnp.float32)
    dispatch, combine, aux = moe._route(
        h, params['layers']['router'][0], config)
    d = np.asarray(dispatch)
    c = np.asarray(combine)
    # Each (expert, capacity) slot holds at most one token.
    assert (d.sum(axis=0) <= 1.0 + 1e-6).all()
    # Each token lands in at most num_experts_per_tok slots.
    per_token = d.sum(axis=(1, 2))
    assert (per_token <= config.num_experts_per_tok + 1e-6).all()
    # Combine weights of routed tokens sum to ~1 (renormalized top-k),
    # unless dropped by capacity.
    routed = per_token >= config.num_experts_per_tok - 1e-6
    sums = c.sum(axis=(1, 2))[routed]
    assert np.allclose(sums, 1.0, atol=1e-5)
    assert float(aux) > 0.0


def test_forward_and_loss(tiny):
    config, params = tiny
    tokens = jax.random.randint(jax.random.key(1), (2, 16), 0,
                                config.vocab_size, jnp.int32)
    logits, aux = moe.forward(params, tokens, config)
    assert logits.shape == (2, 16, config.vocab_size)
    assert jnp.isfinite(logits).all()
    loss = moe.loss_fn(params, {'tokens': tokens}, config)
    assert jnp.isfinite(loss)
    # Loss decreases under a few SGD steps (model actually learns).
    grad_fn = jax.jit(jax.value_and_grad(
        lambda p: moe.loss_fn(p, {'tokens': tokens}, config)))
    l0, grads = grad_fn(params)
    p = jax.tree.map(lambda w, g: w - 0.5 * g.astype(w.dtype), params,
                     grads)
    l1, _ = grad_fn(p)
    assert float(l1) < float(l0)


def test_expert_parallel_matches_single_device(tiny):
    """Sharding experts over the mesh must not change the math."""
    config, params = tiny
    tokens = jax.random.randint(jax.random.key(2), (4, 16), 0,
                                config.vocab_size, jnp.int32)
    logits_ref, _ = jax.jit(
        lambda p, t: moe.forward(p, t, config))(params, tokens)

    mesh = mesh_lib.make_mesh(
        mesh_lib.MeshSpec(data=2, fsdp=1, expert=4, tensor=1))
    logical = moe.param_logical_axes(config)
    param_sh = sharding.tree_shardings(mesh, logical)
    with mesh_lib.use_mesh(mesh):
        sharded_params = jax.jit(lambda p: p,
                                 out_shardings=param_sh)(params)
        logits_sharded, _ = jax.jit(
            lambda p, t: moe.forward(p, t, config, mesh=mesh))(
            sharded_params, tokens)
    np.testing.assert_allclose(np.asarray(logits_ref),
                               np.asarray(logits_sharded),
                               rtol=2e-3, atol=2e-3)


def test_param_counts(tiny):
    config, params = tiny
    actual = sum(x.size for x in jax.tree.leaves(params))
    assert actual == config.num_params()
    assert config.active_params() < config.num_params()


@pytest.mark.slow
def test_moe_trainer_step():
    """The generic trainer drives the MoE family end-to-end."""
    from skypilot_tpu.train import trainer as trainer_lib
    mesh = mesh_lib.make_mesh(
        mesh_lib.MeshSpec(data=2, fsdp=1, expert=4, tensor=1))
    cfg = trainer_lib.TrainerConfig(model='tiny-moe', batch_size=4,
                                    seq_len=32, max_steps=2,
                                    warmup_steps=1)
    state = trainer_lib.make_train_state(cfg, mesh)
    batch = trainer_lib.synthetic_batch(cfg, mesh)
    step = trainer_lib.make_train_step(cfg, mesh)
    with mesh_lib.use_mesh(mesh):
        state, metrics = step(state, batch)
        state, metrics2 = step(state, batch)
    assert jnp.isfinite(metrics2['loss'])
    assert float(metrics2['loss']) < float(metrics['loss']) + 1.0
