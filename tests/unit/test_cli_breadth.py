"""CLI breadth: show-gpus, storage ls/delete, config, api info/stop.

Reference analog: sky show-gpus / sky storage / sky api (client CLI,
sky/client/cli/command.py).
"""
import pytest
from click.testing import CliRunner

from skypilot_tpu.client import cli as cli_mod
from skypilot_tpu.server import app as app_mod
from skypilot_tpu.server import requests_db


@pytest.fixture
def server(monkeypatch):
    requests_db.reset_for_tests()
    with app_mod.ServerThread() as srv:
        monkeypatch.setenv('SKYTPU_API_SERVER_URL', srv.url)
        yield srv
    requests_db.reset_for_tests()


def test_show_gpus_lists_tpus_and_gpus(server):
    result = CliRunner().invoke(cli_mod.cli, ['show-gpus'])
    assert result.exit_code == 0, result.output
    assert 'tpu-v5p' in result.output
    assert 'A100' in result.output
    # AWS rows prove the multi-cloud catalog is consulted.
    assert 'p4d.24xlarge' in result.output


def test_show_gpus_filter(server):
    result = CliRunner().invoke(cli_mod.cli, ['show-gpus', 'tpu'])
    assert result.exit_code == 0
    assert 'tpu-v5e' in result.output
    assert 'A100' not in result.output


def test_storage_ls_and_delete_roundtrip(server, tmp_path):
    from skypilot_tpu.data import storage as storage_lib
    src = tmp_path / 'd'
    src.mkdir()
    (src / 'x.txt').write_text('x')
    storage = storage_lib.Storage(name='cli-bkt', source=str(src),
                                  store='local')
    storage.sync()

    result = CliRunner().invoke(cli_mod.cli, ['storage', 'ls'])
    assert result.exit_code == 0, result.output
    assert 'cli-bkt' in result.output
    assert 'local' in result.output

    result = CliRunner().invoke(
        cli_mod.cli, ['storage', 'delete', 'cli-bkt', '--yes'])
    assert result.exit_code == 0, result.output
    assert 'cli-bkt' in result.output
    result = CliRunner().invoke(cli_mod.cli, ['storage', 'ls'])
    assert 'cli-bkt' not in result.output
    assert not storage.store.exists()


def test_storage_delete_requires_target(server):
    result = CliRunner().invoke(cli_mod.cli, ['storage', 'delete'])
    assert result.exit_code != 0
    assert '--all' in result.output


def test_api_info(server):
    result = CliRunner().invoke(cli_mod.cli, ['api', 'info'])
    assert result.exit_code == 0, result.output
    assert 'api_version' in result.output


def test_api_stop_refuses_remote(server):
    # SKYTPU_API_SERVER_URL is set by the fixture → treated as remote.
    result = CliRunner().invoke(cli_mod.cli, ['api', 'stop'])
    assert result.exit_code != 0
    assert 'remote' in result.output.lower()


def test_config_prints_merged_yaml(server, monkeypatch):
    import os
    cfg_path = os.path.expanduser('~/.skytpu/config.yaml')
    os.makedirs(os.path.dirname(cfg_path), exist_ok=True)
    with open(cfg_path, 'w', encoding='utf-8') as f:
        f.write('jobs:\n  controller:\n    mode: consolidated\n')
    result = CliRunner().invoke(cli_mod.cli, ['config'])
    assert result.exit_code == 0
    assert 'consolidated' in result.output


def test_dashboard_log_viewer(server):
    import urllib.request
    from skypilot_tpu.client import sdk
    request_id = sdk.status()
    sdk.get(request_id, timeout=30)
    with urllib.request.urlopen(f'{server.url}/dashboard',
                                timeout=10) as resp:
        page = resp.read().decode()
    assert f'/dashboard/requests/{request_id}/log' in page
    with urllib.request.urlopen(
            f'{server.url}/dashboard/requests/{request_id}/log',
            timeout=10) as resp:
        log_page = resp.read().decode()
    assert 'request ' + request_id in log_page
    # Unknown ids 404 instead of leaking paths.
    import urllib.error
    try:
        urllib.request.urlopen(
            f'{server.url}/dashboard/requests/nope/log', timeout=10)
        raise AssertionError('expected 404')
    except urllib.error.HTTPError as e:
        assert e.code == 404


def test_dashboard_spa_api(server):
    """The SPA's live-refresh surface: JSON summary + raw log tails
    (what the embedded JS polls)."""
    import json as json_lib
    import urllib.request
    from skypilot_tpu.client import sdk
    request_id = sdk.status()
    sdk.get(request_id, timeout=30)
    with urllib.request.urlopen(f'{server.url}/dashboard/api/summary',
                                timeout=10) as resp:
        data = json_lib.loads(resp.read())
    assert set(data) >= {'version', 'clusters', 'jobs', 'services',
                         'requests', 'infra'}
    ids = [r['id'] for r in data['requests']]
    assert request_id in ids
    row = next(r for r in data['requests'] if r['id'] == request_id)
    assert row['status'] == 'SUCCEEDED'
    # infra lists every registered cloud with enablement flags.
    clouds = {i['cloud'] for i in data['infra']}
    assert {'gcp', 'aws', 'lambda', 'runpod', 'local'} <= clouds
    # raw tail for the JS poller is plain text carrying the live
    # title (status) so the viewer header tracks state changes.
    with urllib.request.urlopen(
            f'{server.url}/dashboard/requests/{request_id}/log?raw=1',
            timeout=10) as resp:
        assert resp.headers['Content-Type'].startswith('text/plain')
        assert 'SUCCEEDED' in resp.headers['X-Log-Title']


def test_ssh_print_command_local_and_guards(server, enable_clouds):
    enable_clouds('local')
    import skypilot_tpu as sky
    from skypilot_tpu import task as task_lib
    sky.launch(task_lib.Task(run='true', name='s'), cluster_name='sshc')
    result = CliRunner().invoke(
        cli_mod.cli, ['ssh', 'sshc', '--print-command'],
        env={'SKYTPU_API_SERVER_URL': ''})
    assert result.exit_code == 0, result.output
    assert result.output.strip() == 'bash'  # local cloud → local shell
    # out-of-range host rank (incl. negative) is rejected
    for rank in ('5', '-1'):
        result = CliRunner().invoke(
            cli_mod.cli, ['ssh', 'sshc', '--host-rank', rank,
                          '--print-command'],
            env={'SKYTPU_API_SERVER_URL': ''})
        assert result.exit_code != 0
    # remote API server → route through the websocket shell proxy
    result = CliRunner().invoke(
        cli_mod.cli, ['ssh', 'sshc', '--print-command'],
        env={'SKYTPU_API_SERVER_URL': 'http://elsewhere:1'})
    assert result.exit_code == 0, result.output
    assert '[ws-proxy]' in result.output
    assert '/api/v1/clusters/sshc/shell' in result.output
    sky.down('sshc')


def test_ssh_command_for_ssh_cluster_uses_runner_options():
    from skypilot_tpu.utils import command_runner
    runner = command_runner.SSHCommandRunner('1.2.3.4', user='u',
                                             private_key='~/.ssh/k')
    argv = runner.interactive_argv()
    assert argv[0] == 'ssh' and argv[-1] == 'u@1.2.3.4'
    assert argv[-2] == '-t'
    assert 'ControlMaster=auto' in argv  # reuses the shared options


def test_websocket_shell_proxy(server, enable_clouds):
    """ws shell bridges a remote client to a cluster host through the
    API server (reference /kubernetes-pod-ssh-proxy)."""
    import asyncio
    import aiohttp
    import skypilot_tpu as sky
    from skypilot_tpu import task as task_lib

    enable_clouds('local')
    sky.launch(task_lib.Task(run='true', name='w'), cluster_name='wsc')

    async def roundtrip():
        async with aiohttp.ClientSession() as session:
            url = f'{server.url}/api/v1/clusters/wsc/shell'
            async with session.ws_connect(url) as ws:
                await ws.send_bytes(b'echo WS-OK-$((40+2))\nexit\n')
                collected = b''

                async def _drain():
                    nonlocal collected
                    async for msg in ws:
                        if msg.type == aiohttp.WSMsgType.BINARY:
                            collected += msg.data
                        if b'WS-OK-42' in collected:
                            return

                try:  # asyncio.timeout is 3.11+; wait_for runs on 3.10
                    await asyncio.wait_for(_drain(), timeout=20)
                except asyncio.TimeoutError:
                    pass
                return collected

    out = asyncio.run(roundtrip())
    assert b'WS-OK-42' in out, out[-300:]

    async def bad_cluster():
        async with aiohttp.ClientSession() as session:
            url = f'{server.url}/api/v1/clusters/nope/shell'
            resp = await session.get(url)
            return resp.status

    assert asyncio.run(bad_cluster()) == 400

    # RBAC: a shell is `exec`-equivalent — viewers get 403.
    import os
    cfg_path = os.path.expanduser('~/.skytpu/config.yaml')
    os.makedirs(os.path.dirname(cfg_path), exist_ok=True)
    with open(cfg_path, 'w', encoding='utf-8') as f:
        f.write('api_server:\n  auth: true\n  users:\n'
                '    - {name: v, token: tok-v, role: viewer}\n')
    from skypilot_tpu import config as config_lib
    config_lib.reload()

    async def viewer_shell():
        async with aiohttp.ClientSession(
                headers={'Authorization': 'Bearer tok-v'}) as session:
            resp = await session.get(
                f'{server.url}/api/v1/clusters/wsc/shell')
            return resp.status

    assert asyncio.run(viewer_shell()) == 403
    os.remove(cfg_path)
    config_lib.reload()
    sky.down('wsc')


def test_api_login_stores_credentials(server):
    import os
    from skypilot_tpu.client import sdk
    result = CliRunner().invoke(
        cli_mod.cli,
        ['api', 'login', '--endpoint', 'http://far:46590/',
         '--token', 'tok-login'])
    assert result.exit_code == 0, result.output
    cfg_path = os.path.expanduser('~/.skytpu/config.yaml')
    assert oct(os.stat(cfg_path).st_mode & 0o777) == '0o600'
    # Env (set by the server fixture) still wins over the config...
    assert sdk.api_server_url() == os.environ['SKYTPU_API_SERVER_URL']
    assert sdk.api_token() == 'tok-login'
    # ...and without the env override the stored endpoint applies.
    del os.environ['SKYTPU_API_SERVER_URL']
    try:
        assert sdk.api_server_url() == 'http://far:46590'
    finally:
        os.environ['SKYTPU_API_SERVER_URL'] = ''


def test_catalog_qa_and_diff(tmp_path):
    """tsky catalog qa/diff wrap the analyzer gate (catalog/analyze.py)
    with its exit-code contract."""
    runner = CliRunner()
    res = runner.invoke(cli_mod.cli, ['catalog', 'qa'])
    assert res.exit_code == 0, res.output
    assert 'errors' in res.output
    # Warnings exist in the shipped catalogs (single-cloud GPUs), so
    # --strict flips the exit code without changing the findings.
    strict = runner.invoke(cli_mod.cli, ['catalog', 'qa', '--strict'])
    assert strict.exit_code == 1

    new_dir = tmp_path / 'fresh'
    (new_dir / 'aws').mkdir(parents=True)
    import shutil
    from skypilot_tpu.catalog import common as cat_common
    shutil.copy(cat_common.catalog_path('aws', 'vms'),
                new_dir / 'aws' / 'vms.csv')
    res = runner.invoke(cli_mod.cli, ['catalog', 'diff', str(new_dir)])
    assert res.exit_code == 0, res.output
    assert '+0 offers, -0, 0 price moves' in res.output
