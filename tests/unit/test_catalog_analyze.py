"""Catalog QA gate + refresh differ (catalog/analyze.py).

The first test IS the gate the tool exists for: the shipped CSVs must
be error-free, so a bad catalog commit fails CI the way the reference
keeps catalogs honest by hand-running its analyze.py
(sky/catalog/data_fetchers/analyze.py:1). The rest exercise each check
on synthetic fixtures.
"""
import os

import pandas as pd
import pytest

from skypilot_tpu.catalog import analyze


def _df(rows):
    return pd.DataFrame(rows, columns=analyze._VM_COLUMNS)


def _row(**kw):
    base = {'instance_type': 'g1', 'accelerator_name': 'A100-80GB',
            'accelerator_count': 8, 'cpus': 96, 'memory_gb': 768,
            'price': 12.0, 'spot_price': 4.0, 'region': 'r1',
            'zone': 'r1-a'}
    base.update(kw)
    return base


class TestShippedCatalogs:

    def test_qa_gate_zero_errors(self):
        findings = analyze.run_qa()
        errors = [f for f in findings if f.severity == 'error']
        assert not errors, '\n'.join(f.render() for f in errors)

    def test_cli_qa_exits_zero(self, capsys):
        assert analyze.main(['qa']) == 0
        assert 'errors' in capsys.readouterr().out

    def test_cli_json_flag_after_subcommand(self, capsys):
        import json as json_mod
        assert analyze.main(['qa', '--json']) == 0
        findings = json_mod.loads(capsys.readouterr().out)
        assert all({'severity', 'cloud', 'check', 'detail'} <= set(f)
                   for f in findings)

    def test_covers_every_shipped_cloud(self):
        # The gate must not silently skip a catalog dir.
        clouds = analyze._clouds(analyze.common._DATA_DIR)
        assert len(clouds) >= 16
        assert {'aws', 'gcp', 'azure', 'lambda'} <= set(clouds)


class TestVmChecks:

    def check(self, rows):
        return {f.check for f in analyze.qa_vms('c', _df(rows))}

    def test_clean_row_passes(self):
        assert self.check([_row()]) == set()

    def test_duplicate_offer(self):
        assert 'duplicate-offer' in self.check([_row(), _row()])

    def test_bad_price(self):
        assert 'bad-price' in self.check([_row(price=0)])
        assert 'bad-price' in self.check([_row(price=None)])

    def test_spot_above_ondemand(self):
        assert 'spot-above-ondemand' in self.check(
            [_row(price=1.0, spot_price=2.0)])

    def test_missing_spot_ok(self):
        assert self.check([_row(spot_price=None)]) == set()

    def test_accelerator_count_mismatch(self):
        assert 'accelerator-count' in self.check(
            [_row(accelerator_count=0)])
        assert 'accelerator-count' in self.check(
            [_row(accelerator_name=None, accelerator_count=4)])

    def test_cpu_only_row_ok(self):
        assert self.check(
            [_row(accelerator_name=None, accelerator_count=0)]) == set()

    def test_non_canonical_accelerator(self):
        # The exact failure ADVICE r4 flagged in fetch_oci: vendor
        # prefix spellings are unmatchable by the optimizer.
        assert 'non-canonical-accelerator' in self.check(
            [_row(accelerator_name='NVIDIA-A100-80GB')])
        assert 'non-canonical-accelerator' in self.check(
            [_row(accelerator_name='A100-80GB-SXM4')])

    def test_tpu_names_exempt_from_gpu_vocabulary(self):
        assert self.check(
            [_row(accelerator_name='tpu-v5e', accelerator_count=4)]) == set()

    def test_missing_column_is_schema_error(self):
        df = _df([_row()]).drop(columns=['price'])
        assert {f.check for f in analyze.qa_vms('c', df)} == {'schema'}

    def test_nan_count_is_an_error_not_a_pass(self):
        # NaN fails both <=0 and >0; the gate must not let an empty
        # count cell through (nor crash on a non-numeric one).
        assert 'accelerator-count' in self.check(
            [_row(accelerator_count=None)])
        assert 'accelerator-count' in self.check(
            [_row(accelerator_count='eight')])

    def test_non_numeric_price_is_bad_price_not_crash(self):
        # '$1.20' isn't in pandas' NA set: it must surface as a
        # finding, not a ValueError traceback with zero findings.
        assert 'bad-price' in self.check([_row(price='$1.20')])
        assert 'bad-price' in self.check([_row(spot_price='n/a')])

    def test_nan_count_excluded_from_cross_cloud_prices(self):
        frames = {'a': _df([_row(accelerator_count=None)]),
                  'b': _df([_row()]), 'c': _df([_row()])}
        # Must neither crash nor produce NaN-poisoned outliers.
        warns = analyze.qa_cross_cloud(frames)
        assert not [f for f in warns if f.check == 'price-outlier']


class TestTpuChecks:

    def test_shipped_gcp_tpus_clean(self):
        df = pd.read_csv(os.path.join(analyze.common._DATA_DIR, 'gcp',
                                      'tpus.csv'))
        assert analyze.qa_tpus('gcp', df) == []

    def test_spot_above_ondemand(self):
        df = pd.DataFrame([{'generation': 'tpu-v5e', 'region': 'r',
                            'zone': 'r-a', 'price_per_chip': 1.0,
                            'spot_price_per_chip': 2.0}])
        assert [f.check for f in analyze.qa_tpus('gcp', df)] == [
            'spot-above-ondemand']


class TestCrossCloud:

    def test_price_outlier_flags_unit_bug(self):
        # One cloud reporting cents-as-dollars: 100x the median.
        frames = {
            'a': _df([_row(price=8.0)]),
            'b': _df([_row(price=10.0)]),
            'c': _df([_row(price=1000.0)]),
        }
        warns = analyze.qa_cross_cloud(frames)
        assert any(f.check == 'price-outlier' and f.cloud == 'c'
                   for f in warns)

    def test_agreeing_prices_pass(self):
        frames = {'a': _df([_row(price=8.0)]),
                  'b': _df([_row(price=10.0)]),
                  'c': _df([_row(price=12.0)])}
        assert not [f for f in analyze.qa_cross_cloud(frames)
                    if f.check == 'price-outlier']

    def test_single_cloud_vocab_warns(self):
        frames = {'a': _df([_row(accelerator_name='B300',
                                 accelerator_count=8)])}
        warns = analyze.qa_cross_cloud(frames)
        assert any(f.check == 'single-cloud-accelerator' for f in warns)

    def test_schema_broken_frame_skipped_not_crashed(self):
        # A frame missing 'price' already produced a schema error in
        # qa_vms; the cross-cloud pass must skip it, not KeyError and
        # mask that finding.
        broken = _df([_row()]).drop(columns=['price'])
        frames = {'a': broken, 'b': _df([_row()])}
        analyze.qa_cross_cloud(frames)  # must not raise

    def test_run_qa_reports_schema_error_end_to_end(self, tmp_path):
        (tmp_path / 'x').mkdir()
        _df([_row()]).drop(columns=['price']).to_csv(
            tmp_path / 'x' / 'vms.csv', index=False)
        findings = analyze.run_qa(str(tmp_path))
        assert any(f.check == 'schema' for f in findings)


class TestDiff:

    def test_added_removed_and_price_moves(self, tmp_path):
        old_dir = tmp_path / 'old'
        new_dir = tmp_path / 'new'
        for d in (old_dir, new_dir):
            (d / 'x').mkdir(parents=True)
        _df([_row(), _row(instance_type='gone')]).to_csv(
            old_dir / 'x' / 'vms.csv', index=False)
        _df([_row(price=13.0), _row(instance_type='fresh')]).to_csv(
            new_dir / 'x' / 'vms.csv', index=False)
        (res,) = analyze.run_diff(str(new_dir), data_dir=str(old_dir))
        assert res.cloud == 'x'
        assert len(res.added) == 1 and 'fresh' in res.added[0]
        assert len(res.removed) == 1 and 'gone' in res.removed[0]
        assert len(res.price_changed) == 1 and '13.0' in res.price_changed[0]
        assert res.total == 3

    def test_identical_catalogs_diff_empty(self, tmp_path):
        old_dir = tmp_path / 'old'
        new_dir = tmp_path / 'new'
        for d in (old_dir, new_dir):
            (d / 'x').mkdir(parents=True)
            _df([_row()]).to_csv(d / 'x' / 'vms.csv', index=False)
        (res,) = analyze.run_diff(str(new_dir), data_dir=str(old_dir))
        assert res.total == 0

    def test_identical_nan_prices_are_not_a_price_move(self, tmp_path):
        # NaN != NaN: an unguarded tuple compare reports an unchanged
        # priceless offer as changed on every diff, forever.
        old_dir = tmp_path / 'old'
        new_dir = tmp_path / 'new'
        for d in (old_dir, new_dir):
            (d / 'x').mkdir(parents=True)
            _df([_row(price=None, spot_price=None)]).to_csv(
                d / 'x' / 'vms.csv', index=False)
        (res,) = analyze.run_diff(str(new_dir), data_dir=str(old_dir))
        assert res.price_changed == []

    def test_schema_broken_side_reports_error_not_keyerror(
            self, tmp_path, capsys):
        old_dir = tmp_path / 'old'
        new_dir = tmp_path / 'new'
        (old_dir / 'x').mkdir(parents=True)
        (new_dir / 'x').mkdir(parents=True)
        _df([_row()]).to_csv(old_dir / 'x' / 'vms.csv', index=False)
        _df([_row()]).drop(columns=['spot_price']).to_csv(
            new_dir / 'x' / 'vms.csv', index=False)
        (res,) = analyze.run_diff(str(new_dir), data_dir=str(old_dir))
        assert res.error and 'spot_price' in res.error
        assert analyze.main(['diff', str(new_dir),
                             '--data-dir', str(old_dir)]) == 1
        assert 'ERROR' in capsys.readouterr().out

    def test_cli_diff(self, tmp_path, capsys):
        new_dir = tmp_path / 'new'
        (new_dir / 'aws').mkdir(parents=True)
        _df([_row()]).to_csv(new_dir / 'aws' / 'vms.csv', index=False)
        assert analyze.main(['diff', str(new_dir)]) == 0
        assert 'aws' in capsys.readouterr().out
