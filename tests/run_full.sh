#!/bin/bash
# The whole test matrix: the default suite AND the compile-heavy slow
# set (deselected by default for iteration speed). Run this before
# releases / at round end so slow-set regressions can't slip through.
set -e
cd "$(dirname "$0")/.."
python -m pytest tests/ -q
python -m pytest tests/ -q -m slow
