#!/bin/bash
# The whole test matrix: the default suite AND the compile-heavy slow
# set (deselected by default for iteration speed). Run this before
# releases / at round end so slow-set regressions can't slip through.
set -e
cd "$(dirname "$0")/.."
# Static-analysis gate first: a lint finding fails fast, before the
# compile-heavy suites spend minutes.
python -m skypilot_tpu.analysis
python -m pytest tests/ -q
python -m pytest tests/ -q -m slow
