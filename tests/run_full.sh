#!/bin/bash
# The whole test matrix: the default suite AND the compile-heavy slow
# set (deselected by default for iteration speed). Run this before
# releases / at round end so slow-set regressions can't slip through.
set -e
cd "$(dirname "$0")/.."
# Static-analysis gate first: a lint finding fails fast, before the
# compile-heavy suites spend minutes.
python -m skypilot_tpu.analysis
python -m pytest tests/ -q
python -m pytest tests/ -q -m slow
# Fleet-scale soak gate: every registered scenario through the CLI
# (virtual clock; minutes of simulated chaos, seconds of wall time).
# Non-zero rc == an SLO regression; SLO_<scenario>.json carries the
# evidence. JAX_PLATFORMS=cpu keeps the sim off any real accelerator.
for scenario in smoke fused_decode spec_decode shared_prefix \
        sharded_serve prefix_affinity zone_loss rolling_update \
        preemption_wave; do
    JAX_PLATFORMS=cpu python -m skypilot_tpu.fleetsim \
        --scenario "$scenario" --out /tmp
done
# HF checkpoint round-trip smoke: export the tiny model (multi-shard)
# then the import + verify CLIs must exit 0 — the same commands an
# operator runs against a real pretrained download.
ckpt_dir=$(mktemp -d)
JAX_PLATFORMS=cpu python - "$ckpt_dir" <<'EOF'
import sys

import jax

from skypilot_tpu import checkpoints
from skypilot_tpu.models import llama

cfg = llama.CONFIGS['tiny']
checkpoints.export_params(llama.init_params(cfg, jax.random.key(0)),
                          cfg, sys.argv[1], max_shard_bytes=200 * 1024)
EOF
JAX_PLATFORMS=cpu python -m skypilot_tpu.checkpoints verify "$ckpt_dir"
JAX_PLATFORMS=cpu python -m skypilot_tpu.checkpoints import "$ckpt_dir"
rm -rf "$ckpt_dir"
