#!/bin/bash
# The whole test matrix: the default suite AND the compile-heavy slow
# set (deselected by default for iteration speed). Run this before
# releases / at round end so slow-set regressions can't slip through.
set -e
cd "$(dirname "$0")/.."
# Static-analysis gate first: a lint finding fails fast, before the
# compile-heavy suites spend minutes. The changed-only pass surfaces
# findings in the files being worked on within a second or two; the
# full pass behind it still catches cross-file and project-scope
# drift.
python -m skypilot_tpu.analysis --changed-only HEAD --format github
python -m skypilot_tpu.analysis
python -m pytest tests/ -q
python -m pytest tests/ -q -m slow
# Fleet-scale soak gate: every registered scenario through the CLI
# (virtual clock; minutes of simulated chaos, seconds of wall time).
# Non-zero rc == an SLO regression; SLO_<scenario>.json carries the
# evidence. JAX_PLATFORMS=cpu keeps the sim off any real accelerator.
for scenario in smoke fused_decode spec_decode shared_prefix \
        sharded_serve prefix_affinity watchdog zone_loss \
        rolling_update preemption_wave preemption_migration; do
    JAX_PLATFORMS=cpu python -m skypilot_tpu.fleetsim \
        --scenario "$scenario" --out /tmp
done
# Flight-recorder drill: trace_breach fails BY DESIGN (unmeetable
# TTFT target + zone loss) — the gate is that the failing report
# carries the span flight recorder, not that it passes.
breach_dir=$(mktemp -d)
JAX_PLATFORMS=cpu python -m skypilot_tpu.fleetsim \
    --scenario trace_breach --out "$breach_dir" && exit 1 || true
JAX_PLATFORMS=cpu python - "$breach_dir" <<'EOF'
import json, sys
doc = json.load(open(f'{sys.argv[1]}/SLO_trace_breach.json'))
assert doc['rc'] != 0, 'trace_breach unexpectedly passed'
trees = doc.get('flight_recorder', [])
assert trees, 'failing report carried no flight-recorder trees'
names = {s['name'] for t in trees for s in t['spans']}
assert {'lb.proxy', 'lb.upstream'} <= names, names
print(f'flight recorder: {len(trees)} tree(s) in failing report')
EOF
rm -rf "$breach_dir"
# Distributed-trace smoke: one real server, one traced request, and
# /internal/trace must return a well-formed tree with prefill and
# decode engine phases under the server's request span.
JAX_PLATFORMS=cpu SKYTPU_TRACE_SAMPLE=1 python - <<'EOF'
import json, subprocess, sys, time, urllib.request

proc = subprocess.Popen(
    [sys.executable, '-m', 'skypilot_tpu.inference.server',
     '--model', 'tiny', '--port', '18321', '--batch-size', '4',
     '--max-seq-len', '128'],
    stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
try:
    base = 'http://127.0.0.1:18321'
    for _ in range(120):
        try:
            doc = json.load(urllib.request.urlopen(
                f'{base}/health', timeout=2))
            if doc.get('status') == 'ok':
                break
        except Exception:
            time.sleep(1)
    else:
        raise SystemExit('server never became ready')
    req = urllib.request.Request(
        f'{base}/generate',
        data=json.dumps({'prompt_tokens': [5, 6, 7, 8],
                         'max_new_tokens': 8}).encode(),
        headers={'Content-Type': 'application/json'})
    resp = urllib.request.urlopen(req, timeout=300)
    trace_id = resp.headers.get('X-Trace-ID')
    assert trace_id, 'response carried no X-Trace-ID'
    resp.read()
    time.sleep(1)   # let the engine thread finish its spans
    tree = json.load(urllib.request.urlopen(
        f'{base}/internal/trace?trace_id={trace_id}', timeout=10))
    names = {s['name'] for s in tree['spans']}
    assert 'inference.request' in names, names
    assert 'engine.prefill' in names, names
    assert 'engine.decode' in names, names
    assert tree['tree'], 'empty tree view'
    print(f'trace smoke: {len(tree["spans"])} span(s) for '
          f'{trace_id}: {sorted(names)}')
finally:
    proc.terminate()
    proc.wait(timeout=10)
EOF
# HF checkpoint round-trip smoke: export the tiny model (multi-shard)
# then the import + verify CLIs must exit 0 — the same commands an
# operator runs against a real pretrained download.
ckpt_dir=$(mktemp -d)
JAX_PLATFORMS=cpu python - "$ckpt_dir" <<'EOF'
import sys

import jax

from skypilot_tpu import checkpoints
from skypilot_tpu.models import llama

cfg = llama.CONFIGS['tiny']
checkpoints.export_params(llama.init_params(cfg, jax.random.key(0)),
                          cfg, sys.argv[1], max_shard_bytes=200 * 1024)
EOF
JAX_PLATFORMS=cpu python -m skypilot_tpu.checkpoints verify "$ckpt_dir"
JAX_PLATFORMS=cpu python -m skypilot_tpu.checkpoints import "$ckpt_dir"
rm -rf "$ckpt_dir"
# Preemption-migration smoke: two real servers; stream from A, drain A
# mid-stream (the preemption notice), splice the migrate blob into B,
# and the combined client stream must equal an uninterrupted greedy
# run — token for token, no duplicates, no drops.
JAX_PLATFORMS=cpu python - <<'EOF'
import base64, json, subprocess, sys, threading, time
import urllib.error, urllib.request

PORT_A, PORT_B = 18341, 18342
ARGS = ['--model', 'tiny', '--batch-size', '2',
        '--decode-fuse-steps', '2', '--max-seq-len', '2048']

def wait_health(port):
    for _ in range(120):
        try:
            with urllib.request.urlopen(
                    f'http://127.0.0.1:{port}/health', timeout=2) as r:
                if r.status == 200:
                    return
        except Exception:
            pass
        time.sleep(1)
    raise SystemExit(f'server on {port} never became healthy')

def post(port, path, body, timeout=300):
    raw = isinstance(body, bytes)
    req = urllib.request.Request(
        f'http://127.0.0.1:{port}{path}',
        data=body if raw else json.dumps(body).encode(),
        headers={'Content-Type': 'application/octet-stream' if raw
                 else 'application/json'})
    return urllib.request.urlopen(req, timeout=timeout)

def sse_events(resp):
    buf = b''
    while True:
        chunk = resp.read(1)
        if not chunk:
            return
        buf += chunk
        while b'\n\n' in buf:
            frame, buf = buf.split(b'\n\n', 1)
            for line in frame.split(b'\n'):
                if line.startswith(b'data: '):
                    yield json.loads(line[6:])

procs = [subprocess.Popen(
    [sys.executable, '-m', 'skypilot_tpu.inference.server',
     '--port', str(port)] + ARGS,
    stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    for port in (PORT_A, PORT_B)]
try:
    wait_health(PORT_A)
    wait_health(PORT_B)
    body = {'prompt_tokens': list(range(7, 19)),
            'max_new_tokens': 1200, 'temperature': 0.0}
    with post(PORT_B, '/generate', body) as r:
        ref = json.loads(r.read())['tokens']
    assert len(ref) == 1200, len(ref)

    resp = post(PORT_A, '/generate', dict(body, stream=True))
    assert resp.headers.get('X-SkyTPU-Migration-Key')
    got, migrate, t = [], None, None
    def drain():
        post(PORT_A, '/internal/drain?deadline=0.05', {}).read()
    for ev in sse_events(resp):
        if 'token' in ev:
            got.append(ev['token'])
            if t is None:
                t = threading.Thread(target=drain)
                t.start()
        elif 'migrate' in ev:
            migrate = ev['migrate']
            break
        else:
            raise SystemExit(f'unexpected frame: {ev}')
    assert migrate is not None, f'drain never landed; got {len(got)}'
    t.join(timeout=30)
    assert migrate['sent'] == len(got)
    try:  # the draining replica must refuse new admissions
        post(PORT_A, '/generate', body).read()
        raise SystemExit('draining replica accepted a request')
    except urllib.error.HTTPError as e:
        assert e.code == 503, e.code

    blob = base64.b64decode(migrate['snapshot'])
    r2 = post(PORT_B, f'/internal/restore?sent={len(got)}&stream=1',
              blob)
    rest, done_tokens = [], None
    for ev in sse_events(r2):
        if 'token' in ev:
            rest.append(ev['token'])
        elif 'done' in ev:
            done_tokens = ev['tokens']
            break
        else:
            raise SystemExit(f'unexpected frame: {ev}')
    assert got + rest == ref, 'client stream != uninterrupted run'
    assert done_tokens == ref, 'done payload != full token list'
    print(f'drain smoke: {len(got)} streamed on A + {len(rest)} '
          f'restored on B == uninterrupted reference')
finally:
    for p in procs:
        p.kill()
EOF
# Federated-watchdog smoke: two real servers behind a REAL load
# balancer, telemetry cranked to a 0.5s cadence. SIGTERM one replica:
# the LB's scrape loop writes skytpu_replica_up=0 for it, the
# replica_up rule must FIRE on /internal/alerts (localized to the
# dead replica), and pruning the dead replica from the set — the
# controller's move — must CLEAR it. The degradation ladder end to
# end, observed purely through the LB's own alert plane.
JAX_PLATFORMS=cpu SKYTPU_TS_SAMPLE_SECONDS=0.5 \
SKYTPU_WATCHDOG_TICK_SECONDS=0.5 python - <<'EOF'
import json, signal, subprocess, sys, time, urllib.request

PORT_A, PORT_B = 18361, 18362
procs = [subprocess.Popen(
    [sys.executable, '-m', 'skypilot_tpu.inference.server',
     '--port', str(port), '--model', 'tiny', '--batch-size', '2',
     '--max-seq-len', '128'],
    stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    for port in (PORT_A, PORT_B)]
lb = None
try:
    for port in (PORT_A, PORT_B):
        for _ in range(120):
            try:
                with urllib.request.urlopen(
                        f'http://127.0.0.1:{port}/health',
                        timeout=2) as r:
                    if r.status == 200:
                        break
            except Exception:
                time.sleep(1)
        else:
            raise SystemExit(f'server on {port} never became healthy')

    from skypilot_tpu.serve import load_balancer as lb_lib
    urls = [f'http://127.0.0.1:{p}' for p in (PORT_A, PORT_B)]
    lb = lb_lib.LoadBalancer('round_robin', honor_env_policy=False)
    lb.set_replicas(urls)
    lb_port = lb.start()

    def alerts():
        with urllib.request.urlopen(
                f'http://127.0.0.1:{lb_port}/internal/alerts',
                timeout=5) as r:
            return json.load(r)

    def wait_event(state, timeout_s=60.0):
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            doc = alerts()
            for ev in doc.get('events', ()):
                if ev['rule'] == 'replica_up' and \
                        ev['state'] == state:
                    return ev
            time.sleep(0.5)
        raise SystemExit(
            f'replica_up never reached {state!r}: {alerts()}')

    # Both replicas up: give the scrape loop a few ticks and demand
    # silence.
    time.sleep(3)
    doc = alerts()
    assert not any(r['firing'] for r in doc['rules']), doc['rules']

    procs[0].send_signal(signal.SIGTERM)
    fired = wait_event('fire')
    assert urls[0] in fired['detail'], fired

    lb.set_replicas(urls[1:])      # the controller prunes the corpse
    wait_event('clear')
    print(f'watchdog smoke: replica_up fired on {urls[0]} '
          f'and cleared after pruning')
finally:
    if lb is not None:
        lb.stop()
    for p in procs:
        p.kill()
EOF
