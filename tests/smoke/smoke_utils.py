"""Declarative smoke harness: Test tuples driving the REAL tsky CLI.

Reference analog: tests/smoke_tests/smoke_tests_utils.py:292 (the
`Test(name, commands, teardown, timeout)` tuple) and :426
(`run_one_test`: sequential shell commands, streamed log, teardown
always runs). This is the third level of the test pyramid (SURVEY §4):
unit tests fake the clouds, the local-cloud e2e runs real processes,
and smoke tests drive the shipped CLI binary the way a user does —
today against the local cloud and GCP dry-runs, and against real
cloud projects the day credentials are pointed at them.

Gating: smoke tests only run under `pytest -m smoke` (deselected by
default); tests that would touch a REAL cloud additionally skip
unless SKYTPU_SMOKE_REAL_GCP=1.
"""
import dataclasses
import os
import subprocess
import sys
import tempfile
from typing import List, Optional

TSKY = [sys.executable, '-m', 'skypilot_tpu.client.cli']


@dataclasses.dataclass
class Test:
    __test__ = False  # a data tuple, not a pytest collectable
    name: str
    commands: List[str]
    teardown: Optional[str] = None
    timeout: int = 900

    def echo(self, message: str) -> None:
        print(f'[smoke:{self.name}] {message}', flush=True)


def _run_shell(command: str, log, timeout: int) -> int:
    """One command under bash with `tsky` aliased to this checkout's
    CLI; output streams to the log file (tail it live while a smoke
    run is in flight, exactly like the reference harness)."""
    tsky = ' '.join(TSKY)
    proc = subprocess.run(
        ['bash', '-c', f'set -o pipefail; {command}'],
        stdout=log, stderr=subprocess.STDOUT, timeout=timeout,
        env={**os.environ, 'TSKY': tsky},
        check=False)
    return proc.returncode


def run_one_test(test: Test) -> None:
    """Reference smoke_tests_utils.py:426 — run commands in order,
    fail fast on the first non-zero exit (with the log path in the
    message), ALWAYS run teardown."""
    log = tempfile.NamedTemporaryFile(
        mode='w', prefix=f'skytpu-smoke-{test.name}-', suffix='.log',
        delete=False)
    test.echo(f'log: {log.name}')
    failed_at: Optional[str] = None
    try:
        with log:
            try:
                for command in test.commands:
                    test.echo(command)
                    log.write(f'\n$ {command}\n')
                    log.flush()
                    rc = _run_shell(command, log, test.timeout)
                    if rc != 0:
                        failed_at = command
                        break
            except subprocess.TimeoutExpired:
                # A hung command must still reach teardown — leaking
                # a real cluster is worse than a late failure.
                failed_at = f'{command} (timed out after ' \
                            f'{test.timeout}s)'
            if test.teardown:
                test.echo(f'teardown: {test.teardown}')
                log.write(f'\n$ [teardown] {test.teardown}\n')
                log.flush()
                try:
                    _run_shell(test.teardown, log, test.timeout)
                except subprocess.TimeoutExpired:
                    test.echo('teardown timed out')
    finally:
        if failed_at is not None:
            tail = ''
            try:
                with open(log.name, encoding='utf-8') as f:
                    tail = ''.join(f.readlines()[-30:])
            except OSError:
                pass
            raise AssertionError(
                f'smoke test {test.name!r} failed at: {failed_at}\n'
                f'log: {log.name}\n--- tail ---\n{tail}')
