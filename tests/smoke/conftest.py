"""Smoke-run environment: isolated HOME, local cloud enabled, a
dedicated API server on a non-default port (a real user's server on
46590 must never be touched), torn down with the session."""
import json
import os
import socket
import subprocess
import sys
import time

import pytest


@pytest.fixture(scope='session')
def smoke_env(tmp_path_factory):
    home = tmp_path_factory.mktemp('smoke-home')
    state = home / '.skytpu'
    state.mkdir()
    # local always; gcp so the dry-run target has an enabled cloud.
    (state / 'enabled_clouds.json').write_text(
        json.dumps({'enabled': ['gcp', 'local']}))
    with socket.socket() as s:
        s.bind(('127.0.0.1', 0))
        port = s.getsockname()[1]
    env = {**os.environ,
           'HOME': str(home),
           'SKYTPU_API_SERVER_URL': f'http://127.0.0.1:{port}',
           'SKYTPU_SERVE_LOOP_INTERVAL': '0.5',
           'JAX_PLATFORMS': 'cpu'}
    server = subprocess.Popen(
        [sys.executable, '-m', 'skypilot_tpu.server.app',
         '--port', str(port)],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    deadline = time.time() + 30
    import urllib.request
    while time.time() < deadline:
        try:
            urllib.request.urlopen(
                f'http://127.0.0.1:{port}/api/v1/health', timeout=2)
            break
        except OSError:
            time.sleep(0.5)
    else:
        server.kill()
        raise RuntimeError('smoke API server failed to start')
    old = dict(os.environ)
    os.environ.update(env)
    yield env
    os.environ.clear()
    os.environ.update(old)
    server.terminate()
    try:
        server.wait(timeout=10)
    except subprocess.TimeoutExpired:
        server.kill()
