"""Smoke suite: declarative Test tuples over the real tsky CLI.

Run with:  python -m pytest -m smoke tests/smoke/ -q
(deselected from the default run; SKYTPU_SMOKE_REAL_GCP=1 additionally
enables tests that would touch a real GCP project.)

Reference analog: tests/smoke_tests/ — same harness shape
(smoke_utils.Test / run_one_test), so pointing a test at a real cloud
is editing a tuple, not writing a framework.
"""
import os
import tempfile

import pytest

from tests.smoke import smoke_utils
from tests.smoke.smoke_utils import Test

pytestmark = pytest.mark.smoke


def test_local_cluster_lifecycle(smoke_env):
    smoke_utils.run_one_test(Test(
        'local-lifecycle',
        [
            '$TSKY launch -c smoke1 --infra local -- echo smoke-ran',
            '$TSKY status | grep smoke1',
            '$TSKY queue smoke1 | grep SUCCEEDED',
            '$TSKY exec smoke1 -- echo exec-ran',
            '$TSKY logs smoke1 1 --no-follow | grep smoke-ran',
            '$TSKY autostop smoke1 -i 30 --down',
            '$TSKY status | grep smoke1 | grep -i up',
        ],
        teardown='echo y | $TSKY down smoke1',
        timeout=300,
    ))


def test_managed_job_lifecycle(smoke_env):
    yaml = tempfile.NamedTemporaryFile(
        mode='w', suffix='.yaml', delete=False)
    yaml.write('name: smokejob\n'
               'resources:\n  infra: local\n'
               'run: echo managed-smoke-ran\n')
    yaml.close()
    smoke_utils.run_one_test(Test(
        'managed-job',
        [
            f'$TSKY jobs launch {yaml.name} --name smokejob '
            '--detach-run',
            'for i in $(seq 1 60); do '
            '  $TSKY jobs queue | grep smokejob | '
            '    grep -q SUCCEEDED && break; sleep 2; done; '
            '$TSKY jobs queue | grep smokejob | grep SUCCEEDED',
        ],
        teardown='$TSKY jobs cancel --all --yes || true',
        timeout=300,
    ))


def test_serve_lifecycle(smoke_env):
    yaml = tempfile.NamedTemporaryFile(
        mode='w', suffix='.yaml', delete=False)
    yaml.write('name: smokesvc\n'
               'resources:\n  infra: local\n'
               'service:\n'
               '  readiness_probe:\n    path: /\n'
               '    initial_delay_seconds: 60\n'
               '  replica_port: 18732\n'
               '  replicas: 1\n'
               'run: cd /tmp && exec python3 -m http.server 18732\n')
    yaml.close()
    smoke_utils.run_one_test(Test(
        'serve-lifecycle',
        [
            f'$TSKY serve up {yaml.name} -n smokesvc',
            'for i in $(seq 1 90); do '
            '  $TSKY serve status | grep smokesvc | '
            '    grep -q READY && break; sleep 2; done; '
            '$TSKY serve status | grep smokesvc | grep READY',
        ],
        teardown='echo y | $TSKY serve down smokesvc --purge',
        timeout=600,
    ))


def test_workspace_and_user_admin(smoke_env):
    """The multi-tenancy surface through the real CLI (open local
    mode: the default user is admin)."""
    smoke_utils.run_one_test(Test(
        'admin-crud',
        [
            '$TSKY workspace create smokews --allowed-clouds local '
            '--description smoke',
            '$TSKY workspace list | grep smokews | grep local',
            '$TSKY user add smokeuser --role viewer | grep "shown once"',
            '$TSKY user list | grep smokeuser | grep viewer',
            '$TSKY user disable smokeuser',
            '$TSKY user list | grep smokeuser | grep disabled',
            '$TSKY user rm smokeuser -y',
            '$TSKY workspace delete smokews -y',
            '! $TSKY workspace list | grep smokews',
        ],
        timeout=300,
    ))


def test_gcp_dryrun_optimizes_without_credentials(smoke_env):
    """The GCP target exercises catalog + optimizer through the real
    CLI with --dryrun (no API calls, no credentials): the shape every
    real-cloud smoke test will take."""
    smoke_utils.run_one_test(Test(
        'gcp-dryrun',
        [
            '$TSKY launch -c smokegcp --infra gcp --gpus tpu-v5e:8 '
            '--dryrun -- echo never-runs',
        ],
        timeout=300,
    ))


@pytest.mark.skipif(
    os.environ.get('SKYTPU_SMOKE_REAL_GCP') != '1',
    reason='set SKYTPU_SMOKE_REAL_GCP=1 with real GCP credentials')
def test_real_gcp_cluster_lifecycle(smoke_env):
    """The day a real project is pointed at this suite, this runs a
    full provision/teardown — until then it documents the shape."""
    smoke_utils.run_one_test(Test(
        'real-gcp',
        [
            '$TSKY launch -c smokegcp-real --infra gcp --cpus 2 '
            '-- echo real-gcp-ran',
            '$TSKY status | grep smokegcp-real | grep -i up',
        ],
        teardown='echo y | $TSKY down smokegcp-real',
        timeout=1800,
    ))


def test_serve_openai_surface(smoke_env):
    """The OpenAI surface through the REAL serve stack: tsky serve up
    an in-tree engine replica, wait READY, then an OpenAI-style
    completion (token-array prompt — no tokenizer mounted) against
    the replica's /v1 endpoint."""
    yaml = tempfile.NamedTemporaryFile(
        mode='w', suffix='.yaml', delete=False)
    yaml.write('name: smokeoai\n'
               'resources:\n  infra: local\n'
               'service:\n'
               '  readiness_probe:\n    path: /health\n'
               '    initial_delay_seconds: 120\n'
               '  replica_port: 18734\n'
               '  replicas: 1\n'
               'run: exec env JAX_PLATFORMS=cpu python3 -m '
               'skypilot_tpu.inference.server --model tiny '
               '--port 18734 --batch-size 2\n')
    yaml.close()
    smoke_utils.run_one_test(Test(
        'serve-openai-surface',
        [
            f'$TSKY serve up {yaml.name} -n smokeoai',
            'for i in $(seq 1 120); do '
            '  $TSKY serve status | grep smokeoai | '
            '    grep -q READY && break; sleep 2; done; '
            '$TSKY serve status | grep smokeoai | grep READY',
            'curl -sf http://127.0.0.1:18734/v1/models | '
            '  grep -q tiny',
            'curl -sf http://127.0.0.1:18734/v1/completions '
            '  -H "Content-Type: application/json" '
            '  -d \'{"prompt": [3, 7, 11], "max_tokens": 3, '
            '       "temperature": 0}\' | '
            '  grep -q text_completion',
        ],
        teardown='echo y | $TSKY serve down smokeoai --purge',
        timeout=600,
    ))
