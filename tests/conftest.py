"""Test env: force JAX onto a virtual 8-device CPU mesh, isolate state dirs.

Mirrors the reference's zero-credential strategy
(tests/common_test_fixtures.py:191 `enable_all_clouds`): unit tests run the
real code paths against the local cloud and mocked GCP REST, never a real
cloud.
"""
import os

# Tests run on a virtual 8-device CPU mesh, never the real chip.
# The axon sitecustomize sets JAX_PLATFORMS=axon AND initializes the
# TPU backend at interpreter start, so env vars alone are too late —
# re-point the env and clear the already-initialized backends.
os.environ['JAX_PLATFORMS'] = 'cpu'
# Keep control-plane subprocesses (skylet, gang driver) off the tunnel.
os.environ.pop('PALLAS_AXON_POOL_IPS', None)
xla_flags = os.environ.get('XLA_FLAGS', '')
if '--xla_force_host_platform_device_count' not in xla_flags:
    os.environ['XLA_FLAGS'] = (
        xla_flags + ' --xla_force_host_platform_device_count=8').strip()

import jax

jax.config.update('jax_platforms', 'cpu')
try:
    from jax.extend import backend as _jexb
    _jexb.clear_backends()
except Exception:  # pragma: no cover - older jax
    jax.clear_backends()
assert jax.devices()[0].platform == 'cpu'

import pytest


@pytest.fixture(autouse=True)
def isolated_state(tmp_path, monkeypatch):
    """Point all on-disk state (~/.skytpu) at a per-test tmp dir."""
    home = tmp_path / 'home'
    home.mkdir()
    monkeypatch.setenv('HOME', str(home))
    monkeypatch.setenv('SKYTPU_STATE_DIR', str(home / '.skytpu'))
    # Drop caches that may hold paths from a previous HOME.
    from skypilot_tpu import config as config_lib
    config_lib.reload()
    try:
        from skypilot_tpu import state as state_lib
        state_lib.reset_for_tests()
    except ImportError:
        pass
    yield


@pytest.fixture
def enable_clouds(monkeypatch):
    """Enable a fixed set of clouds without probing credentials."""
    def _enable(*names):
        from skypilot_tpu import check as check_lib
        monkeypatch.setattr(
            check_lib, 'get_cached_enabled_clouds_or_refresh',
            lambda raise_if_no_cloud_access=False: sorted(names))
        return sorted(names)
    return _enable
