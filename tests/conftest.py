"""Test env: force JAX onto a virtual 8-device CPU mesh, isolate state dirs.

Mirrors the reference's zero-credential strategy
(tests/common_test_fixtures.py:191 `enable_all_clouds`): unit tests run the
real code paths against the local cloud and mocked GCP REST, never a real
cloud.
"""
import os

# Tests run on a virtual 8-device CPU mesh, never the real chip.
# The axon sitecustomize sets JAX_PLATFORMS=axon AND initializes the
# TPU backend at interpreter start, so env vars alone are too late —
# re-point the env and clear the already-initialized backends.
os.environ['JAX_PLATFORMS'] = 'cpu'
# Keep control-plane subprocesses (skylet, gang driver) off the tunnel.
os.environ.pop('PALLAS_AXON_POOL_IPS', None)
xla_flags = os.environ.get('XLA_FLAGS', '')
if '--xla_force_host_platform_device_count' not in xla_flags:
    os.environ['XLA_FLAGS'] = (
        xla_flags + ' --xla_force_host_platform_device_count=8').strip()

import jax

jax.config.update('jax_platforms', 'cpu')
try:
    from jax.extend import backend as _jexb
    _jexb.clear_backends()
except Exception:  # pragma: no cover - older jax
    jax.clear_backends()
assert jax.devices()[0].platform == 'cpu'

import pytest


def _kill_processes_referencing(marker: str) -> None:
    """SIGKILL processes whose cmdline/environ references `marker`
    (a per-test HOME): tests may leave clusters UP on purpose, and
    their skylet/gang daemons must die with the test's state dir."""
    import glob
    import signal as _signal

    needle = marker.encode()
    me = os.getpid()
    for pid_dir in glob.glob('/proc/[0-9]*'):
        try:
            pid = int(os.path.basename(pid_dir))
            if pid == me:
                continue
            with open(os.path.join(pid_dir, 'cmdline'), 'rb') as f:
                cmd = f.read()
            with open(os.path.join(pid_dir, 'environ'), 'rb') as f:
                env = f.read()
        except (OSError, ValueError):
            continue
        if needle not in cmd and needle not in env:
            continue
        try:
            os.killpg(pid, _signal.SIGKILL)
        except OSError:
            try:
                os.kill(pid, _signal.SIGKILL)
            except OSError:
                pass


@pytest.fixture(autouse=True)
def isolated_state(tmp_path, monkeypatch):
    """Point all on-disk state (~/.skytpu) at a per-test tmp dir."""
    home = tmp_path / 'home'
    home.mkdir()
    monkeypatch.setenv('HOME', str(home))
    monkeypatch.setenv('SKYTPU_STATE_DIR', str(home / '.skytpu'))
    # Drop caches that may hold paths from a previous HOME.
    from skypilot_tpu import config as config_lib
    config_lib.reload()
    try:
        from skypilot_tpu import state as state_lib
        state_lib.reset_for_tests()
    except ImportError:
        pass
    yield
    _kill_processes_referencing(str(home))


@pytest.fixture
def enable_clouds(monkeypatch):
    """Enable a fixed set of clouds without probing credentials."""
    def _enable(*names):
        from skypilot_tpu import check as check_lib
        monkeypatch.setattr(
            check_lib, 'get_cached_enabled_clouds_or_refresh',
            lambda raise_if_no_cloud_access=False: sorted(names))
        return sorted(names)
    return _enable


def pytest_sessionfinish(session, exitstatus):
    """Zero-leaked-processes guard: any control-plane daemon (skylet,
    gang runner, controllers) still alive at session end is a test bug —
    kill it and fail the run so leaks can't accumulate.

    Scoped strictly to THIS session: a process counts as ours only when
    its cmdline or environment references this run's tmp basetemp (every
    test daemon inherits HOME/SKYTPU_STATE_DIR under it). A concurrent
    pytest run or a real deployment on the same host is never touched.
    """
    import glob
    import signal as _signal

    try:
        basetemp = str(
            session.config._tmp_path_factory.getbasetemp())  # noqa: SLF001
    except Exception:  # no tmp dir was ever created
        return
    marker = basetemp.encode()
    me = os.getpid()

    def _scan():
        found = []
        for pid_dir in glob.glob('/proc/[0-9]*'):
            try:
                pid = int(os.path.basename(pid_dir))
            except ValueError:
                continue
            if pid == me:
                continue
            try:
                with open(os.path.join(pid_dir, 'cmdline'), 'rb') as f:
                    cmd = f.read()
                with open(os.path.join(pid_dir, 'environ'), 'rb') as f:
                    env = f.read()
            except OSError:
                continue
            if marker in cmd or marker in env:
                found.append((pid, cmd.replace(b'\0', b' ').decode(
                    errors='replace').strip()))
        return found

    candidates = _scan()
    if candidates:
        # Grace re-check: orphan reapers and topology-watch daemons
        # self-terminate within ~1s of their cluster dying — only
        # processes that survive the grace window are true leaks.
        import time as _time
        _time.sleep(1.5)
        alive = {pid for pid, _ in _scan()}
        candidates = [(pid, cmd) for pid, cmd in candidates
                      if pid in alive]
    leaked = []
    for pid, cmd in candidates:
        leaked.append((pid, cmd))
        try:
            os.killpg(pid, _signal.SIGKILL)
        except OSError:
            try:
                os.kill(pid, _signal.SIGKILL)
            except OSError:
                pass
    if leaked:
        print('\nLEAKED PROCESSES (killed by conftest guard):')
        for pid, cmd in leaked:
            print(f'  {pid}: {cmd[:140]}')
        session.exitstatus = 1
