"""Load balancer: aiohttp reverse proxy in front of ready replicas.

Reference analog: sky/serve/load_balancer.py:23 (`SkyServeLoadBalancer`
— FastAPI proxy syncing replica URLs from the controller). Ours embeds a
QPS window the controller's autoscaler reads via /internal/stats.
"""
import asyncio
import base64
import collections
import contextlib
import itertools
import json
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from skypilot_tpu import envs
from skypilot_tpu.observability import instruments as obs
from skypilot_tpu.observability import metrics as metrics_lib
from skypilot_tpu.observability import spans
from skypilot_tpu.observability import timeseries as timeseries_lib
from skypilot_tpu.observability import watchdog as watchdog_lib
from skypilot_tpu.resilience import circuit
from skypilot_tpu.resilience import faults
from skypilot_tpu.resilience import retries
from skypilot_tpu.serve import load_balancing_policies as lb_policies

_QPS_WINDOW_SECONDS = 60.0
# Bodies above this are never JSON-parsed for routing context: the
# peek must stay O(prompt), not O(attachment).
_CONTEXT_PEEK_MAX_BYTES = 4 * 1024 * 1024


def request_context(body: Optional[bytes],
                    content_type: Optional[str],
                    content_length: Optional[int]
                    ) -> Optional[Dict[str, Any]]:
    """Peek the routing context out of an already-buffered request
    body. Only declared-length JSON bodies are parsed — a streamed
    (chunked, no content-length) upload is proxied as before and
    routes context-free, never buffered twice or parsed
    speculatively. Returns {'prompt_tokens', 'max_new_tokens'} or
    None when the request carries nothing routable."""
    if (not body or content_type != 'application/json'
            or content_length is None
            or content_length > _CONTEXT_PEEK_MAX_BYTES):
        return None
    try:
        doc = json.loads(body)
    except (ValueError, UnicodeDecodeError):
        return None
    if not isinstance(doc, dict):
        return None
    ctx: Dict[str, Any] = {}
    tokens = doc.get('prompt_tokens')
    if not (isinstance(tokens, list) and tokens
            and all(isinstance(t, int) for t in tokens)):
        # OpenAI-style bodies may carry the tokenized prompt under
        # `prompt` (a list of ids): that IS a real token count —
        # classifying it through the chars/4 string estimate (or not
        # at all) would mis-gate the prompt threshold.
        prompt = doc.get('prompt')
        if isinstance(prompt, list) and prompt and \
                all(isinstance(t, int) for t in prompt):
            tokens = prompt
        else:
            tokens = None
    if tokens is not None:
        ctx['prompt_tokens'] = tokens
    elif isinstance(doc.get('prompt'), str) and doc['prompt']:
        ctx['prompt'] = doc['prompt']
    else:
        return None
    max_new = doc.get('max_new_tokens')
    if isinstance(max_new, int):
        ctx['max_new_tokens'] = max_new
    if doc.get('stream') is True:
        # Only streamed requests can carry the non-terminal handoff
        # frame; key added only when set so poolless callers see the
        # same context dicts as before.
        ctx['stream'] = True
    return ctx


def _sse_frame_doc(frame: bytes) -> Optional[Dict[str, Any]]:
    """The JSON dict of one SSE frame's `data:` line, or None for
    frames the managed relay should pass through uninterpreted
    (comments, keep-alives, non-JSON payloads)."""
    for line in frame.split(b'\n'):
        if line.startswith(b'data: '):
            try:
                doc = json.loads(line[6:])
            except (ValueError, UnicodeDecodeError):
                return None
            return doc if isinstance(doc, dict) else None
    return None


def classify_pool_role(context: Optional[Dict[str, Any]]
                       ) -> Optional[str]:
    """Request shape -> pool role: long-prompt AND short-gen requests
    prefer the prefill-heavy pool; everything else with routable
    content is decode-bound. None (no context) routes unrestricted."""
    if not context:
        return None
    tokens = context.get('prompt_tokens')
    if tokens:
        prompt_len = len(tokens)
    else:
        # The threshold is token-denominated; a raw string is ~4
        # chars/token — estimate rather than misclassify every
        # medium-length string prompt as long.
        prompt_len = len(context.get('prompt') or '') // 4
    max_new = context.get('max_new_tokens', 64)
    if prompt_len >= envs.SKYTPU_LB_POOL_PROMPT_THRESHOLD.get() and \
            max_new <= envs.SKYTPU_LB_POOL_MAX_NEW_THRESHOLD.get():
        return 'prefill'
    return 'decode'


def handoff_eligible(context: Optional[Dict[str, Any]]) -> bool:
    """Whether a request may take the two-leg (prefill -> planned
    handoff -> decode) route. Stricter than classify_pool_role on two
    axes: only a prompt that arrived TOKENIZED counts — the ~4
    chars/token string estimate must never gate
    SKYTPU_LB_POOL_PROMPT_THRESHOLD for a handoff, since a mis-flagged
    short request would pause at the boundary for nothing — and only a
    streamed request can carry the non-terminal handoff frame. The
    other half of the guard is engine-side and structural: the pause
    only exists AFTER the first generated token, so a request still
    queued or mid-prefill (whose snapshot would be a layout-'none'
    host-only blob) can never export a handoff."""
    if not context or not context.get('stream'):
        return False
    if not context.get('prompt_tokens'):
        return False
    return classify_pool_role(context) == 'prefill'


class RequestRateTracker:
    def __init__(self, now_fn: Callable[[], float] = time.time) -> None:
        self._times = collections.deque()
        self._lock = threading.Lock()
        self._now = now_fn

    def record(self) -> None:
        with self._lock:
            self._times.append(self._now())

    def qps(self) -> float:
        cutoff = self._now() - _QPS_WINDOW_SECONDS
        with self._lock:
            while self._times and self._times[0] < cutoff:
                self._times.popleft()
            return len(self._times) / _QPS_WINDOW_SECONDS


class LoadBalancer:
    def __init__(self, policy_name: str = 'least_load',
                 port: int = 0,
                 now_fn: Callable[[], float] = time.time,
                 honor_env_policy: bool = True) -> None:
        # SKYTPU_LB_POLICY outranks the spec: live routing A/Bs must
        # not require a task-YAML edit + version bump. Callers that
        # ARE the A/B (fleetsim's comparison passes, the loadgen
        # capstone) pass honor_env_policy=False — a stray exported
        # override silently running both passes on one policy would
        # turn the comparison into a phantom regression.
        self.policy_name = policy_name
        if honor_env_policy:
            self.policy_name = envs.SKYTPU_LB_POLICY.get() or \
                policy_name
        self.policy = lb_policies.make_policy(
            self.policy_name,
            now_fn=(time.monotonic if now_fn is time.time else now_fn))
        self.port = port
        # url -> pool ROLE ('prefill'/'decode'/'general'); empty means
        # no pool routing (single undifferentiated fleet).
        self._pool_roles: Dict[str, str] = {}
        self.tracker = RequestRateTracker(now_fn)
        # Replica endpoints that keep failing at the transport layer
        # get routed around instead of 502ing live traffic. now_fn is
        # the clock seam: the fleet simulator runs breaker recovery
        # windows on its virtual clock; the production default keeps
        # the breaker on monotonic time (immune to wall-clock jumps).
        self.breaker = circuit.CircuitBreaker(
            'lb', failure_threshold=3, recovery_timeout=15.0,
            now_fn=(time.monotonic if now_fn is time.time else now_fn),
            on_open=self._dump_on_breaker_open)
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._runner = None
        self._thread: Optional[threading.Thread] = None
        # Fleet telemetry federation: the LB's watchdog scrapes every
        # replica's /internal/timeseries on its tick (pre_tick seam)
        # into the shared store, each series stamped with a `replica`
        # label — so /internal/timeseries here answers per-replica
        # AND fleet-merged queries, and the watchdog's rules run over
        # the whole fleet's series.
        self._watchdog: Optional[watchdog_lib.Watchdog] = None
        self._scrape_since: Dict[str, float] = {}
        # Fire-and-forget coroutines (handoff-source abandons): the
        # event loop holds tasks weakly, so keep strong refs until
        # each one finishes.
        self._bg_tasks: set = set()

    def _spawn_bg(self, coro) -> None:
        task = asyncio.ensure_future(coro)
        self._bg_tasks.add(task)
        task.add_done_callback(self._bg_tasks.discard)

    def set_replicas(self, urls: List[str],
                     pools: Optional[Dict[str, str]] = None) -> None:
        """`pools` maps url -> pool role; None keeps the previous
        mapping (or no pools at all) so poolless callers are
        untouched."""
        old = set(self.policy.replicas) - set(urls)
        self.policy.set_replicas(urls)
        if pools is not None:
            self._pool_roles = dict(pools)
        for gone in old:
            self.breaker.forget(gone)
            self._pool_roles.pop(gone, None)

    def _dump_on_breaker_open(self, target: str) -> None:
        """A circuit opening means this LB just gave up on a replica —
        dump the span flight recorder so the trees leading up to the
        failures survive for offline triage. No-op unless
        SKYTPU_TRACE_DUMP_DIR is set."""
        out_dir = envs.SKYTPU_TRACE_DUMP_DIR.get()
        if out_dir:
            spans.dump_flight_recorder(out_dir, 'breaker_open')

    def _pool_candidates(self, context) -> Optional[List[str]]:
        """Replica-pool slice for this request's shape, or None for
        no restriction (no pools configured, no routable context, or
        the preferred pool currently has no ready replica — shape
        preference must never 503 a servable request)."""
        if not self._pool_roles:
            return None
        role = classify_pool_role(context)
        if role is None:
            return None
        urls = [r for r in self.policy.replicas
                if self._pool_roles.get(r) == role]
        if not urls:
            return None
        obs.LB_POOL_REQUESTS.labels(pool=role).inc()
        return urls

    def _failover_order(self, context=None):
        """Upstream try-order: the policy's pick first, then the rest
        of its pool, then every other replica — a failed upstream
        must not 502 the client while healthy replicas exist. None
        when the rotation is empty; otherwise a LAZY iterator (the
        common case consumes one element, and a 1000-replica rotation
        must not allocate a full list per request). Shared by the
        HTTP proxy AND dispatch(), so the simulator routes exactly
        like production."""
        pool = self._pool_candidates(context)
        first = self.policy.select(context=context, candidates=pool)
        if first is None:
            return None
        if pool is None:
            return itertools.chain(
                (first,),
                (r for r in self.policy.replicas if r != first))
        pool_set = set(pool)
        return itertools.chain(
            (first,), (r for r in pool if r != first),
            (r for r in self.policy.replicas
             if r != first and r not in pool_set))

    def _restore_candidates(self, context=None,
                            role: str = 'decode') -> List[str]:
        """Candidate order for RESTORE legs (planned handoff and crash
        migration): the work remaining after any snapshot is
        decode-only, so the decode pool's breaker-allowed replicas are
        exhausted FIRST, then the rest of the fleet spills in. The
        request's original shape classification must NOT drive this
        order — it classified the *whole* request (long prompt =>
        prefill pool), which is exactly wrong for the remainder, and
        walking the shape-classified failover order let a general-pool
        replica shadow an idle decode replica. Poolless deployments
        degrade to plain fleet order."""
        del context  # shape classification deliberately unused here
        pool = [r for r in self.policy.replicas
                if self._pool_roles.get(r) == role]
        pool_set = set(pool)
        return pool + [r for r in self.policy.replicas
                       if r not in pool_set]

    # -- the simulator / non-HTTP seam ---------------------------------------

    def dispatch(self, send: Callable[[str], bool],
                 context: Optional[Dict[str, Any]] = None) -> str:
        """Route ONE request through the real policy + breaker +
        failover discipline without the HTTP layer — the fleet
        simulator's seam into this LB. `send(url)` performs the
        request against one upstream and returns success; failures
        feed the breaker and fail over exactly like _handle_proxy's
        pre-bytes phase. `context` is the routing context the HTTP
        path peeks from JSON bodies (prompt tokens, max_new_tokens)
        — content-aware policies and pool routing consume it here
        exactly as in production. Returns 'ok', 'no_replica' (empty
        rotation), 'all_open' (candidates exist, every circuit open)
        or 'error' (every attempted upstream failed).

        Each dispatch records the same lb.proxy/lb.upstream span
        shape as the HTTP proxy, so fleetsim's flight recorder holds
        real routing trees when an SLO assert fails."""
        self.tracker.record()
        root_attrs: Dict[str, Any] = {'transport': 'dispatch'}
        with spans.span('lb.proxy', attrs=root_attrs) as root:
            result = self._dispatch_traced(send, context, root)
            root_attrs['result'] = result
            if result != 'ok':
                spans.COLLECTOR.mark_error(root.trace_id)
            return result

    def _dispatch_traced(self, send: Callable[[str], bool],
                         context: Optional[Dict[str, Any]],
                         root: spans.SpanContext) -> str:
        candidates = self._failover_order(context)
        if candidates is None:
            obs.LB_NO_REPLICA.inc()
            return 'no_replica'
        attempted = 0
        for target in candidates:
            if not self.breaker.allow(target):
                continue
            attempted += 1
            if attempted > 1:
                obs.LB_UPSTREAM_RETRIES.inc()
            obs.LB_REPLICA_REQUESTS.labels(replica=target).inc()
            self.policy.on_request_start(target, context=context)
            leg_attrs: Dict[str, Any] = {'replica': target,
                                         'attempt': attempted}
            try:
                with spans.span('lb.upstream', attrs=leg_attrs):
                    ok = send(target)
                    leg_attrs['ok'] = bool(ok)
            finally:
                self.policy.on_request_end(target)
            if ok:
                self.breaker.record_success(target)
                return 'ok'
            obs.LB_PROXY_ERRORS.inc()
            self.breaker.record_failure(target)
            # Failed legs make the trace keep-worthy even when a later
            # leg succeeds: the breaker-open dump should contain the
            # requests that fed the breaker.
            spans.COLLECTOR.mark_error(root.trace_id)
        if attempted == 0:
            obs.LB_NO_REPLICA.inc()
            return 'all_open'
        return 'error'

    # -- aiohttp handlers ----------------------------------------------------

    async def _handle_stats(self, request):
        from aiohttp import web
        # Per-replica circuit state + how many replicas are actually
        # routable: when traffic shifts, operators (and the soak
        # harness) can see WHY from this one endpoint. snapshot() is
        # non-mutating — polling stats must not burn half-open trials.
        states = self.breaker.snapshot()
        replicas = list(self.policy.replicas)
        breakers = {
            url: states.get(url, circuit.State.CLOSED).name.lower()
            for url in replicas}
        return web.json_response({
            'qps': self.tracker.qps(),
            'replicas': replicas,
            'breakers': breakers,
            'candidates': sum(1 for s in breakers.values()
                              if s != 'open'),
            # Per-bucket exemplars from the LB's own histograms:
            # each carries the trace id of a request that landed in
            # that bucket — the jump-off from "p99 spiked" to the
            # exact span tree of a request that paid it.
            'exemplars': metrics_lib.exemplars_snapshot(),
            # WHY traffic shifted: the policy's affinity-table shape
            # (per-replica indexed-prefix counts) plus the hit/miss/
            # bounded-load counters. A dropped fleet cache-hit ratio
            # reads differently when affinity misses spiked (index
            # churn / cold prefixes) vs when fallbacks spiked (a hot
            # family overflowing its affine replica).
            'routing': {
                'policy': self.policy_name,
                'pools': dict(self._pool_roles),
                'affinity': {
                    **self.policy.stats(),
                    'hits': int(obs.LB_AFFINITY_HITS.value()),
                    'misses': int(obs.LB_AFFINITY_MISSES.value()),
                    'fallbacks':
                        int(obs.LB_AFFINITY_FALLBACKS.value()),
                },
            },
            # Engine pressure from the process-local registry (real
            # series in co-located/fleetsim deployments): utilization
            # alone can't explain a dropped prefix-cache hit ratio —
            # the free/cached/private page split can.
            'engine': {
                'queue_depth': obs.QUEUE_DEPTH.value(),
                'kv_cache_utilization':
                    obs.KV_CACHE_UTILIZATION.value(),
                'kv_pages': {
                    'total': int(obs.KV_PAGES_TOTAL.value()),
                    'free': int(obs.KV_PAGES_FREE.value()),
                    'cached': int(obs.PREFIX_CACHE_PAGES.value()),
                    'private': int(obs.KV_PAGES_PRIVATE.value()),
                },
                'prefix_cache_hits':
                    int(obs.PREFIX_CACHE_HITS.value()),
                'prefix_cache_misses':
                    int(obs.PREFIX_CACHE_MISSES.value()),
            },
        })

    async def _handle_proxy(self, request):
        from aiohttp import web
        self.tracker.record()
        # The retry discipline already buffers the body once (a
        # failed-over request must replay identical bytes); the
        # routing peek reuses THAT buffer — request_context refuses
        # undeclared-length/oversized bodies, so streamed uploads are
        # never parsed, only proxied.
        body = await request.read()
        context = request_context(body, request.content_type,
                                  request.content_length)
        # Join the caller's trace when it sent a traceparent; root a
        # new one otherwise. Every proxied leg carries a fresh
        # traceparent downstream and every response carries X-Trace-ID
        # back, so a slow request's tree is one /internal/trace query
        # away.
        inbound = spans.parse_traceparent(
            request.headers.get(spans.TRACEPARENT_HEADER))
        root_attrs: Dict[str, Any] = {'method': request.method,
                                      'path': request.rel_url.path}
        with spans.span('lb.proxy', parent=inbound,
                        attrs=root_attrs) as root:
            response = await self._proxy_traced(request, body,
                                                context, root)
            root_attrs['status'] = response.status
            if response.status >= 500:
                spans.COLLECTOR.mark_error(root.trace_id)
            if not response.prepared:
                # Streamed responses already sent their headers (the
                # trace header was stamped before prepare()).
                response.headers.setdefault(
                    spans.TRACE_ID_RESPONSE_HEADER, root.trace_id)
            return response

    async def _proxy_traced(self, request, body, context,
                            root: spans.SpanContext):
        """One routing pass under `root`'s trace: upstreams tried in
        failover order, each attempt wrapped in an lb.upstream span
        whose OWN id rides the outgoing traceparent — the replica's
        server span parents on the leg that actually reached it, so
        failover attempts stay separable in the merged tree."""
        from aiohttp import ClientSession, ClientTimeout, web
        import aiohttp
        candidates = self._failover_order(context)
        if candidates is None:
            obs.LB_NO_REPLICA.inc()
            return web.Response(
                status=503, headers={'Retry-After': '1'},
                text='No ready replicas. Retry shortly.\n')
        tail = request.match_info['tail']
        last_error: Optional[BaseException] = None
        attempted = 0
        for target in candidates:
            if not self.breaker.allow(target):
                continue
            attempted += 1
            if attempted > 1:
                obs.LB_UPSTREAM_RETRIES.inc()
            obs.LB_REPLICA_REQUESTS.labels(replica=target).inc()
            url = target.rstrip('/') + '/' + tail
            if request.query_string:
                url += f'?{request.query_string}'
            self.policy.on_request_start(target, context=context)
            session = upstream = None
            leg_attrs: Dict[str, Any] = {'replica': target,
                                         'attempt': attempted}
            leg_scope = contextlib.ExitStack()
            leg_ctx = leg_scope.enter_context(
                spans.span('lb.upstream', attrs=leg_attrs))
            try:
                # Phase 1 — contact the upstream. Failures here are
                # the REPLICA's: feed the breaker, fail over.
                try:
                    faults.inject('lb.upstream', env_exc=OSError)
                    session = ClientSession(
                        timeout=ClientTimeout(total=3600))
                    # Strip any inbound traceparent: the replica must
                    # parent on THIS leg, not on the client's span.
                    # X-SkyTPU-Handoff is LB-owned too — only the
                    # pool-routing decision below may set it.
                    hdrs = {k: v
                            for k, v in request.headers.items()
                            if k.lower() not in (
                                'host', 'content-length',
                                'x-skytpu-handoff',
                                spans.TRACEPARENT_HEADER)}
                    hdrs[spans.TRACEPARENT_HEADER] = \
                        spans.format_traceparent(leg_ctx)
                    if (self._pool_roles
                            and handoff_eligible(context)
                            and envs.SKYTPU_MIGRATION_ENABLE.get()):
                        # Two-leg route: the prefill replica pauses at
                        # the first token under a lease and exports a
                        # non-terminal handoff frame; _relay_managed
                        # walks the decode-leg ladder when it arrives.
                        hdrs['X-SkyTPU-Handoff'] = '1'
                    upstream = await session.request(
                        request.method, url, data=body,
                        headers=hdrs, allow_redirects=False)
                except (OSError, aiohttp.ClientError) as e:
                    obs.LB_PROXY_ERRORS.inc()
                    self.breaker.record_failure(target)
                    last_error = e
                    leg_attrs['error'] = type(e).__name__
                    # A failed leg makes the trace keep-worthy even if
                    # a later leg succeeds: the breaker-open dump must
                    # contain the requests that fed the breaker.
                    spans.COLLECTOR.mark_error(leg_ctx.trace_id)
                    # Nothing written: fail over to the next replica.
                    continue
                # The replica answered: success for breaker purposes.
                # Errors past this point interleave upstream reads
                # with CLIENT-socket writes — blaming the replica
                # here would let one dead client open circuits on
                # healthy replicas.
                self.breaker.record_success(target)
                leg_attrs['status'] = upstream.status
                # Stream the upstream body chunk-by-chunk: LLM
                # serving fronts SSE/chunked token streams, which
                # must flow as generated, not after completion.
                response = web.StreamResponse(
                    status=upstream.status,
                    headers={k: v
                             for k, v in upstream.headers.items()
                             if k.lower() not in (
                                 'transfer-encoding',
                                 'content-length',
                                 'connection')})
                # Before prepare(): headers are immutable afterwards.
                response.headers[spans.TRACE_ID_RESPONSE_HEADER] = \
                    leg_ctx.trace_id
                try:
                    await response.prepare(request)
                except (OSError, aiohttp.ClientError):
                    # Client socket failed before headers went out.
                    return response
                # Per-READ timeout (not a session-wide sock_read,
                # which would also cap time-to-first-byte and fail
                # slow prefills onto the breaker): only the gap
                # between chunks of an ALREADY-STARTED stream is
                # bounded — a wedged upstream mid-stream must
                # terminate the client's response, not hang it.
                read_gap = envs.SKYTPU_LB_STREAM_READ_TIMEOUT.get()
                mig_key = upstream.headers.get(
                    'X-SkyTPU-Migration-Key')
                if (mig_key and context is not None
                        and upstream.status == 200
                        and (upstream.headers.get('Content-Type')
                             or '').startswith('text/event-stream')
                        and envs.SKYTPU_MIGRATION_ENABLE.get()):
                    # Migratable token stream: relay frame-aware so an
                    # interruption (drain's terminal migrate event, or
                    # the upstream dying mid-stream) can be resumed on
                    # another replica instead of honest-terminated.
                    return await self._relay_managed(
                        request, response, upstream, target, mig_key,
                        context, read_gap, leg_attrs, leg_ctx)
                while True:
                    # Upstream reads and client writes fail for
                    # DIFFERENT parties; keep them in separate try
                    # blocks so a dead replica is never blamed on the
                    # client or vice versa.
                    try:
                        faults.inject('lb.upstream_midstream',
                                      env_exc=OSError)
                        chunk = await asyncio.wait_for(
                            upstream.content.readany(),
                            timeout=read_gap if read_gap > 0
                            else None)
                    except (asyncio.TimeoutError, OSError,
                            aiohttp.ClientError):
                        # The upstream died AFTER bytes went out: a
                        # retry would corrupt the stream, and a clean
                        # write_eof would forge a COMPLETE chunked
                        # response out of a truncated one. The only
                        # honest signal left is closing the client
                        # connection mid-body.
                        obs.LB_PROXY_ERRORS.inc()
                        obs.LB_MIDSTREAM_FAILURES.inc()
                        leg_attrs['midstream_error'] = True
                        spans.COLLECTOR.mark_error(leg_ctx.trace_id)
                        response.force_close()
                        with contextlib.suppress(Exception):
                            request.transport.close()
                        return response
                    if not chunk:
                        break
                    try:
                        await response.write(chunk)
                    except (OSError, aiohttp.ClientError):
                        # The CLIENT went away; the replica is fine.
                        return response
                try:
                    await response.write_eof()
                except (OSError, aiohttp.ClientError):
                    # Client vanished between last chunk and EOF —
                    # also not the replica's fault, and not worth an
                    # unhandled-error traceback.
                    pass
                return response
            finally:
                leg_scope.close()
                self.policy.on_request_end(target)
                if upstream is not None:
                    upstream.close()
                if session is not None:
                    await session.close()
        if last_error is None:
            # Candidates existed but every circuit was open.
            obs.LB_NO_REPLICA.inc()
            return web.Response(
                status=503, headers={'Retry-After': '1'},
                text='All replicas are circuit-open. Retry shortly.\n')
        return web.Response(
            status=502,
            text=f'All {attempted} upstream(s) failed; last error: '
                 f'{last_error}\n')

    async def _relay_managed(self, request, response, upstream,
                             target, mig_key, context, read_gap,
                             leg_attrs, leg_ctx):
        """Frame-aware SSE relay for migratable generate streams.

        Token frames are forwarded verbatim and COUNTED — that count
        is the ground truth of what the client has seen, and rides
        `?sent=` into /internal/restore so the resumed stream starts
        at exactly the next unseen token (no duplicates, no drops).
        Two interruption shapes trigger migration: the upstream
        draining (its terminal `migrate` SSE event carries the blob),
        and the upstream dying mid-read (the blob is fetched from
        /internal/snapshot by migration key — the replica process may
        still be alive behind a dead connection or an injected
        transport fault). Honest termination (PR 9) is the last rung:
        only when migration fails inside its deadline budget.

        A NON-terminal `handoff` frame is the planned two-leg route:
        the prefill replica paused at the first token with the slot
        still live under a lease. The ladder (_handoff_stream) either
        restores onto a decode-pool replica (switch upstreams, drop
        any bytes buffered past the frame — they were never counted
        into `sent`, so the restored stream re-sends them) or resumes
        the SAME upstream co-located (keep reading, buffer intact —
        tokens simply continue). Only if the prefill replica died too
        does it fall through to the crash-migration rung with the
        handoff blob already in hand."""
        import aiohttp
        state = {'sent': 0, 'last_token': time.monotonic()}
        own: List[Any] = []  # (session, upstream) from migrations
        cur_up, cur_target, cur_key = upstream, target, mig_key
        buf = b''
        try:
            while True:
                migrate_payload = None
                handoff_payload = None
                interrupted = False
                while not interrupted and migrate_payload is None \
                        and handoff_payload is None:
                    # Drain frames already buffered BEFORE reading
                    # more: a co-located fallback re-enters here with
                    # leftover bytes that must not be dropped.
                    while b'\n\n' in buf:
                        frame, buf = buf.split(b'\n\n', 1)
                        doc = _sse_frame_doc(frame)
                        if doc is not None and 'migrate' in doc:
                            migrate_payload = doc['migrate']
                            break
                        if doc is not None and 'handoff' in doc:
                            handoff_payload = doc['handoff']
                            break
                        if doc is None or 'token' in doc:
                            if doc is not None:
                                state['sent'] += 1
                                state['last_token'] = time.monotonic()
                            try:
                                await response.write(frame + b'\n\n')
                            except (OSError, aiohttp.ClientError):
                                return response  # client went away
                            continue
                        # done / error: terminal, forward verbatim.
                        try:
                            await response.write(frame + b'\n\n')
                            await response.write_eof()
                        except (OSError, aiohttp.ClientError):
                            pass
                        return response
                    if migrate_payload is not None or \
                            handoff_payload is not None:
                        break
                    try:
                        faults.inject('lb.upstream_midstream',
                                      env_exc=OSError)
                        chunk = await asyncio.wait_for(
                            cur_up.content.readany(),
                            timeout=read_gap if read_gap > 0
                            else None)
                    except (asyncio.TimeoutError, OSError,
                            aiohttp.ClientError):
                        interrupted = True
                        break
                    if not chunk:
                        # EOF without a terminal frame: the upstream
                        # vanished mid-stream.
                        interrupted = True
                        break
                    buf += chunk
                if handoff_payload is not None:
                    res = await self._handoff_stream(
                        context, state, cur_target, cur_key,
                        handoff_payload)
                    if isinstance(res, tuple):
                        # The decode leg owns the request now: close
                        # the prefill leg's response and tell the
                        # replica to drop its copy. Left open, the
                        # lease would expire into a zombie co-located
                        # decode of the SAME tokens — wasted compute
                        # and a spurious fallback count for a handoff
                        # that succeeded.
                        with contextlib.suppress(Exception):
                            cur_up.close()
                        self._spawn_bg(self._abandon_source(
                            cur_target, cur_key))
                        session2, up2, cur_target, cur_key = res
                        own.append((session2, up2))
                        cur_up = up2
                        # Bytes past the handoff frame were never
                        # counted into `sent`; the restored stream
                        # re-sends them from ?sent= on.
                        buf = b''
                        continue
                    if res == 'fallback':
                        # Co-located resume: the prefill replica's
                        # stream (and our buffer) just continues —
                        # degraded success, never an error.
                        continue
                    # The prefill replica is unreachable too: crash
                    # migration is the backstop, and the handoff
                    # payload already carries the blob.
                    migrate_payload = handoff_payload
                new = await self._migrate_stream(
                    context, state, cur_target, cur_key,
                    migrate_payload)
                if new is None:
                    # Failure ladder's last rung: honest termination.
                    obs.LB_PROXY_ERRORS.inc()
                    obs.LB_MIDSTREAM_FAILURES.inc()
                    leg_attrs['midstream_error'] = True
                    spans.COLLECTOR.mark_error(leg_ctx.trace_id)
                    response.force_close()
                    with contextlib.suppress(Exception):
                        request.transport.close()
                    return response
                session2, up2, cur_target, cur_key = new
                own.append((session2, up2))
                cur_up = up2
                buf = b''
                # Loop: the restored stream is itself migratable.
        finally:
            for s, u in own:
                u.close()
                await s.close()

    async def _fetch_snapshot(self, target: str, key: str,
                              deadline: float) -> Optional[bytes]:
        """GET the request's KV snapshot off the interrupted replica
        by migration key; None when it can't be had (replica truly
        dead, request already finished, key unknown)."""
        from aiohttp import ClientSession, ClientTimeout
        import aiohttp
        if not key:
            return None
        budget = deadline - time.monotonic()
        if budget <= 0:
            return None
        try:
            async with ClientSession(timeout=ClientTimeout(
                    total=max(0.1, min(5.0, budget)))) as session:
                async with session.get(
                        target.rstrip('/') + '/internal/snapshot',
                        params={'key': key}) as r:
                    if r.status != 200:
                        return None
                    return await r.read()
        except (OSError, aiohttp.ClientError, asyncio.TimeoutError):
            return None

    async def _migrate_stream(self, context, state, dead_target,
                              dead_key, migrate_payload):
        """Resume one interrupted stream on another replica: blob from
        the drain event (or fetched by key), restored decode-pool-
        first (_restore_candidates — the remainder is decode-only
        work) under the migration deadline budget. Returns (session,
        upstream, target, new_key) or None — the caller
        honest-terminates on None."""
        from aiohttp import ClientSession, ClientTimeout
        import aiohttp
        policy = retries.RetryPolicy(
            deadline=envs.SKYTPU_MIGRATION_DEADLINE_SECONDS.get(),
            base_delay=0.1, max_delay=1.0)
        deadline = time.monotonic() + (policy.deadline or 0.0)
        obs.MIGRATION_ATTEMPTS.inc()
        t0 = time.monotonic()
        attrs: Dict[str, Any] = {'from': dead_target,
                                 'sent': state['sent']}
        with spans.span('lb.migrate', attrs=attrs):
            try:
                faults.inject('lb.migrate', env_exc=OSError)
                blob: Optional[bytes] = None
                if migrate_payload is not None:
                    try:
                        blob = base64.b64decode(
                            migrate_payload.get('snapshot') or '')
                    except (ValueError, TypeError):
                        blob = None
                if not blob:
                    blob = await self._fetch_snapshot(
                        dead_target, dead_key, deadline)
                if not blob:
                    raise OSError('no snapshot available for the '
                                  'interrupted stream')
                if len(blob) > envs.SKYTPU_MIGRATION_MAX_BYTES.get():
                    raise OSError(
                        f'snapshot is {len(blob)} bytes, over '
                        'SKYTPU_MIGRATION_MAX_BYTES')
                attrs['blob_bytes'] = len(blob)
                delay = policy.base_delay
                while True:
                    candidates = self._restore_candidates(context)
                    for cand in candidates or ():
                        if cand == dead_target or \
                                not self.breaker.allow(cand):
                            continue
                        if time.monotonic() >= deadline:
                            break
                        url = (cand.rstrip('/') + '/internal/restore'
                               f'?sent={state["sent"]}&stream=1')
                        session = ClientSession(
                            timeout=ClientTimeout(total=3600))
                        try:
                            up = await session.request(
                                'POST', url, data=blob,
                                headers={'Content-Type':
                                         'application/octet-stream'})
                        except (OSError, aiohttp.ClientError):
                            await session.close()
                            self.breaker.record_failure(cand)
                            continue
                        if up.status == 400:
                            # The blob itself is bad — no other
                            # replica will accept it either.
                            up.close()
                            await session.close()
                            raise OSError(
                                'restore rejected the snapshot blob')
                        if up.status != 200:
                            # Capacity/draining (409/503): next one.
                            up.close()
                            await session.close()
                            continue
                        self.breaker.record_success(cand)
                        attrs['to'] = cand
                        obs.MIGRATION_SUCCESSES.inc()
                        obs.MIGRATION_SECONDS.observe(
                            time.monotonic() - t0)
                        obs.MIGRATION_INTERRUPTION_SECONDS.observe(
                            time.monotonic() - state['last_token'])
                        return (session, up, cand,
                                up.headers.get(
                                    'X-SkyTPU-Migration-Key') or '')
                    if time.monotonic() + delay >= deadline:
                        raise OSError('no replica could restore the '
                                      'stream inside the migration '
                                      'deadline')
                    # READY sets change under us (a drained replica's
                    # successor registering): wait and re-list.
                    await asyncio.sleep(delay)
                    delay = min(delay * 2, policy.max_delay)
            except (OSError, aiohttp.ClientError) as e:
                attrs['error'] = str(e)
                obs.MIGRATION_FAILURES.inc()
                return None

    async def _handoff_stream(self, context, state, src_target,
                              src_key, payload):
        """Walk the planned prefill->decode handoff ladder for one
        paused stream. Rungs, in order:

        1. Restore onto a decode-pool candidate (_restore_candidates,
           breaker-allowed, source excluded) under the
           SKYTPU_HANDOFF_DEADLINE_SECONDS retry budget; the blob is
           capped by SKYTPU_HANDOFF_MAX_BYTES.
        2. On exhaustion, POST /internal/resume on the prefill
           replica: its slot is still live under the lease, so the
           co-located fallback is a state transition — the client
           stream just continues. Counted as a handoff fallback,
           never surfaced as an error.

        Returns (session, upstream, target, new_key) after a
        decode-leg restore, 'fallback' after a co-located resume, or
        None when the prefill replica is unreachable too — the caller
        then falls through to the crash-migration backstop with the
        blob in hand."""
        from aiohttp import ClientSession, ClientTimeout
        import aiohttp
        obs.HANDOFF_ATTEMPTS.inc()
        policy = retries.RetryPolicy(
            deadline=envs.SKYTPU_HANDOFF_DEADLINE_SECONDS.get(),
            base_delay=0.05, max_delay=0.5)
        t0 = time.monotonic()
        deadline = t0 + (policy.deadline or 0.0)
        attrs: Dict[str, Any] = {'from': src_target,
                                 'sent': state['sent']}
        with spans.span('lb.handoff', attrs=attrs):
            try:
                faults.inject('lb.handoff', env_exc=OSError)
                try:
                    blob = base64.b64decode(
                        payload.get('snapshot') or '')
                except (ValueError, TypeError):
                    blob = b''
                if not blob:
                    raise OSError('handoff frame carried no snapshot')
                if len(blob) > envs.SKYTPU_HANDOFF_MAX_BYTES.get():
                    raise OSError(
                        f'handoff blob is {len(blob)} bytes, over '
                        'SKYTPU_HANDOFF_MAX_BYTES')
                attrs['blob_bytes'] = len(blob)
                delay = policy.base_delay
                while True:
                    candidates = [
                        c for c in self._restore_candidates(context)
                        if c != src_target]
                    if not candidates:
                        # Nothing to wait for: a one-replica fleet
                        # resumes co-located immediately.
                        raise OSError('no other replica to take the '
                                      'decode leg')
                    for cand in candidates:
                        if not self.breaker.allow(cand):
                            continue
                        if time.monotonic() >= deadline:
                            break
                        url = (cand.rstrip('/') + '/internal/restore'
                               f'?sent={state["sent"]}&stream=1')
                        session = ClientSession(
                            timeout=ClientTimeout(total=3600))
                        try:
                            up = await session.request(
                                'POST', url, data=blob,
                                headers={'Content-Type':
                                         'application/octet-stream'})
                        except (OSError, aiohttp.ClientError):
                            await session.close()
                            self.breaker.record_failure(cand)
                            continue
                        if up.status == 400:
                            # Bad blob: no replica will take it; the
                            # co-located original is still decodable.
                            up.close()
                            await session.close()
                            raise OSError(
                                'restore rejected the handoff blob')
                        if up.status != 200:
                            # Capacity/draining (409/503): next one.
                            up.close()
                            await session.close()
                            continue
                        self.breaker.record_success(cand)
                        attrs['to'] = cand
                        obs.HANDOFF_SUCCESSES.inc()
                        obs.HANDOFF_TRANSFER_SECONDS.observe(
                            time.monotonic() - t0)
                        state['last_token'] = time.monotonic()
                        return (session, up, cand,
                                up.headers.get(
                                    'X-SkyTPU-Migration-Key') or '')
                    if time.monotonic() + delay >= deadline:
                        raise OSError(
                            'no decode-pool replica took the handoff '
                            'inside SKYTPU_HANDOFF_DEADLINE_SECONDS')
                    await asyncio.sleep(delay)
                    delay = min(delay * 2, policy.max_delay)
            except (OSError, aiohttp.ClientError) as e:
                attrs['error'] = str(e)
            status = await self._resume_local(src_target, src_key)
            if status is not None:
                attrs['fallback'] = 'resume'
                if status == 'resumed':
                    # 'active' means the lease already expired and
                    # the ENGINE counted the fallback — counting here
                    # too would double it.
                    obs.HANDOFF_FALLBACKS.inc()
                state['last_token'] = time.monotonic()
                return 'fallback'
            # The prefill replica is gone too; the lease would have
            # resumed it if it were alive. Crash migration (caller)
            # is the remaining rung.
            attrs['fallback'] = 'migrate'
            return None

    async def _abandon_source(self, target: str, key: str) -> None:
        """Best-effort: tell the prefill replica its copy of a
        handed-off request is no longer needed (the decode-leg
        restore was confirmed) so the lease-paused slot frees now.
        Failure is harmless — the replica's own lease expiry (or the
        write failure on our closed connection) reclaims the slot
        eventually; this call only makes it prompt and keeps the
        fallback counter honest."""
        from aiohttp import ClientSession, ClientTimeout
        if not key:
            return
        with contextlib.suppress(Exception):
            async with ClientSession(
                    timeout=ClientTimeout(total=5.0)) as session:
                async with session.post(
                        target.rstrip('/') + '/internal/resume',
                        params={'key': key, 'abandon': '1'}):
                    pass

    async def _resume_local(self, target: str,
                            key: str) -> Optional[str]:
        """POST /internal/resume?key= on the prefill replica: flips
        the lease-paused slot back to decoding — cheap, in-place, and
        the already-open stream continues by itself. Returns the
        replica's status ('resumed', or 'active' when the lease had
        already expired and the slot resumed itself), or None when
        the replica can't be reached or no longer knows the key."""
        from aiohttp import ClientSession, ClientTimeout
        import aiohttp
        if not key:
            return None
        try:
            async with ClientSession(
                    timeout=ClientTimeout(total=5.0)) as session:
                async with session.post(
                        target.rstrip('/') + '/internal/resume',
                        params={'key': key}) as r:
                    if r.status != 200:
                        return None
                    try:
                        doc = await r.json()
                    except (ValueError, aiohttp.ClientError):
                        return 'resumed'
                    return str(doc.get('status') or 'resumed')
        except (OSError, aiohttp.ClientError, asyncio.TimeoutError):
            return None

    async def _handle_trace(self, request):
        """Merged trace view: the LB's own spans for a trace id plus,
        best-effort, whatever each ready replica's /internal/trace
        knows about it — one query returns the LB leg AND the
        replica's server/engine phases under one tree."""
        from aiohttp import ClientSession, ClientTimeout, web
        import aiohttp
        trace_id = request.query.get('trace_id')
        if not trace_id:
            trees = spans.COLLECTOR.recent_trees()
            return web.json_response({'traces': [
                {'trace_id': t['trace_id'], 'error': t['error'],
                 'duration': t['duration'],
                 'spans': len(t['spans'])} for t in trees]})
        records = list(spans.COLLECTOR.spans_for(trace_id))
        for target in list(self.policy.replicas):
            url = target.rstrip('/') + '/internal/trace'
            try:
                async with ClientSession(
                        timeout=ClientTimeout(total=2)) as session:
                    async with session.get(
                            url, params={'trace_id': trace_id}) as r:
                        if r.status != 200:
                            continue
                        doc = await r.json()
            except (OSError, aiohttp.ClientError, ValueError,
                    asyncio.TimeoutError):
                # A replica that is down (or never saw the trace)
                # contributes nothing; the LB's own legs still render.
                continue
            records.extend(doc.get('spans') or [])
        if not records:
            return web.json_response(
                {'error': f'unknown trace_id {trace_id!r} (dropped by '
                          'sampling, evicted, or never seen here)'},
                status=404)
        return web.json_response({
            'trace_id': trace_id,
            'spans': records,
            'tree': spans.tree_view(records),
            'traceEvents':
                spans.to_chrome_trace(records)['traceEvents'],
        })

    # -- fleet telemetry federation -------------------------------------------

    def _scrape_replicas(self, wd: watchdog_lib.Watchdog) -> None:
        """Watchdog pre_tick: pull every replica's retained series
        (incrementally, via `since=`) into the shared store under a
        `replica=<url>` label, and write the synthetic
        skytpu_replica_up gauge per scrape outcome. Runs in the
        watchdog's own thread — blocking urllib is fine here and
        keeps the proxy's event loop out of it entirely."""
        import urllib.request
        store = wd.store
        for target in list(self.policy.replicas):
            url = (target.rstrip('/') + '/internal/timeseries')
            since = self._scrape_since.get(target)
            if since is not None:
                url += f'?since={since}'
            up = 0.0
            try:
                with urllib.request.urlopen(url, timeout=2) as r:
                    doc = json.loads(r.read().decode('utf-8'))
                store.ingest_dump(doc, extra_labels={'replica': target})
                self._scrape_since[target] = float(
                    doc.get('now') or 0.0) or self._scrape_since.get(
                        target, 0.0)
                up = 1.0
            except (OSError, ValueError):
                pass
            store.add_sample('skytpu_replica_up', {'replica': target},
                             up, now=wd.now_fn())

    def _fleet_rules(self) -> List[Any]:
        """The LB's live rules: whatever SKYTPU_WATCHDOG_RULES /
        anomaly defaults say, plus replica liveness over the CURRENT
        replica set — membership is re-read each tick, so pruning a
        dead replica from the set clears its alert."""
        rules = watchdog_lib.default_rules()
        rules.append(watchdog_lib.ReplicaUp(
            'replica_up',
            replicas_fn=lambda: list(self.policy.replicas)))
        return rules

    def _create_app(self):
        from aiohttp import web
        app = web.Application(client_max_size=1024 * 1024 * 256)
        app.router.add_get('/internal/stats', self._handle_stats)
        app.router.add_get('/internal/trace', self._handle_trace)
        # Registered before the catch-all proxy: the LB's own metrics,
        # not a replica's (a replica's /metrics is scraped directly).
        app.router.add_get('/metrics', metrics_lib.aiohttp_handler)
        # Fleet-merged telemetry: the store behind these holds the
        # LB's own series plus every replica's (replica-labeled), so
        # one curl localizes a regression to a replica or the fleet.
        app.router.add_get('/internal/timeseries',
                           timeseries_lib.aiohttp_handler)
        app.router.add_get('/internal/alerts',
                           watchdog_lib.aiohttp_handler)
        if self._watchdog is not None:
            app['skytpu_watchdog'] = self._watchdog
        app.router.add_route('*', '/{tail:.*}', self._handle_proxy)
        return app

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> int:
        """Start in a daemon thread; returns the bound port."""
        # The telemetry plane rides the LB lifecycle: local registry
        # sampler plus a federated watchdog whose every tick first
        # scrapes the replicas' series (each a no-op when its
        # interval knob is 0).
        timeseries_lib.start_sampler()
        if envs.SKYTPU_WATCHDOG_TICK_SECONDS.get() > 0:
            self._watchdog = watchdog_lib.Watchdog(
                rules=self._fleet_rules(),
                pre_tick=self._scrape_replicas)
            self._watchdog.start()
        ready = threading.Event()

        def _serve():
            from aiohttp import web
            self._loop = asyncio.new_event_loop()
            asyncio.set_event_loop(self._loop)

            async def _start():
                self._runner = web.AppRunner(self._create_app())
                await self._runner.setup()
                site = web.TCPSite(self._runner, '0.0.0.0', self.port)
                await site.start()
                self.port = site._server.sockets[0].getsockname()[1]  # noqa: SLF001
            self._loop.run_until_complete(_start())
            ready.set()
            self._loop.run_forever()

        self._thread = threading.Thread(target=_serve, daemon=True)
        self._thread.start()
        if not ready.wait(timeout=10):
            raise RuntimeError('Load balancer failed to start')
        return self.port

    def stop(self) -> None:
        if self._watchdog is not None:
            self._watchdog.stop()
            self._watchdog = None
        if self._loop is not None:
            async def _cleanup():
                if self._runner is not None:
                    await self._runner.cleanup()
            fut = asyncio.run_coroutine_threadsafe(_cleanup(), self._loop)
            fut.result(timeout=10)
            self._loop.call_soon_threadsafe(self._loop.stop)
            if self._thread is not None:
                self._thread.join(timeout=10)
