"""Load balancer: aiohttp reverse proxy in front of ready replicas.

Reference analog: sky/serve/load_balancer.py:23 (`SkyServeLoadBalancer`
— FastAPI proxy syncing replica URLs from the controller). Ours embeds a
QPS window the controller's autoscaler reads via /internal/stats.
"""
import asyncio
import collections
import contextlib
import threading
import time
from typing import List, Optional

from skypilot_tpu.observability import instruments as obs
from skypilot_tpu.observability import metrics as metrics_lib
from skypilot_tpu.resilience import circuit
from skypilot_tpu.resilience import faults
from skypilot_tpu.serve import load_balancing_policies as lb_policies

_QPS_WINDOW_SECONDS = 60.0


class RequestRateTracker:
    def __init__(self) -> None:
        self._times = collections.deque()
        self._lock = threading.Lock()

    def record(self) -> None:
        with self._lock:
            self._times.append(time.time())

    def qps(self) -> float:
        cutoff = time.time() - _QPS_WINDOW_SECONDS
        with self._lock:
            while self._times and self._times[0] < cutoff:
                self._times.popleft()
            return len(self._times) / _QPS_WINDOW_SECONDS


class LoadBalancer:
    def __init__(self, policy_name: str = 'least_load',
                 port: int = 0) -> None:
        self.policy = lb_policies.make_policy(policy_name)
        self.port = port
        self.tracker = RequestRateTracker()
        # Replica endpoints that keep failing at the transport layer
        # get routed around instead of 502ing live traffic.
        self.breaker = circuit.CircuitBreaker(
            'lb', failure_threshold=3, recovery_timeout=15.0)
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._runner = None
        self._thread: Optional[threading.Thread] = None

    def set_replicas(self, urls: List[str]) -> None:
        old = set(self.policy.replicas) - set(urls)
        self.policy.set_replicas(urls)
        for gone in old:
            self.breaker.forget(gone)

    def _candidates(self) -> List[str]:
        """Upstream try-order: the policy's pick first, then every
        other replica — a failed upstream must not 502 the client
        while healthy replicas exist."""
        first = self.policy.select()
        if first is None:
            return []
        rest = [r for r in self.policy.replicas if r != first]
        return [first] + rest

    # -- aiohttp handlers ----------------------------------------------------

    async def _handle_stats(self, request):
        from aiohttp import web
        return web.json_response({
            'qps': self.tracker.qps(),
            'replicas': list(self.policy.replicas),
        })

    async def _handle_proxy(self, request):
        from aiohttp import ClientSession, ClientTimeout, web
        import aiohttp
        self.tracker.record()
        candidates = self._candidates()
        if not candidates:
            obs.LB_NO_REPLICA.inc()
            return web.Response(
                status=503, headers={'Retry-After': '1'},
                text='No ready replicas. Retry shortly.\n')
        body = await request.read()
        tail = request.match_info['tail']
        last_error: Optional[BaseException] = None
        attempted = 0
        for target in candidates:
            if not self.breaker.allow(target):
                continue
            attempted += 1
            if attempted > 1:
                obs.LB_UPSTREAM_RETRIES.inc()
            obs.LB_REPLICA_REQUESTS.labels(replica=target).inc()
            url = target.rstrip('/') + '/' + tail
            if request.query_string:
                url += f'?{request.query_string}'
            self.policy.on_request_start(target)
            session = upstream = None
            try:
                # Phase 1 — contact the upstream. Failures here are
                # the REPLICA's: feed the breaker, fail over.
                try:
                    faults.inject('lb.upstream', env_exc=OSError)
                    session = ClientSession(
                        timeout=ClientTimeout(total=3600))
                    upstream = await session.request(
                        request.method, url, data=body,
                        headers={k: v
                                 for k, v in request.headers.items()
                                 if k.lower() not in (
                                     'host', 'content-length')},
                        allow_redirects=False)
                except (OSError, aiohttp.ClientError) as e:
                    obs.LB_PROXY_ERRORS.inc()
                    self.breaker.record_failure(target)
                    last_error = e
                    # Nothing written: fail over to the next replica.
                    continue
                # The replica answered: success for breaker purposes.
                # Errors past this point interleave upstream reads
                # with CLIENT-socket writes — blaming the replica
                # here would let one dead client open circuits on
                # healthy replicas.
                self.breaker.record_success(target)
                # Stream the upstream body chunk-by-chunk: LLM
                # serving fronts SSE/chunked token streams, which
                # must flow as generated, not after completion.
                response = web.StreamResponse(
                    status=upstream.status,
                    headers={k: v
                             for k, v in upstream.headers.items()
                             if k.lower() not in (
                                 'transfer-encoding',
                                 'content-length',
                                 'connection')})
                try:
                    await response.prepare(request)
                    async for chunk in \
                            upstream.content.iter_chunked(64 * 1024):
                        await response.write(chunk)
                    await response.write_eof()
                    return response
                except (OSError, aiohttp.ClientError):
                    obs.LB_PROXY_ERRORS.inc()
                    # Headers (and possibly bytes) may already be
                    # out: a retry would corrupt the stream — the
                    # only honest signal left is truncating it.
                    with contextlib.suppress(Exception):
                        await response.write_eof()
                    return response
            finally:
                self.policy.on_request_end(target)
                if upstream is not None:
                    upstream.close()
                if session is not None:
                    await session.close()
        if last_error is None:
            # Candidates existed but every circuit was open.
            obs.LB_NO_REPLICA.inc()
            return web.Response(
                status=503, headers={'Retry-After': '1'},
                text='All replicas are circuit-open. Retry shortly.\n')
        return web.Response(
            status=502,
            text=f'All {attempted} upstream(s) failed; last error: '
                 f'{last_error}\n')

    def _create_app(self):
        from aiohttp import web
        app = web.Application(client_max_size=1024 * 1024 * 256)
        app.router.add_get('/internal/stats', self._handle_stats)
        # Registered before the catch-all proxy: the LB's own metrics,
        # not a replica's (a replica's /metrics is scraped directly).
        app.router.add_get('/metrics', metrics_lib.aiohttp_handler)
        app.router.add_route('*', '/{tail:.*}', self._handle_proxy)
        return app

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> int:
        """Start in a daemon thread; returns the bound port."""
        ready = threading.Event()

        def _serve():
            from aiohttp import web
            self._loop = asyncio.new_event_loop()
            asyncio.set_event_loop(self._loop)

            async def _start():
                self._runner = web.AppRunner(self._create_app())
                await self._runner.setup()
                site = web.TCPSite(self._runner, '0.0.0.0', self.port)
                await site.start()
                self.port = site._server.sockets[0].getsockname()[1]  # noqa: SLF001
            self._loop.run_until_complete(_start())
            ready.set()
            self._loop.run_forever()

        self._thread = threading.Thread(target=_serve, daemon=True)
        self._thread.start()
        if not ready.wait(timeout=10):
            raise RuntimeError('Load balancer failed to start')
        return self.port

    def stop(self) -> None:
        if self._loop is not None:
            async def _cleanup():
                if self._runner is not None:
                    await self._runner.cleanup()
            fut = asyncio.run_coroutine_threadsafe(_cleanup(), self._loop)
            fut.result(timeout=10)
            self._loop.call_soon_threadsafe(self._loop.stop)
            if self._thread is not None:
                self._thread.join(timeout=10)
