"""Autoscalers: QPS-target scaling with hysteresis + load signals.

Reference analog: sky/serve/autoscalers.py (`Autoscaler` :116,
`RequestRateAutoscaler` :441: target_qps_per_replica with
upscale/downscale delays so transient spikes/dips don't thrash).
Beyond the reference: `LoadSignals` feeds engine-side pressure (queue
depth, KV-cache utilization from the `skytpu_*` registry) into the
same hysteresis pipeline, so scaling can react to saturation the
request *rate* alone can't see (long prompts, slow decodes).
"""
import dataclasses
import time
from typing import Dict, List, Optional

from skypilot_tpu.serve import service_spec as spec_lib


@dataclasses.dataclass
class ScalingDecision:
    target_replicas: int
    reason: str = ''


@dataclasses.dataclass(frozen=True)
class LoadSignals:
    """One reading of the fleet's load beyond raw request rate.

    queue_depth is requests accepted but not yet decoding; kv_util
    is the mean fraction of KV-cache positions holding live tokens
    (0-1); ttft_p95 / decode_step_p95 are windowed latency quantiles
    (seconds) resolved from histogram bucket deltas — the saturation
    signals the per-pool autoscalers breach-test. None means "signal
    unavailable" — scaling then falls back to whatever signals
    remain (ultimately request rate).
    """
    queue_depth: Optional[float] = None
    kv_util: Optional[float] = None
    ttft_p95: Optional[float] = None
    decode_step_p95: Optional[float] = None


# Below this many histogram samples in a read window, a p95 is noise,
# not a signal — report it unavailable instead.
_P95_MIN_SAMPLES = 5


class MetricsSignalSource:
    """Reads LoadSignals off THIS process's skytpu_* registry — the
    same series /metrics exposes, so what the autoscaler acted on is
    always scrape-able after the fact.

    Gauges (queue depth, KV utilization) read instantaneously, with
    per-pool series (skytpu_pool_queue_depth{pool=...}) preferred and
    the fleet-wide gauge as fallback when a pool series was never
    written. Latency p95s resolve from histogram bucket DELTAS
    between successive read_pools() calls (the same
    bucket-upper-bound convention fleetsim's SLO evaluator uses), so
    one controller tick sees that tick's latency, not the process
    lifetime's.

    Scope caveat: these series are written by whatever shares the
    process — the fleet simulator's SimFleet, or a co-located engine.
    A production controller whose replicas run elsewhere reads 0.0
    (signals absent, scaling falls back to request rate) until a
    scraping source is wired in: the controller takes any object with
    read()/read_pools() via its signal_source seam, and aggregating
    replica /metrics into one is the ROADMAP item-2 follow-up.

    The histogram windows live in the shared time-series ring
    (observability/timeseries.py): each read_pools() call appends one
    targeted sample of just its two histograms and resolves the p95
    from the bucket delta since its previous call — the identical
    window any operator can query back out of /internal/timeseries,
    instead of private snapshot bookkeeping only this object saw."""

    def __init__(self, ttft_metric: str = 'skytpu_prefill_seconds',
                 decode_step_metric: str = 'skytpu_decode_step_seconds',
                 store=None, now_fn=None) -> None:
        self.ttft_metric = ttft_metric
        self.decode_step_metric = decode_step_metric
        self._store = store
        self._now_fn = now_fn
        self._last_read: Optional[float] = None

    def _pool_gauge(self, gauge, pool: Optional[str],
                    fallback) -> float:
        """Per-pool series when it exists, fleet-wide otherwise: a
        never-written labeled gauge reads 0.0 through value(), which
        would look like 'no pressure' — existence-check instead."""
        if pool is not None:
            for series, labels, value in gauge.samples():
                if dict(labels).get('pool') == pool:
                    return value
        return fallback.value()

    def _p95_delta(self, metric_name: str, now: float
                   ) -> Optional[float]:
        import math
        store = self._resolved_store()
        # since=None on the first read means "everything so far" —
        # the same lifetime-baseline first reading the old private
        # snapshots produced.
        delta = store.hist_delta(metric_name, window=None, now=now,
                                 since=self._last_read)
        if delta is None:
            return None
        buckets, count = delta
        if count < _P95_MIN_SAMPLES:
            return None
        top_finite = None
        for bound, cum in sorted(buckets):
            if bound != math.inf:
                top_finite = bound
            if cum >= 0.95 * count:
                # A p95 past the top finite bucket is still a BREACH
                # signal, not a missing one: report the top finite
                # bound as a known floor — returning None here would
                # blind the pool autoscaler exactly at worst
                # saturation.
                return top_finite if bound == math.inf else bound
        return None

    def _resolved_store(self):
        if self._store is None:
            from skypilot_tpu.observability import timeseries
            self._store = timeseries.STORE
        return self._store

    def read(self) -> LoadSignals:
        from skypilot_tpu.observability import instruments as obs
        return LoadSignals(queue_depth=obs.QUEUE_DEPTH.value(),
                           kv_util=obs.KV_CACHE_UTILIZATION.value())

    def read_pools(self, pools) -> Dict[Optional[str], LoadSignals]:
        """One snapshot for all pools: the histogram windows are
        consumed ONCE per call (per-pool calls would hand the delta
        to whichever pool asked first)."""
        from skypilot_tpu.observability import instruments as obs
        now = (self._now_fn or time.time)()
        # One targeted sample of just our two histograms — the whole
        # registry is the background Sampler's job, not the
        # controller tick's.
        self._resolved_store().sample_now(
            now=now, names=(self.ttft_metric,
                            self.decode_step_metric))
        ttft_p95 = self._p95_delta(self.ttft_metric, now)
        decode_p95 = self._p95_delta(self.decode_step_metric, now)
        self._last_read = now
        out: Dict[Optional[str], LoadSignals] = {}
        for pool in pools:
            out[pool] = LoadSignals(
                queue_depth=self._pool_gauge(
                    obs.POOL_QUEUE_DEPTH, pool, obs.QUEUE_DEPTH),
                kv_util=self._pool_gauge(
                    obs.POOL_KV_UTILIZATION, pool,
                    obs.KV_CACHE_UTILIZATION),
                ttft_p95=ttft_p95,
                decode_step_p95=decode_p95)
        return out


class Autoscaler:
    def __init__(self, spec: spec_lib.ServiceSpec) -> None:
        self.spec = spec

    def update_spec(self, spec: spec_lib.ServiceSpec) -> None:
        self.spec = spec

    def decide(self, num_ready: int, num_total: int,
               qps: Optional[float],
               signals: Optional[LoadSignals] = None) -> ScalingDecision:
        raise NotImplementedError


class FixedReplicaAutoscaler(Autoscaler):
    """No autoscaling: hold min_replicas."""

    def decide(self, num_ready: int, num_total: int,
               qps: Optional[float],
               signals: Optional[LoadSignals] = None) -> ScalingDecision:
        return ScalingDecision(self.spec.min_replicas, 'fixed')


class RequestRateAutoscaler(Autoscaler):
    """Scale so qps/replica ~= target, with upscale/downscale delays."""

    def __init__(self, spec: spec_lib.ServiceSpec,
                 now_fn=time.time) -> None:
        super().__init__(spec)
        self._now = now_fn
        self._upscale_since: Optional[float] = None
        self._downscale_since: Optional[float] = None

    def _desired(self, qps: float,
                 signals: Optional[LoadSignals] = None) -> int:
        import math
        target = self.spec.target_qps_per_replica
        desired = math.ceil(qps / target) if target else \
            self.spec.min_replicas
        # Pressure signals only ever RAISE the rate-derived target:
        # queue depth / KV saturation mean the current fleet is behind
        # even if qps looks fine; their absence (or low values) must
        # not fight the rate signal downward.
        if signals is not None:
            tqd = self.spec.target_queue_per_replica
            if tqd and signals.queue_depth:
                desired = max(desired,
                              math.ceil(signals.queue_depth / tqd))
            kv_hi = self.spec.kv_util_upscale_threshold
            if kv_hi is not None and signals.kv_util is not None and \
                    signals.kv_util >= kv_hi:
                # Saturated caches: one more replica per decision
                # round — bounded pressure relief, hysteresis still
                # paces the actual resize.
                desired += 1
        lo = self.spec.min_replicas
        hi = self.spec.max_replicas or max(lo, desired)
        return max(lo, min(hi, desired))

    def decide(self, num_ready: int, num_total: int,
               qps: Optional[float],
               signals: Optional[LoadSignals] = None) -> ScalingDecision:
        if qps is None:
            return ScalingDecision(max(num_total, self.spec.min_replicas),
                                   'no traffic data')
        desired = self._desired(qps, signals)
        now = self._now()
        if desired > num_total:
            self._downscale_since = None
            if self._upscale_since is None:
                self._upscale_since = now
            if now - self._upscale_since >= self.spec.upscale_delay_seconds:
                self._upscale_since = None
                return ScalingDecision(
                    desired, f'qps={qps:.2f} sustained above target')
            return ScalingDecision(num_total, 'upscale pending delay')
        if desired < num_total:
            self._upscale_since = None
            if self._downscale_since is None:
                self._downscale_since = now
            if now - self._downscale_since >= \
                    self.spec.downscale_delay_seconds:
                self._downscale_since = None
                return ScalingDecision(
                    desired, f'qps={qps:.2f} sustained below target')
            return ScalingDecision(num_total, 'downscale pending delay')
        self._upscale_since = None
        self._downscale_since = None
        return ScalingDecision(num_total, 'at target')


@dataclasses.dataclass
class MixedScalingDecision:
    """Spot + on-demand targets (reference FallbackRequestRateAutoscaler,
    autoscalers.py:557)."""
    target_spot: int
    target_ondemand: int
    reason: str = ''

    @property
    def target_replicas(self) -> int:
        return self.target_spot + self.target_ondemand


class FallbackRequestRateAutoscaler(RequestRateAutoscaler):
    """Request-rate scaling over a spot fleet with on-demand fallback.

    The traffic-derived target is served by spot. On top of that:
    - base_ondemand_fallback_replicas are ALWAYS on-demand (a safety
      floor that survives any spot stockout);
    - with dynamic_ondemand_fallback, spot capacity lost to preemption
      is covered by extra on-demand replicas until spot recovers.
    """

    def decide_mixed(self, num_ready_spot: int, num_spot: int,
                     num_ondemand: int,
                     qps: Optional[float],
                     signals: Optional[LoadSignals] = None
                     ) -> MixedScalingDecision:
        base = self.spec.base_ondemand_fallback_replicas
        dynamic = self.spec.dynamic_ondemand_fallback
        current = num_spot + num_ondemand
        # Hysteresis-filtered total target over the whole fleet.
        total = self.decide(num_ready_spot + num_ondemand, current,
                            qps, signals).target_replicas
        if total == current:
            # Hold: no resize is due (at target, or a scale is pending
            # its hysteresis delay) — keep the pools as they are, only
            # covering unready spot with on-demand if dynamic.
            spot_target, ondemand_target = num_spot, num_ondemand
            if dynamic:
                shortfall = max(0, num_spot - num_ready_spot)
                # Cap the cover at what the RATE actually needs beyond
                # ready spot. Capping at the hysteresis-held `total`
                # (== current) compounds instead: every tick's cover
                # inflates `current`, which licenses a bigger cover
                # next tick — during a spot stockout that launched
                # shortfall-many NEW on-demand replicas per tick,
                # unboundedly (caught by the fleetsim preemption_wave
                # soak: 4416 replicas driven for a 300-replica fleet).
                if qps is None:
                    cover_cap = num_ondemand
                else:
                    cover_cap = max(0, self._desired(qps, signals)
                                    - num_ready_spot)
                ondemand_target = min(num_ondemand + shortfall,
                                      max(num_ondemand, cover_cap))
                if self.spec.max_replicas is not None:
                    # The user's hard spend ceiling outranks cover:
                    # spot pool + cover together never exceed it.
                    ondemand_target = min(
                        ondemand_target,
                        max(0, self.spec.max_replicas - num_spot))
        else:
            spot_target = max(0, total - base)
            ondemand_target = min(base, total)
            if dynamic:
                # Cover the spot shortfall (requested minus ready) with
                # on-demand; shrinks automatically as spot recovers.
                shortfall = max(0, spot_target - num_ready_spot)
                ondemand_target = min(total, ondemand_target + shortfall)
        return MixedScalingDecision(
            spot_target, ondemand_target,
            f'total={total} spot_ready={num_ready_spot}')


class PoolAutoscaler(RequestRateAutoscaler):
    """Signal-driven scaling for ONE named replica pool.

    The pool's role picks its saturation signals via the PoolSpec
    thresholds: a prefill pool scales on queue depth + TTFT p95, a
    decode pool on KV utilization + decode-step p95 — never raw
    request rate alone (target_qps_per_replica is optional and, when
    set, interprets the FLEET rate as a floor, since per-pool request
    rates are not separable at the tracker). Inherits the
    upscale/downscale hysteresis so p95 blips don't thrash the pool.
    """

    def __init__(self, pool: spec_lib.PoolSpec,
                 now_fn=time.time) -> None:
        # PoolSpec quacks like the spec the hysteresis base class
        # reads (min/max_replicas, delays); Autoscaler.__init__ just
        # stores it.
        super().__init__(pool, now_fn=now_fn)

    def _desired(self, qps: float,
                 signals: Optional[LoadSignals] = None) -> int:
        import math
        p = self.spec
        desired = p.min_replicas
        if p.target_qps_per_replica:
            desired = max(desired,
                          math.ceil(qps / p.target_qps_per_replica))
        # Pressure signals only ever RAISE the target (same rule as
        # the fleet-wide autoscaler): their absence must not fight
        # the other signals downward.
        if signals is not None:
            if p.target_queue_per_replica and signals.queue_depth:
                desired = max(
                    desired, math.ceil(signals.queue_depth
                                       / p.target_queue_per_replica))
            for value, threshold in (
                    (signals.kv_util, p.kv_util_upscale_threshold),
                    (signals.ttft_p95, p.ttft_p95_upscale_threshold),
                    (signals.decode_step_p95,
                     p.decode_step_p95_upscale_threshold)):
                if threshold is not None and value is not None and \
                        value >= threshold:
                    # One extra replica per breached signal per
                    # decision round: bounded relief, hysteresis
                    # still paces the resize.
                    desired += 1
        hi = p.max_replicas if p.max_replicas is not None else \
            max(p.min_replicas, desired)
        return max(p.min_replicas, min(hi, desired))


def make_pool_autoscalers(spec: spec_lib.ServiceSpec,
                          now_fn=time.time
                          ) -> Dict[str, PoolAutoscaler]:
    """One PoolAutoscaler per named pool (empty for poolless specs)."""
    if not spec.pools:
        return {}
    return {name: PoolAutoscaler(pool, now_fn=now_fn)
            for name, pool in spec.pools.items()}


def make_autoscaler(spec: spec_lib.ServiceSpec,
                    now_fn=time.time) -> Autoscaler:
    """now_fn is the hysteresis clock seam: the fleet simulator runs
    upscale/downscale delays on a virtual clock, production uses
    time.time."""
    if spec.use_spot and (spec.base_ondemand_fallback_replicas > 0
                          or spec.dynamic_ondemand_fallback):
        return FallbackRequestRateAutoscaler(spec, now_fn=now_fn)
    if spec.max_replicas is not None and \
            spec.max_replicas > spec.min_replicas and \
            spec.target_qps_per_replica is not None:
        return RequestRateAutoscaler(spec, now_fn=now_fn)
    return FixedReplicaAutoscaler(spec)
