"""Autoscalers: QPS-target scaling with hysteresis.

Reference analog: sky/serve/autoscalers.py (`Autoscaler` :116,
`RequestRateAutoscaler` :441: target_qps_per_replica with
upscale/downscale delays so transient spikes/dips don't thrash).
"""
import dataclasses
import time
from typing import List, Optional

from skypilot_tpu.serve import service_spec as spec_lib


@dataclasses.dataclass
class ScalingDecision:
    target_replicas: int
    reason: str = ''


class Autoscaler:
    def __init__(self, spec: spec_lib.ServiceSpec) -> None:
        self.spec = spec

    def update_spec(self, spec: spec_lib.ServiceSpec) -> None:
        self.spec = spec

    def decide(self, num_ready: int, num_total: int,
               qps: Optional[float]) -> ScalingDecision:
        raise NotImplementedError


class FixedReplicaAutoscaler(Autoscaler):
    """No autoscaling: hold min_replicas."""

    def decide(self, num_ready: int, num_total: int,
               qps: Optional[float]) -> ScalingDecision:
        return ScalingDecision(self.spec.min_replicas, 'fixed')


class RequestRateAutoscaler(Autoscaler):
    """Scale so qps/replica ~= target, with upscale/downscale delays."""

    def __init__(self, spec: spec_lib.ServiceSpec,
                 now_fn=time.time) -> None:
        super().__init__(spec)
        self._now = now_fn
        self._upscale_since: Optional[float] = None
        self._downscale_since: Optional[float] = None

    def _desired(self, qps: float) -> int:
        import math
        target = self.spec.target_qps_per_replica
        desired = math.ceil(qps / target) if target else \
            self.spec.min_replicas
        lo = self.spec.min_replicas
        hi = self.spec.max_replicas or max(lo, desired)
        return max(lo, min(hi, desired))

    def decide(self, num_ready: int, num_total: int,
               qps: Optional[float]) -> ScalingDecision:
        if qps is None:
            return ScalingDecision(max(num_total, self.spec.min_replicas),
                                   'no traffic data')
        desired = self._desired(qps)
        now = self._now()
        if desired > num_total:
            self._downscale_since = None
            if self._upscale_since is None:
                self._upscale_since = now
            if now - self._upscale_since >= self.spec.upscale_delay_seconds:
                self._upscale_since = None
                return ScalingDecision(
                    desired, f'qps={qps:.2f} sustained above target')
            return ScalingDecision(num_total, 'upscale pending delay')
        if desired < num_total:
            self._upscale_since = None
            if self._downscale_since is None:
                self._downscale_since = now
            if now - self._downscale_since >= \
                    self.spec.downscale_delay_seconds:
                self._downscale_since = None
                return ScalingDecision(
                    desired, f'qps={qps:.2f} sustained below target')
            return ScalingDecision(num_total, 'downscale pending delay')
        self._upscale_since = None
        self._downscale_since = None
        return ScalingDecision(num_total, 'at target')


@dataclasses.dataclass
class MixedScalingDecision:
    """Spot + on-demand targets (reference FallbackRequestRateAutoscaler,
    autoscalers.py:557)."""
    target_spot: int
    target_ondemand: int
    reason: str = ''

    @property
    def target_replicas(self) -> int:
        return self.target_spot + self.target_ondemand


class FallbackRequestRateAutoscaler(RequestRateAutoscaler):
    """Request-rate scaling over a spot fleet with on-demand fallback.

    The traffic-derived target is served by spot. On top of that:
    - base_ondemand_fallback_replicas are ALWAYS on-demand (a safety
      floor that survives any spot stockout);
    - with dynamic_ondemand_fallback, spot capacity lost to preemption
      is covered by extra on-demand replicas until spot recovers.
    """

    def decide_mixed(self, num_ready_spot: int, num_spot: int,
                     num_ondemand: int,
                     qps: Optional[float]) -> MixedScalingDecision:
        base = self.spec.base_ondemand_fallback_replicas
        dynamic = self.spec.dynamic_ondemand_fallback
        current = num_spot + num_ondemand
        # Hysteresis-filtered total target over the whole fleet.
        total = self.decide(num_ready_spot + num_ondemand, current,
                            qps).target_replicas
        if total == current:
            # Hold: no resize is due (at target, or a scale is pending
            # its hysteresis delay) — keep the pools as they are, only
            # covering unready spot with on-demand if dynamic.
            spot_target, ondemand_target = num_spot, num_ondemand
            if dynamic:
                shortfall = max(0, num_spot - num_ready_spot)
                ondemand_target = min(max(total, num_ondemand),
                                      num_ondemand + shortfall)
        else:
            spot_target = max(0, total - base)
            ondemand_target = min(base, total)
            if dynamic:
                # Cover the spot shortfall (requested minus ready) with
                # on-demand; shrinks automatically as spot recovers.
                shortfall = max(0, spot_target - num_ready_spot)
                ondemand_target = min(total, ondemand_target + shortfall)
        return MixedScalingDecision(
            spot_target, ondemand_target,
            f'total={total} spot_ready={num_ready_spot}')


def make_autoscaler(spec: spec_lib.ServiceSpec) -> Autoscaler:
    if spec.use_spot and (spec.base_ondemand_fallback_replicas > 0
                          or spec.dynamic_ondemand_fallback):
        return FallbackRequestRateAutoscaler(spec)
    if spec.max_replicas is not None and \
            spec.max_replicas > spec.min_replicas and \
            spec.target_qps_per_replica is not None:
        return RequestRateAutoscaler(spec)
    return FixedReplicaAutoscaler(spec)
