"""Autoscalers: QPS-target scaling with hysteresis + load signals.

Reference analog: sky/serve/autoscalers.py (`Autoscaler` :116,
`RequestRateAutoscaler` :441: target_qps_per_replica with
upscale/downscale delays so transient spikes/dips don't thrash).
Beyond the reference: `LoadSignals` feeds engine-side pressure (queue
depth, KV-cache utilization from the `skytpu_*` registry) into the
same hysteresis pipeline, so scaling can react to saturation the
request *rate* alone can't see (long prompts, slow decodes).
"""
import dataclasses
import time
from typing import List, Optional

from skypilot_tpu.serve import service_spec as spec_lib


@dataclasses.dataclass
class ScalingDecision:
    target_replicas: int
    reason: str = ''


@dataclasses.dataclass(frozen=True)
class LoadSignals:
    """One reading of the fleet's load beyond raw request rate.

    queue_depth is fleet-wide requests accepted but not yet decoding;
    kv_util is the mean fraction of KV-cache positions holding live
    tokens (0-1). None means "signal unavailable" — scaling then
    falls back to pure request rate.
    """
    queue_depth: Optional[float] = None
    kv_util: Optional[float] = None


class MetricsSignalSource:
    """Reads LoadSignals off THIS process's skytpu_* registry
    (skytpu_queue_depth / skytpu_kv_cache_utilization) — the same
    series /metrics exposes, so what the autoscaler acted on is
    always scrape-able after the fact.

    Scope caveat: those gauges are written by whatever shares the
    process — the fleet simulator's SimFleet, or a co-located engine.
    A production controller whose replicas run elsewhere reads 0.0
    (signals absent, scaling falls back to request rate) until a
    scraping source is wired in: the controller takes any object with
    read() via its signal_source seam, and aggregating replica
    /metrics into one is the ROADMAP item-3 follow-up."""

    def read(self) -> LoadSignals:
        from skypilot_tpu.observability import instruments as obs
        return LoadSignals(queue_depth=obs.QUEUE_DEPTH.value(),
                           kv_util=obs.KV_CACHE_UTILIZATION.value())


class Autoscaler:
    def __init__(self, spec: spec_lib.ServiceSpec) -> None:
        self.spec = spec

    def update_spec(self, spec: spec_lib.ServiceSpec) -> None:
        self.spec = spec

    def decide(self, num_ready: int, num_total: int,
               qps: Optional[float],
               signals: Optional[LoadSignals] = None) -> ScalingDecision:
        raise NotImplementedError


class FixedReplicaAutoscaler(Autoscaler):
    """No autoscaling: hold min_replicas."""

    def decide(self, num_ready: int, num_total: int,
               qps: Optional[float],
               signals: Optional[LoadSignals] = None) -> ScalingDecision:
        return ScalingDecision(self.spec.min_replicas, 'fixed')


class RequestRateAutoscaler(Autoscaler):
    """Scale so qps/replica ~= target, with upscale/downscale delays."""

    def __init__(self, spec: spec_lib.ServiceSpec,
                 now_fn=time.time) -> None:
        super().__init__(spec)
        self._now = now_fn
        self._upscale_since: Optional[float] = None
        self._downscale_since: Optional[float] = None

    def _desired(self, qps: float,
                 signals: Optional[LoadSignals] = None) -> int:
        import math
        target = self.spec.target_qps_per_replica
        desired = math.ceil(qps / target) if target else \
            self.spec.min_replicas
        # Pressure signals only ever RAISE the rate-derived target:
        # queue depth / KV saturation mean the current fleet is behind
        # even if qps looks fine; their absence (or low values) must
        # not fight the rate signal downward.
        if signals is not None:
            tqd = self.spec.target_queue_per_replica
            if tqd and signals.queue_depth:
                desired = max(desired,
                              math.ceil(signals.queue_depth / tqd))
            kv_hi = self.spec.kv_util_upscale_threshold
            if kv_hi is not None and signals.kv_util is not None and \
                    signals.kv_util >= kv_hi:
                # Saturated caches: one more replica per decision
                # round — bounded pressure relief, hysteresis still
                # paces the actual resize.
                desired += 1
        lo = self.spec.min_replicas
        hi = self.spec.max_replicas or max(lo, desired)
        return max(lo, min(hi, desired))

    def decide(self, num_ready: int, num_total: int,
               qps: Optional[float],
               signals: Optional[LoadSignals] = None) -> ScalingDecision:
        if qps is None:
            return ScalingDecision(max(num_total, self.spec.min_replicas),
                                   'no traffic data')
        desired = self._desired(qps, signals)
        now = self._now()
        if desired > num_total:
            self._downscale_since = None
            if self._upscale_since is None:
                self._upscale_since = now
            if now - self._upscale_since >= self.spec.upscale_delay_seconds:
                self._upscale_since = None
                return ScalingDecision(
                    desired, f'qps={qps:.2f} sustained above target')
            return ScalingDecision(num_total, 'upscale pending delay')
        if desired < num_total:
            self._upscale_since = None
            if self._downscale_since is None:
                self._downscale_since = now
            if now - self._downscale_since >= \
                    self.spec.downscale_delay_seconds:
                self._downscale_since = None
                return ScalingDecision(
                    desired, f'qps={qps:.2f} sustained below target')
            return ScalingDecision(num_total, 'downscale pending delay')
        self._upscale_since = None
        self._downscale_since = None
        return ScalingDecision(num_total, 'at target')


@dataclasses.dataclass
class MixedScalingDecision:
    """Spot + on-demand targets (reference FallbackRequestRateAutoscaler,
    autoscalers.py:557)."""
    target_spot: int
    target_ondemand: int
    reason: str = ''

    @property
    def target_replicas(self) -> int:
        return self.target_spot + self.target_ondemand


class FallbackRequestRateAutoscaler(RequestRateAutoscaler):
    """Request-rate scaling over a spot fleet with on-demand fallback.

    The traffic-derived target is served by spot. On top of that:
    - base_ondemand_fallback_replicas are ALWAYS on-demand (a safety
      floor that survives any spot stockout);
    - with dynamic_ondemand_fallback, spot capacity lost to preemption
      is covered by extra on-demand replicas until spot recovers.
    """

    def decide_mixed(self, num_ready_spot: int, num_spot: int,
                     num_ondemand: int,
                     qps: Optional[float],
                     signals: Optional[LoadSignals] = None
                     ) -> MixedScalingDecision:
        base = self.spec.base_ondemand_fallback_replicas
        dynamic = self.spec.dynamic_ondemand_fallback
        current = num_spot + num_ondemand
        # Hysteresis-filtered total target over the whole fleet.
        total = self.decide(num_ready_spot + num_ondemand, current,
                            qps, signals).target_replicas
        if total == current:
            # Hold: no resize is due (at target, or a scale is pending
            # its hysteresis delay) — keep the pools as they are, only
            # covering unready spot with on-demand if dynamic.
            spot_target, ondemand_target = num_spot, num_ondemand
            if dynamic:
                shortfall = max(0, num_spot - num_ready_spot)
                # Cap the cover at what the RATE actually needs beyond
                # ready spot. Capping at the hysteresis-held `total`
                # (== current) compounds instead: every tick's cover
                # inflates `current`, which licenses a bigger cover
                # next tick — during a spot stockout that launched
                # shortfall-many NEW on-demand replicas per tick,
                # unboundedly (caught by the fleetsim preemption_wave
                # soak: 4416 replicas driven for a 300-replica fleet).
                if qps is None:
                    cover_cap = num_ondemand
                else:
                    cover_cap = max(0, self._desired(qps, signals)
                                    - num_ready_spot)
                ondemand_target = min(num_ondemand + shortfall,
                                      max(num_ondemand, cover_cap))
                if self.spec.max_replicas is not None:
                    # The user's hard spend ceiling outranks cover:
                    # spot pool + cover together never exceed it.
                    ondemand_target = min(
                        ondemand_target,
                        max(0, self.spec.max_replicas - num_spot))
        else:
            spot_target = max(0, total - base)
            ondemand_target = min(base, total)
            if dynamic:
                # Cover the spot shortfall (requested minus ready) with
                # on-demand; shrinks automatically as spot recovers.
                shortfall = max(0, spot_target - num_ready_spot)
                ondemand_target = min(total, ondemand_target + shortfall)
        return MixedScalingDecision(
            spot_target, ondemand_target,
            f'total={total} spot_ready={num_ready_spot}')


def make_autoscaler(spec: spec_lib.ServiceSpec,
                    now_fn=time.time) -> Autoscaler:
    """now_fn is the hysteresis clock seam: the fleet simulator runs
    upscale/downscale delays on a virtual clock, production uses
    time.time."""
    if spec.use_spot and (spec.base_ondemand_fallback_replicas > 0
                          or spec.dynamic_ondemand_fallback):
        return FallbackRequestRateAutoscaler(spec, now_fn=now_fn)
    if spec.max_replicas is not None and \
            spec.max_replicas > spec.min_replicas and \
            spec.target_qps_per_replica is not None:
        return RequestRateAutoscaler(spec, now_fn=now_fn)
    return FixedReplicaAutoscaler(spec)
