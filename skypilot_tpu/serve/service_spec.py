"""ServiceSpec: the `service:` section of a task YAML.

Reference analog: sky/serve/service_spec.py (422 LoC). Round 1 carries the
schema + validation; the controller/LB consume it in the serve subsystem.
"""
import dataclasses
from typing import Any, Dict, Optional

from skypilot_tpu import exceptions


@dataclasses.dataclass
class ReadinessProbe:
    path: str = '/'
    initial_delay_seconds: int = 1200
    timeout_seconds: int = 15
    post_data: Optional[Dict[str, Any]] = None

    @classmethod
    def from_config(cls, cfg) -> 'ReadinessProbe':
        if isinstance(cfg, str):
            return cls(path=cfg)
        if isinstance(cfg, dict):
            return cls(
                path=cfg.get('path', '/'),
                initial_delay_seconds=int(
                    cfg.get('initial_delay_seconds', 1200)),
                timeout_seconds=int(cfg.get('timeout_seconds', 15)),
                post_data=cfg.get('post_data'))
        raise exceptions.InvalidTaskError(
            f'Invalid readiness_probe: {cfg!r}')


@dataclasses.dataclass
class ServiceSpec:
    readiness_probe: ReadinessProbe
    min_replicas: int = 1
    max_replicas: Optional[int] = None
    target_qps_per_replica: Optional[float] = None
    upscale_delay_seconds: int = 300
    downscale_delay_seconds: int = 1200
    replica_port: int = 8080
    load_balancing_policy: str = 'least_load'
    # Spot policy (reference spot_placer.py + FallbackRequestRateAutoscaler
    # autoscalers.py:557): run replicas on spot, optionally keep
    # base_ondemand_fallback_replicas always-on-demand, and with
    # dynamic_ondemand_fallback cover preempted spot capacity with
    # on-demand until spot recovers.
    use_spot: bool = False
    spot_zones: Optional[list] = None
    base_ondemand_fallback_replicas: int = 0
    dynamic_ondemand_fallback: bool = False
    # Metrics-driven scaling signals (beyond raw request rate): queued
    # requests per replica the fleet should absorb, and the KV-cache
    # utilization above which decode capacity counts as saturated.
    # None disables the respective signal.
    target_queue_per_replica: Optional[float] = None
    kv_util_upscale_threshold: Optional[float] = None

    @classmethod
    def from_yaml_config(cls, cfg: Dict[str, Any]) -> 'ServiceSpec':
        from skypilot_tpu.utils import schemas
        schemas.validate_service(cfg)
        if 'readiness_probe' not in cfg:
            raise exceptions.InvalidTaskError(
                'service: requires a readiness_probe')
        rp = ReadinessProbe.from_config(cfg['readiness_probe'])
        replicas = cfg.get('replicas')
        policy = cfg.get('replica_policy') or {}
        min_replicas = int(policy.get('min_replicas',
                                      replicas if replicas else 1))
        max_replicas = policy.get('max_replicas')
        spec = cls(
            readiness_probe=rp,
            min_replicas=min_replicas,
            max_replicas=int(max_replicas) if max_replicas else None,
            target_qps_per_replica=policy.get('target_qps_per_replica'),
            upscale_delay_seconds=int(
                policy.get('upscale_delay_seconds', 300)),
            downscale_delay_seconds=int(
                policy.get('downscale_delay_seconds', 1200)),
            replica_port=int(cfg.get('replica_port', 8080)),
            load_balancing_policy=cfg.get('load_balancing_policy',
                                          'least_load'),
            use_spot=bool(policy.get('use_spot', False)),
            spot_zones=policy.get('spot_zones'),
            base_ondemand_fallback_replicas=int(
                policy.get('base_ondemand_fallback_replicas', 0)),
            dynamic_ondemand_fallback=bool(
                policy.get('dynamic_ondemand_fallback', False)),
            target_queue_per_replica=policy.get(
                'target_queue_per_replica'),
            kv_util_upscale_threshold=policy.get(
                'kv_util_upscale_threshold'),
        )
        if spec.max_replicas is not None and \
                spec.max_replicas < spec.min_replicas:
            raise exceptions.InvalidTaskError(
                'service: max_replicas < min_replicas')
        if not spec.use_spot and (
                spec.base_ondemand_fallback_replicas > 0
                or spec.dynamic_ondemand_fallback
                or spec.spot_zones):
            raise exceptions.InvalidTaskError(
                'service: spot fallback/zone options require use_spot')
        if (spec.max_replicas is not None and
                spec.max_replicas > spec.min_replicas and
                spec.target_qps_per_replica is None):
            raise exceptions.InvalidTaskError(
                'service: autoscaling (max>min) requires '
                'target_qps_per_replica')
        return spec

    def to_yaml_config(self) -> Dict[str, Any]:
        cfg: Dict[str, Any] = {
            'readiness_probe': {
                'path': self.readiness_probe.path,
                'initial_delay_seconds':
                    self.readiness_probe.initial_delay_seconds,
                'timeout_seconds': self.readiness_probe.timeout_seconds,
            },
            'replica_policy': {
                'min_replicas': self.min_replicas,
            },
            'replica_port': self.replica_port,
            'load_balancing_policy': self.load_balancing_policy,
        }
        if self.readiness_probe.post_data is not None:
            cfg['readiness_probe']['post_data'] = \
                self.readiness_probe.post_data
        pol = cfg['replica_policy']
        if self.max_replicas is not None:
            pol['max_replicas'] = self.max_replicas
        if self.target_qps_per_replica is not None:
            pol['target_qps_per_replica'] = self.target_qps_per_replica
        if self.target_queue_per_replica is not None:
            pol['target_queue_per_replica'] = \
                self.target_queue_per_replica
        if self.kv_util_upscale_threshold is not None:
            pol['kv_util_upscale_threshold'] = \
                self.kv_util_upscale_threshold
        if self.use_spot:
            pol['use_spot'] = True
            if self.spot_zones:
                pol['spot_zones'] = list(self.spot_zones)
            if self.base_ondemand_fallback_replicas:
                pol['base_ondemand_fallback_replicas'] = \
                    self.base_ondemand_fallback_replicas
            if self.dynamic_ondemand_fallback:
                pol['dynamic_ondemand_fallback'] = True
        return cfg
