"""ServiceSpec: the `service:` section of a task YAML.

Reference analog: sky/serve/service_spec.py (422 LoC). Round 1 carries the
schema + validation; the controller/LB consume it in the serve subsystem.
"""
import dataclasses
from typing import Any, Dict, Optional

from skypilot_tpu import exceptions


@dataclasses.dataclass
class ReadinessProbe:
    path: str = '/'
    initial_delay_seconds: int = 1200
    timeout_seconds: int = 15
    post_data: Optional[Dict[str, Any]] = None

    @classmethod
    def from_config(cls, cfg) -> 'ReadinessProbe':
        if isinstance(cfg, str):
            return cls(path=cfg)
        if isinstance(cfg, dict):
            return cls(
                path=cfg.get('path', '/'),
                initial_delay_seconds=int(
                    cfg.get('initial_delay_seconds', 1200)),
                timeout_seconds=int(cfg.get('timeout_seconds', 15)),
                post_data=cfg.get('post_data'))
        raise exceptions.InvalidTaskError(
            f'Invalid readiness_probe: {cfg!r}')


_POOL_ROLES = ('prefill', 'decode', 'general')


@dataclasses.dataclass
class PoolSpec:
    """One named replica pool: a role (what request shape it serves),
    its own scaling envelope, and the saturation signals its
    autoscaler consumes. Disaggregated prefill/decode serving
    (ROADMAP item 2): prefill-heavy and decode-heavy hardware scale
    independently, each on the signal that actually saturates it —
    never raw request rate alone.
    """
    name: str
    role: str = 'general'
    min_replicas: int = 1
    max_replicas: Optional[int] = None
    target_qps_per_replica: Optional[float] = None
    target_queue_per_replica: Optional[float] = None
    kv_util_upscale_threshold: Optional[float] = None
    # p95 breach thresholds (seconds): one extra replica per decision
    # round while breached — bounded pressure relief, the shared
    # hysteresis paces the actual resize.
    ttft_p95_upscale_threshold: Optional[float] = None
    decode_step_p95_upscale_threshold: Optional[float] = None
    upscale_delay_seconds: int = 300
    downscale_delay_seconds: int = 1200
    # Per-pool resource overrides merged over the task's resources:
    # a prefill pool runs compute-heavy slices, a decode pool
    # memory-heavy ones.
    resources: Optional[Dict[str, Any]] = None

    @classmethod
    def from_config(cls, name: str, cfg: Dict[str, Any],
                    defaults: 'ServiceSpec') -> 'PoolSpec':
        role = cfg.get('role', 'general')
        if role not in _POOL_ROLES:
            raise exceptions.InvalidTaskError(
                f'service: pool {name!r} role {role!r} invalid; one '
                f'of {", ".join(_POOL_ROLES)}')
        max_replicas = cfg.get('max_replicas')
        spec = cls(
            name=name,
            role=role,
            min_replicas=int(cfg.get('min_replicas', 1)),
            max_replicas=int(max_replicas) if max_replicas else None,
            target_qps_per_replica=cfg.get('target_qps_per_replica'),
            target_queue_per_replica=cfg.get(
                'target_queue_per_replica'),
            kv_util_upscale_threshold=cfg.get(
                'kv_util_upscale_threshold'),
            ttft_p95_upscale_threshold=cfg.get(
                'ttft_p95_upscale_threshold'),
            decode_step_p95_upscale_threshold=cfg.get(
                'decode_step_p95_upscale_threshold'),
            upscale_delay_seconds=int(cfg.get(
                'upscale_delay_seconds',
                defaults.upscale_delay_seconds)),
            downscale_delay_seconds=int(cfg.get(
                'downscale_delay_seconds',
                defaults.downscale_delay_seconds)),
            resources=cfg.get('resources'),
        )
        if spec.min_replicas < 0:
            raise exceptions.InvalidTaskError(
                f'service: pool {name!r} min_replicas < 0')
        if spec.max_replicas is not None and \
                spec.max_replicas < spec.min_replicas:
            raise exceptions.InvalidTaskError(
                f'service: pool {name!r} max_replicas < min_replicas')
        return spec

    def to_config(self) -> Dict[str, Any]:
        cfg: Dict[str, Any] = {
            'role': self.role,
            'min_replicas': self.min_replicas,
            'upscale_delay_seconds': self.upscale_delay_seconds,
            'downscale_delay_seconds': self.downscale_delay_seconds,
        }
        for key in ('max_replicas', 'target_qps_per_replica',
                    'target_queue_per_replica',
                    'kv_util_upscale_threshold',
                    'ttft_p95_upscale_threshold',
                    'decode_step_p95_upscale_threshold', 'resources'):
            value = getattr(self, key)
            if value is not None:
                cfg[key] = value
        return cfg


@dataclasses.dataclass
class ServiceSpec:
    readiness_probe: ReadinessProbe
    min_replicas: int = 1
    max_replicas: Optional[int] = None
    target_qps_per_replica: Optional[float] = None
    upscale_delay_seconds: int = 300
    downscale_delay_seconds: int = 1200
    replica_port: int = 8080
    load_balancing_policy: str = 'least_load'
    # Spot policy (reference spot_placer.py + FallbackRequestRateAutoscaler
    # autoscalers.py:557): run replicas on spot, optionally keep
    # base_ondemand_fallback_replicas always-on-demand, and with
    # dynamic_ondemand_fallback cover preempted spot capacity with
    # on-demand until spot recovers.
    use_spot: bool = False
    spot_zones: Optional[list] = None
    base_ondemand_fallback_replicas: int = 0
    dynamic_ondemand_fallback: bool = False
    # Metrics-driven scaling signals (beyond raw request rate): queued
    # requests per replica the fleet should absorb, and the KV-cache
    # utilization above which decode capacity counts as saturated.
    # None disables the respective signal.
    target_queue_per_replica: Optional[float] = None
    kv_util_upscale_threshold: Optional[float] = None
    # Disaggregated replica pools: name -> PoolSpec. None means one
    # undifferentiated fleet governed by replica_policy (the legacy
    # path, untouched). With pools, min/max_replicas above are the
    # pool sums (derived, for consumers that think fleet-wide).
    pools: Optional[Dict[str, PoolSpec]] = None

    @classmethod
    def from_yaml_config(cls, cfg: Dict[str, Any]) -> 'ServiceSpec':
        from skypilot_tpu.utils import schemas
        schemas.validate_service(cfg)
        if 'readiness_probe' not in cfg:
            raise exceptions.InvalidTaskError(
                'service: requires a readiness_probe')
        rp = ReadinessProbe.from_config(cfg['readiness_probe'])
        if cfg.get('pools') is not None:
            return cls._from_pools_config(cfg, rp)
        replicas = cfg.get('replicas')
        policy = cfg.get('replica_policy') or {}
        min_replicas = int(policy.get('min_replicas',
                                      replicas if replicas else 1))
        max_replicas = policy.get('max_replicas')
        spec = cls(
            readiness_probe=rp,
            min_replicas=min_replicas,
            max_replicas=int(max_replicas) if max_replicas else None,
            target_qps_per_replica=policy.get('target_qps_per_replica'),
            upscale_delay_seconds=int(
                policy.get('upscale_delay_seconds', 300)),
            downscale_delay_seconds=int(
                policy.get('downscale_delay_seconds', 1200)),
            replica_port=int(cfg.get('replica_port', 8080)),
            load_balancing_policy=cfg.get('load_balancing_policy',
                                          'least_load'),
            use_spot=bool(policy.get('use_spot', False)),
            spot_zones=policy.get('spot_zones'),
            base_ondemand_fallback_replicas=int(
                policy.get('base_ondemand_fallback_replicas', 0)),
            dynamic_ondemand_fallback=bool(
                policy.get('dynamic_ondemand_fallback', False)),
            target_queue_per_replica=policy.get(
                'target_queue_per_replica'),
            kv_util_upscale_threshold=policy.get(
                'kv_util_upscale_threshold'),
        )
        if spec.max_replicas is not None and \
                spec.max_replicas < spec.min_replicas:
            raise exceptions.InvalidTaskError(
                'service: max_replicas < min_replicas')
        if not spec.use_spot and (
                spec.base_ondemand_fallback_replicas > 0
                or spec.dynamic_ondemand_fallback
                or spec.spot_zones):
            raise exceptions.InvalidTaskError(
                'service: spot fallback/zone options require use_spot')
        if (spec.max_replicas is not None and
                spec.max_replicas > spec.min_replicas and
                spec.target_qps_per_replica is None):
            raise exceptions.InvalidTaskError(
                'service: autoscaling (max>min) requires '
                'target_qps_per_replica')
        return spec

    @classmethod
    def _from_pools_config(cls, cfg: Dict[str, Any],
                           rp: ReadinessProbe) -> 'ServiceSpec':
        if cfg.get('replica_policy') or cfg.get('replicas'):
            raise exceptions.InvalidTaskError(
                'service: pools and replica_policy/replicas are '
                'mutually exclusive — each pool declares its own '
                'scaling envelope')
        defaults = cls(readiness_probe=rp)
        pools: Dict[str, PoolSpec] = {}
        for name, pool_cfg in cfg['pools'].items():
            pools[name] = PoolSpec.from_config(name, pool_cfg or {},
                                               defaults)
        if not pools:
            raise exceptions.InvalidTaskError(
                'service: pools requires at least one pool')
        total_min = sum(p.min_replicas for p in pools.values())
        if total_min < 1:
            raise exceptions.InvalidTaskError(
                'service: pool min_replicas must sum to >= 1')
        maxes = [p.max_replicas for p in pools.values()]
        total_max = sum(m for m in maxes if m is not None) \
            if all(m is not None for m in maxes) else None
        return cls(
            readiness_probe=rp,
            min_replicas=total_min,
            max_replicas=total_max,
            replica_port=int(cfg.get('replica_port', 8080)),
            load_balancing_policy=cfg.get('load_balancing_policy',
                                          'least_load'),
            pools=pools,
        )

    def to_yaml_config(self) -> Dict[str, Any]:
        if self.pools is not None:
            cfg: Dict[str, Any] = {
                'readiness_probe': {
                    'path': self.readiness_probe.path,
                    'initial_delay_seconds':
                        self.readiness_probe.initial_delay_seconds,
                    'timeout_seconds':
                        self.readiness_probe.timeout_seconds,
                },
                'replica_port': self.replica_port,
                'load_balancing_policy': self.load_balancing_policy,
                'pools': {name: pool.to_config()
                          for name, pool in self.pools.items()},
            }
            if self.readiness_probe.post_data is not None:
                cfg['readiness_probe']['post_data'] = \
                    self.readiness_probe.post_data
            return cfg
        return self._to_yaml_config_poolless()

    def _to_yaml_config_poolless(self) -> Dict[str, Any]:
        cfg: Dict[str, Any] = {
            'readiness_probe': {
                'path': self.readiness_probe.path,
                'initial_delay_seconds':
                    self.readiness_probe.initial_delay_seconds,
                'timeout_seconds': self.readiness_probe.timeout_seconds,
            },
            'replica_policy': {
                'min_replicas': self.min_replicas,
            },
            'replica_port': self.replica_port,
            'load_balancing_policy': self.load_balancing_policy,
        }
        if self.readiness_probe.post_data is not None:
            cfg['readiness_probe']['post_data'] = \
                self.readiness_probe.post_data
        pol = cfg['replica_policy']
        if self.max_replicas is not None:
            pol['max_replicas'] = self.max_replicas
        if self.target_qps_per_replica is not None:
            pol['target_qps_per_replica'] = self.target_qps_per_replica
        if self.target_queue_per_replica is not None:
            pol['target_queue_per_replica'] = \
                self.target_queue_per_replica
        if self.kv_util_upscale_threshold is not None:
            pol['kv_util_upscale_threshold'] = \
                self.kv_util_upscale_threshold
        if self.use_spot:
            pol['use_spot'] = True
            if self.spot_zones:
                pol['spot_zones'] = list(self.spot_zones)
            if self.base_ondemand_fallback_replicas:
                pol['base_ondemand_fallback_replicas'] = \
                    self.base_ondemand_fallback_replicas
            if self.dynamic_ondemand_fallback:
                pol['dynamic_ondemand_fallback'] = True
        return cfg
