"""Replica manager: replica cluster lifecycle + readiness probes.

Reference analog: sky/serve/replica_managers.py (launch_cluster :60,
`ReplicaInfo` :388, probe loop). Each replica is a full cluster launched
through the normal stack (optimizer -> provision -> gang run), so TPU
replicas get slice semantics (preempted -> terminate+relaunch) for free.
"""
import logging
import threading
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.serve import serve_state
from skypilot_tpu.serve import service_spec as spec_lib

logger = logging.getLogger(__name__)

_MAX_CONSECUTIVE_FAILURES = 3


def replica_cluster_name(service_name: str, replica_id: int) -> str:
    return f'tsky-serve-{service_name}-{replica_id}'


class ReplicaManager:

    def __init__(self, service_name: str, task,
                 spec: spec_lib.ServiceSpec) -> None:
        self.service_name = service_name
        self.task = task
        self.spec = spec
        self.spot_placer = None
        if spec.use_spot and spec.spot_zones:
            from skypilot_tpu.serve import spot_placer as placer_lib
            self.spot_placer = placer_lib.SpotPlacer(list(spec.spot_zones))

    # -- lifecycle -----------------------------------------------------------

    def scale_up(self, n: int = 1,
                 use_spot: Optional[bool] = None) -> List[int]:
        """Launch n new replica clusters in BACKGROUND threads so the
        control loop keeps probing healthy replicas while slices
        provision (TPU pods can take many minutes; reference replica
        manager launches async the same way).

        use_spot overrides the spec default (the fallback autoscaler
        launches on-demand replicas into a spot service).
        """
        launched = []
        service = serve_state.get_service(self.service_name)
        version = service['version'] if service else 1
        spot = self.spec.use_spot if use_spot is None else use_spot
        for _ in range(n):
            replica_id = serve_state.next_replica_id(self.service_name)
            cluster = replica_cluster_name(self.service_name, replica_id)
            zone = None
            if spot and self.spot_placer is not None:
                counts: Dict[str, int] = {}
                for r in serve_state.get_replicas(self.service_name):
                    if r.get('zone'):
                        counts[r['zone']] = counts.get(r['zone'], 0) + 1
                zone = self.spot_placer.select(counts)
            serve_state.add_replica(self.service_name, replica_id, cluster,
                                    version, use_spot=spot, zone=zone)
            thread = threading.Thread(
                target=self._launch_replica,
                args=(replica_id, cluster, spot, zone),
                daemon=True)
            thread.start()
            launched.append(replica_id)
        return launched

    def _launch_replica(self, replica_id: int, cluster: str,
                        use_spot: bool, zone: Optional[str]) -> None:
        try:
            from skypilot_tpu import execution
            execution.launch(self._replica_task(use_spot, zone),
                             cluster_name=cluster,
                             stream_logs=False, detach_run=True)
            serve_state.set_replica_status(
                self.service_name, replica_id,
                serve_state.ReplicaStatus.STARTING,
                endpoint=self._endpoint_for(cluster))
        except exceptions.SkyTpuError as e:
            logger.warning('Replica %s launch failed: %s', replica_id, e)
            serve_state.set_replica_status(
                self.service_name, replica_id,
                serve_state.ReplicaStatus.FAILED)

    def _replica_task(self, use_spot: bool = False,
                      zone: Optional[str] = None):
        """A fresh Task per replica (Tasks hold best_resources state),
        with the placer's spot/zone decision applied to every resource
        option."""
        from skypilot_tpu import task as task_lib
        task = task_lib.Task.from_yaml_config(self.task.to_yaml_config())
        # Apply whenever the service runs mixed pools: an on-demand
        # fallback replica must override a task-level use_spot: true.
        if self.spec.use_spot or use_spot or zone is not None:
            task.set_resources([
                r.copy(use_spot=use_spot,
                       **({'zone': zone} if zone else {}))
                for r in task.resources])
        return task

    def _endpoint_for(self, cluster_name: str) -> Optional[str]:
        from skypilot_tpu import state as state_lib
        record = state_lib.get_cluster_from_name(cluster_name)
        if record is None or record['handle'] is None:
            return None
        ip = record['handle'].head_ip()
        if ip is None:
            return None
        return f'http://{ip}:{self.spec.replica_port}'

    def scale_down(self, replica_ids: List[int]) -> None:
        from skypilot_tpu import core
        for replica_id in replica_ids:
            serve_state.set_replica_status(
                self.service_name, replica_id,
                serve_state.ReplicaStatus.SHUTTING_DOWN)
            cluster = replica_cluster_name(self.service_name, replica_id)
            try:
                core.down(cluster, purge=True)
            except exceptions.ClusterDoesNotExist:
                pass
            serve_state.remove_replica(self.service_name, replica_id)

    def terminate_all(self) -> None:
        self.scale_down([r['replica_id']
                         for r in serve_state.get_replicas(
                             self.service_name)])

    # -- probing -------------------------------------------------------------

    def _probe_replica(self, replica: Dict) -> bool:
        endpoint = replica['endpoint']
        if not endpoint:
            return False
        url = endpoint.rstrip('/') + self.spec.readiness_probe.path
        try:
            req = urllib.request.Request(url)
            post = self.spec.readiness_probe.post_data
            if post is not None:
                import json
                req = urllib.request.Request(
                    url, data=json.dumps(post).encode(),
                    headers={'Content-Type': 'application/json'})
            with urllib.request.urlopen(
                    req,
                    timeout=self.spec.readiness_probe.timeout_seconds):
                return True
        except (urllib.error.URLError, OSError, ValueError):
            return False

    def _cluster_lost(self, replica: Dict) -> bool:
        from skypilot_tpu import state as state_lib
        record = state_lib.get_cluster_from_name(replica['cluster_name'])
        return record is None or record['handle'] is None

    def probe_all(self) -> None:
        """One probe round: update replica statuses, replace dead ones."""
        for replica in serve_state.get_replicas(self.service_name):
            status = replica['status']
            if status in (serve_state.ReplicaStatus.SHUTTING_DOWN,
                          serve_state.ReplicaStatus.FAILED,
                          serve_state.ReplicaStatus.PROVISIONING):
                # PROVISIONING: a background launch thread owns it.
                continue
            if self._cluster_lost(replica):
                # Preempted / externally deleted: replace (same
                # spot-ness; the placer steers the new replica away
                # from the preempted zone).
                serve_state.set_replica_status(
                    self.service_name, replica['replica_id'],
                    serve_state.ReplicaStatus.PREEMPTED)
                if replica.get('use_spot') and self.spot_placer:
                    self.spot_placer.handle_preemption(replica.get('zone'))
                self.scale_down([replica['replica_id']])
                self.scale_up(1, use_spot=replica.get('use_spot'))
                continue
            if replica['endpoint'] is None:
                endpoint = self._endpoint_for(replica['cluster_name'])
                if endpoint:
                    serve_state.set_replica_status(
                        self.service_name, replica['replica_id'],
                        status, endpoint=endpoint)
                    replica = dict(replica, endpoint=endpoint)
            if self._probe_replica(replica):
                serve_state.clear_replica_failures(
                    self.service_name, replica['replica_id'])
                if status != serve_state.ReplicaStatus.READY:
                    serve_state.set_replica_status(
                        self.service_name, replica['replica_id'],
                        serve_state.ReplicaStatus.READY)
                    if replica.get('use_spot') and self.spot_placer:
                        self.spot_placer.handle_active(replica.get('zone'))
            else:
                failures = serve_state.bump_replica_failures(
                    self.service_name, replica['replica_id'])
                if status == serve_state.ReplicaStatus.READY:
                    serve_state.set_replica_status(
                        self.service_name, replica['replica_id'],
                        serve_state.ReplicaStatus.NOT_READY)
                if status == serve_state.ReplicaStatus.STARTING:
                    # Probe failures during startup are expected until
                    # initial_delay_seconds; past it, the app is deemed
                    # crashed and the replica is replaced.
                    age = time.time() - (replica['launched_at'] or 0)
                    if age > self.spec.readiness_probe. \
                            initial_delay_seconds:
                        self.scale_down([replica['replica_id']])
                        self.scale_up(1)
                elif failures >= _MAX_CONSECUTIVE_FAILURES:
                    # Persistent failure: replace the replica.
                    self.scale_down([replica['replica_id']])
                    self.scale_up(1)

    def ready_endpoints(self) -> List[str]:
        return [r['endpoint']
                for r in serve_state.get_replicas(self.service_name)
                if r['status'] == serve_state.ReplicaStatus.READY and
                r['endpoint']]
