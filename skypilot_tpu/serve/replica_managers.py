"""Replica manager: replica cluster lifecycle + readiness probes.

Reference analog: sky/serve/replica_managers.py (launch_cluster :60,
`ReplicaInfo` :388, probe loop). Each replica is a full cluster launched
through the normal stack (optimizer -> provision -> gang run), so TPU
replicas get slice semantics (preempted -> terminate+relaunch) for free.
"""
import logging
import threading
import time
import urllib.error
import urllib.request
from typing import Dict, List, NamedTuple, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import envs
from skypilot_tpu.resilience import circuit
from skypilot_tpu.resilience import faults
from skypilot_tpu.resilience import retries
from skypilot_tpu.serve import serve_state
from skypilot_tpu.serve import service_spec as spec_lib

logger = logging.getLogger(__name__)

_MAX_CONSECUTIVE_FAILURES = 3


class ProbeResult(NamedTuple):
    """One probe outcome, with the failure mode preserved — refused
    (app not listening yet) vs timeout (wedged) vs HTTP 5xx (up but
    erroring) drive different operator diagnoses, so they must not
    collapse into one boolean at the source."""
    ok: bool
    detail: str


def replica_cluster_name(service_name: str, replica_id: int) -> str:
    return f'tsky-serve-{service_name}-{replica_id}'


class ReplicaManager:

    def __init__(self, service_name: str, task,
                 spec: spec_lib.ServiceSpec) -> None:
        self.service_name = service_name
        self.task = task
        self.spec = spec
        self.spot_placer = None
        if spec.use_spot and spec.spot_zones:
            from skypilot_tpu.serve import spot_placer as placer_lib
            self.spot_placer = placer_lib.SpotPlacer(list(spec.spot_zones))
        # A flapping endpoint must not eat a full probe timeout every
        # round: past the failure threshold its circuit opens and
        # probes short-circuit until the recovery window passes. The
        # threshold sits BELOW the replacement threshold so the final
        # pre-replacement round fast-fails instead of burning another
        # full probe timeout (equal thresholds would open the circuit
        # on the same round that forgets the endpoint).
        self._probe_breaker = circuit.CircuitBreaker(
            'probe',
            failure_threshold=max(1, _MAX_CONSECUTIVE_FAILURES - 1),
            recovery_timeout=envs.SKYTPU_PROBE_BREAKER_RECOVERY.get())

    # -- lifecycle -----------------------------------------------------------

    def scale_up(self, n: int = 1,
                 use_spot: Optional[bool] = None,
                 pool: Optional[str] = None) -> List[int]:
        """Launch n new replica clusters in BACKGROUND threads so the
        control loop keeps probing healthy replicas while slices
        provision (TPU pods can take many minutes; reference replica
        manager launches async the same way).

        use_spot overrides the spec default (the fallback autoscaler
        launches on-demand replicas into a spot service); `pool`
        names the replica pool the new replicas belong to — its
        PoolSpec resource overrides shape the launched cluster
        (prefill-heavy vs decode-heavy hardware).
        """
        launched = []
        service = serve_state.get_service(self.service_name)
        version = service['version'] if service else 1
        spot = self.spec.use_spot if use_spot is None else use_spot
        for _ in range(n):
            replica_id = serve_state.next_replica_id(self.service_name)
            cluster = replica_cluster_name(self.service_name, replica_id)
            zone = None
            if spot and self.spot_placer is not None:
                counts: Dict[str, int] = {}
                for r in serve_state.get_replicas(self.service_name):
                    if r.get('zone'):
                        counts[r['zone']] = counts.get(r['zone'], 0) + 1
                zone = self.spot_placer.select(counts)
            serve_state.add_replica(self.service_name, replica_id, cluster,
                                    version, use_spot=spot, zone=zone,
                                    pool=pool)
            thread = threading.Thread(
                target=self._launch_replica,
                args=(replica_id, cluster, spot, zone, pool),
                daemon=True)
            thread.start()
            launched.append(replica_id)
        return launched

    def _launch_replica(self, replica_id: int, cluster: str,
                        use_spot: bool, zone: Optional[str],
                        pool: Optional[str] = None) -> None:
        try:
            from skypilot_tpu import execution

            def _launch_once() -> None:
                faults.inject(
                    'provision.launch',
                    env_exc=exceptions.ResourcesUnavailableError)
                execution.launch(self._replica_task(use_spot, zone,
                                                    pool=pool),
                                 cluster_name=cluster,
                                 stream_logs=False, detach_run=True)

            # Transient capacity/setup errors retry under the shared
            # policy; anything else fails the replica immediately.
            gap = envs.SKYTPU_SERVE_LAUNCH_RETRY_GAP.get()
            retries.call(
                _launch_once,
                policy=retries.RetryPolicy(max_attempts=3,
                                           base_delay=gap,
                                           max_delay=gap * 8),
                retry_on=(exceptions.ResourcesUnavailableError,
                          exceptions.ClusterSetUpError),
                describe=f'launch replica {replica_id}')
            serve_state.set_replica_status(
                self.service_name, replica_id,
                serve_state.ReplicaStatus.STARTING,
                endpoint=self._endpoint_for(cluster))
        except exceptions.SkyTpuError as e:
            logger.warning('Replica %s launch failed: %s', replica_id, e)
            serve_state.set_replica_status(
                self.service_name, replica_id,
                serve_state.ReplicaStatus.FAILED)

    def _replica_task(self, use_spot: bool = False,
                      zone: Optional[str] = None,
                      pool: Optional[str] = None):
        """A fresh Task per replica (Tasks hold best_resources state),
        with the placer's spot/zone decision applied to every resource
        option and the pool's resource overrides (distinct hardware
        per pool role) merged over the task's own `resources:`."""
        from skypilot_tpu import task as task_lib
        cfg = self.task.to_yaml_config()
        pool_spec = (self.spec.pools or {}).get(pool) \
            if pool is not None else None
        if pool_spec is not None and pool_spec.resources:
            resources = dict(cfg.get('resources') or {})
            resources.update(pool_spec.resources)
            cfg['resources'] = resources
        task = task_lib.Task.from_yaml_config(cfg)
        # Apply whenever the service runs mixed spot pools: an
        # on-demand fallback replica must override a task-level
        # use_spot: true.
        if self.spec.use_spot or use_spot or zone is not None:
            task.set_resources([
                r.copy(use_spot=use_spot,
                       **({'zone': zone} if zone else {}))
                for r in task.resources])
        return task

    def _endpoint_for(self, cluster_name: str) -> Optional[str]:
        from skypilot_tpu import state as state_lib
        record = state_lib.get_cluster_from_name(cluster_name)
        if record is None or record['handle'] is None:
            return None
        ip = record['handle'].head_ip()
        if ip is None:
            return None
        return f'http://{ip}:{self.spec.replica_port}'

    def scale_down(self, replica_ids: List[int]) -> None:
        from skypilot_tpu import core
        by_id = {r['replica_id']: r
                 for r in serve_state.get_replicas(self.service_name)}
        for replica_id in replica_ids:
            gone = by_id.get(replica_id)
            if gone is not None and gone.get('endpoint'):
                # Dead endpoints must not linger as open circuits.
                self._probe_breaker.forget(gone['endpoint'])
            serve_state.set_replica_status(
                self.service_name, replica_id,
                serve_state.ReplicaStatus.SHUTTING_DOWN)
            cluster = replica_cluster_name(self.service_name, replica_id)
            try:
                core.down(cluster, purge=True)
            except exceptions.ClusterDoesNotExist:
                pass
            serve_state.remove_replica(self.service_name, replica_id)

    def terminate_all(self) -> None:
        self.scale_down([r['replica_id']
                         for r in serve_state.get_replicas(
                             self.service_name)])

    # -- probing -------------------------------------------------------------

    def _probe_replica(self, replica: Dict) -> ProbeResult:
        endpoint = replica['endpoint']
        if not endpoint:
            return ProbeResult(False, 'no_endpoint')
        url = endpoint.rstrip('/') + self.spec.readiness_probe.path
        # STARTING replicas bypass the breaker: refusals while the app
        # boots are EXPECTED, and an open circuit here would suppress
        # the very probe that detects the app coming up — the replica
        # would blow its grace window unprobed and crash-loop.
        starting = (replica.get('status') ==
                    serve_state.ReplicaStatus.STARTING)
        if not starting and not self._probe_breaker.allow(endpoint):
            # Open circuit: fail fast instead of burning a full probe
            # timeout on an endpoint that just failed repeatedly.
            return ProbeResult(False, 'circuit_open')
        detail = 'error'
        try:
            faults.inject('probe.http', env_exc=ConnectionRefusedError)
            req = urllib.request.Request(url)
            post = self.spec.readiness_probe.post_data
            if post is not None:
                import json
                req = urllib.request.Request(
                    url, data=json.dumps(post).encode(),
                    headers={'Content-Type': 'application/json'})
            with urllib.request.urlopen(
                    req,
                    timeout=self.spec.readiness_probe.timeout_seconds):
                self._probe_breaker.record_success(endpoint)
                return ProbeResult(True, 'ok')
        except urllib.error.HTTPError as e:
            detail = f'http_{e.code}'
        except urllib.error.URLError as e:
            detail = self._classify_probe_error(e.reason)
        except (TimeoutError, OSError, ValueError) as e:
            detail = self._classify_probe_error(e)
        except faults.FaultInjected:
            detail = 'injected'
        if not starting:
            # Boot-time refusals are expected and must not raise the
            # circuit-open alarm on every normal scale-up.
            self._probe_breaker.record_failure(endpoint)
        logger.debug('Probe of replica %s failed: %s (%s)',
                     replica['replica_id'], detail, url)
        return ProbeResult(False, detail)

    @staticmethod
    def _classify_probe_error(reason) -> str:
        if isinstance(reason, ConnectionRefusedError):
            return 'refused'
        if isinstance(reason, (TimeoutError, )) or \
                'timed out' in str(reason):
            return 'timeout'
        return f'error:{type(reason).__name__}'

    def _cluster_lost(self, replica: Dict) -> bool:
        from skypilot_tpu import state as state_lib
        record = state_lib.get_cluster_from_name(replica['cluster_name'])
        return record is None or record['handle'] is None

    def probe_all(self) -> None:
        """One probe round: update replica statuses, replace dead ones."""
        for replica in serve_state.get_replicas(self.service_name):
            status = replica['status']
            if status in (serve_state.ReplicaStatus.SHUTTING_DOWN,
                          serve_state.ReplicaStatus.FAILED,
                          serve_state.ReplicaStatus.PROVISIONING):
                # PROVISIONING: a background launch thread owns it.
                continue
            if self._cluster_lost(replica):
                # Preempted / externally deleted: replace (same
                # spot-ness; the placer steers the new replica away
                # from the preempted zone).
                serve_state.set_replica_status(
                    self.service_name, replica['replica_id'],
                    serve_state.ReplicaStatus.PREEMPTED)
                if replica.get('use_spot') and self.spot_placer:
                    self.spot_placer.handle_preemption(replica.get('zone'))
                self.scale_down([replica['replica_id']])
                # Replacement keeps the dead replica's pool: a lost
                # decode replica must not come back on base-task
                # hardware outside its pool's scaling envelope.
                self.scale_up(1, use_spot=replica.get('use_spot'),
                              pool=replica.get('pool'))
                continue
            if replica['endpoint'] is None:
                endpoint = self._endpoint_for(replica['cluster_name'])
                if endpoint:
                    serve_state.set_replica_status(
                        self.service_name, replica['replica_id'],
                        status, endpoint=endpoint)
                    replica = dict(replica, endpoint=endpoint)
            probe = self._probe_replica(replica)
            if probe.ok:
                serve_state.clear_replica_failures(
                    self.service_name, replica['replica_id'])
                if status != serve_state.ReplicaStatus.READY:
                    serve_state.set_replica_status(
                        self.service_name, replica['replica_id'],
                        serve_state.ReplicaStatus.READY)
                    if replica.get('use_spot') and self.spot_placer:
                        self.spot_placer.handle_active(replica.get('zone'))
            else:
                failures = serve_state.bump_replica_failures(
                    self.service_name, replica['replica_id'])
                logger.info('Replica %s probe failed (%s), %d '
                            'consecutive', replica['replica_id'],
                            probe.detail, failures)
                if status == serve_state.ReplicaStatus.READY:
                    serve_state.set_replica_status(
                        self.service_name, replica['replica_id'],
                        serve_state.ReplicaStatus.NOT_READY)
                if status == serve_state.ReplicaStatus.STARTING:
                    # Probe failures during startup are expected until
                    # initial_delay_seconds; past it, the app is deemed
                    # crashed and the replica is replaced.
                    launched_at = replica['launched_at']
                    if launched_at is None:
                        # A None launched_at must not compute an age
                        # of ~Unix-epoch and instantly blow the grace
                        # window: grant the full window from now.
                        launched_at = time.time()
                        logger.warning(
                            'Replica %s is STARTING with no '
                            'launched_at; granting grace from now',
                            replica['replica_id'])
                        serve_state.set_replica_launched_at(
                            self.service_name, replica['replica_id'],
                            launched_at)
                    age = time.time() - launched_at
                    if age > self.spec.readiness_probe. \
                            initial_delay_seconds:
                        self.scale_down([replica['replica_id']])
                        self.scale_up(1, pool=replica.get('pool'))
                elif failures >= _MAX_CONSECUTIVE_FAILURES:
                    # Persistent failure: replace the replica.
                    self.scale_down([replica['replica_id']])
                    self.scale_up(1, pool=replica.get('pool'))

    def ready_endpoints(self) -> List[str]:
        return [r['endpoint']
                for r in serve_state.get_replicas(self.service_name)
                if r['status'] == serve_state.ReplicaStatus.READY and
                r['endpoint']]
