"""Serve public API: up / down / status.

Reference analog: sky/serve/server + serve_utils. Consolidated mode: the
controller (+embedded LB) is a local process of the API-server host.
"""
import os
import socket
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.serve import serve_state


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(('127.0.0.1', 0))
        return s.getsockname()[1]


def up(task, service_name: str, wait_seconds: float = 0.0
       ) -> Dict[str, Any]:
    """Create a service from a task with a `service:` section."""
    if task.service is None:
        raise exceptions.InvalidTaskError(
            'Task has no service: section; cannot `serve up`.')
    if serve_state.get_service(service_name) is not None:
        raise exceptions.ServeError(
            f'Service {service_name!r} already exists.')
    lb_port = _free_port()
    serve_state.add_service(service_name, task.to_yaml_config(), lb_port,
                            controller_port=0)
    from skypilot_tpu.utils import controller_utils
    if controller_utils.controller_mode('serve') == 'dedicated':
        from skypilot_tpu import execution
        from skypilot_tpu import task as task_lib
        handle = controller_utils.ensure_controller_cluster('serve')
        cmd = controller_utils.controller_run_command(
            handle, 'skypilot_tpu.serve.controller',
            '--service-name', service_name)
        ctrl = task_lib.Task(name=f'serve-ctrl-{service_name}',
                             run=f'JAX_PLATFORMS=cpu {cmd}')
        execution.exec_cmd(ctrl, cluster_name=handle.cluster_name,
                           detach_run=True)
    else:
        log_path = serve_state.controller_log_path(service_name)
        with open(log_path, 'ab') as log_f:
            proc = subprocess.Popen(
                [sys.executable, '-m', 'skypilot_tpu.serve.controller',
                 '--service-name', service_name],
                stdout=log_f, stderr=log_f, start_new_session=True,
                env=dict(os.environ, JAX_PLATFORMS='cpu'))
        serve_state.set_service_controller(service_name, proc.pid)
    if wait_seconds:
        deadline = time.time() + wait_seconds
        while time.time() < deadline:
            service = serve_state.get_service(service_name)
            if service and service['status'] == \
                    serve_state.ServiceStatus.READY:
                break
            time.sleep(0.5)
    return {'service_name': service_name,
            'endpoint': f'http://127.0.0.1:{lb_port}'}


def update(task, service_name: str) -> Dict[str, Any]:
    """Rolling update: bump the service version with a new task; the
    controller replaces replicas one at a time, keeping capacity up."""
    service = serve_state.get_service(service_name)
    if service is None:
        raise exceptions.ServeError(
            f'Service {service_name!r} does not exist.')
    if task.service is None:
        raise exceptions.InvalidTaskError(
            'Updated task has no service: section.')
    new_version = service['version'] + 1
    serve_state.set_service_version(service_name, new_version,
                                    task.to_yaml_config())
    return {'service_name': service_name, 'version': new_version}


def down(service_name: str, purge: bool = False) -> None:
    service = serve_state.get_service(service_name)
    if service is None:
        if purge:
            return
        raise exceptions.ServeError(
            f'Service {service_name!r} does not exist.')
    serve_state.set_service_status(service_name,
                                   serve_state.ServiceStatus.SHUTTING_DOWN)
    # Controller notices and cleans up — but only wait for it if its
    # process is actually alive (it may have crashed FAILED earlier).
    # A dedicated controller runs on its own cluster, where a local pid
    # probe is meaningless: rely on its loop seeing SHUTTING_DOWN and
    # removing the service row (its cluster job then exits).
    from skypilot_tpu.utils import controller_utils
    dedicated = controller_utils.controller_mode('serve') == 'dedicated'
    pid = service['controller_pid']
    controller_alive = False
    if pid and not dedicated:
        try:
            os.kill(pid, 0)
            controller_alive = True
        except (ProcessLookupError, PermissionError):
            pass
    if controller_alive or dedicated:
        deadline = time.time() + 120
        while time.time() < deadline:
            if serve_state.get_service(service_name) is None:
                return
            time.sleep(0.5)
        if pid and not dedicated:
            try:
                os.kill(pid, 15)
            except ProcessLookupError:
                pass
    from skypilot_tpu import task as task_lib
    from skypilot_tpu.serve import replica_managers
    task = task_lib.Task.from_yaml_config(service['task_yaml'])
    replica_managers.ReplicaManager(
        service_name, task, task.service).terminate_all()
    serve_state.remove_service(service_name)


def status(service_names: Optional[List[str]] = None
           ) -> List[Dict[str, Any]]:
    out = []
    for service in serve_state.get_services():
        if service_names and service['name'] not in service_names:
            continue
        replicas = serve_state.get_replicas(service['name'])
        out.append({
            'name': service['name'],
            'status': service['status'].value,
            'endpoint': f'http://127.0.0.1:{service["lb_port"]}',
            'version': service['version'],
            'replicas': [{
                'replica_id': r['replica_id'],
                'status': r['status'].value,
                'cluster_name': r['cluster_name'],
                'endpoint': r['endpoint'],
            } for r in replicas],
        })
    return out


def tail_logs(service_name: str, follow: bool = True,
              poll_interval: float = 1.0) -> int:
    from skypilot_tpu.utils import context as context_lib
    service = serve_state.get_service(service_name)
    if service is None:
        raise exceptions.ServeError(
            f'Service {service_name!r} does not exist.')
    path = serve_state.controller_log_path(service_name)
    pos = 0
    while True:
        try:
            with open(path, 'r', encoding='utf-8') as f:
                f.seek(pos)
                chunk = f.read()
        except FileNotFoundError:
            chunk = ''
        if chunk:
            print(chunk, end='', flush=True)
            pos += len(chunk.encode())
        if context_lib.is_cancelled():
            return 1
        if not follow or serve_state.get_service(service_name) is None:
            return 0
        time.sleep(poll_interval)
