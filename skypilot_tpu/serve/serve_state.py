"""Service/replica state DB (SQLite).

Reference analog: sky/serve/serve_state.py (658 LoC): services table +
replica infos with status/version tracking.
"""
import enum
import json
import os
import sqlite3
import threading
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu.utils import paths

_lock = threading.Lock()


def _after_fork_in_child() -> None:
    """Fresh lock + connection in forked children: the parent process
    is multi-threaded (API server), so the inherited lock may be held
    by a thread that does not exist in the child."""
    global _lock, _conn, _conn_path
    _lock = threading.Lock()
    _conn = None
    _conn_path = None


os.register_at_fork(after_in_child=_after_fork_in_child)
_conn: Optional[sqlite3.Connection] = None
_conn_path: Optional[str] = None


class ServiceStatus(enum.Enum):
    CONTROLLER_INIT = 'CONTROLLER_INIT'
    REPLICA_INIT = 'REPLICA_INIT'      # no ready replicas yet
    READY = 'READY'
    SHUTTING_DOWN = 'SHUTTING_DOWN'
    FAILED = 'FAILED'
    NO_REPLICA = 'NO_REPLICA'          # scaled to zero / all failed


class ReplicaStatus(enum.Enum):
    PROVISIONING = 'PROVISIONING'
    STARTING = 'STARTING'              # cluster up; app not ready
    READY = 'READY'
    NOT_READY = 'NOT_READY'            # probe failing; grace period
    SHUTTING_DOWN = 'SHUTTING_DOWN'
    FAILED = 'FAILED'
    PREEMPTED = 'PREEMPTED'

    @property
    def is_terminal(self) -> bool:
        return self in (ReplicaStatus.FAILED,)


def serve_db_path() -> str:
    return os.path.join(paths.state_dir(), 'serve.db')


def controller_log_path(service_name: str) -> str:
    d = os.path.join(paths.state_dir(), 'serve_logs')
    os.makedirs(d, exist_ok=True)
    return os.path.join(d, f'{service_name}.controller.log')


def _get_conn() -> sqlite3.Connection:
    global _conn, _conn_path
    path = serve_db_path()
    with _lock:
        if _conn is None or _conn_path != path:
            _conn = sqlite3.connect(path, check_same_thread=False,
                                    timeout=30.0)
            _conn.execute('PRAGMA journal_mode=WAL')
            _conn.execute("""
                CREATE TABLE IF NOT EXISTS services (
                    name TEXT PRIMARY KEY,
                    task_yaml TEXT,
                    status TEXT,
                    created_at REAL,
                    controller_pid INTEGER,
                    lb_port INTEGER,
                    controller_port INTEGER,
                    version INTEGER DEFAULT 1
                )""")
            _conn.execute("""
                CREATE TABLE IF NOT EXISTS replicas (
                    service_name TEXT,
                    replica_id INTEGER,
                    cluster_name TEXT,
                    status TEXT,
                    version INTEGER,
                    endpoint TEXT,
                    launched_at REAL,
                    consecutive_failures INTEGER DEFAULT 0,
                    use_spot INTEGER DEFAULT 0,
                    zone TEXT,
                    pool TEXT,
                    PRIMARY KEY (service_name, replica_id)
                )""")
            cols = [r[1] for r in _conn.execute(
                'PRAGMA table_info(replicas)')]
            if 'use_spot' not in cols:  # pre-spot DBs
                _conn.execute('ALTER TABLE replicas ADD COLUMN '
                              'use_spot INTEGER DEFAULT 0')
                _conn.execute('ALTER TABLE replicas ADD COLUMN zone TEXT')
            if 'pool' not in cols:  # pre-pool DBs
                _conn.execute('ALTER TABLE replicas ADD COLUMN '
                              'pool TEXT')
            _conn.commit()
            _conn_path = path
        return _conn


def reset_for_tests() -> None:
    global _conn, _conn_path
    with _lock:
        if _conn is not None:
            _conn.close()
        _conn = None
        _conn_path = None


# --- services ---------------------------------------------------------------

def add_service(name: str, task_yaml: Dict[str, Any], lb_port: int,
                controller_port: int) -> None:
    conn = _get_conn()
    with _lock:
        conn.execute(
            'INSERT INTO services (name, task_yaml, status, created_at, '
            'lb_port, controller_port) VALUES (?,?,?,?,?,?)',
            (name, json.dumps(task_yaml),
             ServiceStatus.CONTROLLER_INIT.value, time.time(), lb_port,
             controller_port))
        conn.commit()


def set_service_status(name: str, status: ServiceStatus) -> None:
    conn = _get_conn()
    with _lock:
        conn.execute('UPDATE services SET status=? WHERE name=?',
                     (status.value, name))
        conn.commit()


def set_service_controller(name: str, pid: int) -> None:
    conn = _get_conn()
    with _lock:
        conn.execute('UPDATE services SET controller_pid=? WHERE name=?',
                     (pid, name))
        conn.commit()


def set_service_version(name: str, version: int,
                        task_yaml: Dict[str, Any]) -> None:
    conn = _get_conn()
    with _lock:
        conn.execute(
            'UPDATE services SET version=?, task_yaml=? WHERE name=?',
            (version, json.dumps(task_yaml), name))
        conn.commit()


def remove_service(name: str) -> None:
    conn = _get_conn()
    with _lock:
        conn.execute('DELETE FROM services WHERE name=?', (name,))
        conn.execute('DELETE FROM replicas WHERE service_name=?', (name,))
        conn.commit()


def get_service(name: str) -> Optional[Dict[str, Any]]:
    conn = _get_conn()
    row = conn.execute(
        'SELECT name, task_yaml, status, created_at, controller_pid, '
        'lb_port, controller_port, version FROM services WHERE name=?',
        (name,)).fetchone()
    return _service_row(row) if row else None


def get_services() -> List[Dict[str, Any]]:
    conn = _get_conn()
    rows = conn.execute(
        'SELECT name, task_yaml, status, created_at, controller_pid, '
        'lb_port, controller_port, version FROM services '
        'ORDER BY created_at').fetchall()
    return [_service_row(r) for r in rows]


def _service_row(row) -> Dict[str, Any]:
    (name, task_yaml, status, created_at, controller_pid, lb_port,
     controller_port, version) = row
    return {
        'name': name,
        'task_yaml': json.loads(task_yaml) if task_yaml else None,
        'status': ServiceStatus(status),
        'created_at': created_at,
        'controller_pid': controller_pid,
        'lb_port': lb_port,
        'controller_port': controller_port,
        'version': version,
    }


# --- replicas ---------------------------------------------------------------

def add_replica(service_name: str, replica_id: int, cluster_name: str,
                version: int, use_spot: bool = False,
                zone: Optional[str] = None,
                pool: Optional[str] = None) -> None:
    conn = _get_conn()
    with _lock:
        conn.execute(
            'INSERT OR REPLACE INTO replicas (service_name, replica_id, '
            'cluster_name, status, version, launched_at, use_spot, '
            'zone, pool) VALUES (?,?,?,?,?,?,?,?,?)',
            (service_name, replica_id, cluster_name,
             ReplicaStatus.PROVISIONING.value, version, time.time(),
             int(use_spot), zone, pool))
        conn.commit()


def set_replica_status(service_name: str, replica_id: int,
                       status: ReplicaStatus,
                       endpoint: Optional[str] = None) -> None:
    conn = _get_conn()
    with _lock:
        if endpoint is not None:
            conn.execute(
                'UPDATE replicas SET status=?, endpoint=? '
                'WHERE service_name=? AND replica_id=?',
                (status.value, endpoint, service_name, replica_id))
        else:
            conn.execute(
                'UPDATE replicas SET status=? '
                'WHERE service_name=? AND replica_id=?',
                (status.value, service_name, replica_id))
        conn.commit()


def set_replica_launched_at(service_name: str, replica_id: int,
                            launched_at: float) -> None:
    """Repair a missing launch timestamp (probe grace-window anchor)."""
    conn = _get_conn()
    with _lock:
        conn.execute(
            'UPDATE replicas SET launched_at=? '
            'WHERE service_name=? AND replica_id=?',
            (launched_at, service_name, replica_id))
        conn.commit()


def bump_replica_failures(service_name: str, replica_id: int) -> int:
    conn = _get_conn()
    with _lock:
        conn.execute(
            'UPDATE replicas SET consecutive_failures='
            'consecutive_failures+1 WHERE service_name=? AND replica_id=?',
            (service_name, replica_id))
        conn.commit()
        row = conn.execute(
            'SELECT consecutive_failures FROM replicas '
            'WHERE service_name=? AND replica_id=?',
            (service_name, replica_id)).fetchone()
    return int(row[0]) if row else 0


def clear_replica_failures(service_name: str, replica_id: int) -> None:
    conn = _get_conn()
    with _lock:
        conn.execute(
            'UPDATE replicas SET consecutive_failures=0 '
            'WHERE service_name=? AND replica_id=?',
            (service_name, replica_id))
        conn.commit()


def remove_replica(service_name: str, replica_id: int) -> None:
    conn = _get_conn()
    with _lock:
        conn.execute(
            'DELETE FROM replicas WHERE service_name=? AND replica_id=?',
            (service_name, replica_id))
        conn.commit()


def get_replicas(service_name: str) -> List[Dict[str, Any]]:
    conn = _get_conn()
    rows = conn.execute(
        'SELECT service_name, replica_id, cluster_name, status, version, '
        'endpoint, launched_at, consecutive_failures, use_spot, zone, '
        'pool FROM replicas WHERE service_name=? ORDER BY replica_id',
        (service_name,)).fetchall()
    return [{
        'service_name': r[0], 'replica_id': r[1], 'cluster_name': r[2],
        'status': ReplicaStatus(r[3]), 'version': r[4], 'endpoint': r[5],
        'launched_at': r[6], 'consecutive_failures': r[7],
        'use_spot': bool(r[8]), 'zone': r[9], 'pool': r[10],
    } for r in rows]


def next_replica_id(service_name: str) -> int:
    conn = _get_conn()
    row = conn.execute(
        'SELECT COALESCE(MAX(replica_id), 0) FROM replicas '
        'WHERE service_name=?', (service_name,)).fetchone()
    return int(row[0]) + 1
