"""Load-balancing policies.

Reference analog: sky/serve/load_balancing_policies.py
(`RoundRobinPolicy` :85, `LeastLoadPolicy` :111 — the default).

Beyond the reference: `PrefixAffinityPolicy` (ROADMAP item 2) routes
by prompt CONTENT. The LB keeps a host-side fingerprint index of the
page-aligned prompt prefixes it has routed — mirroring the engine's
`inference/prefix_cache.py` radix semantics at the same page
granularity — and sends a request to the replica most likely to hold
its prefix warm in that replica's radix KV cache, so the per-replica
6x warm-TTFT win survives fleet-scale scatter. Affinity is bounded:
once the affine replica's load crosses `c x` the fleet mean the
request falls back to least-load (affinity must never create a
hotspot — the bounded-load rule of Mirrokni et al.'s consistent
hashing, applied to an explicit index instead of a hash ring).

`select()` takes an optional request `context` (a dict with
`prompt_tokens` / `max_new_tokens`, produced by the LB's JSON peek or
the fleetsim workload) and an optional `candidates` restriction (the
replica-pool slice the LB computed from request shape). Policies that
ignore content simply ignore both.
"""
import collections
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

from skypilot_tpu import envs
from skypilot_tpu.observability import instruments as obs


class LoadBalancingPolicy:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.replicas: List[str] = []

    def set_replicas(self, replicas: List[str]) -> None:
        with self._lock:
            self.replicas = list(replicas)

    def select(self, context: Optional[Dict[str, Any]] = None,
               candidates: Optional[Sequence[str]] = None
               ) -> Optional[str]:
        raise NotImplementedError

    def on_request_start(self, url: str,
                         context: Optional[Dict[str, Any]] = None
                         ) -> None:
        pass

    def on_request_end(self, url: str) -> None:
        pass

    def stats(self) -> Dict[str, Any]:
        """Routing-internal state for /internal/stats (non-mutating)."""
        return {}


class RoundRobinPolicy(LoadBalancingPolicy):
    def __init__(self) -> None:
        super().__init__()
        self._index = 0

    def select(self, context: Optional[Dict[str, Any]] = None,
               candidates: Optional[Sequence[str]] = None
               ) -> Optional[str]:
        with self._lock:
            pool = list(candidates) if candidates else self.replicas
            if not pool:
                return None
            url = pool[self._index % len(pool)]
            self._index += 1
            return url


class LeastLoadPolicy(LoadBalancingPolicy):
    """Route to the replica with the fewest in-flight requests."""

    def __init__(self) -> None:
        super().__init__()
        self._in_flight: Dict[str, int] = {}

    def set_replicas(self, replicas: List[str]) -> None:
        with self._lock:
            self.replicas = list(replicas)
            self._in_flight = {r: self._in_flight.get(r, 0)
                               for r in replicas}

    def select(self, context: Optional[Dict[str, Any]] = None,
               candidates: Optional[Sequence[str]] = None
               ) -> Optional[str]:
        with self._lock:
            pool = list(candidates) if candidates else self.replicas
            if not pool:
                return None
            return min(pool,
                       key=lambda r: self._in_flight.get(r, 0))

    def on_request_start(self, url: str,
                         context: Optional[Dict[str, Any]] = None
                         ) -> None:
        with self._lock:
            self._in_flight[url] = self._in_flight.get(url, 0) + 1

    def on_request_end(self, url: str) -> None:
        with self._lock:
            self._in_flight[url] = max(
                0, self._in_flight.get(url, 0) - 1)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {'in_flight': {r: self._in_flight.get(r, 0)
                                  for r in self.replicas}}


class PrefixAffinityPolicy(LeastLoadPolicy):
    """Content-aware routing with a bounded-load hotspot guard.

    Index model: every routed prompt contributes one fingerprint per
    page-aligned prefix (a hash chain over `page_tokens`-token pages,
    the LB-side mirror of the engine radix tree's full-page-only
    rule), each mapping to the replicas that served it. A lookup
    walks the chain and picks the replica with the DEEPEST match —
    the one holding the most reusable KV pages. The index is pure
    host bookkeeping bounded by `max_entries` (LRU over
    fingerprints): it predicts warmth, it never pins replica memory,
    so a stale entry costs one mispredicted route, not correctness.

    Load model: in-flight requests plus request starts within
    `load_window` seconds (the recency term keeps a burst dispatched
    within one scheduling quantum — before any request finishes —
    from piling onto a single warm replica). The affine pick is taken
    only while `load + 1 <= ceil(c * (total_load + 1) / n_replicas)`;
    past that the request spills to least-load AND the spill target
    is indexed too, so a hot prefix family automatically replicates
    across exactly as many replicas as its traffic needs.
    """

    def __init__(self, now_fn=time.monotonic) -> None:
        super().__init__()
        self._now = now_fn
        self._page = max(1, envs.SKYTPU_LB_AFFINITY_PAGE_TOKENS.get())
        self._bound = envs.SKYTPU_LB_AFFINITY_BOUND.get()
        self._max_entries = max(
            1, envs.SKYTPU_LB_AFFINITY_MAX_ENTRIES.get())
        self._window = envs.SKYTPU_LB_AFFINITY_LOAD_WINDOW.get()
        # fingerprint -> {url: last-use tick}; _order is the LRU.
        self._index: Dict[int, Dict[str, int]] = {}
        self._order: 'collections.OrderedDict[int, None]' = \
            collections.OrderedDict()
        self._url_entries: Dict[str, int] = {}
        self._recent: Dict[str, collections.deque] = {}
        self._rr = 0
        self._tick = 0

    # -- fingerprinting -------------------------------------------------------

    def _fingerprints(self, context: Optional[Dict[str, Any]]
                      ) -> List[int]:
        """One fingerprint per full page-aligned prompt prefix (the
        hash chain makes fp_k depend on all k pages, so equal tails
        under different heads never collide structurally). Memoized
        in the context dict: select(), failover retries, and
        on_request_start() all see the same request, so the
        O(prompt) hashing under the routing lock runs once, not once
        per hook."""
        if not context:
            return []
        cached = context.get('_fps')
        if cached is not None:
            return cached
        tokens = context.get('prompt_tokens')
        if not tokens:
            prompt = context.get('prompt')
            if not isinstance(prompt, str) or not prompt:
                return []
            tokens = list(prompt.encode('utf-8'))
        ps = self._page
        fps: List[int] = []
        h = 0
        for off in range(0, len(tokens) - ps + 1, ps):
            h = hash((h, tuple(tokens[off:off + ps])))
            fps.append(h)
        context['_fps'] = fps
        return fps

    # -- load accounting ------------------------------------------------------

    def _load_locked(self, url: str) -> int:
        load = self._in_flight.get(url, 0)
        if self._window > 0:
            recent = self._recent.get(url)
            if recent:
                cutoff = self._now() - self._window
                while recent and recent[0] < cutoff:
                    recent.popleft()
                load += len(recent)
        return load

    def _least_load_locked(self, pool: Sequence[str]) -> str:
        """Least-load with a rotating tie-break: equal-load replicas
        (the cold-start common case) must not all collapse onto
        list position zero — that would seed every prefix family on
        one replica."""
        loads = [self._load_locked(r) for r in pool]
        lo = min(loads)
        ties = [r for r, l in zip(pool, loads) if l == lo]
        self._rr += 1
        return ties[self._rr % len(ties)]

    # -- selection ------------------------------------------------------------

    def select(self, context: Optional[Dict[str, Any]] = None,
               candidates: Optional[Sequence[str]] = None
               ) -> Optional[str]:
        with self._lock:
            pool = list(candidates) if candidates else self.replicas
            if not pool:
                return None
            fps = self._fingerprints(context)
            if not fps:
                # No routable content (GET, opaque body): plain
                # least-load, not an affinity miss.
                return self._least_load_locked(pool)
            pool_set = set(pool)
            depth: Dict[str, int] = {}
            for d, fp in enumerate(fps):
                entry = self._index.get(fp)
                if entry is None:
                    break
                matched = False
                for url in entry:
                    if url in pool_set:
                        depth[url] = d + 1
                        matched = True
                if not matched:
                    break
            if not depth:
                obs.LB_AFFINITY_MISSES.inc()
                return self._least_load_locked(pool)
            best = max(depth.values())
            affine = [u for u, d in depth.items() if d == best]
            target = min(affine, key=self._load_locked)
            # Bounded load: ceil(c * (total + 1) / n) is the per-
            # replica capacity; an affine pick past it spills.
            total = sum(self._load_locked(r) for r in pool)
            cap = -(-self._bound * (total + 1) // len(pool))
            if self._load_locked(target) + 1 <= cap:
                obs.LB_AFFINITY_HITS.inc()
                return target
            obs.LB_AFFINITY_FALLBACKS.inc()
            spill = [r for r in pool if r != target] or pool
            return self._least_load_locked(spill)

    # -- index maintenance ----------------------------------------------------

    def on_request_start(self, url: str,
                         context: Optional[Dict[str, Any]] = None
                         ) -> None:
        super().on_request_start(url)
        with self._lock:
            if self._window > 0:
                self._recent.setdefault(
                    url, collections.deque()).append(self._now())
            self._tick += 1
            for fp in self._fingerprints(context):
                entry = self._index.get(fp)
                if entry is None:
                    entry = self._index[fp] = {}
                else:
                    self._order.move_to_end(fp)
                if url not in entry:
                    self._url_entries[url] = \
                        self._url_entries.get(url, 0) + 1
                entry[url] = self._tick
                self._order[fp] = None
            while len(self._index) > self._max_entries:
                old_fp, _ = self._order.popitem(last=False)
                for gone in self._index.pop(old_fp, {}):
                    left = self._url_entries.get(gone, 0) - 1
                    if left <= 0:
                        self._url_entries.pop(gone, None)
                    else:
                        self._url_entries[gone] = left
            obs.LB_AFFINITY_ENTRIES.set(len(self._index))

    def set_replicas(self, replicas: List[str]) -> None:
        super().set_replicas(replicas)
        with self._lock:
            # Index entries for departed replicas age out via LRU;
            # only the recency deques are dropped eagerly (they are
            # per-URL and unbounded in key count otherwise).
            for gone in set(self._recent) - set(replicas):
                del self._recent[gone]

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                'entries': len(self._index),
                'page_tokens': self._page,
                'bound': self._bound,
                'per_replica_entries': {
                    r: self._url_entries.get(r, 0)
                    for r in self.replicas},
                'in_flight': {r: self._in_flight.get(r, 0)
                              for r in self.replicas},
            }


POLICIES = {
    'round_robin': RoundRobinPolicy,
    'least_load': LeastLoadPolicy,
    'prefix_affinity': PrefixAffinityPolicy,
}


def make_policy(name: str, now_fn=None) -> LoadBalancingPolicy:
    """`now_fn` is the affinity load-window clock seam (the fleet
    simulator routes on its virtual clock); policies that keep no
    clocks ignore it."""
    cls = POLICIES.get(name)
    if cls is None:
        raise ValueError(
            f'unknown load-balancing policy {name!r}; valid: '
            f'{", ".join(sorted(POLICIES))}')
    if cls is PrefixAffinityPolicy and now_fn is not None:
        return cls(now_fn=now_fn)
    return cls()
