"""Load-balancing policies.

Reference analog: sky/serve/load_balancing_policies.py
(`RoundRobinPolicy` :85, `LeastLoadPolicy` :111 — the default).
"""
import threading
from typing import Dict, List, Optional


class LoadBalancingPolicy:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.replicas: List[str] = []

    def set_replicas(self, replicas: List[str]) -> None:
        with self._lock:
            self.replicas = list(replicas)

    def select(self) -> Optional[str]:
        raise NotImplementedError

    def on_request_start(self, url: str) -> None:
        pass

    def on_request_end(self, url: str) -> None:
        pass


class RoundRobinPolicy(LoadBalancingPolicy):
    def __init__(self) -> None:
        super().__init__()
        self._index = 0

    def select(self) -> Optional[str]:
        with self._lock:
            if not self.replicas:
                return None
            url = self.replicas[self._index % len(self.replicas)]
            self._index += 1
            return url


class LeastLoadPolicy(LoadBalancingPolicy):
    """Route to the replica with the fewest in-flight requests."""

    def __init__(self) -> None:
        super().__init__()
        self._in_flight: Dict[str, int] = {}

    def set_replicas(self, replicas: List[str]) -> None:
        with self._lock:
            self.replicas = list(replicas)
            self._in_flight = {r: self._in_flight.get(r, 0)
                               for r in replicas}

    def select(self) -> Optional[str]:
        with self._lock:
            if not self.replicas:
                return None
            return min(self.replicas,
                       key=lambda r: self._in_flight.get(r, 0))

    def on_request_start(self, url: str) -> None:
        with self._lock:
            self._in_flight[url] = self._in_flight.get(url, 0) + 1

    def on_request_end(self, url: str) -> None:
        with self._lock:
            self._in_flight[url] = max(
                0, self._in_flight.get(url, 0) - 1)


POLICIES = {
    'round_robin': RoundRobinPolicy,
    'least_load': LeastLoadPolicy,
}


def make_policy(name: str) -> LoadBalancingPolicy:
    return POLICIES[name]()
