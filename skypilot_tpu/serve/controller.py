"""Service controller: probe -> autoscale -> sync LB, in one loop.

Reference analog: sky/serve/controller.py:36 (`SkyServeController`) +
service.py:155 (bootstrap/cleanup). One controller process per service
runs the replica manager loop AND hosts the load balancer (consolidated;
the reference splits them into two uvicorn processes on the controller
VM — ours keeps one process with the LB on its own thread).
"""
import argparse
import logging
import os
import time
import traceback
from typing import Callable, Optional

from skypilot_tpu import envs
from skypilot_tpu.observability import instruments as obs
from skypilot_tpu.resilience import faults
from skypilot_tpu.serve import autoscalers
from skypilot_tpu.serve import load_balancer as lb_lib
from skypilot_tpu.serve import replica_managers
from skypilot_tpu.serve import serve_state
from skypilot_tpu.serve import service_spec as spec_lib

logger = logging.getLogger(__name__)

def _loop_interval_seconds() -> float:
    """Read at call time: controllers are spawned as fresh processes
    and tests tune the cadence after import."""
    return envs.SKYTPU_SERVE_LOOP_INTERVAL.get()


def _pick_victims(pool, n, protected=frozenset()):
    """Replica ids to retire: not-ready first, then newest (highest
    id, least-warm); never a protected (rolling-update surge) one."""
    candidates = sorted(
        (r for r in pool if r['replica_id'] not in protected),
        key=lambda r: (r['status'] == serve_state.ReplicaStatus.READY,
                       -r['replica_id']))
    return [r['replica_id'] for r in candidates[:n]]


class ServeController:
    """One service's reconcile loop.

    `manager`, `lb`, `now_fn` and `sleep_fn` are injection seams: the
    fleet simulator (skypilot_tpu/fleetsim) drives this EXACT class
    against thousands of mock replicas on a virtual clock, so the
    reconcile logic soak-tested in CI is the code production runs —
    the same discipline resilience/retries.py uses for its clocks.
    """

    def __init__(self, service_name: str,
                 manager=None, lb=None,
                 now_fn: Callable[[], float] = time.time,
                 sleep_fn: Callable[[float], None] = time.sleep,
                 signal_source: Optional[
                     autoscalers.MetricsSignalSource] = None) -> None:
        self.service_name = service_name
        service = serve_state.get_service(service_name)
        assert service is not None, service_name
        from skypilot_tpu import task as task_lib
        self.task = task_lib.Task.from_yaml_config(service['task_yaml'])
        assert self.task.service is not None
        self.spec: spec_lib.ServiceSpec = self.task.service
        self.manager = manager if manager is not None else \
            replica_managers.ReplicaManager(
                service_name, self.task, self.spec)
        self.autoscaler = autoscalers.make_autoscaler(self.spec,
                                                      now_fn=now_fn)
        # Disaggregated pools: one signal-driven autoscaler per named
        # pool; empty for legacy poolless specs (the fleet-wide
        # autoscaler above governs those).
        self.pool_autoscalers = autoscalers.make_pool_autoscalers(
            self.spec, now_fn=now_fn)
        self.lb = lb if lb is not None else lb_lib.LoadBalancer(
            self.spec.load_balancing_policy, port=service['lb_port'],
            now_fn=now_fn)
        self.signals = signal_source if signal_source is not None \
            else autoscalers.MetricsSignalSource()
        self._now = now_fn
        self._sleep = sleep_fn
        self._stop = False

    def run(self) -> None:
        try:
            serve_state.set_service_controller(self.service_name,
                                               os.getpid())
            self.lb.start()
            serve_state.set_service_status(
                self.service_name, serve_state.ServiceStatus.REPLICA_INIT)
            if self.spec.pools:
                for name, pool in self.spec.pools.items():
                    self.manager.scale_up(pool.min_replicas, pool=name)
            else:
                self.manager.scale_up(self.spec.min_replicas)
            while not self._stop:
                self._step()
                self._sleep(_loop_interval_seconds())
        except BaseException:  # noqa: BLE001
            traceback.print_exc()
            serve_state.set_service_status(
                self.service_name, serve_state.ServiceStatus.FAILED)
            raise

    def _step(self) -> None:
        # Armed with latency this models a stalled controller, with an
        # exception a crashed tick — chaos schedules exercise both.
        faults.inject('controller.step', sleep_fn=self._sleep,
                      env_exc=RuntimeError)
        service = serve_state.get_service(self.service_name)
        if service is None or \
                service['status'] == serve_state.ServiceStatus.SHUTTING_DOWN:
            self._shutdown()
            return
        self._maybe_reload_spec(service)
        self.manager.probe_all()
        updating = self._rolling_update(service)
        replicas = serve_state.get_replicas(self.service_name)
        ready = self.manager.ready_endpoints()

        live = [r for r in replicas
                if r['status'] not in (
                    serve_state.ReplicaStatus.SHUTTING_DOWN,
                    serve_state.ReplicaStatus.FAILED)]
        if self.spec.pools:
            # Pool-aware LB sync: the routing layer needs each ready
            # endpoint's pool ROLE to steer request shapes.
            self.lb.set_replicas(
                ready, pools=self._pool_role_map(replicas))
            target = self._scale_pools(service, live, ready, updating)
            self._export_metrics(replicas, live, target)
            self._set_health_status(live, ready)
            return
        self.lb.set_replicas(ready)
        # During a rolling update the ROLLOUT owns replacing old
        # replicas; the autoscaler must neither kill the new-version
        # surge replicas nor treat them as excess. Protection is
        # CAPPED at the rollout's own entitlement (min_replicas + 1
        # newest new-version replicas): autoscaler-spawned spike
        # replicas also carry the new version, and blanket-protecting
        # them would let a stalled update pin a scaled-up fleet at
        # peak cost — the failure mode this gate exists to avoid.
        surge = sorted(
            (r for r in live
             if updating and r['version'] >= service['version']),
            key=lambda r: -r['replica_id'])
        protected = frozenset(
            r['replica_id']
            for r in surge[:self.spec.min_replicas + 1])
        if isinstance(self.autoscaler,
                      autoscalers.FallbackRequestRateAutoscaler):
            target = self._scale_mixed(live, protected)
        else:
            decision = self.autoscaler.decide(
                len(ready), len(live), self.lb.tracker.qps(),
                self.signals.read())
            target = decision.target_replicas
            if decision.target_replicas > len(live):
                self.manager.scale_up(
                    decision.target_replicas - len(live))
            else:
                n = len(live) - decision.target_replicas - len(protected)
                if n > 0:
                    self.manager.scale_down(
                        _pick_victims(live, n, protected))

        self._export_metrics(replicas, live, target)
        self._set_health_status(live, ready)

    # -- replica pools --------------------------------------------------------

    def _pool_name_of(self, replica) -> str:
        """A row's pool, defaulting unpooled strays (pre-migration
        rows) into the first declared pool so they stay governed."""
        pool = replica.get('pool')
        if pool in self.spec.pools:
            return pool
        return next(iter(self.spec.pools))

    def _pool_role_map(self, replicas) -> dict:
        return {
            r['endpoint']: self.spec.pools[self._pool_name_of(r)].role
            for r in replicas
            if r['endpoint'] and
            r['status'] == serve_state.ReplicaStatus.READY}

    def _scale_pools(self, service, live, ready, updating) -> int:
        """Per-pool reconcile: each pool's signal-driven autoscaler
        sees only its own replicas and its own pressure signals (one
        shared snapshot per tick so pools never race each other for
        the histogram windows). Returns the combined target."""
        names = list(self.spec.pools)
        reader = getattr(self.signals, 'read_pools', None)
        signals = reader(names) if reader is not None else \
            {name: self.signals.read() for name in names}
        qps = self.lb.tracker.qps()
        ready_set = set(ready)
        total_target = 0
        for name, pool in self.spec.pools.items():
            pool_live = [r for r in live
                         if self._pool_name_of(r) == name]
            pool_ready = [r for r in pool_live
                          if r['endpoint'] in ready_set]
            # Same surge-protection rule as the fleet-wide path,
            # scoped to this pool's rollout entitlement.
            surge = sorted(
                (r for r in pool_live
                 if updating and r['version'] >= service['version']),
                key=lambda r: -r['replica_id'])
            protected = frozenset(
                r['replica_id']
                for r in surge[:pool.min_replicas + 1])
            decision = self.pool_autoscalers[name].decide(
                len(pool_ready), len(pool_live), qps,
                signals.get(name))
            target = decision.target_replicas
            total_target += target
            if target > len(pool_live):
                self.manager.scale_up(target - len(pool_live),
                                      pool=name)
            else:
                n = len(pool_live) - target - len(protected)
                if n > 0:
                    self.manager.scale_down(
                        _pick_victims(pool_live, n, protected))
            obs.POOL_TARGET_REPLICAS.labels(
                service=self.service_name, pool=name).set(target)
            obs.POOL_READY_REPLICAS.labels(
                service=self.service_name, pool=name).set(
                    len(pool_ready))
        return total_target

    def _export_metrics(self, replicas, live, target) -> None:
        """Serve-plane gauges: replica counts per lifecycle state plus
        autoscaler target vs. actual — the launch→ready gap and
        scaling lag become scrapes instead of log archaeology. Every
        state is set each tick (including to 0) so a drained state's
        stale gauge can't linger."""
        counts = {state: 0 for state in serve_state.ReplicaStatus}
        for r in replicas:
            counts[r['status']] = counts.get(r['status'], 0) + 1
        for state, n in counts.items():
            obs.SERVE_REPLICAS.labels(service=self.service_name,
                                      state=state.value).set(n)
        obs.AUTOSCALER_TARGET_REPLICAS.labels(
            service=self.service_name).set(target)
        obs.AUTOSCALER_ACTUAL_REPLICAS.labels(
            service=self.service_name).set(len(live))

    def _set_health_status(self, live, ready) -> None:
        status = (serve_state.ServiceStatus.READY if ready else
                  (serve_state.ServiceStatus.NO_REPLICA if not live else
                   serve_state.ServiceStatus.REPLICA_INIT))
        serve_state.set_service_status(self.service_name, status)

    def _scale_mixed(self, live, protected=frozenset()) -> int:
        """Spot fleet with on-demand fallback: reconcile the two pools
        separately toward the mixed decision. `protected` replicas
        (rolling-update surge) are never victims and grant their pool
        an equal headroom allowance. Returns the combined target."""
        spot = [r for r in live if r.get('use_spot')]
        ondemand = [r for r in live if not r.get('use_spot')]
        ready_spot = [r for r in spot
                      if r['status'] == serve_state.ReplicaStatus.READY]
        decision = self.autoscaler.decide_mixed(
            len(ready_spot), len(spot), len(ondemand),
            self.lb.tracker.qps(), self.signals.read())

        def reconcile(pool, target, use_spot):
            if target > len(pool):
                self.manager.scale_up(target - len(pool),
                                      use_spot=use_spot)
            else:
                shielded = sum(1 for r in pool
                               if r['replica_id'] in protected)
                n = len(pool) - target - shielded
                if n > 0:
                    self.manager.scale_down(
                        _pick_victims(pool, n, protected))

        reconcile(spot, decision.target_spot, True)
        reconcile(ondemand, decision.target_ondemand, False)
        return decision.target_spot + decision.target_ondemand

    def _maybe_reload_spec(self, service) -> None:
        """Pick up a version bump from `serve update` (new task YAML)."""
        if service['version'] == getattr(self, '_loaded_version', 1):
            return
        from skypilot_tpu import task as task_lib
        self.task = task_lib.Task.from_yaml_config(service['task_yaml'])
        self.spec = self.task.service
        self.manager.task = self.task
        self.manager.spec = self.spec
        self.autoscaler.update_spec(self.spec)
        # Pool membership may have changed shape entirely (pools
        # added/dropped): rebuild rather than patch, but preserve
        # each surviving pool's hysteresis clock state.
        fresh = autoscalers.make_pool_autoscalers(self.spec,
                                                  now_fn=self._now)
        for name, scaler in fresh.items():
            old = self.pool_autoscalers.get(name)
            if old is not None:
                old.update_spec(scaler.spec)
                fresh[name] = old
        self.pool_autoscalers = fresh
        self._loaded_version = service['version']

    def _rolling_update(self, service) -> bool:
        """Replace old-version replicas one at a time, never dropping
        below the ready set (reference rolling update,
        replica_managers.py version tracking). With pools, each pool
        rolls independently (its own surge, its own min_replicas
        floor) — a slow prefill-pool rollout must not stall decode's.
        Returns True while an update is in progress (old-version
        replicas still live)."""
        replicas = serve_state.get_replicas(self.service_name)
        if self.spec.pools:
            updating = False
            for name, pool in self.spec.pools.items():
                rows = [r for r in replicas
                        if self._pool_name_of(r) == name]
                updating |= self._rolling_update_pool(
                    service, rows, pool.min_replicas, pool=name)
            return updating
        return self._rolling_update_pool(
            service, replicas, self.spec.min_replicas, pool=None)

    def _rolling_update_pool(self, service, replicas,
                             min_replicas: int,
                             pool: Optional[str]) -> bool:
        version = service['version']
        old = [r for r in replicas if r['version'] < version and
               r['status'] not in (serve_state.ReplicaStatus.SHUTTING_DOWN,
                                   serve_state.ReplicaStatus.FAILED)]
        if not old:
            return False
        new_live = [r for r in replicas if r['version'] >= version and
                    r['status'] not in (
                        serve_state.ReplicaStatus.SHUTTING_DOWN,
                        serve_state.ReplicaStatus.FAILED)]
        new_ready = [r for r in new_live
                     if r['status'] == serve_state.ReplicaStatus.READY]
        # One surge replica at a time: launch a new-version replica if
        # none is in flight. Retirement pacing counts only READY old
        # replicas as capacity: retire dead weight (not-ready old)
        # freely once replacements appear, but retire a READY old one
        # only while (old_ready + new_ready) stays above min_replicas —
        # retiring per tick merely because SOME new replica is ready
        # would collapse serving capacity while later surges boot.
        if len(new_live) < min_replicas + 1 and \
                len(new_live) == len(new_ready):
            if pool is None:
                self.manager.scale_up(1)
            else:
                self.manager.scale_up(1, pool=pool)
        if new_ready:
            old_ready = [r for r in old if r['status'] ==
                         serve_state.ReplicaStatus.READY]
            old_not_ready = [r for r in old if r['status'] !=
                             serve_state.ReplicaStatus.READY]
            if old_not_ready:
                victim = min(old_not_ready,
                             key=lambda r: r['replica_id'])
                self.manager.scale_down([victim['replica_id']])
            elif old_ready and len(old_ready) + len(new_ready) > \
                    min_replicas:
                victim = min(old_ready, key=lambda r: r['replica_id'])
                self.manager.scale_down([victim['replica_id']])
        return True

    def _shutdown(self) -> None:
        self.manager.terminate_all()
        self.lb.stop()
        serve_state.remove_service(self.service_name)
        self._stop = True


def start(service_name: str) -> None:
    ServeController(service_name).run()


if __name__ == '__main__':
    parser = argparse.ArgumentParser()
    parser.add_argument('--service-name', required=True)
    args = parser.parse_args()
    start(args.service_name)
