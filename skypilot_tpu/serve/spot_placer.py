"""Spot placer: zone selection for spot replicas with preemption memory.

Reference analog: sky/serve/spot_placer.py (`SpotPlacer` :170,
`DynamicFallbackSpotPlacer` :254). Zones live in two sets:

  ACTIVE      — believed to have spot capacity; new replicas go here.
  PREEMPTIVE  — a replica was recently preempted there; avoided.

On preemption the zone moves ACTIVE → PREEMPTIVE. When every zone has
become preemptive the placer resets them all to ACTIVE (capacity
conditions change; starving forever is worse than re-probing). A
successful long-lived replica moves its zone back to ACTIVE. New
replicas pick the least-loaded ACTIVE zone so the service spreads
across independent capacity pools.
"""
from __future__ import annotations

import collections
import threading
from typing import Dict, List, Optional


class SpotPlacer:
    """Active/preemptive zone-set placement for spot replicas."""

    def __init__(self, zones: List[str]) -> None:
        if not zones:
            raise ValueError('SpotPlacer requires at least one zone')
        self._lock = threading.Lock()
        self._active = list(dict.fromkeys(zones))  # ordered, de-duped
        self._preemptive: List[str] = []

    # -- introspection (tests/serve status) ---------------------------------

    @property
    def active_zones(self) -> List[str]:
        with self._lock:
            return list(self._active)

    @property
    def preemptive_zones(self) -> List[str]:
        with self._lock:
            return list(self._preemptive)

    # -- placement -----------------------------------------------------------

    def select(self, existing_zone_counts: Optional[Dict[str, int]] = None
               ) -> str:
        """Zone for the next spot replica: least-loaded ACTIVE zone
        (ties broken by configured order)."""
        counts = collections.Counter(existing_zone_counts or {})
        with self._lock:
            return min(self._active, key=lambda z: (counts[z],
                                                    self._active.index(z)))

    # -- feedback ------------------------------------------------------------

    def handle_preemption(self, zone: Optional[str]) -> None:
        """A spot replica in `zone` was preempted: demote the zone; if
        nothing is left active, reset (DynamicFallbackSpotPlacer
        behavior — all-preemptive means our memory is stale, not that
        the whole region is permanently dry)."""
        if zone is None:
            return
        with self._lock:
            if zone in self._active:
                self._active.remove(zone)
                self._preemptive.append(zone)
            if not self._active:
                self._active = list(self._preemptive)
                self._preemptive = []

    def handle_active(self, zone: Optional[str]) -> None:
        """A replica in `zone` turned READY: the zone has capacity."""
        if zone is None:
            return
        with self._lock:
            if zone in self._preemptive:
                self._preemptive.remove(zone)
            if zone not in self._active:
                self._active.append(zone)
