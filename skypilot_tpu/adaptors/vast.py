"""Vast.ai adaptor: api-key REST v0 API.

Reference analog: sky/provision/vast/utils.py (the reference drives
the `vastai_sdk`; the public console API is plain JSON). Vast is a
spot-like GPU MARKET: capacity is discovered by searching offers
('bundles') and an instance is created by accepting an offer ('ask').
Credential: VAST_API_KEY env var or ~/.vast_api_key (the vast CLI's
drop location).
"""
from typing import Dict, Optional

from skypilot_tpu.adaptors import rest

API_ENDPOINT = 'https://console.vast.ai'
CREDENTIALS_PATH = '~/.vast_api_key'

RestApiError = rest.RestApiError


def get_api_key() -> Optional[str]:
    return rest.env_or_file_credential('VAST_API_KEY', CREDENTIALS_PATH)


def _make_client() -> rest.RestClient:
    def _headers() -> Dict[str, str]:
        key = get_api_key()
        if not key:
            from skypilot_tpu import exceptions
            raise exceptions.ProvisionError(
                'Vast API key not found; set VAST_API_KEY or create '
                f'{CREDENTIALS_PATH}.')
        return {'Authorization': f'Bearer {key}'}

    return rest.RestClient(
        API_ENDPOINT, _headers,
        error_code_fn=lambda payload: payload.get('error', ''))


_slot = rest.ClientSlot(_make_client)
client = _slot.get
set_client_factory = _slot.set_factory


def classify_api_error(err: RestApiError):
    from skypilot_tpu import exceptions
    text = str(err).lower()
    if ('no_such_ask' in text or 'ask is gone' in text
            or 'no offers' in text or err.status == 410):
        # The offer was taken by someone else — a capacity condition:
        # retry elsewhere.
        return exceptions.CapacityError(str(err))
    if 'quota' in text or 'credit' in text:
        return exceptions.QuotaExceededError(str(err))
    return err
