"""AWS EC2 adaptor: SigV4-signed Query API over stdlib urllib.

Reference analog: sky/adaptors/aws.py wraps boto3 (lazy import +
per-thread session caching); boto3 is not available in this build, so
ours signs EC2 Query-API calls directly (AWS Signature Version 4, the
documented HMAC-SHA256 scheme) and parses the XML responses into plain
dicts. The client is injectable so unit tests run the full provisioner
against an in-memory EC2 (the reference uses moto for the same,
tests/common_test_fixtures.py:414).

Client interface (real and fake): `call(action, params) -> dict` where
dict is the XML response converted with <xSet>/<item> lists flattened.
"""
import configparser
import datetime
import hashlib
import hmac
import os
import threading
import urllib.error
import urllib.parse
import urllib.request
import xml.etree.ElementTree as ET
from typing import Any, Callable, Dict, List, Optional

from skypilot_tpu import exceptions

_EC2_API_VERSION = '2016-11-15'


class AwsApiError(exceptions.ProvisionError):
    def __init__(self, message: str, code: str = '', status: int = 0):
        super().__init__(message)
        self.code = code
        self.status = status


def classify_api_error(err: 'AwsApiError') -> exceptions.ProvisionError:
    """Map EC2 error codes onto the failover taxonomy (quota/stockout →
    retry in another zone), mirroring the reference's
    FailoverCloudErrorHandlerV2 treatment of botocore ClientErrors."""
    code = err.code
    if code in ('InsufficientInstanceCapacity', 'InsufficientHostCapacity',
                'InsufficientReservedInstanceCapacity', 'Unsupported'):
        return exceptions.CapacityError(str(err))
    if (code in ('InstanceLimitExceeded', 'VcpuLimitExceeded',
                 'MaxSpotInstanceCountExceeded', 'RequestLimitExceeded')
            or 'LimitExceeded' in code):
        return exceptions.QuotaExceededError(str(err))
    return err


# ---------------------------------------------------------------------------
# Credentials


def load_credentials() -> Optional[Dict[str, str]]:
    """Static credentials from env or ~/.aws/credentials (default
    profile). Returns None when nothing is configured."""
    key = os.environ.get('AWS_ACCESS_KEY_ID')
    secret = os.environ.get('AWS_SECRET_ACCESS_KEY')
    token = os.environ.get('AWS_SESSION_TOKEN')
    if key and secret:
        return {'access_key': key, 'secret_key': secret,
                **({'token': token} if token else {})}
    path = os.environ.get('AWS_SHARED_CREDENTIALS_FILE',
                          os.path.expanduser('~/.aws/credentials'))
    if os.path.isfile(path):
        parser = configparser.ConfigParser()
        try:
            parser.read(path)
            profile = os.environ.get('AWS_PROFILE', 'default')
            if parser.has_section(profile):
                sec = parser[profile]
                if ('aws_access_key_id' in sec
                        and 'aws_secret_access_key' in sec):
                    creds = {
                        'access_key': sec['aws_access_key_id'],
                        'secret_key': sec['aws_secret_access_key'],
                    }
                    if 'aws_session_token' in sec:
                        creds['token'] = sec['aws_session_token']
                    return creds
        except configparser.Error:
            return None
    return None


# ---------------------------------------------------------------------------
# SigV4 (AWS Signature Version 4 — public, documented scheme)


def _sign(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def _sigv4_headers(creds: Dict[str, str], region: str, host: str,
                   body: str) -> Dict[str, str]:
    now = datetime.datetime.now(datetime.timezone.utc)
    amz_date = now.strftime('%Y%m%dT%H%M%SZ')
    date = now.strftime('%Y%m%d')
    service = 'ec2'
    payload_hash = hashlib.sha256(body.encode()).hexdigest()
    headers = {
        'content-type': 'application/x-www-form-urlencoded; charset=utf-8',
        'host': host,
        'x-amz-date': amz_date,
    }
    if creds.get('token'):
        headers['x-amz-security-token'] = creds['token']
    signed_headers = ';'.join(sorted(headers))
    canonical_headers = ''.join(
        f'{k}:{headers[k]}\n' for k in sorted(headers))
    canonical_request = '\n'.join([
        'POST', '/', '', canonical_headers, signed_headers, payload_hash])
    scope = f'{date}/{region}/{service}/aws4_request'
    string_to_sign = '\n'.join([
        'AWS4-HMAC-SHA256', amz_date, scope,
        hashlib.sha256(canonical_request.encode()).hexdigest()])
    k = _sign(('AWS4' + creds['secret_key']).encode(), date)
    k = _sign(k, region)
    k = _sign(k, service)
    k = _sign(k, 'aws4_request')
    signature = hmac.new(k, string_to_sign.encode(),
                         hashlib.sha256).hexdigest()
    headers['authorization'] = (
        f'AWS4-HMAC-SHA256 Credential={creds["access_key"]}/{scope}, '
        f'SignedHeaders={signed_headers}, Signature={signature}')
    return headers


# ---------------------------------------------------------------------------
# XML → dict


def _xml_to_obj(elem: ET.Element) -> Any:
    """EC2 response XML → plain python: elements whose children are all
    <item> become lists; leaves become strings."""
    children = list(elem)
    if not children:
        return elem.text or ''
    if all(_local(c.tag) == 'item' for c in children):
        return [_xml_to_obj(c) for c in children]
    out: Dict[str, Any] = {}
    for c in children:
        out[_local(c.tag)] = _xml_to_obj(c)
    return out


def _local(tag: str) -> str:
    return tag.rsplit('}', 1)[-1]


def parse_response(text: str) -> Dict[str, Any]:
    root = ET.fromstring(text)
    obj = _xml_to_obj(root)
    return obj if isinstance(obj, dict) else {'items': obj}


# ---------------------------------------------------------------------------
# Client


class Ec2Client:
    """Real EC2 Query-API client for one region."""

    def __init__(self, region: str,
                 creds: Optional[Dict[str, str]] = None) -> None:
        self.region = region
        self._creds = creds

    def call(self, action: str, params: Optional[Dict[str, str]] = None
             ) -> Dict[str, Any]:
        if self._creds is None:
            self._creds = load_credentials()
        creds = self._creds
        if creds is None:
            raise exceptions.ProvisionError(
                'AWS credentials not found; set AWS_ACCESS_KEY_ID / '
                'AWS_SECRET_ACCESS_KEY or populate ~/.aws/credentials.')
        host = f'ec2.{self.region}.amazonaws.com'
        query = {'Action': action, 'Version': _EC2_API_VERSION}
        query.update(params or {})
        body = urllib.parse.urlencode(sorted(query.items()))
        headers = _sigv4_headers(creds, self.region, host, body)
        req = urllib.request.Request(
            f'https://{host}/', data=body.encode(), headers=headers,
            method='POST')
        try:
            with urllib.request.urlopen(req, timeout=60) as resp:
                return parse_response(resp.read().decode())
        except urllib.error.HTTPError as e:
            payload = e.read().decode(errors='replace')
            code = ''
            try:
                err = parse_response(payload)
                errors = err.get('Errors', {})
                if isinstance(errors, dict):
                    errors = errors.get('Error', errors)
                code = (errors or {}).get('Code', '')
            except ET.ParseError:
                pass
            raise AwsApiError(
                f'{action}: HTTP {e.code}: {payload[:500]}',
                code=code, status=e.code) from e
        except urllib.error.URLError as e:
            raise AwsApiError(f'{action}: {e.reason}') from e


_client_factory: Callable[[str], Any] = Ec2Client
_clients: Dict[str, Any] = {}
_lock = threading.Lock()


def _after_fork_in_child() -> None:
    """Fresh lock in forked children (parent is multi-threaded)."""
    global _lock
    _lock = threading.Lock()
    _clients.clear()


os.register_at_fork(after_in_child=_after_fork_in_child)


def set_client_factory(factory: Callable[[str], Any]) -> None:
    """Test hook: inject a fake EC2 (drops cached clients)."""
    global _client_factory
    with _lock:
        _client_factory = factory
        _clients.clear()


def client(region: str) -> Any:
    with _lock:
        if region not in _clients:
            _clients[region] = _client_factory(region)
        return _clients[region]


def flat_params(prefix: str, values: List[Any]) -> Dict[str, str]:
    """['a','b'] with prefix 'Filter.1.Value' style numbering."""
    return {f'{prefix}.{i + 1}': v for i, v in enumerate(values)}


def tag_filters(cluster_name_on_cloud: str,
                extra: Optional[Dict[str, List[str]]] = None
                ) -> Dict[str, str]:
    """DescribeInstances Filter params selecting this cluster's nodes."""
    filters: List[Dict[str, Any]] = [
        {'Name': 'tag:skytpu-cluster', 'Values': [cluster_name_on_cloud]},
    ]
    for name, values in (extra or {}).items():
        filters.append({'Name': name, 'Values': values})
    params: Dict[str, str] = {}
    for i, f in enumerate(filters, 1):
        params[f'Filter.{i}.Name'] = f['Name']
        for j, v in enumerate(f['Values'], 1):
            params[f'Filter.{i}.Value.{j}'] = v
    return params
