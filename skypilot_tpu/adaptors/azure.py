"""Azure Resource Manager (ARM) adaptor: JSON REST with az-CLI auth.

Reference analog: sky/adaptors/azure.py wraps the azure SDK; ours talks
the ARM REST API directly (the azure SDK stack is not a dependency in
this build) behind an injectable client so unit tests run the full
provisioner against an in-memory ARM fake — same pattern as the GCP
transport and AWS client fakes.

Client interface (real and fake):
    request(method, path, params=None, json_body=None) -> dict
`path` is relative to https://management.azure.com and must carry its
api-version in `params`.
"""
import json
import subprocess
import threading
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Callable, Dict, Optional

from skypilot_tpu import exceptions

ARM_ENDPOINT = 'https://management.azure.com'
COMPUTE_API_VERSION = '2023-09-01'
NETWORK_API_VERSION = '2023-09-01'


class AzureApiError(exceptions.ProvisionError):
    def __init__(self, message: str, code: str = '', status: int = 0):
        super().__init__(message)
        self.code = code
        self.status = status


def classify_api_error(err: 'AzureApiError') -> exceptions.ProvisionError:
    """ARM error codes → failover taxonomy (stockout/quota errors are
    retryable in another region), mirroring the reference's
    FailoverCloudErrorHandler treatment of azure errors."""
    code = err.code
    if code in ('SkuNotAvailable', 'AllocationFailed',
                'ZonalAllocationFailed', 'OverconstrainedAllocationRequest'):
        return exceptions.CapacityError(str(err))
    if code in ('QuotaExceeded', 'OperationNotAllowed') or \
            'Quota' in code:
        return exceptions.QuotaExceededError(str(err))
    return err


def _az_token() -> str:
    proc = subprocess.run(
        ['az', 'account', 'get-access-token', '--output', 'json'],
        capture_output=True, timeout=30, check=False)
    if proc.returncode != 0:
        raise exceptions.ProvisionError(
            'Cannot obtain an Azure access token: '
            f'{proc.stderr.decode(errors="replace").strip()}')
    return json.loads(proc.stdout)['accessToken']


def default_subscription() -> str:
    import os
    sub = os.environ.get('AZURE_SUBSCRIPTION_ID')
    if sub:
        return sub
    proc = subprocess.run(
        ['az', 'account', 'show', '--query', 'id', '--output', 'tsv'],
        capture_output=True, timeout=15, check=False)
    sub = proc.stdout.decode().strip()
    if proc.returncode != 0 or not sub:
        raise exceptions.ProvisionError(
            'No Azure subscription configured; set AZURE_SUBSCRIPTION_ID '
            'or run `az login`.')
    return sub


class ArmClient:
    """Real ARM REST client (bearer token from the az CLI)."""

    def __init__(self) -> None:
        self._token: Optional[str] = None
        self._lock = threading.Lock()

    def _headers(self) -> Dict[str, str]:
        with self._lock:
            if self._token is None:
                self._token = _az_token()
            return {'Authorization': f'Bearer {self._token}',
                    'Content-Type': 'application/json'}

    def request(self, method: str, path: str,
                params: Optional[Dict[str, str]] = None,
                json_body: Optional[Dict[str, Any]] = None,
                _retry_auth: bool = True) -> Dict[str, Any]:
        url = f'{ARM_ENDPOINT}{path}'
        if params:
            url += f'?{urllib.parse.urlencode(params)}'
        data = None
        if json_body is not None:
            data = json.dumps(json_body).encode()
        req = urllib.request.Request(url, data=data,
                                     headers=self._headers(),
                                     method=method)
        try:
            with urllib.request.urlopen(req, timeout=60) as resp:
                body = resp.read()
        except urllib.error.HTTPError as e:
            if e.code == 401 and _retry_auth:
                # az tokens live ~1h; refresh once and retry (long-
                # lived controllers outlast the first token).
                with self._lock:
                    self._token = None
                return self.request(method, path, params=params,
                                    json_body=json_body,
                                    _retry_auth=False)
            payload = e.read().decode(errors='replace')
            code = ''
            try:
                code = json.loads(payload).get('error', {}).get('code', '')
            except (json.JSONDecodeError, AttributeError):
                pass
            raise AzureApiError(
                f'{method} {path}: HTTP {e.code}: {payload[:500]}',
                code=code, status=e.code) from e
        except urllib.error.URLError as e:
            raise AzureApiError(f'{method} {path}: {e.reason}') from e
        return json.loads(body) if body else {}


_client_factory: Callable[[], Any] = ArmClient
_client: Optional[Any] = None
_lock = threading.Lock()


def _after_fork_in_child() -> None:
    """Fresh lock in forked children (parent is multi-threaded)."""
    global _lock, _client
    _lock = threading.Lock()
    _client = None


import os  # noqa: E402
os.register_at_fork(after_in_child=_after_fork_in_child)


def set_client_factory(factory: Callable[[], Any]) -> None:
    """Test hook: inject a fake ARM (drops the cached client)."""
    global _client_factory, _client
    with _lock:
        _client_factory = factory
        _client = None


def client() -> Any:
    global _client
    with _lock:
        if _client is None:
            _client = _client_factory()
        return _client
