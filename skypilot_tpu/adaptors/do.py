"""DigitalOcean adaptor: bearer-token REST v2 API.

Reference analog: sky/provision/do/utils.py (the reference uses
pydo/azure-core; the public v2 REST surface is plain JSON).
Credential: DIGITALOCEAN_TOKEN env var or the doctl config's
access-token.
"""
from typing import Dict, Optional

from skypilot_tpu.adaptors import rest

API_ENDPOINT = 'https://api.digitalocean.com'
CREDENTIALS_PATH = '~/.config/doctl/config.yaml'

RestApiError = rest.RestApiError


def get_token() -> Optional[str]:
    return rest.env_or_file_credential('DIGITALOCEAN_TOKEN',
                                       CREDENTIALS_PATH,
                                       line_keys=('access-token',),
                                       sep=':')


def _make_client() -> rest.RestClient:
    def _headers() -> Dict[str, str]:
        token = get_token()
        if not token:
            from skypilot_tpu import exceptions
            raise exceptions.ProvisionError(
                'DigitalOcean token not found; set DIGITALOCEAN_TOKEN '
                f'or configure doctl ({CREDENTIALS_PATH}).')
        return {'Authorization': f'Bearer {token}'}

    return rest.RestClient(
        API_ENDPOINT, _headers,
        error_code_fn=lambda payload: payload.get('id', ''))


_slot = rest.ClientSlot(_make_client)
client = _slot.get
set_client_factory = _slot.set_factory


def classify_api_error(err: RestApiError):
    from skypilot_tpu import exceptions
    text = str(err).lower()
    if err.status == 422 and ('unavailable' in text
                              or 'out of capacity' in text):
        return exceptions.CapacityError(str(err))
    if 'limit' in text and err.status in (403, 422):
        return exceptions.QuotaExceededError(str(err))
    return err
