"""Shared JSON-over-HTTPS plumbing for the flat REST VM clouds.

Reference analog: sky/adaptors/common.py (LazyImport around cloud
SDKs). The GPU-neocloud APIs (Lambda Cloud, RunPod, Nebius,
DigitalOcean) are all bearer-token JSON REST — no SDK is worth the
dependency, so each adaptor is a thin per-cloud wrapper over this
module: one `RestClient` plus one injectable client slot so unit tests
run the real provisioner against an in-memory fake API (same strategy
as the GCP transport / AWS client / ARM fakes).
"""
import json
import os
import threading
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Callable, Dict, Optional

from skypilot_tpu import exceptions


class RestApiError(exceptions.ProvisionError):
    """HTTP-level failure from a cloud REST API."""

    def __init__(self, message: str, code: str = '', status: int = 0):
        super().__init__(message)
        self.code = code
        self.status = status


class RestClient:
    """Minimal JSON REST client.

    `headers_fn` is called per request so short-lived tokens refresh
    naturally; `error_code_fn` extracts a cloud-specific error code
    string from the decoded error payload for failover taxonomy.
    """

    def __init__(self, base_url: str,
                 headers_fn: Callable[[], Dict[str, str]],
                 error_code_fn: Optional[Callable[[Any], str]] = None,
                 timeout: float = 60.0):
        self._base_url = base_url.rstrip('/')
        self._headers_fn = headers_fn
        self._error_code_fn = error_code_fn
        self._timeout = timeout

    def request(self, method: str, path: str,
                params: Optional[Dict[str, str]] = None,
                json_body: Optional[Any] = None) -> Any:
        url = f'{self._base_url}{path}'
        if params:
            url += f'?{urllib.parse.urlencode(params)}'
        data = None
        headers = {'Content-Type': 'application/json',
                   **self._headers_fn()}
        if json_body is not None:
            data = json.dumps(json_body).encode()
        req = urllib.request.Request(url, data=data, headers=headers,
                                     method=method)
        try:
            with urllib.request.urlopen(req,
                                        timeout=self._timeout) as resp:
                body = resp.read()
        except urllib.error.HTTPError as e:
            payload = e.read().decode(errors='replace')
            code = ''
            if self._error_code_fn is not None:
                try:
                    code = self._error_code_fn(json.loads(payload)) or ''
                except (json.JSONDecodeError, AttributeError, KeyError,
                        TypeError):
                    code = ''
            raise RestApiError(
                f'{method} {path}: HTTP {e.code}: {payload[:500]}',
                code=code, status=e.code) from e
        except urllib.error.URLError as e:
            raise RestApiError(f'{method} {path}: {e.reason}') from e
        return json.loads(body) if body else {}


class ClientSlot:
    """Injectable, fork-safe, lazily-constructed client singleton.

    Every REST-cloud adaptor owns one; tests swap the factory for an
    in-memory fake. Forked executor children get a fresh lock and drop
    the cached client (sockets don't survive fork).
    """

    def __init__(self, default_factory: Callable[[], Any]):
        self._factory = default_factory
        self._client: Optional[Any] = None
        self._lock = threading.Lock()
        os.register_at_fork(after_in_child=self._after_fork_in_child)

    def _after_fork_in_child(self) -> None:
        # The fork child is single-threaded by construction (only the
        # forking thread survives), and the parent's lock may be held
        # by a thread that no longer exists — so replace the lock and
        # drop the client WITHOUT taking it.
        self._lock = threading.Lock()
        self._client = None  # skytpu-lint: ignore[unguarded-mutation]

    def set_factory(self, factory: Callable[[], Any]) -> None:
        with self._lock:
            self._factory = factory
            self._client = None

    def get(self) -> Any:
        with self._lock:
            if self._client is None:
                self._client = self._factory()
            return self._client


def env_or_file_credential(env_var: str, path: str,
                           key: Optional[str] = None,
                           line_keys: Optional[tuple] = None,
                           sep: str = '=') -> Optional[str]:
    """API key from env var, else from a file (~-expanded).

    File interpretation: with `key` the body is JSON and that key is
    returned; with `line_keys` the file is scanned for a
    `<key><sep><value>` line (ini/toml/yaml-ish credential drops —
    quotes stripped); otherwise the stripped body IS the credential.
    Unreadable file == no credential (check_credentials must report
    (False, reason), never crash)."""
    value = os.environ.get(env_var)
    if value:
        return value
    full = os.path.expanduser(path)
    if not os.path.isfile(full):
        return None
    try:
        with open(full, 'r', encoding='utf-8') as f:
            body = f.read()
    except OSError:
        return None
    if line_keys is not None:
        for line in body.splitlines():
            name, _, val = line.partition(sep)
            val = val.strip().strip('"\'')
            if name.strip() in line_keys and val:
                return val
        return None
    body = body.strip()
    if not body:
        return None
    if key is None:
        return body
    try:
        return json.loads(body).get(key)
    except json.JSONDecodeError:
        return None
