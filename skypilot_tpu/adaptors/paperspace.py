"""Paperspace adaptor: api-key REST v1 API.

Reference analog: sky/provision/paperspace/utils.py (the reference
uses `requests` against the same public API). Credential:
PAPERSPACE_API_KEY env var or ~/.paperspace/credentials.toml
(`api_key = "<key>"`, the pspace CLI drop location).
"""
from typing import Dict, Optional

from skypilot_tpu.adaptors import rest

API_ENDPOINT = 'https://api.paperspace.com/v1'
CREDENTIALS_PATH = '~/.paperspace/credentials.toml'

RestApiError = rest.RestApiError


def get_api_key() -> Optional[str]:
    return rest.env_or_file_credential('PAPERSPACE_API_KEY',
                                       CREDENTIALS_PATH,
                                       line_keys=('api_key', 'apiKey'))


def _make_client() -> rest.RestClient:
    def _headers() -> Dict[str, str]:
        key = get_api_key()
        if not key:
            from skypilot_tpu import exceptions
            raise exceptions.ProvisionError(
                'Paperspace API key not found; set PAPERSPACE_API_KEY '
                f'or create {CREDENTIALS_PATH}.')
        return {'Authorization': f'Bearer {key}'}

    return rest.RestClient(
        API_ENDPOINT, _headers,
        error_code_fn=lambda payload: str(payload.get('error', '')))


_slot = rest.ClientSlot(_make_client)
client = _slot.get
set_client_factory = _slot.set_factory


def classify_api_error(err: RestApiError):
    from skypilot_tpu import exceptions
    text = str(err).lower()
    if 'out of capacity' in text or 'no available' in text or \
            err.status == 503:
        return exceptions.CapacityError(str(err))
    if 'quota' in text or 'limit' in text:
        return exceptions.QuotaExceededError(str(err))
    return err
