"""OCI adaptor: request-signed core-services REST API.

Reference analog: sky/adaptors/oci.py (oci SDK). The SDK's transport
is the signed REST API at iaas.{region}.oraclecloud.com; we sign
requests directly (draft-cavage HTTP signatures, RSA-SHA256 over
(request-target)/date/host, plus content headers on writes) with the
`cryptography` package, from the standard ~/.oci/config profile
(user/fingerprint/tenancy/region/key_file).
"""
import base64
import configparser
import datetime
import email.utils
import hashlib
import json
import os
import urllib.parse
import urllib.request
from typing import Any, Dict, Optional

from skypilot_tpu.adaptors import rest

CONFIG_PATH = '~/.oci/config'
API_VERSION = '20160918'

RestApiError = rest.RestApiError


def load_config(profile: str = 'DEFAULT') -> Optional[Dict[str, str]]:
    """The ~/.oci/config profile as a dict, or None if unusable."""
    path = os.path.expanduser(os.environ.get('OCI_CONFIG_PATH',
                                             CONFIG_PATH))
    if not os.path.isfile(path):
        return None
    parser = configparser.ConfigParser()
    try:
        parser.read(path)
    except configparser.Error:
        return None
    section = dict(parser.defaults())
    if parser.has_section(profile):
        section.update(parser.items(profile))
    required = ('user', 'fingerprint', 'tenancy', 'region', 'key_file')
    if not all(section.get(k) for k in required):
        return None
    return section


def default_compartment_id() -> Optional[str]:
    cfg = load_config()
    return os.environ.get('OCI_COMPARTMENT_ID') or (
        cfg.get('tenancy') if cfg else None)


class OciSigner:
    """draft-cavage HTTP signature over OCI's required header set."""

    def __init__(self, config: Dict[str, str]):
        from cryptography.hazmat.primitives import serialization
        self._key_id = (f'{config["tenancy"]}/{config["user"]}/'
                        f'{config["fingerprint"]}')
        key_path = os.path.expanduser(config['key_file'])
        with open(key_path, 'rb') as f:
            self._key = serialization.load_pem_private_key(
                f.read(), password=None)

    def sign_headers(self, method: str, url: str,
                     body: Optional[bytes]) -> Dict[str, str]:
        from cryptography.hazmat.primitives import hashes
        from cryptography.hazmat.primitives.asymmetric import padding
        parsed = urllib.parse.urlsplit(url)
        target = parsed.path + (f'?{parsed.query}' if parsed.query
                                else '')
        date = email.utils.formatdate(usegmt=True)
        headers = {'date': date, 'host': parsed.netloc}
        to_sign = ['(request-target)', 'date', 'host']
        lines = [f'(request-target): {method.lower()} {target}',
                 f'date: {date}', f'host: {parsed.netloc}']
        if method.upper() in ('POST', 'PUT', 'PATCH'):
            body = body or b''
            sha = base64.b64encode(
                hashlib.sha256(body).digest()).decode()
            headers['x-content-sha256'] = sha
            headers['content-type'] = 'application/json'
            headers['content-length'] = str(len(body))
            to_sign += ['x-content-sha256', 'content-type',
                        'content-length']
            lines += [f'x-content-sha256: {sha}',
                      'content-type: application/json',
                      f'content-length: {len(body)}']
        signature = base64.b64encode(self._key.sign(
            '\n'.join(lines).encode(), padding.PKCS1v15(),
            hashes.SHA256())).decode()
        headers['authorization'] = (
            'Signature version="1",'
            f'keyId="{self._key_id}",'
            'algorithm="rsa-sha256",'
            f'headers="{" ".join(to_sign)}",'
            f'signature="{signature}"')
        return headers


class OciClient:
    """Signed JSON client for the core-services API (region from the
    profile; paths are rooted at /<API_VERSION>)."""

    def __init__(self) -> None:
        config = load_config()
        if config is None:
            from skypilot_tpu import exceptions
            raise exceptions.ProvisionError(
                f'OCI config not found/incomplete at {CONFIG_PATH} '
                '(need user/fingerprint/tenancy/region/key_file).')
        self._config = config
        self._signer = OciSigner(config)
        self._base = (f'https://iaas.{config["region"]}.oraclecloud.com'
                      f'/{API_VERSION}')

    def request(self, method: str, path: str,
                params: Optional[Dict[str, str]] = None,
                json_body: Optional[Any] = None) -> Any:
        url = f'{self._base}{path}'
        if params:
            url += f'?{urllib.parse.urlencode(params)}'
        body = (json.dumps(json_body).encode()
                if json_body is not None else None)
        headers = self._signer.sign_headers(method, url, body)
        req = urllib.request.Request(url, data=body, headers=headers,
                                     method=method)
        try:
            with urllib.request.urlopen(req, timeout=60) as resp:
                payload = resp.read()
        except urllib.error.HTTPError as e:
            text = e.read().decode(errors='replace')
            code = ''
            try:
                code = json.loads(text).get('code', '')
            except (json.JSONDecodeError, AttributeError):
                pass
            raise RestApiError(f'{method} {path}: HTTP {e.code}: '
                               f'{text[:500]}', code=code,
                               status=e.code) from e
        except urllib.error.URLError as e:
            raise RestApiError(f'{method} {path}: {e.reason}') from e
        return json.loads(payload) if payload else {}


_slot = rest.ClientSlot(OciClient)
client = _slot.get
set_client_factory = _slot.set_factory


def classify_api_error(err: RestApiError):
    from skypilot_tpu import exceptions
    code = getattr(err, 'code', '')
    text = str(err).lower()
    if code in ('OutOfHostCapacity', 'InternalError') and \
            'capacity' in text or 'out of host capacity' in text:
        return exceptions.CapacityError(str(err))
    if code in ('LimitExceeded', 'QuotaExceeded') or 'quota' in text:
        return exceptions.QuotaExceededError(str(err))
    if err.status == 429:
        return exceptions.CapacityError(str(err))
    return err
