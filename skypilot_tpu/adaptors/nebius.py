"""Nebius AI Cloud adaptor: IAM-bearer REST over the compute v1 API.

Reference analog: sky/provision/nebius/utils.py (the reference drives
the `nebius` SDK; the same compute surface is reachable as JSON REST
at the regional API endpoint). Credential: NEBIUS_IAM_TOKEN env var or
~/.nebius/NEBIUS_IAM_TOKEN.txt (the SDK's drop location); the parent
project id comes from provider config or NEBIUS_PROJECT_ID.
"""
import os
from typing import Dict, Optional

from skypilot_tpu.adaptors import rest

API_ENDPOINT = 'https://api.eu.nebius.cloud'
CREDENTIALS_PATH = '~/.nebius/NEBIUS_IAM_TOKEN.txt'

RestApiError = rest.RestApiError


def get_iam_token() -> Optional[str]:
    return rest.env_or_file_credential('NEBIUS_IAM_TOKEN',
                                       CREDENTIALS_PATH)


def default_project_id() -> Optional[str]:
    return os.environ.get('NEBIUS_PROJECT_ID')


def _make_client() -> rest.RestClient:
    def _headers() -> Dict[str, str]:
        token = get_iam_token()
        if not token:
            from skypilot_tpu import exceptions
            raise exceptions.ProvisionError(
                'Nebius IAM token not found; set NEBIUS_IAM_TOKEN or '
                f'create {CREDENTIALS_PATH}.')
        return {'Authorization': f'Bearer {token}'}

    return rest.RestClient(
        API_ENDPOINT, _headers,
        error_code_fn=lambda payload: payload.get('code', ''))


_slot = rest.ClientSlot(_make_client)
client = _slot.get
set_client_factory = _slot.set_factory


def classify_api_error(err: RestApiError):
    from skypilot_tpu import exceptions
    text = str(err).lower()
    if ('resource_exhausted' in (err.code or '').lower()
            or 'not enough capacity' in text or err.status == 503):
        return exceptions.CapacityError(str(err))
    if 'quota' in text:
        return exceptions.QuotaExceededError(str(err))
    return err
