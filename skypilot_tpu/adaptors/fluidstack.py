"""Fluidstack adaptor: api-key REST v1 API.

Reference analog: sky/provision/fluidstack/fluidstack_utils.py (the
reference wraps the same platform API with `requests`). Credential:
FLUIDSTACK_API_KEY env var or ~/.fluidstack/api_key.
"""
from typing import Dict, Optional

from skypilot_tpu.adaptors import rest

API_ENDPOINT = 'https://platform.fluidstack.io'
CREDENTIALS_PATH = '~/.fluidstack/api_key'

RestApiError = rest.RestApiError


def get_api_key() -> Optional[str]:
    return rest.env_or_file_credential('FLUIDSTACK_API_KEY',
                                       CREDENTIALS_PATH)


def _make_client() -> rest.RestClient:
    def _headers() -> Dict[str, str]:
        key = get_api_key()
        if not key:
            from skypilot_tpu import exceptions
            raise exceptions.ProvisionError(
                'Fluidstack API key not found; set FLUIDSTACK_API_KEY '
                f'or create {CREDENTIALS_PATH}.')
        return {'api-key': key}

    return rest.RestClient(
        API_ENDPOINT, _headers,
        error_code_fn=lambda payload: payload.get('error', ''))


_slot = rest.ClientSlot(_make_client)
client = _slot.get
set_client_factory = _slot.set_factory


def classify_api_error(err: RestApiError):
    from skypilot_tpu import exceptions
    text = str(err).lower()
    if 'no capacity' in text or 'unavailable' in text or \
            err.status == 503:
        return exceptions.CapacityError(str(err))
    if 'quota' in text or 'limit' in text:
        return exceptions.QuotaExceededError(str(err))
    return err
