"""SCP (Samsung Cloud Platform) adaptor: HMAC-signed open API.

Reference analog: sky/adaptors/scp.py + sky/provision/scp/instance.py
(requests with AccessKey/Secret HMAC headers). Credential:
SCP_ACCESS_KEY/SCP_SECRET_KEY/SCP_PROJECT_ID env vars or
~/.scp/scp_credential (`access_key = ...` lines, the reference's drop
location). Every request carries the signed header set
(X-Cmp-AccessKey, X-Cmp-Timestamp, X-Cmp-Signature over
method+url+timestamp+access key+project id).
"""
import base64
import hashlib
import hmac
import os
import time
import urllib.parse
from typing import Any, Dict, Optional

from skypilot_tpu.adaptors import rest

API_ENDPOINT = 'https://openapi.samsungsdscloud.com'
CREDENTIALS_PATH = '~/.scp/scp_credential'

RestApiError = rest.RestApiError


def _credential(env: str, keys: tuple) -> Optional[str]:
    return rest.env_or_file_credential(env, CREDENTIALS_PATH,
                                       line_keys=keys, sep='=')


def get_access_key() -> Optional[str]:
    return _credential('SCP_ACCESS_KEY', ('access_key',))


def get_secret_key() -> Optional[str]:
    return _credential('SCP_SECRET_KEY', ('secret_key',))


def get_project_id() -> Optional[str]:
    return _credential('SCP_PROJECT_ID', ('project_id',))


class ScpClient:
    """Signed JSON client (signature = HMAC-SHA256 of
    method+url+timestamp+access_key+project_id, base64)."""

    def __init__(self) -> None:
        self._access = get_access_key()
        self._secret = get_secret_key()
        self._project = get_project_id()
        if not (self._access and self._secret and self._project):
            from skypilot_tpu import exceptions
            raise exceptions.ProvisionError(
                'SCP credentials not found; set SCP_ACCESS_KEY/'
                'SCP_SECRET_KEY/SCP_PROJECT_ID or create '
                f'{CREDENTIALS_PATH}.')

    def request(self, method: str, path: str,
                params: Optional[Dict[str, str]] = None,
                json_body: Optional[Any] = None) -> Any:
        url = f'{API_ENDPOINT}{path}'
        if params:
            url += f'?{urllib.parse.urlencode(params)}'
        timestamp = str(int(time.time() * 1000))
        message = (method.upper() + url + timestamp + self._access +
                   self._project)
        signature = base64.b64encode(
            hmac.new(self._secret.encode(), message.encode(),
                     hashlib.sha256).digest()).decode()

        def _headers() -> Dict[str, str]:
            return {
                'X-Cmp-AccessKey': self._access,
                'X-Cmp-Timestamp': timestamp,
                'X-Cmp-Signature': signature,
                'X-Cmp-ProjectId': self._project,
            }

        inner = rest.RestClient(
            API_ENDPOINT, _headers,
            error_code_fn=lambda payload: payload.get('errorCode', ''))
        return inner.request(method, path, params=params,
                             json_body=json_body)


_slot = rest.ClientSlot(ScpClient)
client = _slot.get
set_client_factory = _slot.set_factory


def classify_api_error(err: RestApiError):
    from skypilot_tpu import exceptions
    text = str(err).lower()
    if ('not enough' in text or 'capacity' in text or 'sold out' in text
            or err.status == 503):
        return exceptions.CapacityError(str(err))
    if 'quota' in text or 'limit exceeded' in text:
        return exceptions.QuotaExceededError(str(err))
    return err
