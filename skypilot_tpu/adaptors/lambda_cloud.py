"""Lambda Cloud adaptor: bearer-token REST v1 API.

Reference analog: sky/adaptors/... + sky/provision/lambda_cloud/
lambda_utils.py (the reference wraps the same public API with
`requests`). Credential: LAMBDA_API_KEY env var or
~/.lambda_cloud/lambda_keys (`api_key = <key>` line, the format the
reference's lambda_utils reads).
"""
from typing import Dict, Optional

from skypilot_tpu.adaptors import rest

API_ENDPOINT = 'https://cloud.lambdalabs.com/api/v1'
CREDENTIALS_PATH = '~/.lambda_cloud/lambda_keys'

RestApiError = rest.RestApiError


def get_api_key() -> Optional[str]:
    return rest.env_or_file_credential('LAMBDA_API_KEY',
                                       CREDENTIALS_PATH,
                                       line_keys=('api_key',))


def _make_client() -> rest.RestClient:
    def _headers() -> Dict[str, str]:
        key = get_api_key()
        if not key:
            from skypilot_tpu import exceptions
            raise exceptions.ProvisionError(
                'Lambda Cloud API key not found; set LAMBDA_API_KEY or '
                f'create {CREDENTIALS_PATH}.')
        return {'Authorization': f'Bearer {key}'}

    return rest.RestClient(
        API_ENDPOINT, _headers,
        error_code_fn=lambda payload: payload['error']['code'])


_slot = rest.ClientSlot(_make_client)
client = _slot.get
set_client_factory = _slot.set_factory


def classify_api_error(err: RestApiError):
    """Lambda error codes → failover taxonomy.

    `insufficient-capacity` / `instance-operations/launch/
    insufficient-capacity` style codes mean try another region;
    `quota-exceeded` maps to the quota bucket.
    """
    from skypilot_tpu import exceptions
    code = err.code or ''
    if 'insufficient-capacity' in code or err.status == 503:
        return exceptions.CapacityError(str(err))
    if 'quota' in code:
        return exceptions.QuotaExceededError(str(err))
    return err
