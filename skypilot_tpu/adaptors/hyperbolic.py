"""Hyperbolic adaptor: bearer-token marketplace REST API.

Reference analog: sky/provision/hyperbolic/utils.py (requests against
api.hyperbolic.xyz). Credential: HYPERBOLIC_API_KEY env var or
~/.hyperbolic/api_key (bare token file).
"""
from typing import Dict, Optional

from skypilot_tpu.adaptors import rest

API_ENDPOINT = 'https://api.hyperbolic.xyz'
CREDENTIALS_PATH = '~/.hyperbolic/api_key'

RestApiError = rest.RestApiError


def get_api_key() -> Optional[str]:
    return rest.env_or_file_credential('HYPERBOLIC_API_KEY',
                                       CREDENTIALS_PATH)


def _make_client() -> rest.RestClient:
    def _headers() -> Dict[str, str]:
        key = get_api_key()
        if not key:
            from skypilot_tpu import exceptions
            raise exceptions.ProvisionError(
                'Hyperbolic API key not found; set HYPERBOLIC_API_KEY '
                f'or create {CREDENTIALS_PATH}.')
        return {'Authorization': f'Bearer {key}'}

    return rest.RestClient(
        API_ENDPOINT, _headers,
        error_code_fn=lambda payload: payload.get('error_code', ''))


_slot = rest.ClientSlot(_make_client)
client = _slot.get
set_client_factory = _slot.set_factory


def classify_api_error(err: RestApiError):
    from skypilot_tpu import exceptions
    text = str(err).lower()
    if ('no machines available' in text or 'out of capacity' in text
            or 'insufficient' in text or err.status == 503):
        return exceptions.CapacityError(str(err))
    if 'quota' in text or 'limit' in text:
        return exceptions.QuotaExceededError(str(err))
    return err
