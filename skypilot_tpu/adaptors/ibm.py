"""IBM Cloud adaptor: IAM token exchange + regional VPC REST API.

Reference analog: sky/adaptors/ibm.py (ibm_vpc SDK + IAM
authenticator; the SDK is a thin wrapper over the VPC REST API at
{region}.iaas.cloud.ibm.com). Credential: IBM_API_KEY env var or
~/.ibm/credentials.yaml (`iam_api_key: <key>` — the reference's drop
location). The IAM bearer token is cached until shortly before
expiry.
"""
import json
import threading
import time
import urllib.parse
import urllib.request
from typing import Any, Dict, Optional

from skypilot_tpu.adaptors import rest

IAM_ENDPOINT = 'https://iam.cloud.ibm.com/identity/token'
CREDENTIALS_PATH = '~/.ibm/credentials.yaml'
# VPC API version pin (date-versioned API; generation 2).
API_VERSION = '2025-01-01'
DEFAULT_REGION = 'us-south'

RestApiError = rest.RestApiError


def get_api_key() -> Optional[str]:
    return rest.env_or_file_credential(
        'IBM_API_KEY', CREDENTIALS_PATH,
        line_keys=('iam_api_key', 'api_key'), sep=':')


class IbmVpcClient:
    """Regional VPC REST client with IAM token refresh.

    `request` takes an optional `region=` kwarg (the VPC API is
    region-scoped by hostname); omitted, it uses IBM_REGION or
    us-south.
    """

    def __init__(self) -> None:
        self._token: Optional[str] = None
        self._token_expiry = 0.0
        self._lock = threading.Lock()

    def _bearer(self) -> str:
        with self._lock:
            if self._token and time.time() < self._token_expiry - 60:
                return self._token
            api_key = get_api_key()
            if not api_key:
                from skypilot_tpu import exceptions
                raise exceptions.ProvisionError(
                    'IBM API key not found; set IBM_API_KEY or create '
                    f'{CREDENTIALS_PATH}.')
            body = urllib.parse.urlencode({
                'grant_type': 'urn:ibm:params:oauth:grant-type:apikey',
                'apikey': api_key,
            }).encode()
            req = urllib.request.Request(
                IAM_ENDPOINT, data=body, method='POST',
                headers={'Content-Type':
                         'application/x-www-form-urlencoded'})
            try:
                with urllib.request.urlopen(req, timeout=30) as resp:
                    payload = json.loads(resp.read())
            except Exception as e:  # noqa: BLE001
                raise RestApiError(f'IBM IAM token exchange: {e}') from e
            self._token = payload['access_token']
            self._token_expiry = time.time() + float(
                payload.get('expires_in', 3600))
            return self._token

    def request(self, method: str, path: str,
                params: Optional[Dict[str, str]] = None,
                json_body: Optional[Any] = None,
                region: Optional[str] = None) -> Any:
        import os
        region = region or os.environ.get('IBM_REGION', DEFAULT_REGION)
        base = f'https://{region}.iaas.cloud.ibm.com'
        merged = {'version': API_VERSION, 'generation': '2',
                  **(params or {})}
        inner = rest.RestClient(
            base, lambda: {'Authorization': f'Bearer {self._bearer()}'},
            error_code_fn=lambda payload: (
                (payload.get('errors') or [{}])[0].get('code', '')))
        return inner.request(method, path, params=merged,
                             json_body=json_body)


_slot = rest.ClientSlot(IbmVpcClient)
client = _slot.get
set_client_factory = _slot.set_factory


def classify_api_error(err: RestApiError):
    from skypilot_tpu import exceptions
    text = str(err).lower()
    code = getattr(err, 'code', '')
    if ('insufficient' in text or 'capacity' in text
            or code == 'over_quota' or err.status == 503):
        if 'quota' in text or code == 'over_quota':
            return exceptions.QuotaExceededError(str(err))
        return exceptions.CapacityError(str(err))
    if 'quota' in text:
        return exceptions.QuotaExceededError(str(err))
    return err
