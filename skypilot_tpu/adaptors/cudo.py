"""Cudo Compute adaptor: api-key REST v1 API.

Reference analog: sky/provision/cudo/ (the reference drives the
cudo-compute SDK; the public REST surface at rest.compute.cudo.org is
plain JSON). Credential: CUDO_API_KEY env var or ~/.config/cudo/
cudo.yml (`key: <key>` line, the cudoctl drop location); the parent
project comes from config or CUDO_PROJECT_ID.
"""
import os
from typing import Dict, Optional

from skypilot_tpu.adaptors import rest

API_ENDPOINT = 'https://rest.compute.cudo.org'
CREDENTIALS_PATH = '~/.config/cudo/cudo.yml'

RestApiError = rest.RestApiError


def get_api_key() -> Optional[str]:
    return rest.env_or_file_credential('CUDO_API_KEY',
                                       CREDENTIALS_PATH,
                                       line_keys=('key', 'api_key'),
                                       sep=':')


def default_project_id() -> Optional[str]:
    return os.environ.get('CUDO_PROJECT_ID')


def _make_client() -> rest.RestClient:
    def _headers() -> Dict[str, str]:
        key = get_api_key()
        if not key:
            from skypilot_tpu import exceptions
            raise exceptions.ProvisionError(
                'Cudo API key not found; set CUDO_API_KEY or '
                f'create {CREDENTIALS_PATH}.')
        return {'Authorization': f'Bearer {key}'}

    return rest.RestClient(
        API_ENDPOINT, _headers,
        error_code_fn=lambda payload: payload.get('code', ''))


_slot = rest.ClientSlot(_make_client)
client = _slot.get
set_client_factory = _slot.set_factory


def classify_api_error(err: RestApiError):
    from skypilot_tpu import exceptions
    text = str(err).lower()
    if 'no host available' in text or 'out of stock' in text or \
            err.status == 503:
        return exceptions.CapacityError(str(err))
    if 'quota' in text or 'limit' in text:
        return exceptions.QuotaExceededError(str(err))
    return err
