"""GCP REST adaptor: auth + JSON transport for tpu/compute APIs.

Reference analog: sky/adaptors/gcp.py wraps googleapiclient; ours talks
REST directly via urllib (no SDK dependency) behind an injectable
transport so unit tests run the full provisioner against a fake API
(the reference leans on googleapiclient mocks / moto for the same).
"""
import json
import os
import subprocess
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Callable, Dict, Optional

from skypilot_tpu import exceptions

TPU_API = 'https://tpu.googleapis.com/v2'
COMPUTE_API = 'https://compute.googleapis.com/compute/v1'


class GcpApiError(exceptions.ProvisionError):
    """HTTP-level error from a GCP API."""

    def __init__(self, message: str, status: int = 0,
                 reason: str = '') -> None:
        super().__init__(message)
        self.status = status
        self.reason = reason


def classify_api_error(err: 'GcpApiError') -> exceptions.ProvisionError:
    """Map an API error onto the failover taxonomy (reference
    FailoverCloudErrorHandlerV2, cloud_vm_ray_backend.py:876): quota and
    stockout errors are retryable-in-another-zone."""
    text = f'{err.reason} {err}'.lower()
    if err.status == 429 or 'quota' in text or 'rate limit' in text:
        return exceptions.QuotaExceededError(str(err))
    if ('resource_exhausted' in text or 'stockout' in text or
            'no more capacity' in text or 'out of capacity' in text or
            'insufficient' in text or err.status == 503):
        return exceptions.CapacityError(str(err))
    return err


class Transport:
    """Real HTTP transport with bearer-token auth."""

    def __init__(self, token_fn: Callable[[], str]) -> None:
        self._token_fn = token_fn

    def request(self, method: str, url: str,
                params: Optional[Dict[str, str]] = None,
                json_body: Optional[Dict[str, Any]] = None
                ) -> Dict[str, Any]:
        if params:
            url = f'{url}?{urllib.parse.urlencode(params)}'
        data = None
        headers = {'Authorization': f'Bearer {self._token_fn()}'}
        if json_body is not None:
            data = json.dumps(json_body).encode()
            headers['Content-Type'] = 'application/json'
        req = urllib.request.Request(url, data=data, headers=headers,
                                     method=method)
        try:
            with urllib.request.urlopen(req, timeout=60) as resp:
                body = resp.read()
        except urllib.error.HTTPError as e:
            payload = e.read().decode(errors='replace')
            try:
                reason = json.loads(payload)['error'].get('status', '')
            except (json.JSONDecodeError, KeyError, TypeError):
                reason = ''
            raise GcpApiError(f'{method} {url}: HTTP {e.code}: {payload}',
                              status=e.code, reason=reason) from e
        except urllib.error.URLError as e:
            raise GcpApiError(f'{method} {url}: {e.reason}') from e
        return json.loads(body) if body else {}


def _gcloud_token() -> str:
    proc = subprocess.run(['gcloud', 'auth', 'print-access-token'],
                          capture_output=True, timeout=30, check=False)
    if proc.returncode != 0:
        raise exceptions.ProvisionError(
            'Cannot obtain a GCP access token: '
            f'{proc.stderr.decode(errors="replace").strip()}')
    return proc.stdout.decode().strip()


class _CachedToken:
    """Access tokens are valid ~1h; refresh with slack."""

    def __init__(self, fetch: Callable[[], str], ttl: float = 2700.0) -> None:
        self._fetch = fetch
        self._ttl = ttl
        self._token: Optional[str] = None
        self._expiry = 0.0
        self._lock = threading.Lock()

    def __call__(self) -> str:
        with self._lock:
            if self._token is None or time.time() > self._expiry:
                self._token = self._fetch()
                self._expiry = time.time() + self._ttl
            return self._token


_transport_factory: Callable[[], Any] = lambda: Transport(
    _CachedToken(_gcloud_token))
_transport: Optional[Any] = None
_transport_lock = threading.Lock()


def _after_fork_in_child() -> None:
    """Fresh lock in forked children (parent is multi-threaded)."""
    global _transport_lock, _transport
    _transport_lock = threading.Lock()
    _transport = None


os.register_at_fork(after_in_child=_after_fork_in_child)


def set_transport_factory(factory: Callable[[], Any]) -> None:
    """Test hook: inject a fake transport (and drop any cached one)."""
    global _transport_factory, _transport
    with _transport_lock:
        _transport_factory = factory
        _transport = None


def transport() -> Any:
    global _transport
    with _transport_lock:
        if _transport is None:
            _transport = _transport_factory()
        return _transport


def default_project() -> str:
    project = os.environ.get('GOOGLE_CLOUD_PROJECT') or os.environ.get(
        'CLOUDSDK_CORE_PROJECT')
    if project:
        return project
    proc = subprocess.run(['gcloud', 'config', 'get-value', 'project'],
                          capture_output=True, timeout=15, check=False)
    project = proc.stdout.decode().strip()
    if proc.returncode != 0 or not project or project == '(unset)':
        raise exceptions.ProvisionError(
            'No GCP project configured; set GOOGLE_CLOUD_PROJECT or run '
            '`gcloud config set project`.')
    return project


def wait_operation(op: Dict[str, Any], poll_url: str,
                   timeout: float = 900.0, interval: float = 5.0
                   ) -> Dict[str, Any]:
    """Poll a long-running operation until done (both tpu.* and compute.*
    operation shapes)."""
    deadline = time.time() + timeout
    while True:
        done = op.get('done', False) or op.get('status') == 'DONE'
        if done:
            error = op.get('error')
            if error:
                message = error.get('message') or json.dumps(error)
                raise classify_api_error(
                    GcpApiError(f'Operation failed: {message}',
                                reason=str(error.get('status', ''))))
            return op
        if time.time() > deadline:
            raise exceptions.ProvisionError(
                f'Operation timed out after {timeout:.0f}s: '
                f'{op.get("name", poll_url)}')
        time.sleep(interval)
        op = transport().request('GET', poll_url)
