"""Cloud SDK adaptors: lazy, dependency-free imports of cloud APIs.

Reference analog: sky/adaptors/ (LazyImport, sky/adaptors/common.py:9).
Ours are thin REST clients over urllib so `import skypilot_tpu` never
pulls a cloud SDK; tests inject fake transports.
"""
