"""vSphere adaptor: vCenter Automation (REST) API with session auth.

Reference analog: sky/adaptors/vsphere.py + sky/provision/vsphere/
(pyvmomi + the vCenter REST SDK). The Automation API is plain JSON:
POST /api/session with basic auth yields a token sent as
`vmware-api-session-id` on every call. Credentials/endpoint:
VSPHERE_SERVER / VSPHERE_USERNAME / VSPHERE_PASSWORD env vars or
~/.vsphere/credentials.yaml (`server:`/`username:`/`password:` lines).
"""
import base64
import json
import threading
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Dict, Optional

from skypilot_tpu.adaptors import rest

CREDENTIALS_PATH = '~/.vsphere/credentials.yaml'

RestApiError = rest.RestApiError


def _credential(env: str, keys: tuple) -> Optional[str]:
    return rest.env_or_file_credential(env, CREDENTIALS_PATH,
                                       line_keys=keys, sep=':')


def get_server() -> Optional[str]:
    return _credential('VSPHERE_SERVER', ('server', 'host'))


def get_username() -> Optional[str]:
    return _credential('VSPHERE_USERNAME', ('username', 'user'))


def get_password() -> Optional[str]:
    return _credential('VSPHERE_PASSWORD', ('password',))


class VsphereClient:
    """Session-token JSON client against one vCenter."""

    def __init__(self) -> None:
        server = get_server()
        user = get_username()
        password = get_password()
        if not (server and user and password):
            from skypilot_tpu import exceptions
            raise exceptions.ProvisionError(
                'vSphere credentials not found; set VSPHERE_SERVER/'
                'VSPHERE_USERNAME/VSPHERE_PASSWORD or create '
                f'{CREDENTIALS_PATH}.')
        self._base = f'https://{server}'
        self._user = user
        self._password = password
        self._session: Optional[str] = None
        self._lock = threading.Lock()

    def _session_token(self, refresh: bool = False) -> str:
        with self._lock:
            if self._session and not refresh:
                return self._session
            basic = base64.b64encode(
                f'{self._user}:{self._password}'.encode()).decode()
            req = urllib.request.Request(
                f'{self._base}/api/session', method='POST',
                headers={'Authorization': f'Basic {basic}'})
            try:
                with urllib.request.urlopen(req, timeout=30) as resp:
                    token = json.loads(resp.read())
            except Exception as e:  # noqa: BLE001
                raise RestApiError(f'vSphere session: {e}') from e
            self._session = token
            return token

    def request(self, method: str, path: str,
                params: Optional[Dict[str, str]] = None,
                json_body: Optional[Any] = None) -> Any:
        url = f'{self._base}{path}'
        if params:
            url += f'?{urllib.parse.urlencode(params)}'
        body = (json.dumps(json_body).encode()
                if json_body is not None else None)
        for attempt in range(2):
            headers = {
                'vmware-api-session-id':
                    self._session_token(refresh=attempt > 0),
                'Content-Type': 'application/json',
            }
            req = urllib.request.Request(url, data=body,
                                         headers=headers, method=method)
            try:
                with urllib.request.urlopen(req, timeout=60) as resp:
                    payload = resp.read()
                return json.loads(payload) if payload else {}
            except urllib.error.HTTPError as e:
                if e.code == 401 and attempt == 0:
                    continue  # session expired: re-auth once
                text = e.read().decode(errors='replace')
                raise RestApiError(f'{method} {path}: HTTP {e.code}: '
                                   f'{text[:500]}', status=e.code) from e
            except urllib.error.URLError as e:
                raise RestApiError(f'{method} {path}: {e.reason}') from e


_slot = rest.ClientSlot(VsphereClient)
client = _slot.get
set_client_factory = _slot.set_factory


def classify_api_error(err: RestApiError):
    from skypilot_tpu import exceptions
    text = str(err).lower()
    if ('insufficient' in text or 'no hosts' in text
            or 'resource' in text and 'unavailable' in text):
        return exceptions.CapacityError(str(err))
    return err
