"""RunPod adaptor: bearer-token REST v1 API.

Reference analog: sky/provision/runpod/utils.py (the reference drives
the `runpod` SDK's GraphQL API; RunPod's newer REST surface at
rest.runpod.io/v1 covers the same pod lifecycle with plain JSON, which
is all we need). Credential: RUNPOD_API_KEY env var or
~/.runpod/config.toml (`apikey = "<key>"` line, the SDK's location).
"""
from typing import Dict, Optional

from skypilot_tpu.adaptors import rest

API_ENDPOINT = 'https://rest.runpod.io/v1'
CREDENTIALS_PATH = '~/.runpod/config.toml'

RestApiError = rest.RestApiError


def get_api_key() -> Optional[str]:
    return rest.env_or_file_credential('RUNPOD_API_KEY',
                                       CREDENTIALS_PATH,
                                       line_keys=('apikey', 'api_key'))


def _make_client() -> rest.RestClient:
    def _headers() -> Dict[str, str]:
        key = get_api_key()
        if not key:
            from skypilot_tpu import exceptions
            raise exceptions.ProvisionError(
                'RunPod API key not found; set RUNPOD_API_KEY or '
                f'create {CREDENTIALS_PATH}.')
        return {'Authorization': f'Bearer {key}'}

    return rest.RestClient(
        API_ENDPOINT, _headers,
        error_code_fn=lambda payload: payload.get('error', ''))


_slot = rest.ClientSlot(_make_client)
client = _slot.get
set_client_factory = _slot.set_factory


def classify_api_error(err: RestApiError):
    """RunPod errors → failover taxonomy. Capacity exhaustion surfaces
    as 'no instances available' style messages on create."""
    from skypilot_tpu import exceptions
    text = str(err).lower()
    if ('no instances available' in text or 'not enough' in text
            or 'unavailable' in text or err.status == 503):
        return exceptions.CapacityError(str(err))
    if 'quota' in text or 'limit' in text:
        return exceptions.QuotaExceededError(str(err))
    return err
