"""Credential probing + enabled-cloud cache.

Reference analog: sky/check.py:53 (`check_capabilities`),
:356 (`get_cached_enabled_clouds_or_refresh`).
"""
import json
import os
import threading
from typing import Dict, List, Optional, Tuple

from skypilot_tpu import clouds as clouds_lib
from skypilot_tpu import config as config_lib
from skypilot_tpu import exceptions
from skypilot_tpu.utils.registry import CLOUD_REGISTRY

_CACHE_PATH = '~/.skytpu/enabled_clouds.json'
_lock = threading.Lock()


def _after_fork_in_child() -> None:
    """Fresh lock in forked children (parent is multi-threaded)."""
    global _lock
    _lock = threading.Lock()


os.register_at_fork(after_in_child=_after_fork_in_child)


def check_credentials(cloud_names: Optional[List[str]] = None
                      ) -> Dict[str, Tuple[bool, Optional[str]]]:
    """Probe credentials for each cloud; returns {cloud: (ok, reason)}."""
    results: Dict[str, Tuple[bool, Optional[str]]] = {}
    for name in cloud_names or CLOUD_REGISTRY.names():
        cloud = clouds_lib.get_cloud(name)
        try:
            results[name] = cloud.check_credentials()
        except Exception as e:  # noqa: BLE001 — a broken SDK != fatal
            results[name] = (False, f'credential check error: {e}')
    return results


def check(refresh: bool = True, quiet: bool = True) -> List[str]:
    """Probe all clouds, persist the enabled set, return it."""
    allowed = config_lib.get_nested(('allowed_clouds',), None)
    names = [n for n in CLOUD_REGISTRY.names()
             if allowed is None or n in allowed]
    results = check_credentials(names)
    enabled = sorted(n for n, (ok, _) in results.items() if ok)
    path = os.path.expanduser(_CACHE_PATH)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with _lock, open(path, 'w', encoding='utf-8') as f:
        json.dump({'enabled': enabled}, f)
    if not quiet:
        for name, (ok, reason) in sorted(results.items()):
            mark = 'enabled' if ok else f'disabled: {reason}'
            print(f'  {name}: {mark}')
    return enabled


def get_cached_enabled_clouds_or_refresh(
        raise_if_no_cloud_access: bool = False) -> List[str]:
    path = os.path.expanduser(_CACHE_PATH)
    enabled: Optional[List[str]] = None
    if os.path.isfile(path):
        try:
            with open(path, 'r', encoding='utf-8') as f:
                enabled = json.load(f).get('enabled')
        except (json.JSONDecodeError, OSError):
            enabled = None
    if enabled is None:
        enabled = check(quiet=True)
    if raise_if_no_cloud_access and not enabled:
        raise exceptions.NoCloudEnabledError(
            'No cloud is enabled. Run `tsky check` for details.')
    return enabled
