"""Credential probing + enabled-cloud cache.

Reference analog: sky/check.py:53 (`check_capabilities`),
:356 (`get_cached_enabled_clouds_or_refresh`).
"""
import json
import os
import threading
from typing import Dict, List, Optional, Tuple

from skypilot_tpu import clouds as clouds_lib
from skypilot_tpu import config as config_lib
from skypilot_tpu import exceptions
from skypilot_tpu.utils.registry import CLOUD_REGISTRY

_CACHE_PATH = '~/.skytpu/enabled_clouds.json'
_lock = threading.Lock()


def _after_fork_in_child() -> None:
    """Fresh lock in forked children (parent is multi-threaded)."""
    global _lock
    _lock = threading.Lock()


os.register_at_fork(after_in_child=_after_fork_in_child)


def check_credentials(cloud_names: Optional[List[str]] = None,
                      probe: bool = False
                      ) -> Dict[str, Tuple[bool, Optional[str]]]:
    """Check credentials for each cloud; returns {cloud: (ok, reason)}.

    probe=False: local presence checks only (key file / env exists) —
    offline and instant. probe=True: additionally makes one cheap
    AUTHENTICATED API call per present-credential cloud (reference
    sky/check.py:53 check_capabilities), in parallel — a revoked key
    disables the cloud HERE with its name on it, instead of failing
    over mid-provision."""
    import concurrent.futures

    names = list(cloud_names or CLOUD_REGISTRY.names())

    def _one(name: str) -> Tuple[bool, Optional[str]]:
        cloud = clouds_lib.get_cloud(name)
        try:
            if probe:
                return cloud.probe_credentials()
            return cloud.check_credentials()
        except Exception as e:  # noqa: BLE001 — a broken SDK != fatal
            return False, f'credential check error: {e}'

    if not probe or len(names) <= 1:
        return {name: _one(name) for name in names}
    with concurrent.futures.ThreadPoolExecutor(
            max_workers=min(8, len(names))) as pool:
        futures = {name: pool.submit(_one, name) for name in names}
        return {name: fut.result() for name, fut in futures.items()}


def check(refresh: bool = True, quiet: bool = True,
          probe: bool = False) -> List[str]:
    """Check all clouds, persist the enabled set + per-cloud detail,
    return the enabled list."""
    import time

    allowed = config_lib.get_nested(('allowed_clouds',), None)
    names = [n for n in CLOUD_REGISTRY.names()
             if allowed is None or n in allowed]
    results = check_credentials(names, probe=probe)
    enabled = sorted(n for n, (ok, _) in results.items() if ok)
    path = os.path.expanduser(_CACHE_PATH)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    details = {name: {'ok': ok, 'reason': reason,
                      'probed': probe,
                      'checked_at': int(time.time())}
               for name, (ok, reason) in results.items()}
    with _lock, open(path, 'w', encoding='utf-8') as f:
        json.dump({'enabled': enabled, 'details': details}, f)
    if not quiet:
        for name, (ok, reason) in sorted(results.items()):
            mark = 'enabled' if ok else f'disabled: {reason}'
            print(f'  {name}: {mark}')
    return enabled


def cached_details() -> Dict[str, Dict]:
    """Per-cloud result of the last check (reason, probed flag,
    timestamp) — what `tsky check`/the dashboard display without
    re-probing."""
    path = os.path.expanduser(_CACHE_PATH)
    try:
        with open(path, 'r', encoding='utf-8') as f:
            return json.load(f).get('details', {})
    except (json.JSONDecodeError, OSError):
        return {}


def get_cached_enabled_clouds_or_refresh(
        raise_if_no_cloud_access: bool = False) -> List[str]:
    path = os.path.expanduser(_CACHE_PATH)
    enabled: Optional[List[str]] = None
    if os.path.isfile(path):
        try:
            with open(path, 'r', encoding='utf-8') as f:
                enabled = json.load(f).get('enabled')
        except (json.JSONDecodeError, OSError):
            enabled = None
    if enabled is None:
        enabled = check(quiet=True)
    if raise_if_no_cloud_access and not enabled:
        raise exceptions.NoCloudEnabledError(
            'No cloud is enabled. Run `tsky check` for details.')
    return enabled
