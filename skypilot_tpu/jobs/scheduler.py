"""Managed-job scheduler: bounds concurrent launches/controllers.

Reference analog: sky/jobs/scheduler.py (maybe_schedule_next_jobs :113,
submit_job :197; ALIVE/LAUNCHING/WAITING states). Ours: PENDING jobs
start as controller processes whenever the launching count is under the
cap; called after submit and from the jobs API poll paths.
"""
import os
import subprocess
import sys
from typing import Optional

from skypilot_tpu.jobs import state as jobs_state

_MAX_CONCURRENT_LAUNCHES = int(
    os.environ.get('SKYTPU_JOBS_MAX_CONCURRENT_LAUNCHES', '8'))


def _start_controller(job_id: int) -> None:
    log_path = jobs_state.controller_log_path(job_id)
    with open(log_path, 'ab') as log_f:
        proc = subprocess.Popen(
            [sys.executable, '-m', 'skypilot_tpu.jobs.controller',
             '--job-id', str(job_id)],
            stdout=log_f, stderr=log_f,
            start_new_session=True,
            env=dict(os.environ, JAX_PLATFORMS='cpu'))
    jobs_state.set_controller_pid(job_id, proc.pid)


def maybe_schedule_next_jobs() -> int:
    """Start controllers for PENDING jobs up to the cap; returns number
    started. Safe under concurrent callers (forked API workers): the
    PENDING->SUBMITTED claim is an atomic conditional UPDATE."""
    started = 0
    in_flight = jobs_state.num_launching_jobs()
    for job in jobs_state.get_jobs([jobs_state.ManagedJobStatus.PENDING]):
        if in_flight >= _MAX_CONCURRENT_LAUNCHES:
            break
        if not jobs_state.try_claim_pending(job['job_id']):
            continue  # another process claimed it
        _start_controller(job['job_id'])
        in_flight += 1
        started += 1
    return started


def submit_job(name: Optional[str], task_yaml: dict,
               max_recoveries: int = 3,
               strategy: str = 'EAGER_NEXT_REGION') -> int:
    job_id = jobs_state.submit_job(name or f'job-{os.getpid()}', task_yaml,
                                   max_recoveries=max_recoveries,
                                   strategy=strategy)
    maybe_schedule_next_jobs()
    return job_id
