"""Managed-job scheduler: bounds concurrent launches/controllers.

Reference analog: sky/jobs/scheduler.py (maybe_schedule_next_jobs :113,
submit_job :197; ALIVE/LAUNCHING/WAITING states). Ours: PENDING jobs
start as controller processes whenever the launching count is under the
cap; called after submit and from the jobs API poll paths.
"""
import os
import subprocess
import sys
from typing import Optional

from skypilot_tpu import envs
from skypilot_tpu.jobs import state as jobs_state

def _max_concurrent_launches() -> int:
    """Read at call time: the cap is an operator knob, tunable on a
    live server without restarting it."""
    return envs.SKYTPU_JOBS_MAX_CONCURRENT_LAUNCHES.get()


def _start_controller(job_id: int, resume: bool = False) -> None:
    from skypilot_tpu.utils import controller_utils
    if controller_utils.controller_mode('jobs') == 'dedicated':
        _start_controller_on_cluster(job_id, resume=resume)
        return
    log_path = jobs_state.controller_log_path(job_id)
    argv = [sys.executable, '-m', 'skypilot_tpu.jobs.controller',
            '--job-id', str(job_id)]
    if resume:
        argv.append('--resume')
    with open(log_path, 'ab') as log_f:
        proc = subprocess.Popen(
            argv, stdout=log_f, stderr=log_f,
            start_new_session=True,
            env=dict(os.environ, JAX_PLATFORMS='cpu'))
    jobs_state.set_controller_pid(job_id, proc.pid)


def _start_controller_on_cluster(job_id: int,
                                 resume: bool = False) -> None:
    """Dedicated mode: the controller runs as a cluster job on the
    long-lived controller cluster (reference
    templates/jobs-controller.yaml.j2 — ours execs through the normal
    gang stack instead of rendering a template)."""
    from skypilot_tpu import execution
    from skypilot_tpu import task as task_lib
    from skypilot_tpu.utils import controller_utils
    handle = controller_utils.ensure_controller_cluster('jobs')
    args = ['--job-id', str(job_id)] + (['--resume'] if resume else [])
    cmd = controller_utils.controller_run_command(
        handle, 'skypilot_tpu.jobs.controller', *args)
    ctrl = task_lib.Task(name=f'jobs-ctrl-{job_id}',
                         run=f'JAX_PLATFORMS=cpu {cmd}')
    execution.exec_cmd(ctrl, cluster_name=handle.cluster_name,
                       detach_run=True)


def _pid_alive(pid: Optional[int]) -> bool:
    """Controller-process liveness via /proc: a zombie (died, not yet
    reaped — e.g. our own Popen child) counts as DEAD, and the check
    does not depend on signal permissions the way os.kill(pid, 0)
    does."""
    if not pid or pid < 0:
        return False
    try:
        os.waitpid(pid, os.WNOHANG)  # reap if it was our child
    except ChildProcessError:
        pass
    try:
        with open(f'/proc/{pid}/stat', 'rb') as f:
            stat = f.read()
    except OSError:
        return False
    state = stat.rsplit(b')', 1)[-1].split()
    return bool(state) and state[0] != b'Z'


def recover_orphaned_controllers() -> int:
    """Restart controllers for non-terminal jobs whose controller
    process died (API-server crash, OOM, operator kill). The restarted
    controller runs the resume path: reattach to the live cluster job,
    or recover the cluster if it is gone (reference is_resume,
    sky/jobs/controller.py:119). Returns number restarted."""
    from skypilot_tpu.utils import controller_utils
    if controller_utils.controller_mode('jobs') == 'dedicated':
        # Controller liveness is owned by the controller cluster's job
        # queue; local pids are meaningless for remote controllers.
        return 0
    restarted = 0
    for job in jobs_state.get_jobs():
        status = job['status']
        if status.is_terminal or \
                status == jobs_state.ManagedJobStatus.PENDING:
            continue
        if _pid_alive(job['controller_pid']):
            continue
        if not jobs_state.try_claim_orphan(job['job_id'],
                                           job['controller_pid']):
            continue  # another process is restarting it
        _start_controller(job['job_id'], resume=True)
        restarted += 1
    return restarted


def maybe_schedule_next_jobs() -> int:
    """Start controllers for PENDING jobs up to the cap; returns number
    started. Safe under concurrent callers (forked API workers): the
    PENDING->SUBMITTED claim is an atomic conditional UPDATE."""
    recover_orphaned_controllers()
    started = 0
    in_flight = jobs_state.num_launching_jobs()
    for job in jobs_state.get_jobs([jobs_state.ManagedJobStatus.PENDING]):
        if in_flight >= _max_concurrent_launches():
            break
        if not jobs_state.try_claim_pending(job['job_id']):
            continue  # another process claimed it
        _start_controller(job['job_id'])
        in_flight += 1
        started += 1
    return started


def submit_job(name: Optional[str], task_yaml: dict,
               max_recoveries: int = 3,
               strategy: str = 'EAGER_NEXT_REGION') -> int:
    job_id = jobs_state.submit_job(name or f'job-{os.getpid()}', task_yaml,
                                   max_recoveries=max_recoveries,
                                   strategy=strategy)
    maybe_schedule_next_jobs()
    return job_id
