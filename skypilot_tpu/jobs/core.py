"""Managed-jobs public API: launch / queue / cancel / logs.

Reference analog: sky/jobs/{client,server} + utils.py ManagedJobCodeGen.
Consolidated mode: controllers run as local processes of the API-server
host (the reference's jobs-consolidation deployment); a dedicated
controller cluster is a config knob away once multi-host control planes
land.
"""
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.jobs import scheduler
from skypilot_tpu.jobs import state as jobs_state


def launch(task, name: Optional[str] = None,
           max_recoveries: int = 3,
           strategy: str = 'EAGER_NEXT_REGION') -> int:
    """Submit a managed (auto-recovering) job or pipeline.

    Accepts a Task or a chain Dag; a chain becomes a pipeline the
    controller runs stage by stage (each stage on its own cluster,
    recovering independently — reference managed-job pipelines)."""
    from skypilot_tpu import dag as dag_lib
    from skypilot_tpu.utils import controller_utils
    dedicated = controller_utils.controller_mode('jobs') == 'dedicated'

    def _prep(t):
        # Dedicated controllers can't see client-local paths: 2-hop
        # (reference maybe_translate_local_file_mounts_and_sync_up,
        # controller_utils.py:837).
        return (controller_utils.translate_local_file_mounts(t)
                if dedicated else t)

    if isinstance(task, dag_lib.Dag):
        dag = task
        if len(dag.tasks) == 1:
            task = dag.tasks[0]
        else:
            if not dag.is_chain():
                raise exceptions.InvalidDagError(
                    'Managed-job pipelines must be linear chains.')
            ordered = dag.topological_order()
            cfg = {'pipeline': [_prep(t).to_yaml_config()
                                for t in ordered]}
            return scheduler.submit_job(
                name or dag.name or ordered[0].name, cfg,
                max_recoveries=max_recoveries, strategy=strategy)
    cfg = _prep(task).to_yaml_config()
    job_recovery = None
    for r in task.resources:
        job_recovery = getattr(r, 'job_recovery', None) or job_recovery
    if isinstance(job_recovery, str):
        strategy = job_recovery.upper()
    elif isinstance(job_recovery, dict):
        strategy = str(job_recovery.get('strategy', strategy)).upper()
        max_recoveries = int(job_recovery.get('max_restarts',
                                              max_recoveries))
    return scheduler.submit_job(name or task.name, cfg,
                                max_recoveries=max_recoveries,
                                strategy=strategy)


def queue(refresh_schedule: bool = True) -> List[Dict[str, Any]]:
    if refresh_schedule:
        scheduler.maybe_schedule_next_jobs()
    out = []
    for record in jobs_state.get_jobs():
        out.append({
            'job_id': record['job_id'],
            'name': record['name'],
            'status': record['status'].value,
            'cluster_name': record['cluster_name'],
            'submitted_at': record['submitted_at'],
            'started_at': record['started_at'],
            'ended_at': record['ended_at'],
            'recovery_count': record['recovery_count'],
            'failure_reason': record['failure_reason'],
        })
    return out


def cancel(job_ids: Optional[List[int]] = None,
           all_jobs: bool = False) -> List[int]:
    records = jobs_state.get_jobs()
    if not all_jobs:
        wanted = set(job_ids or [])
        records = [r for r in records if r['job_id'] in wanted]
        missing = wanted - {r['job_id'] for r in records}
        if missing:
            raise exceptions.JobNotFoundError(
                f'Managed job(s) not found: {sorted(missing)}')
    cancelled = []
    for r in records:
        if r['status'].is_terminal:
            continue
        if r['status'] == jobs_state.ManagedJobStatus.PENDING:
            jobs_state.set_status(r['job_id'],
                                  jobs_state.ManagedJobStatus.CANCELLED)
        else:
            # Controller notices CANCELLING on its next poll.
            jobs_state.set_status(r['job_id'],
                                  jobs_state.ManagedJobStatus.CANCELLING)
        cancelled.append(r['job_id'])
    return cancelled


def tail_logs(job_id: int, follow: bool = True,
              poll_interval: float = 1.0) -> int:
    """Print the controller log (which carries launch + job output).
    Returns 0 on SUCCEEDED, 1 otherwise."""
    record = jobs_state.get_job(job_id)
    if record is None:
        raise exceptions.JobNotFoundError(
            f'Managed job {job_id} not found.')
    from skypilot_tpu.utils import context as context_lib
    from skypilot_tpu.utils import controller_utils
    if controller_utils.controller_mode('jobs') == 'dedicated':
        return _tail_dedicated_controller_logs(job_id, record, follow)
    path = jobs_state.controller_log_path(job_id)
    pos = 0
    while True:
        try:
            with open(path, 'r', encoding='utf-8') as f:
                f.seek(pos)
                chunk = f.read()
        except FileNotFoundError:
            chunk = ''
        if chunk:
            print(chunk, end='', flush=True)
            pos += len(chunk.encode())
        record = jobs_state.get_job(job_id)
        if record['status'].is_terminal or not follow:
            break
        if context_lib.is_cancelled():
            return 1  # cancelled request: stop the follow loop cleanly
        time.sleep(poll_interval)
    ok = record['status'] == jobs_state.ManagedJobStatus.SUCCEEDED
    return 0 if ok else 1


def _tail_dedicated_controller_logs(job_id: int, record, follow: bool
                                    ) -> int:
    """Dedicated mode: the controller runs as a cluster job on the
    controller cluster, so its output lives in THAT job's log."""
    from skypilot_tpu import core as sky_core
    from skypilot_tpu import state as cluster_state
    from skypilot_tpu.utils import controller_utils
    spec = controller_utils.CONTROLLERS['jobs']
    cluster = cluster_state.get_cluster_from_name(spec.cluster_name)
    if cluster is None or cluster['handle'] is None:
        print(f'Controller cluster {spec.cluster_name!r} is gone; '
              'no logs available.')
        return 0 if record['status'] == \
            jobs_state.ManagedJobStatus.SUCCEEDED else 1
    ctrl_job_id = None
    for job in sky_core.queue(spec.cluster_name):
        if job.get('job_name') == f'jobs-ctrl-{job_id}':
            ctrl_job_id = job['job_id']
    if ctrl_job_id is None:
        print(f'No controller job found for managed job {job_id}.')
        return 1
    sky_core.tail_logs(spec.cluster_name, job_id=ctrl_job_id,
                       follow=follow)
    record = jobs_state.get_job(job_id)
    ok = record['status'] == jobs_state.ManagedJobStatus.SUCCEEDED
    return 0 if ok else 1
