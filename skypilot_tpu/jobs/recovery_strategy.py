"""Recovery strategies: how a managed job relaunches after preemption.

Reference analog: sky/jobs/recovery_strategy.py (`StrategyExecutor` :46,
launch :108, recover :124, `FailoverStrategyExecutor` :425,
`EagerFailoverStrategyExecutor` :513; default EAGER_NEXT_REGION).
TPU-first: recovery ALWAYS terminates the old slice first — preempted
TPU slices hold quota until deleted and cannot restart in place
(reference clouds/gcp.py:1066) — then relaunches, either in the same
placement first (FAILOVER) or immediately elsewhere (EAGER_NEXT_REGION).

Relaunch attempts run under the shared resilience retry policy:
exponential backoff with full jitter (a pod-scale preemption sends
every recovering job at the same regional API at once) bounded by BOTH
an attempt count and a total recovery deadline — time-to-give-up is
what the operator actually cares about, not attempt arithmetic.
"""
import time
from typing import Callable, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import envs
from skypilot_tpu.resilience import faults
from skypilot_tpu.resilience import retries
from skypilot_tpu.utils import registry

STRATEGY_REGISTRY = registry.Registry('recovery strategy')
DEFAULT_STRATEGY = 'EAGER_NEXT_REGION'


def _retry_gap_seconds() -> float:
    """Read at call time, never import time: controllers are spawned
    and tests set SKYTPU_JOBS_RETRY_GAP after this module loads."""
    return envs.SKYTPU_JOBS_RETRY_GAP.get()


def _recovery_deadline_seconds() -> Optional[float]:
    return envs.SKYTPU_JOBS_RECOVERY_DEADLINE.get()


class StrategyExecutor:
    """Launch/recover one managed job's cluster."""

    def __init__(self, task, cluster_name: str,
                 max_launch_retries: int = 3,
                 recovery_deadline_seconds: Optional[float] = None,
                 sleep_fn: Callable[[float], None] = time.sleep,
                 now_fn: Callable[[], float] = time.monotonic) -> None:
        self.task = task
        self.cluster_name = cluster_name
        self.max_launch_retries = max_launch_retries
        self.recovery_deadline_seconds = recovery_deadline_seconds
        self._sleep_fn = sleep_fn
        self._now_fn = now_fn

    # -- hooks ---------------------------------------------------------------

    def launch(self) -> int:
        """First launch. Returns the on-cluster job id."""
        return self._launch_with_retries(blocked=None)

    def recover(self) -> int:
        """Relaunch after the cluster was lost. Returns new job id."""
        raise NotImplementedError

    # -- shared machinery ----------------------------------------------------

    def _terminate_cluster(self) -> None:
        from skypilot_tpu import core
        try:
            core.down(self.cluster_name, purge=True)
        except exceptions.ClusterDoesNotExist:
            pass

    def _launch_once(self, blocked=None) -> int:
        from skypilot_tpu import execution
        faults.inject('provision.launch',
                      env_exc=exceptions.ResourcesUnavailableError)
        job_id, _ = execution.launch(
            self.task, cluster_name=self.cluster_name,
            stream_logs=True, detach_run=True,
            blocked_resources=blocked)
        assert job_id is not None
        return job_id

    def _retry_policy(self) -> retries.RetryPolicy:
        gap = _retry_gap_seconds()
        deadline = self.recovery_deadline_seconds
        if deadline is None:
            deadline = _recovery_deadline_seconds()
        return retries.RetryPolicy(
            max_attempts=self.max_launch_retries,
            base_delay=gap, max_delay=max(gap * 8, gap),
            deadline=deadline)

    def _launch_with_retries(self, blocked=None) -> int:
        attempt_no = {'n': 0}

        def _once() -> int:
            i = attempt_no['n']
            attempt_no['n'] += 1
            return self._launch_once(blocked if i == 0 else None)

        def _on_retry(exc: BaseException, attempt: int) -> None:
            # A failed command leaves a half-set-up cluster behind;
            # tear it down before the relaunch. Capacity errors leave
            # nothing (the launch failed before create).
            if isinstance(exc, exceptions.CommandError):
                self._terminate_cluster()

        try:
            return retries.call(
                _once, policy=self._retry_policy(),
                retry_on=(exceptions.ResourcesUnavailableError,
                          exceptions.CommandError),
                on_retry=_on_retry,
                describe=f'launch {self.cluster_name!r}',
                sleep_fn=self._sleep_fn, now_fn=self._now_fn)
        except (exceptions.ResourcesUnavailableError,
                exceptions.CommandError) as e:
            if isinstance(e, exceptions.CommandError):
                # on_retry only fires BETWEEN attempts: a final
                # failed command still leaves a half-set-up,
                # quota-holding cluster to tear down.
                self._terminate_cluster()
            raise exceptions.ManagedJobReachedMaxRetriesError(
                f'Failed to (re)launch {self.cluster_name!r} after '
                f'{attempt_no["n"]} attempt(s): {e}') from e

    @classmethod
    def make(cls, strategy: str, task, cluster_name: str
             ) -> 'StrategyExecutor':
        impl = STRATEGY_REGISTRY.get(strategy.upper())
        return impl(task, cluster_name)


@STRATEGY_REGISTRY.register(name='FAILOVER')
class FailoverStrategyExecutor(StrategyExecutor):
    """Retry the SAME placement first (data locality / reserved capacity),
    then fail over to the optimizer's next choice."""

    def recover(self) -> int:
        self._terminate_cluster()
        # Phase 1: same resources as launched (sticky placement).
        try:
            return self._launch_once()
        except exceptions.ResourcesUnavailableError:
            pass
        # Phase 2: free placement — let the optimizer pick anew.
        return self._launch_with_retries()


@STRATEGY_REGISTRY.register(name='EAGER_NEXT_REGION')
class EagerFailoverStrategyExecutor(StrategyExecutor):
    """Skip the preempted placement immediately: preemption signals the
    zone is capacity-constrained right now (the reference's default)."""

    def recover(self) -> int:
        from skypilot_tpu import state as state_lib
        record = state_lib.get_cluster_from_name(self.cluster_name)
        blocked = []
        if record is not None and record['handle'] is not None:
            blocked.append(record['handle'].launched_resources)
        self._terminate_cluster()
        return self._launch_with_retries(blocked=blocked)
