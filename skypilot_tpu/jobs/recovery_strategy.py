"""Recovery strategies: how a managed job relaunches after preemption.

Reference analog: sky/jobs/recovery_strategy.py (`StrategyExecutor` :46,
launch :108, recover :124, `FailoverStrategyExecutor` :425,
`EagerFailoverStrategyExecutor` :513; default EAGER_NEXT_REGION).
TPU-first: recovery ALWAYS terminates the old slice first — preempted
TPU slices hold quota until deleted and cannot restart in place
(reference clouds/gcp.py:1066) — then relaunches, either in the same
placement first (FAILOVER) or immediately elsewhere (EAGER_NEXT_REGION).
"""
import os
import time
from typing import Optional

from skypilot_tpu import exceptions
from skypilot_tpu.utils import registry

STRATEGY_REGISTRY = registry.Registry('recovery strategy')
DEFAULT_STRATEGY = 'EAGER_NEXT_REGION'

_LAUNCH_RETRY_GAP_SECONDS = float(
    os.environ.get('SKYTPU_JOBS_RETRY_GAP', '10'))


class StrategyExecutor:
    """Launch/recover one managed job's cluster."""

    def __init__(self, task, cluster_name: str,
                 max_launch_retries: int = 3) -> None:
        self.task = task
        self.cluster_name = cluster_name
        self.max_launch_retries = max_launch_retries

    # -- hooks ---------------------------------------------------------------

    def launch(self) -> int:
        """First launch. Returns the on-cluster job id."""
        return self._launch_with_retries(blocked=None)

    def recover(self) -> int:
        """Relaunch after the cluster was lost. Returns new job id."""
        raise NotImplementedError

    # -- shared machinery ----------------------------------------------------

    def _terminate_cluster(self) -> None:
        from skypilot_tpu import core
        try:
            core.down(self.cluster_name, purge=True)
        except exceptions.ClusterDoesNotExist:
            pass

    def _launch_once(self, blocked=None) -> int:
        from skypilot_tpu import execution
        job_id, _ = execution.launch(
            self.task, cluster_name=self.cluster_name,
            stream_logs=True, detach_run=True,
            blocked_resources=blocked)
        assert job_id is not None
        return job_id

    def _launch_with_retries(self, blocked=None) -> int:
        last_exc: Optional[Exception] = None
        for attempt in range(self.max_launch_retries):
            try:
                return self._launch_once(blocked if attempt == 0 else None)
            except exceptions.ResourcesUnavailableError as e:
                last_exc = e
                time.sleep(_LAUNCH_RETRY_GAP_SECONDS * (attempt + 1))
            except exceptions.CommandError as e:
                last_exc = e
                self._terminate_cluster()
                time.sleep(_LAUNCH_RETRY_GAP_SECONDS)
        raise exceptions.ManagedJobReachedMaxRetriesError(
            f'Failed to (re)launch {self.cluster_name!r} after '
            f'{self.max_launch_retries} attempts: {last_exc}')

    @classmethod
    def make(cls, strategy: str, task, cluster_name: str
             ) -> 'StrategyExecutor':
        impl = STRATEGY_REGISTRY.get(strategy.upper())
        return impl(task, cluster_name)


@STRATEGY_REGISTRY.register(name='FAILOVER')
class FailoverStrategyExecutor(StrategyExecutor):
    """Retry the SAME placement first (data locality / reserved capacity),
    then fail over to the optimizer's next choice."""

    def recover(self) -> int:
        self._terminate_cluster()
        # Phase 1: same resources as launched (sticky placement).
        try:
            return self._launch_once()
        except exceptions.ResourcesUnavailableError:
            pass
        # Phase 2: free placement — let the optimizer pick anew.
        return self._launch_with_retries()


@STRATEGY_REGISTRY.register(name='EAGER_NEXT_REGION')
class EagerFailoverStrategyExecutor(StrategyExecutor):
    """Skip the preempted placement immediately: preemption signals the
    zone is capacity-constrained right now (the reference's default)."""

    def recover(self) -> int:
        from skypilot_tpu import state as state_lib
        record = state_lib.get_cluster_from_name(self.cluster_name)
        blocked = []
        if record is not None and record['handle'] is not None:
            blocked.append(record['handle'].launched_resources)
        self._terminate_cluster()
        return self._launch_with_retries(blocked=blocked)
