"""Managed-job state machine (SQLite).

Reference analog: sky/jobs/state.py (`ManagedJobStatus` :243,
`ManagedJobScheduleState` :385, spot_jobs DB). A managed job owns a
cluster lifecycle: launch -> monitor -> (recover on preemption)* ->
terminal; TPU preemption always recovers by terminate+relaunch because
slices cannot restart in place (reference clouds/gcp.py:1066).
"""
import enum
import json
import os
import sqlite3
import threading
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu.utils import paths

_lock = threading.Lock()


def _after_fork_in_child() -> None:
    """Fresh lock + connection in forked children: the parent process
    is multi-threaded (API server), so the inherited lock may be held
    by a thread that does not exist in the child."""
    global _lock, _conn, _conn_path
    _lock = threading.Lock()
    _conn = None
    _conn_path = None


os.register_at_fork(after_in_child=_after_fork_in_child)
_conn: Optional[sqlite3.Connection] = None
_conn_path: Optional[str] = None


class ManagedJobStatus(enum.Enum):
    PENDING = 'PENDING'            # queued; controller not started
    SUBMITTED = 'SUBMITTED'        # controller process starting
    STARTING = 'STARTING'          # cluster launching
    RUNNING = 'RUNNING'            # user job running
    RECOVERING = 'RECOVERING'      # preempted; relaunching
    SUCCEEDED = 'SUCCEEDED'
    FAILED = 'FAILED'              # user code failed
    FAILED_SETUP = 'FAILED_SETUP'
    FAILED_NO_RESOURCE = 'FAILED_NO_RESOURCE'
    FAILED_CONTROLLER = 'FAILED_CONTROLLER'
    CANCELLING = 'CANCELLING'
    CANCELLED = 'CANCELLED'

    @property
    def is_terminal(self) -> bool:
        return self in _TERMINAL

    @classmethod
    def failure_statuses(cls) -> List['ManagedJobStatus']:
        return [cls.FAILED, cls.FAILED_SETUP, cls.FAILED_NO_RESOURCE,
                cls.FAILED_CONTROLLER]


_TERMINAL = frozenset({
    ManagedJobStatus.SUCCEEDED, ManagedJobStatus.FAILED,
    ManagedJobStatus.FAILED_SETUP, ManagedJobStatus.FAILED_NO_RESOURCE,
    ManagedJobStatus.FAILED_CONTROLLER, ManagedJobStatus.CANCELLED,
})


def jobs_db_path() -> str:
    return os.path.join(paths.state_dir(), 'managed_jobs.db')


def controller_log_path(job_id: int) -> str:
    d = os.path.join(paths.state_dir(), 'managed_jobs_logs')
    os.makedirs(d, exist_ok=True)
    return os.path.join(d, f'{job_id}.log')


def _get_conn() -> sqlite3.Connection:
    global _conn, _conn_path
    path = jobs_db_path()
    with _lock:
        if _conn is None or _conn_path != path:
            _conn = sqlite3.connect(path, check_same_thread=False,
                                    timeout=30.0)
            _conn.execute('PRAGMA journal_mode=WAL')
            _conn.execute("""
                CREATE TABLE IF NOT EXISTS managed_jobs (
                    job_id INTEGER PRIMARY KEY AUTOINCREMENT,
                    name TEXT,
                    task_yaml TEXT,
                    cluster_name TEXT,
                    status TEXT,
                    submitted_at REAL,
                    started_at REAL,
                    ended_at REAL,
                    recovery_count INTEGER DEFAULT 0,
                    max_recoveries INTEGER DEFAULT 3,
                    failure_reason TEXT,
                    controller_pid INTEGER,
                    strategy TEXT DEFAULT 'EAGER_NEXT_REGION',
                    cluster_job_id INTEGER
                )""")
            cols = [r[1] for r in _conn.execute(
                'PRAGMA table_info(managed_jobs)')]
            if 'cluster_job_id' not in cols:  # pre-resume DBs
                _conn.execute('ALTER TABLE managed_jobs ADD COLUMN '
                              'cluster_job_id INTEGER')
            _conn.commit()
            _conn_path = path
        return _conn


def reset_for_tests() -> None:
    global _conn, _conn_path
    with _lock:
        if _conn is not None:
            _conn.close()
        _conn = None
        _conn_path = None


def submit_job(name: str, task_yaml: Dict[str, Any],
               max_recoveries: int = 3,
               strategy: str = 'EAGER_NEXT_REGION') -> int:
    conn = _get_conn()
    with _lock:
        cur = conn.execute(
            'INSERT INTO managed_jobs (name, task_yaml, status, '
            'submitted_at, max_recoveries, strategy) VALUES (?,?,?,?,?,?)',
            (name, json.dumps(task_yaml),
             ManagedJobStatus.PENDING.value, time.time(), max_recoveries,
             strategy))
        conn.commit()
        job_id = cur.lastrowid
    return int(job_id)


def set_status(job_id: int, status: ManagedJobStatus,
               failure_reason: Optional[str] = None) -> None:
    conn = _get_conn()
    with _lock:
        sets = ['status=?']
        args: List[Any] = [status.value]
        if status == ManagedJobStatus.RUNNING:
            sets.append('started_at=COALESCE(started_at, ?)')
            args.append(time.time())
        if status.is_terminal:
            sets.append('ended_at=?')
            args.append(time.time())
        if failure_reason is not None:
            sets.append('failure_reason=?')
            args.append(failure_reason)
        args.append(job_id)
        conn.execute(
            f'UPDATE managed_jobs SET {", ".join(sets)} WHERE job_id=?',
            args)
        conn.commit()


def set_cluster_name(job_id: int, cluster_name: str) -> None:
    conn = _get_conn()
    with _lock:
        conn.execute(
            'UPDATE managed_jobs SET cluster_name=? WHERE job_id=?',
            (cluster_name, job_id))
        conn.commit()


def try_claim_pending(job_id: int) -> bool:
    """Atomically move PENDING -> SUBMITTED; False if someone else won.
    The cross-process guard against duplicate controllers."""
    conn = _get_conn()
    with _lock:
        cur = conn.execute(
            'UPDATE managed_jobs SET status=? WHERE job_id=? AND status=?',
            (ManagedJobStatus.SUBMITTED.value, job_id,
             ManagedJobStatus.PENDING.value))
        conn.commit()
        return cur.rowcount == 1


def try_claim_orphan(job_id: int, dead_pid: Optional[int]) -> bool:
    """Atomically claim an orphaned job for controller restart: only
    one caller wins by clearing the dead pid (cross-process guard
    against duplicate resumed controllers)."""
    conn = _get_conn()
    with _lock:
        if dead_pid is None:
            cur = conn.execute(
                'UPDATE managed_jobs SET controller_pid=-1 '
                'WHERE job_id=? AND controller_pid IS NULL', (job_id,))
        else:
            cur = conn.execute(
                'UPDATE managed_jobs SET controller_pid=-1 '
                'WHERE job_id=? AND controller_pid=?',
                (job_id, dead_pid))
        conn.commit()
        return cur.rowcount == 1


def set_cluster_job_id(job_id: int,
                       cluster_job_id: Optional[int]) -> None:
    """Remember the on-cluster job id so a restarted controller can
    resume monitoring instead of relaunching (reference is_resume,
    sky/jobs/controller.py:119)."""
    conn = _get_conn()
    with _lock:
        conn.execute(
            'UPDATE managed_jobs SET cluster_job_id=? WHERE job_id=?',
            (cluster_job_id, job_id))
        conn.commit()


def set_controller_pid(job_id: int, pid: int) -> None:
    conn = _get_conn()
    with _lock:
        conn.execute(
            'UPDATE managed_jobs SET controller_pid=? WHERE job_id=?',
            (pid, job_id))
        conn.commit()


def bump_recovery_count(job_id: int) -> int:
    conn = _get_conn()
    with _lock:
        conn.execute(
            'UPDATE managed_jobs SET recovery_count=recovery_count+1 '
            'WHERE job_id=?', (job_id,))
        conn.commit()
        row = conn.execute(
            'SELECT recovery_count FROM managed_jobs WHERE job_id=?',
            (job_id,)).fetchone()
    return int(row[0])


_COLS = ('job_id, name, task_yaml, cluster_name, status, submitted_at, '
         'started_at, ended_at, recovery_count, max_recoveries, '
         'failure_reason, controller_pid, strategy, cluster_job_id')


def _row_to_record(row) -> Dict[str, Any]:
    (job_id, name, task_yaml, cluster_name, status, submitted_at,
     started_at, ended_at, recovery_count, max_recoveries, failure_reason,
     controller_pid, strategy, cluster_job_id) = row
    return {
        'job_id': job_id,
        'name': name,
        'task_yaml': json.loads(task_yaml) if task_yaml else None,
        'cluster_name': cluster_name,
        'status': ManagedJobStatus(status),
        'submitted_at': submitted_at,
        'started_at': started_at,
        'ended_at': ended_at,
        'recovery_count': recovery_count,
        'max_recoveries': max_recoveries,
        'failure_reason': failure_reason,
        'controller_pid': controller_pid,
        'cluster_job_id': cluster_job_id,
        'strategy': strategy,
    }


def get_job(job_id: int) -> Optional[Dict[str, Any]]:
    conn = _get_conn()
    row = conn.execute(
        f'SELECT {_COLS} FROM managed_jobs WHERE job_id=?',
        (job_id,)).fetchone()
    return _row_to_record(row) if row else None


def get_jobs(statuses: Optional[List[ManagedJobStatus]] = None
             ) -> List[Dict[str, Any]]:
    conn = _get_conn()
    if statuses:
        marks = ','.join('?' * len(statuses))
        rows = conn.execute(
            f'SELECT {_COLS} FROM managed_jobs WHERE status IN ({marks}) '
            'ORDER BY job_id', [s.value for s in statuses]).fetchall()
    else:
        rows = conn.execute(
            f'SELECT {_COLS} FROM managed_jobs ORDER BY job_id').fetchall()
    return [_row_to_record(r) for r in rows]


def num_launching_jobs() -> int:
    conn = _get_conn()
    row = conn.execute(
        'SELECT COUNT(*) FROM managed_jobs WHERE status IN (?,?,?)',
        (ManagedJobStatus.SUBMITTED.value,
         ManagedJobStatus.STARTING.value,
         ManagedJobStatus.RECOVERING.value)).fetchone()
    return int(row[0])
