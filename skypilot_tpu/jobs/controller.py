"""Managed-job controller: one process per job; monitors and recovers.

Reference analog: sky/jobs/controller.py:53 (`JobsController`,
`_run_one_task` :119, run :468, start :617). The control loop:
launch cluster -> poll the on-cluster job -> on cluster loss/preemption
recover via the strategy -> terminal state -> terminate the cluster.
"""
import argparse
import logging
import os
import re
import time
import traceback
from typing import Any, Dict, Optional

from skypilot_tpu import envs
from skypilot_tpu import exceptions
from skypilot_tpu.jobs import recovery_strategy
from skypilot_tpu.jobs import state as jobs_state
from skypilot_tpu.skylet import job_lib

logger = logging.getLogger(__name__)

def _poll_interval_seconds() -> float:
    """Read at call time: tests and operators tune the poll cadence
    after this module is imported."""
    return envs.SKYTPU_JOBS_POLL_INTERVAL.get()


class JobsController:

    def __init__(self, managed_job_id: int, resume: bool = False) -> None:
        self.job_id = managed_job_id
        self.resume = resume
        record = jobs_state.get_job(managed_job_id)
        assert record is not None, managed_job_id
        self.record = record
        from skypilot_tpu import task as task_lib
        cfg = record['task_yaml']
        if isinstance(cfg, dict) and 'pipeline' in cfg:
            # A chain: one stage at a time, each on its own cluster
            # (reference: managed-job pipelines, sky/jobs/controller.py
            # _run_one_task per dag task).
            self.tasks = [task_lib.Task.from_yaml_config(c)
                          for c in cfg['pipeline']]
        else:
            self.tasks = [task_lib.Task.from_yaml_config(cfg)]
        self.task = self.tasks[0]
        stored = record['cluster_name'] or f'tsky-jobs-{managed_job_id}'
        if len(self.tasks) > 1:
            # The persisted name may be a per-stage name ('<base>-s<N>',
            # written mid-run); recover the base for stage naming.
            stored = re.sub(r'-s\d+$', '', stored)
        self.base_cluster_name = stored
        self.cluster_name = self.base_cluster_name
        jobs_state.set_cluster_name(managed_job_id,
                                    self.base_cluster_name)
        self.strategy = recovery_strategy.StrategyExecutor.make(
            record['strategy'], self.task, self.cluster_name)

    # -- cluster-side probes -------------------------------------------------

    def _cluster_job_status(self, job_id: int
                            ) -> Optional[job_lib.JobStatus]:
        """Status of the on-cluster job; None == cluster lost (the
        preemption signal, reference jobs/utils.py get_job_status)."""
        from skypilot_tpu import core
        from skypilot_tpu import state as state_lib
        record = state_lib.get_cluster_from_name(self.cluster_name)
        if record is None or record['handle'] is None:
            return None
        try:
            queue = core.queue(self.cluster_name)
        except exceptions.SkyTpuError:
            return None
        for job in queue:
            if job['job_id'] == job_id:
                return job_lib.JobStatus(job['status'])
        return None

    def _cluster_alive(self) -> bool:
        """Cloud-truth liveness (catches preemption even while the skylet
        is unreachable)."""
        from skypilot_tpu import state as state_lib
        from skypilot_tpu.backends import gang_backend
        record = state_lib.get_cluster_from_name(self.cluster_name)
        if record is None or record['handle'] is None:
            return False
        try:
            status = gang_backend.GangBackend().query_status(
                record['handle'])
        except exceptions.SkyTpuError:
            return False
        from skypilot_tpu import state
        return status == state.ClusterStatus.UP

    def _tail_into_controller_log(self, cluster_job_id: int) -> None:
        from skypilot_tpu import core
        try:
            core.tail_logs(self.cluster_name, job_id=cluster_job_id,
                           follow=False)
        except exceptions.SkyTpuError:
            pass

    # -- main loop -----------------------------------------------------------

    def run(self) -> None:
        try:
            self._run()
        except exceptions.ManagedJobReachedMaxRetriesError as e:
            jobs_state.set_status(
                self.job_id, jobs_state.ManagedJobStatus.FAILED_NO_RESOURCE,
                failure_reason=str(e))
        except BaseException as e:  # noqa: BLE001
            traceback.print_exc()
            jobs_state.set_status(
                self.job_id, jobs_state.ManagedJobStatus.FAILED_CONTROLLER,
                failure_reason=f'{type(e).__name__}: {e}')
        finally:
            record = jobs_state.get_job(self.job_id)
            if record and record['status'].is_terminal:
                self._cleanup()

    def _resume_stage(self) -> int:
        """Stage a crashed controller was on, from the persisted
        cluster name (pipelines suffix -s<stage>)."""
        current = self.record.get('cluster_name') or ''
        prefix = f'{self.base_cluster_name}-s'
        if len(self.tasks) > 1 and current.startswith(prefix):
            try:
                return min(int(current[len(prefix):]),
                           len(self.tasks) - 1)
            except ValueError:
                return 0
        return 0

    def _run(self) -> None:
        first_stage = self._resume_stage() if self.resume else 0
        for stage, task in enumerate(self.tasks):
            if stage < first_stage:
                continue
            self.task = task
            self.cluster_name = (self.base_cluster_name if
                                 len(self.tasks) == 1 else
                                 f'{self.base_cluster_name}-s{stage}')
            jobs_state.set_cluster_name(self.job_id, self.cluster_name)
            self.strategy = recovery_strategy.StrategyExecutor.make(
                self.record['strategy'], task, self.cluster_name)
            final = stage == len(self.tasks) - 1
            done = self._run_one_task(
                final=final, resume=self.resume and stage == first_stage)
            if not done:
                return  # terminal failure/cancel already recorded
            if not final:
                # Stage finished: release its cluster before the next.
                self._cleanup()
        # _run_one_task set SUCCEEDED on the last stage.

    def _run_one_task(self, final: bool = True,
                      resume: bool = False) -> bool:
        """Run self.task to completion. True iff it succeeded; the
        managed job only turns SUCCEEDED on the final stage.

        resume: the previous controller crashed mid-flight (reference
        is_resume, sky/jobs/controller.py:119) — reattach to the live
        cluster job instead of relaunching when possible."""
        cluster_job_id = None
        if resume:
            cluster_job_id = self.record.get('cluster_job_id')
            if cluster_job_id is not None and self._cluster_alive():
                logger.info('Resuming: monitoring existing cluster job '
                            '%s on %s', cluster_job_id, self.cluster_name)
            elif self.record['status'].is_terminal:
                return self.record['status'] ==                     jobs_state.ManagedJobStatus.SUCCEEDED
            else:
                # Cluster gone while the controller was down: recover.
                jobs_state.set_status(
                    self.job_id, jobs_state.ManagedJobStatus.RECOVERING)
                cluster_job_id = self.strategy.recover()
                jobs_state.set_cluster_job_id(self.job_id, cluster_job_id)
        if cluster_job_id is None:
            jobs_state.set_status(self.job_id,
                                  jobs_state.ManagedJobStatus.STARTING)
            try:
                cluster_job_id = self.strategy.launch()
            except exceptions.ResourcesUnavailableError as e:
                jobs_state.set_status(
                    self.job_id,
                    jobs_state.ManagedJobStatus.FAILED_NO_RESOURCE,
                    failure_reason=str(e))
                return False
            jobs_state.set_cluster_job_id(self.job_id, cluster_job_id)
        jobs_state.set_status(self.job_id,
                              jobs_state.ManagedJobStatus.RUNNING)

        while True:
            status = self._cluster_job_status(cluster_job_id)
            if status == job_lib.JobStatus.SUCCEEDED:
                self._tail_into_controller_log(cluster_job_id)
                if final:
                    jobs_state.set_status(
                        self.job_id,
                        jobs_state.ManagedJobStatus.SUCCEEDED)
                return True
            if status == job_lib.JobStatus.FAILED:
                self._tail_into_controller_log(cluster_job_id)
                jobs_state.set_status(
                    self.job_id, jobs_state.ManagedJobStatus.FAILED,
                    failure_reason='User job exited non-zero.')
                return False
            if status == job_lib.JobStatus.CANCELLED:
                jobs_state.set_status(self.job_id,
                                      jobs_state.ManagedJobStatus.CANCELLED)
                return False
            if status is None and not self._cluster_alive():
                # Preemption / cluster loss -> recover.
                count = jobs_state.bump_recovery_count(self.job_id)
                if count > self.record['max_recoveries']:
                    jobs_state.set_status(
                        self.job_id,
                        jobs_state.ManagedJobStatus.FAILED_NO_RESOURCE,
                        failure_reason=(
                            f'Exceeded max_recoveries '
                            f'({self.record["max_recoveries"]}).'))
                    return False
                jobs_state.set_status(
                    self.job_id, jobs_state.ManagedJobStatus.RECOVERING)
                cluster_job_id = self.strategy.recover()
                jobs_state.set_cluster_job_id(self.job_id, cluster_job_id)
                jobs_state.set_status(self.job_id,
                                      jobs_state.ManagedJobStatus.RUNNING)
            # Cancellation request from the user?
            record = jobs_state.get_job(self.job_id)
            if record['status'] == jobs_state.ManagedJobStatus.CANCELLING:
                self._cancel_cluster_job(cluster_job_id)
                jobs_state.set_status(self.job_id,
                                      jobs_state.ManagedJobStatus.CANCELLED)
                return False
            time.sleep(_poll_interval_seconds())

    def _cancel_cluster_job(self, cluster_job_id: int) -> None:
        from skypilot_tpu import core
        try:
            core.cancel(self.cluster_name, job_ids=[cluster_job_id])
        except exceptions.SkyTpuError:
            pass

    def _cleanup(self) -> None:
        from skypilot_tpu import core
        try:
            core.down(self.cluster_name, purge=True)
        except exceptions.SkyTpuError:
            pass


def start(managed_job_id: int, resume: bool = False) -> None:
    """Entry for the forked controller process."""
    jobs_state.set_controller_pid(managed_job_id, os.getpid())
    JobsController(managed_job_id, resume=resume).run()


if __name__ == '__main__':
    parser = argparse.ArgumentParser()
    parser.add_argument('--job-id', type=int, required=True)
    parser.add_argument('--resume', action='store_true')
    args = parser.parse_args()
    start(args.job_id, resume=args.resume)
