"""Autostop config + enforcement, on-cluster.

Reference analog: sky/skylet/autostop_lib.py (set_autostop :60) with
enforcement in events.py:102. TPU twist: slices cannot stop, so the
backend always sets down=True for TPU clusters (clouds/gcp.py analog of
reference clouds/gcp.py:216-226).
"""
import json
import os
import time
from typing import Any, Dict, Optional

from skypilot_tpu.skylet import constants
from skypilot_tpu.skylet import job_lib


def set_autostop(rt: str, idle_minutes: Optional[int], down: bool,
                 provider_name: str, cluster_name_on_cloud: str,
                 provider_config: Dict[str, Any]) -> None:
    """idle_minutes=None disables autostop."""
    path = constants.autostop_config_path(rt)
    if idle_minutes is None:
        if os.path.exists(path):
            os.remove(path)
        return
    with open(path, 'w', encoding='utf-8') as f:
        json.dump({
            'idle_minutes': idle_minutes,
            'down': down,
            'provider_name': provider_name,
            'cluster_name_on_cloud': cluster_name_on_cloud,
            'provider_config': provider_config,
            'set_at': time.time(),
        }, f)


def get_autostop_config(rt: str) -> Optional[Dict[str, Any]]:
    try:
        with open(constants.autostop_config_path(rt), 'r',
                  encoding='utf-8') as f:
            return json.load(f)
    except (FileNotFoundError, json.JSONDecodeError):
        return None


def should_autostop(rt: str) -> bool:
    cfg = get_autostop_config(rt)
    if cfg is None:
        return False
    if not job_lib.is_cluster_idle(rt):
        return False
    idle_anchor = max(job_lib.last_activity_time(rt), cfg['set_at'])
    return (time.time() - idle_anchor) >= cfg['idle_minutes'] * 60


def execute_autostop(rt: str) -> None:
    """Stop/terminate this cluster from within (reference
    events.py:102 -> _stop_cluster_with_new_provisioner)."""
    cfg = get_autostop_config(rt)
    if cfg is None:
        return
    from skypilot_tpu import provision
    # Drop the config first: if the stop partially succeeds we must not
    # loop forever re-stopping.
    os.remove(constants.autostop_config_path(rt))
    if cfg['down']:
        provision.terminate_instances(cfg['provider_name'],
                                      cfg['cluster_name_on_cloud'],
                                      cfg['provider_config'])
    else:
        provision.stop_instances(cfg['provider_name'],
                                 cfg['cluster_name_on_cloud'],
                                 cfg['provider_config'])
