"""The skylet daemon: runs on the head host, ticks events forever.

Reference analog: sky/skylet/skylet.py:17-34.

    python -m skypilot_tpu.skylet.skylet --runtime-dir D
"""
import argparse
import os
import time

from skypilot_tpu.skylet import constants
from skypilot_tpu.skylet import events


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--runtime-dir', default=None)
    args = parser.parse_args()
    rt = args.runtime_dir or constants.runtime_dir()
    os.environ[constants.RUNTIME_DIR_ENV_VAR] = rt

    with open(constants.skylet_pid_path(rt), 'w', encoding='utf-8') as f:
        f.write(str(os.getpid()))

    evts = [events.JobSchedulerEvent(rt), events.AutostopEvent(rt),
            events.HeartbeatEvent(rt)]
    epoch = constants.topology_epoch(rt)
    while True:
        # The topology file IS the cluster (written once per provision,
        # never recreated by ticks). Gone → torn down behind our back;
        # different epoch → the name was re-provisioned and we are the
        # previous incarnation. Either way: die, don't linger.
        if constants.topology_epoch(rt) != epoch:
            return
        for e in evts:
            e.tick()
        time.sleep(1)


if __name__ == '__main__':
    main()
