"""On-cluster job queue: sqlite-backed, driven by the gang runner.

Reference analog: sky/skylet/job_lib.py (JobStatus :147, FIFOScheduler
:309, JobLibCodeGen :1040). Differences, TPU-first:
- No Ray: the scheduler spawns `python -m skypilot_tpu.skylet.gang` driver
  processes directly; gang semantics live in gang.py.
- No codegen strings: the backend invokes `skypilot_tpu.skylet.cli`
  subcommands over the command runner.
"""
import enum
import getpass
import json
import os
import signal
import sqlite3
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu.skylet import constants


class JobStatus(enum.Enum):
    INIT = 'INIT'
    PENDING = 'PENDING'
    SETTING_UP = 'SETTING_UP'
    RUNNING = 'RUNNING'
    SUCCEEDED = 'SUCCEEDED'
    FAILED = 'FAILED'
    FAILED_SETUP = 'FAILED_SETUP'
    CANCELLED = 'CANCELLED'

    def is_terminal(self) -> bool:
        return self in _TERMINAL

    @classmethod
    def nonterminal_statuses(cls) -> List['JobStatus']:
        return [s for s in cls if not s.is_terminal()]


_TERMINAL = {JobStatus.SUCCEEDED, JobStatus.FAILED, JobStatus.FAILED_SETUP,
             JobStatus.CANCELLED}


def _conn(rt: str) -> sqlite3.Connection:
    conn = sqlite3.connect(constants.job_db_path(rt), timeout=30.0)
    conn.execute('PRAGMA journal_mode=WAL')
    conn.execute("""
        CREATE TABLE IF NOT EXISTS jobs (
            job_id INTEGER PRIMARY KEY AUTOINCREMENT,
            name TEXT,
            username TEXT,
            submitted_at REAL,
            status TEXT,
            run_timestamp TEXT,
            start_at REAL,
            end_at REAL,
            resources TEXT,
            num_nodes INTEGER,
            driver_pid INTEGER,
            exit_code INTEGER
        )""")
    conn.commit()
    return conn


# --- submission -------------------------------------------------------------

def add_job(rt: str, name: str, num_nodes: int,
            resources_str: str = '') -> int:
    conn = _conn(rt)
    run_timestamp = time.strftime('sky-%Y-%m-%d-%H-%M-%S-%f')
    cur = conn.execute(
        """INSERT INTO jobs (name, username, submitted_at, status,
           run_timestamp, num_nodes, resources)
           VALUES (?,?,?,?,?,?,?)""",
        (name, getpass.getuser(), time.time(), JobStatus.PENDING.value,
         run_timestamp, num_nodes, resources_str))
    conn.commit()
    job_id = int(cur.lastrowid)
    conn.close()
    return job_id


def schedule_step(rt: str) -> None:
    """FIFO: start every PENDING job whose predecessors aren't PENDING.

    Jobs run concurrently (like the reference when resources allow); the
    spawn is the gang driver process, detached from the caller.
    """
    conn = _conn(rt)
    rows = conn.execute(
        'SELECT job_id FROM jobs WHERE status=? ORDER BY job_id',
        (JobStatus.PENDING.value,)).fetchall()
    conn.close()
    for (job_id,) in rows:
        _start_job(rt, job_id)


def _start_job(rt: str, job_id: int) -> None:
    log_path = os.path.join(constants.job_dir(rt, job_id), 'driver.log')
    env = dict(os.environ)
    env[constants.RUNTIME_DIR_ENV_VAR] = rt
    # The driver must import skypilot_tpu regardless of cwd.
    pkg_parent = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env['PYTHONPATH'] = pkg_parent + (
        ':' + env['PYTHONPATH'] if env.get('PYTHONPATH') else '')
    with open(log_path, 'ab') as log_f:
        proc = subprocess.Popen(
            [sys.executable, '-m', 'skypilot_tpu.skylet.gang',
             '--runtime-dir', rt, '--job-id', str(job_id)],
            stdout=log_f, stderr=subprocess.STDOUT, env=env,
            start_new_session=True)
    conn = _conn(rt)
    conn.execute(
        'UPDATE jobs SET status=?, start_at=?, driver_pid=? WHERE job_id=?'
        ' AND status=?',
        (JobStatus.SETTING_UP.value, time.time(), proc.pid, job_id,
         JobStatus.PENDING.value))
    conn.commit()
    conn.close()


# --- state transitions (called by the gang driver) --------------------------

def set_status(rt: str, job_id: int, status: JobStatus,
               exit_code: Optional[int] = None) -> None:
    conn = _conn(rt)
    if status.is_terminal():
        conn.execute(
            'UPDATE jobs SET status=?, end_at=?, exit_code=? WHERE job_id=?',
            (status.value, time.time(), exit_code, job_id))
    else:
        conn.execute('UPDATE jobs SET status=? WHERE job_id=?',
                     (status.value, job_id))
    conn.commit()
    conn.close()


# --- queries ----------------------------------------------------------------

def get_job(rt: str, job_id: int) -> Optional[Dict[str, Any]]:
    conn = _conn(rt)
    row = conn.execute(
        'SELECT job_id, name, username, submitted_at, status, run_timestamp,'
        ' start_at, end_at, resources, num_nodes, driver_pid, exit_code'
        ' FROM jobs WHERE job_id=?', (job_id,)).fetchone()
    conn.close()
    return _row_to_dict(row) if row else None


def get_jobs(rt: str, statuses: Optional[List[JobStatus]] = None
             ) -> List[Dict[str, Any]]:
    conn = _conn(rt)
    if statuses:
        qmarks = ','.join('?' * len(statuses))
        rows = conn.execute(
            f'SELECT job_id, name, username, submitted_at, status,'
            f' run_timestamp, start_at, end_at, resources, num_nodes,'
            f' driver_pid, exit_code FROM jobs WHERE status IN ({qmarks})'
            f' ORDER BY job_id DESC',
            [s.value for s in statuses]).fetchall()
    else:
        rows = conn.execute(
            'SELECT job_id, name, username, submitted_at, status,'
            ' run_timestamp, start_at, end_at, resources, num_nodes,'
            ' driver_pid, exit_code FROM jobs ORDER BY job_id DESC'
        ).fetchall()
    conn.close()
    return [_row_to_dict(r) for r in rows]


def _row_to_dict(row) -> Dict[str, Any]:
    return {
        'job_id': row[0], 'job_name': row[1], 'username': row[2],
        'submitted_at': row[3], 'status': JobStatus(row[4]),
        'run_timestamp': row[5], 'start_at': row[6], 'end_at': row[7],
        'resources': row[8], 'num_nodes': row[9], 'driver_pid': row[10],
        'exit_code': row[11],
    }


def get_latest_job_id(rt: str) -> Optional[int]:
    conn = _conn(rt)
    row = conn.execute('SELECT MAX(job_id) FROM jobs').fetchone()
    conn.close()
    return row[0] if row and row[0] is not None else None


def is_cluster_idle(rt: str) -> bool:
    """No job in a non-terminal state (autostop predicate,
    reference job_lib.py:817)."""
    return not get_jobs(rt, JobStatus.nonterminal_statuses())


def last_activity_time(rt: str) -> float:
    """Most recent job end/submit time, for idle-minutes accounting."""
    conn = _conn(rt)
    row = conn.execute(
        'SELECT MAX(COALESCE(end_at, start_at, submitted_at)) FROM jobs'
    ).fetchone()
    conn.close()
    return float(row[0]) if row and row[0] else 0.0


# --- liveness reconciliation ------------------------------------------------

def _pid_alive(pid: Optional[int]) -> bool:
    if not pid:
        return False
    try:
        os.kill(pid, 0)
        return True
    except (ProcessLookupError, PermissionError):
        return False


def update_job_statuses(rt: str) -> None:
    """Mark jobs whose driver died without reporting as FAILED
    (reference update_job_status :644 driver-liveness check)."""
    for job in get_jobs(rt, [JobStatus.SETTING_UP, JobStatus.RUNNING]):
        if not _pid_alive(job['driver_pid']):
            set_status(rt, job['job_id'], JobStatus.FAILED, exit_code=-1)


# --- cancellation -----------------------------------------------------------

def cancel_jobs(rt: str, job_ids: Optional[List[int]] = None,
                all_jobs: bool = False) -> List[int]:
    if all_jobs:
        jobs = get_jobs(rt, JobStatus.nonterminal_statuses())
        job_ids = [j['job_id'] for j in jobs]
    cancelled = []
    for job_id in job_ids or []:
        job = get_job(rt, job_id)
        if job is None or job['status'].is_terminal():
            continue
        pid = job['driver_pid']
        if pid and _pid_alive(pid):
            try:
                os.killpg(os.getpgid(pid), signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                pass
        set_status(rt, job_id, JobStatus.CANCELLED)
        cancelled.append(job_id)
    return cancelled


# --- spec files -------------------------------------------------------------

def write_job_spec(rt: str, job_id: int, spec: Dict[str, Any]) -> str:
    path = os.path.join(constants.job_dir(rt, job_id), 'spec.json')
    with open(path, 'w', encoding='utf-8') as f:
        json.dump(spec, f, indent=1)
    return path


def read_job_spec(rt: str, job_id: int) -> Dict[str, Any]:
    path = os.path.join(constants.job_dir(rt, job_id), 'spec.json')
    with open(path, 'r', encoding='utf-8') as f:
        return json.load(f)


def job_log_path(rt: str, job_id: int) -> str:
    return os.path.join(constants.job_dir(rt, job_id), 'run.log')
