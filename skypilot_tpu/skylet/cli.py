"""On-cluster command surface, invoked by the backend over the runner.

Replaces the reference's CodeGen-classes-serializing-python-into-
`python -c` payloads (job_lib.py:1040, autostop_lib.py:110) with a real
argparse CLI: every control-plane operation on the cluster is

    python -m skypilot_tpu.skylet.cli <subcommand> --runtime-dir D ...

Machine-readable results go to stdout as one JSON document.
"""
import argparse
import json
import os
import subprocess
import sys
from typing import Optional

from skypilot_tpu.skylet import autostop_lib
from skypilot_tpu.skylet import constants
from skypilot_tpu.skylet import job_lib
from skypilot_tpu.skylet import log_lib


def _cmd_submit(args) -> int:
    if args.spec_file:
        with open(args.spec_file, 'r', encoding='utf-8') as f:
            spec = json.load(f)
    else:
        spec = json.load(sys.stdin)
    job_id = job_lib.add_job(args.runtime_dir, spec.get('name') or '-',
                             spec.get('num_nodes', 1),
                             spec.get('resources_str', ''))
    job_lib.write_job_spec(args.runtime_dir, job_id, spec)
    # Start immediately (don't wait for the daemon tick).
    job_lib.schedule_step(args.runtime_dir)
    print(json.dumps({'job_id': job_id}))
    return 0


def _cmd_queue(args) -> int:
    jobs = job_lib.get_jobs(args.runtime_dir)
    out = []
    for j in jobs:
        j = dict(j)
        j['status'] = j['status'].value
        out.append(j)
    print(json.dumps(out))
    return 0


def _cmd_job_status(args) -> int:
    statuses = {}
    for job_id in args.job_ids:
        job = job_lib.get_job(args.runtime_dir, job_id)
        statuses[str(job_id)] = job['status'].value if job else None
    print(json.dumps(statuses))
    return 0


def _cmd_cancel(args) -> int:
    cancelled = job_lib.cancel_jobs(
        args.runtime_dir,
        job_ids=args.job_ids or None,
        all_jobs=args.all)
    print(json.dumps({'cancelled': cancelled}))
    return 0


def _cmd_tail(args) -> int:
    return log_lib.tail_logs(args.runtime_dir,
                             args.job_id,
                             follow=args.follow,
                             tail=args.tail)


def _cmd_set_autostop(args) -> int:
    provider_config = json.loads(args.provider_config or '{}')
    idle = None if args.cancel else args.idle_minutes
    autostop_lib.set_autostop(args.runtime_dir, idle, args.down,
                              args.provider_name,
                              args.cluster_name_on_cloud, provider_config)
    print(json.dumps({'ok': True}))
    return 0


def _cmd_start_skylet(args) -> int:
    """Idempotent daemon start (reference attempt_skylet.py)."""
    rt = args.runtime_dir
    pid_path = constants.skylet_pid_path(rt)
    if os.path.exists(pid_path):
        try:
            with open(pid_path, 'r', encoding='utf-8') as f:
                pid = int(f.read().strip())
            os.kill(pid, 0)
            print(json.dumps({'status': 'already_running', 'pid': pid}))
            return 0
        except (ValueError, ProcessLookupError, PermissionError):
            pass
    log_f = open(constants.skylet_log_path(rt), 'ab')
    proc = subprocess.Popen(
        [sys.executable, '-m', 'skypilot_tpu.skylet.skylet',
         '--runtime-dir', rt],
        stdout=log_f, stderr=subprocess.STDOUT,
        start_new_session=True)
    print(json.dumps({'status': 'started', 'pid': proc.pid}))
    return 0


def _cmd_is_idle(args) -> int:
    print(json.dumps({'idle': job_lib.is_cluster_idle(args.runtime_dir)}))
    return 0


def main(argv: Optional[list] = None) -> int:
    parser = argparse.ArgumentParser(prog='skylet-cli')
    parser.add_argument('--runtime-dir', default=None)
    sub = parser.add_subparsers(dest='cmd', required=True)

    p = sub.add_parser('submit')
    p.add_argument('--spec-file', default=None)
    p.set_defaults(fn=_cmd_submit)

    p = sub.add_parser('queue')
    p.set_defaults(fn=_cmd_queue)

    p = sub.add_parser('job-status')
    p.add_argument('--job-ids', type=int, nargs='+', required=True)
    p.set_defaults(fn=_cmd_job_status)

    p = sub.add_parser('cancel')
    p.add_argument('--job-ids', type=int, nargs='*', default=None)
    p.add_argument('--all', action='store_true')
    p.set_defaults(fn=_cmd_cancel)

    p = sub.add_parser('tail')
    p.add_argument('--job-id', type=int, default=None)
    p.add_argument('--follow', action=argparse.BooleanOptionalAction,
                   default=True)
    p.add_argument('--tail', type=int, default=0)
    p.set_defaults(fn=_cmd_tail)

    p = sub.add_parser('set-autostop')
    p.add_argument('--idle-minutes', type=float, default=5)
    p.add_argument('--down', action='store_true')
    p.add_argument('--cancel', action='store_true')
    p.add_argument('--provider-name', default='local')
    p.add_argument('--cluster-name-on-cloud', default='')
    p.add_argument('--provider-config', default='{}')
    p.set_defaults(fn=_cmd_set_autostop)

    p = sub.add_parser('start-skylet')
    p.set_defaults(fn=_cmd_start_skylet)

    p = sub.add_parser('is-idle')
    p.set_defaults(fn=_cmd_is_idle)

    args = parser.parse_args(argv)
    if args.runtime_dir is None:
        args.runtime_dir = constants.runtime_dir()
    return args.fn(args)


if __name__ == '__main__':
    sys.exit(main())
