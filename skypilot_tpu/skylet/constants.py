"""On-cluster runtime constants & path resolution.

Reference analog: sky/skylet/constants.py (:9-60 runtime env, :350
SKYPILOT_NUM_NODES etc.). The runtime directory is overridable via
$SKYTPU_RUNTIME_DIR so the local cloud can give every cluster its own
runtime on one machine.
"""
import os

from skypilot_tpu import envs

DEFAULT_RUNTIME_DIR = '~/.skytpu_runtime'
RUNTIME_DIR_ENV_VAR = envs.SKYTPU_RUNTIME_DIR.name

# Env vars injected into every job process (the reference's SKYPILOT_NODE_*
# contract, cloud_vm_ray_backend.py:606-670, re-spelled for jax).
# Derived from the central registry (envs.py, stdlib-only): the gang
# WRITERS (skylet/gang.py, job_lib.py) and READERS (parallel/mesh.py)
# share one source of truth for the names.
ENV_NUM_NODES = envs.SKYTPU_NUM_NODES.name    # logical nodes (slices)
ENV_NODE_RANK = envs.SKYTPU_NODE_RANK.name    # this host's slice index
ENV_NODE_IPS = envs.SKYTPU_NODE_IPS.name      # newline-sep head-host IPs
ENV_NUM_PROCESSES = envs.SKYTPU_NUM_PROCESSES.name  # total host procs
ENV_PROCESS_ID = envs.SKYTPU_PROCESS_ID.name  # global host index
ENV_COORDINATOR = envs.SKYTPU_COORDINATOR_ADDR.name  # ip:port of proc 0
ENV_JOB_ID = envs.SKYTPU_JOB_ID.name
ENV_CLUSTER_NAME = envs.SKYTPU_CLUSTER_NAME.name
ENV_ACCELERATORS_PER_NODE = envs.SKYTPU_ACCELERATORS_PER_NODE.name

# jax.distributed / multi-slice (DCN) coordinates. Within one slice libtpu
# does its own ICI rendezvous; across slices (one logical node == one
# slice) megascale needs these.
ENV_MEGASCALE_COORD = 'MEGASCALE_COORDINATOR_ADDRESS'
ENV_MEGASCALE_NUM_SLICES = 'MEGASCALE_NUM_SLICES'
ENV_MEGASCALE_SLICE_ID = 'MEGASCALE_SLICE_ID'
ENV_TPU_WORKER_ID = 'TPU_WORKER_ID'
ENV_TPU_WORKER_HOSTNAMES = 'TPU_WORKER_HOSTNAMES'

JAX_COORDINATOR_PORT = 8476
MEGASCALE_PORT = 8477

SKYLET_DAEMON_INTERVAL_SECONDS = 20


def runtime_dir() -> str:
    from skypilot_tpu import envs
    d = envs.SKYTPU_RUNTIME_DIR.get() or \
        os.path.expanduser(DEFAULT_RUNTIME_DIR)
    os.makedirs(d, exist_ok=True)
    return d


def jobs_dir(rt: str) -> str:
    d = os.path.join(rt, 'jobs')
    os.makedirs(d, exist_ok=True)
    return d


def job_dir(rt: str, job_id: int) -> str:
    d = os.path.join(jobs_dir(rt), str(job_id))
    os.makedirs(d, exist_ok=True)
    return d


def job_db_path(rt: str) -> str:
    return os.path.join(rt, 'jobs.db')


def topology_path(rt: str) -> str:
    return os.path.join(rt, 'cluster_topology.json')


def autostop_config_path(rt: str) -> str:
    return os.path.join(rt, 'autostop.json')


def skylet_pid_path(rt: str) -> str:
    return os.path.join(rt, 'skylet.pid')


def skylet_log_path(rt: str) -> str:
    return os.path.join(rt, 'skylet.log')


def topology_epoch(rt: str):
    """Epoch of the current topology file, or None when it is gone.
    Stale daemons from a previous incarnation of a same-named cluster
    compare against this and exit on mismatch."""
    import json
    try:
        with open(topology_path(rt), 'r', encoding='utf-8') as f:
            return json.load(f).get('epoch')
    except (OSError, ValueError):
        return None
