"""Log tailing with job-status-aware termination.

Reference analog: sky/skylet/log_lib.py (run_with_log :152, tail_logs :441,
_follow_job_logs :357).
"""
import os
import sys
import time
from typing import Optional

from skypilot_tpu.skylet import job_lib

_POLL_INTERVAL = 0.5


def tail_logs(rt: str, job_id: Optional[int] = None, *,
              follow: bool = True, tail: int = 0,
              out=None) -> int:
    """Stream a job's run.log; returns the job's exit code (0 if unknown).

    With follow=True, keeps streaming until the job reaches a terminal
    status AND the file is drained (the reference's status-aware loop).
    """
    out = out or sys.stdout
    if job_id is None:
        job_id = job_lib.get_latest_job_id(rt)
        if job_id is None:
            print('No jobs found on cluster.', file=out)
            return 1
    job = job_lib.get_job(rt, job_id)
    if job is None:
        print(f'Job {job_id} not found.', file=out)
        return 1
    log_path = job_lib.job_log_path(rt, job_id)

    # Wait for the driver to create the log file.
    deadline = time.time() + 30
    while follow and not os.path.exists(log_path):
        job = job_lib.get_job(rt, job_id)
        if job is not None and job['status'].is_terminal():
            break
        if time.time() > deadline:
            break
        time.sleep(_POLL_INTERVAL)

    if not os.path.exists(log_path):
        driver_log = os.path.join(os.path.dirname(log_path), 'driver.log')
        if os.path.exists(driver_log):
            log_path = driver_log
        else:
            print(f'No logs for job {job_id} (status: '
                  f'{job["status"].value}).', file=out)
            return _exit_code(job)

    with open(log_path, 'r', encoding='utf-8', errors='replace') as f:
        if tail > 0:
            lines = f.readlines()
            for line in lines[-tail:]:
                out.write(line)
            out.flush()
        else:
            for line in f:
                out.write(line)
            out.flush()
        if not follow:
            job = job_lib.get_job(rt, job_id)
            return _exit_code(job)
        # Follow: poll file + status.
        while True:
            line = f.readline()
            if line:
                out.write(line)
                out.flush()
                continue
            job = job_lib.get_job(rt, job_id)
            if job is not None and job['status'].is_terminal():
                # Drain whatever arrived between readline and the check.
                rest = f.read()
                if rest:
                    out.write(rest)
                    out.flush()
                return _exit_code(job)
            time.sleep(_POLL_INTERVAL)


def _exit_code(job) -> int:
    if job is None:
        return 1
    code = job.get('exit_code')
    if code is None:
        return 0
    return int(code)
