"""Skylet daemon events, ticked by skylet.py.

Reference analog: sky/skylet/events.py:65-243 (AutostopEvent,
JobSchedulerEvent, ...).
"""
import time
import traceback

from skypilot_tpu.skylet import autostop_lib
from skypilot_tpu.skylet import job_lib


class SkyletEvent:
    EVENT_INTERVAL_SECONDS = 20

    def __init__(self, rt: str):
        self.rt = rt
        self._last = 0.0

    def tick(self) -> None:
        now = time.time()
        if now - self._last < self.EVENT_INTERVAL_SECONDS:
            return
        self._last = now
        try:
            self._run()
        except Exception:  # noqa: BLE001 — daemon must survive anything
            traceback.print_exc()

    def _run(self) -> None:
        raise NotImplementedError


class JobSchedulerEvent(SkyletEvent):
    """Start PENDING jobs; reconcile dead drivers."""
    EVENT_INTERVAL_SECONDS = 2

    def _run(self) -> None:
        job_lib.update_job_statuses(self.rt)
        job_lib.schedule_step(self.rt)


class AutostopEvent(SkyletEvent):
    EVENT_INTERVAL_SECONDS = 20

    def _run(self) -> None:
        if autostop_lib.should_autostop(self.rt):
            autostop_lib.execute_autostop(self.rt)
