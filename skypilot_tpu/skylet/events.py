"""Skylet daemon events, ticked by skylet.py.

Reference analog: sky/skylet/events.py:65-243 (AutostopEvent,
JobSchedulerEvent, UsageHeartbeatReportEvent :94).
"""
import json
import os
import time
import traceback

from skypilot_tpu.skylet import autostop_lib
from skypilot_tpu.skylet import constants
from skypilot_tpu.skylet import job_lib


class SkyletEvent:
    EVENT_INTERVAL_SECONDS = 20

    def __init__(self, rt: str):
        self.rt = rt
        self._last = 0.0

    def tick(self) -> None:
        now = time.time()
        if now - self._last < self.EVENT_INTERVAL_SECONDS:
            return
        self._last = now
        try:
            self._run()
        except Exception:  # noqa: BLE001 — daemon must survive anything
            traceback.print_exc()

    def _run(self) -> None:
        raise NotImplementedError


class JobSchedulerEvent(SkyletEvent):
    """Start PENDING jobs; reconcile dead drivers."""
    EVENT_INTERVAL_SECONDS = 2

    def _run(self) -> None:
        job_lib.update_job_statuses(self.rt)
        job_lib.schedule_step(self.rt)


class AutostopEvent(SkyletEvent):
    EVENT_INTERVAL_SECONDS = 20

    def _run(self) -> None:
        if autostop_lib.should_autostop(self.rt):
            autostop_lib.execute_autostop(self.rt)


class HeartbeatEvent(SkyletEvent):
    """POST a liveness/usage heartbeat to the API server.

    Reference analog: sky/skylet/events.py:94
    (UsageHeartbeatReportEvent, which ships a heartbeat message to the
    usage endpoint every 600s). Ours targets the framework's own API
    server — the topology file carries the server URL at provision time
    — so `tsky status` and the dashboard can tell a live cluster from a
    stale record without a cloud probe. Best-effort: a missing/
    unreachable server must never disturb the daemon.
    """
    EVENT_INTERVAL_SECONDS = 60

    def _run(self) -> None:
        try:
            with open(constants.topology_path(self.rt), 'r',
                      encoding='utf-8') as f:
                topology = json.load(f)
        except (OSError, ValueError):
            return
        url = (topology.get('heartbeat') or {}).get('url')
        if not url:
            return
        counts = {}
        try:
            for job in job_lib.get_jobs(self.rt):
                status = job['status'].value
                counts[status] = counts.get(status, 0) + 1
        except Exception:  # noqa: BLE001 — job DB may not exist yet
            pass
        from skypilot_tpu.observability import instruments as obs
        payload = {
            'cluster_name': topology.get('cluster_name'),
            'epoch': topology.get('epoch'),
            'time': time.time(),
            'skylet_pid': os.getpid(),
            'jobs': counts,
            # Delivery history piggybacked on the beat itself: the
            # skylet exposes no /metrics endpoint, so the counter
            # rides to the API server (stored in the heartbeat
            # payload) where gaps — beats sent but not received, or
            # prior delivery errors — become visible controller-side.
            'sent': {
                'ok': int(obs.HEARTBEATS_SENT.value(outcome='ok')),
                'error': int(obs.HEARTBEATS_SENT.value(
                    outcome='error')),
            },
        }
        self._post(url.rstrip('/') + '/api/v1/heartbeat', payload)

    @staticmethod
    def _post(endpoint: str, payload: dict) -> bool:
        import urllib.request

        from skypilot_tpu.observability import instruments as obs
        try:
            req = urllib.request.Request(
                endpoint, data=json.dumps(payload).encode(),
                headers={'Content-Type': 'application/json'},
                method='POST')
            with urllib.request.urlopen(req, timeout=5):
                pass
        except Exception:  # noqa: BLE001 — liveness must never break skylet
            obs.HEARTBEATS_SENT.labels(outcome='error').inc()
            return False
        obs.HEARTBEATS_SENT.labels(outcome='ok').inc()
        return True
