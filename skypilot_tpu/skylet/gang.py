"""Gang runner: the TPU-native replacement for the reference's Ray driver.

Reference analog: RayCodeGen (cloud_vm_ray_backend.py:232-726) — a
generated Ray program that gang-schedules placement groups and runs bash on
each node with SKYPILOT_* env vars. On TPU there is nothing for Ray to do:
XLA owns intra-slice collectives, so gang execution is just "run the
command on every host of every slice with the right coordinates, and if
any host fails, kill them all". That is this module.

Runs on the head host as a detached driver process per job:

    python -m skypilot_tpu.skylet.gang --runtime-dir D --job-id N

Topology comes from cluster_topology.json (written at provision time);
the job's commands/envs from jobs/N/spec.json.

Injected coordinates (skylet/constants.py): SKYTPU_NUM_NODES / NODE_RANK /
NODE_IPS / NUM_PROCESSES / PROCESS_ID / COORDINATOR_ADDR, plus
MEGASCALE_* + TPU_WORKER_* for multi-slice TPU jobs — these are exactly
what `jax.distributed.initialize()` and megascale DCN bootstrap consume.
"""
import argparse
import json
import os
import shlex
import signal
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu.skylet import constants
from skypilot_tpu.skylet import job_lib


def load_topology(rt: str) -> Dict[str, Any]:
    with open(constants.topology_path(rt), 'r', encoding='utf-8') as f:
        return json.load(f)


def _host_argv(host: Dict[str, Any], cmd: str,
               env: Dict[str, str]) -> List[str]:
    """argv that runs `cmd` with `env` on `host` (local or over ssh)."""
    exports = ' '.join(f'export {k}={shlex.quote(str(v))};'
                       for k, v in env.items())
    full = f'{exports} {cmd}'
    if host.get('local', False):
        return ['bash', '-c', full]
    ssh_opts = [
        '-o', 'StrictHostKeyChecking=no',
        '-o', 'UserKnownHostsFile=/dev/null',
        '-o', 'LogLevel=ERROR',
        '-o', 'ConnectTimeout=30',
        '-p', str(host.get('ssh_port', 22)),
    ]
    if host.get('ssh_key'):
        ssh_opts += ['-i', os.path.expanduser(host['ssh_key'])]
    target = f"{host.get('ssh_user', 'root')}@{host['ip']}"
    return (['ssh'] + ssh_opts + [target,
            f'bash --login -c {shlex.quote(full)}'])


class GangRun:
    """Spawn one process per (node, host); kill-all on first failure."""

    def __init__(self, rt: str, job_id: int, spec: Dict[str, Any],
                 topology: Dict[str, Any]):
        self.rt = rt
        self.job_id = job_id
        self.spec = spec
        self.nodes: List[Dict[str, Any]] = topology['nodes']
        self.cluster_name = topology.get('cluster_name', '')
        self.log_path = job_lib.job_log_path(rt, job_id)
        self._log_lock = threading.Lock()
        self._procs: List[subprocess.Popen] = []
        self._failed = threading.Event()
        self._exit_codes: List[Optional[int]] = []
        self._first_failure_code: Optional[int] = None
        self._failure_lock = threading.Lock()

    # --- env injection ------------------------------------------------------

    def _env_for(self, node_rank: int, host_rank: int,
                 process_id: int) -> Dict[str, str]:
        num_nodes = len(self.nodes)
        total_procs = sum(len(n['hosts']) for n in self.nodes)
        node_head_ips = [n['hosts'][0]['ip'] for n in self.nodes]
        coordinator = (f'{node_head_ips[0]}:'
                       f'{constants.JAX_COORDINATOR_PORT}')
        env: Dict[str, str] = dict(self.spec.get('envs', {}))
        env.update({
            constants.ENV_NUM_NODES: str(num_nodes),
            constants.ENV_NODE_RANK: str(node_rank),
            constants.ENV_NODE_IPS: '\n'.join(node_head_ips),
            constants.ENV_NUM_PROCESSES: str(total_procs),
            constants.ENV_PROCESS_ID: str(process_id),
            constants.ENV_COORDINATOR: coordinator,
            constants.ENV_JOB_ID: str(self.job_id),
            constants.ENV_CLUSTER_NAME: self.cluster_name,
        })
        accs = self.spec.get('accelerators_per_node')
        if accs:
            env[constants.ENV_ACCELERATORS_PER_NODE] = str(accs)
        if self.spec.get('is_tpu', False):
            hosts = self.nodes[node_rank]['hosts']
            env[constants.ENV_TPU_WORKER_ID] = str(host_rank)
            env[constants.ENV_TPU_WORKER_HOSTNAMES] = ','.join(
                h['ip'] for h in hosts)
            if num_nodes > 1:
                # Multi-slice: each logical node is one slice; DCN
                # coordination via megascale.
                env[constants.ENV_MEGASCALE_COORD] = (
                    f'{node_head_ips[0]}:{constants.MEGASCALE_PORT}')
                env[constants.ENV_MEGASCALE_NUM_SLICES] = str(num_nodes)
                env[constants.ENV_MEGASCALE_SLICE_ID] = str(node_rank)
        return env

    # --- logging ------------------------------------------------------------

    def _pump(self, proc: subprocess.Popen, prefix: str, idx: int) -> None:
        assert proc.stdout is not None
        with open(self.log_path, 'ab') as f:
            for line in iter(proc.stdout.readline, b''):
                with self._log_lock:
                    f.write(prefix.encode() + line)
                    f.flush()
        rc = proc.wait()
        self._exit_codes[idx] = rc
        if rc != 0:
            with self._failure_lock:
                # Record the CAUSAL failure: a process that died before
                # the gang kill, not one we SIGTERMed as collateral.
                if self._first_failure_code is None and \
                        not self._failed.is_set():
                    self._first_failure_code = rc
            self._failed.set()

    def _log(self, msg: str) -> None:
        with self._log_lock, open(self.log_path, 'ab') as f:
            f.write(f'[gang] {msg}\n'.encode())

    # --- phases -------------------------------------------------------------

    def run_phase(self, cmd: str, phase: str) -> int:
        """Run `cmd` on every (node, host); return worst exit code."""
        self._procs = []
        self._failed.clear()
        threads = []
        total = sum(len(n['hosts']) for n in self.nodes)
        self._exit_codes = [None] * total
        self._log(f'{phase}: launching on {len(self.nodes)} node(s), '
                  f'{total} host process(es)')
        process_id = 0
        for node_rank, node in enumerate(self.nodes):
            for host_rank, host in enumerate(node['hosts']):
                env = self._env_for(node_rank, host_rank, process_id)
                argv = _host_argv(host, cmd, env)
                proc = subprocess.Popen(
                    argv, stdout=subprocess.PIPE,
                    stderr=subprocess.STDOUT,
                    start_new_session=True)
                self._procs.append(proc)
                self._spawn_reaper(proc.pid)
                multi_host = len(node['hosts']) > 1
                prefix = (f'({node_rank},{host_rank}) ' if multi_host
                          else (f'(node-{node_rank}) '
                                if len(self.nodes) > 1 else ''))
                t = threading.Thread(target=self._pump,
                                     args=(proc, prefix, process_id),
                                     daemon=True)
                t.start()
                threads.append(t)
                process_id += 1
        # Gang watchdog: first failure kills the rest; a vanished or
        # re-provisioned (epoch change) topology kills everything too,
        # so job processes never outlive their cluster incarnation.
        epoch = constants.topology_epoch(self.rt)
        while any(t.is_alive() for t in threads):
            if self._failed.is_set():
                self._kill_all()
                break
            if constants.topology_epoch(self.rt) != epoch:
                self._log('cluster gone: killing gang')
                self._kill_all()
                break
            time.sleep(0.2)
        for t in threads:
            t.join()
        codes = [c if c is not None else -1 for c in self._exit_codes]
        worst = self._first_failure_code
        if worst is None:
            worst = next((c for c in codes if c != 0), 0)
        self._log(f'{phase}: done, exit codes {codes}')
        return worst

    def _spawn_reaper(self, child_pid: int) -> None:
        """One orphan reaper per host process (reference
        subprocess_daemon.py): if THIS driver dies, the child's whole
        process group is torn down instead of outliving it."""
        subprocess.Popen(
            [sys.executable, '-m',
             'skypilot_tpu.skylet.subprocess_daemon',
             '--parent-pid', str(os.getpid()),
             '--proc-pid', str(child_pid)],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            start_new_session=True)

    def _kill_all(self) -> None:
        for proc in self._procs:
            if proc.poll() is None:
                try:
                    os.killpg(os.getpgid(proc.pid), signal.SIGTERM)
                except (ProcessLookupError, PermissionError):
                    pass
        deadline = time.time() + 10
        for proc in self._procs:
            timeout = max(0.1, deadline - time.time())
            try:
                proc.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                try:
                    os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument('--runtime-dir', required=True)
    parser.add_argument('--job-id', type=int, required=True)
    args = parser.parse_args(argv)
    rt = args.runtime_dir
    job_id = args.job_id

    spec = job_lib.read_job_spec(rt, job_id)
    topology = load_topology(rt)
    num_nodes = spec.get('num_nodes', 1)
    # A job may use fewer nodes than the cluster has.
    topology = dict(topology, nodes=topology['nodes'][:num_nodes])
    run = GangRun(rt, job_id, spec, topology)

    setup_cmd = spec.get('setup')
    if setup_cmd:
        job_lib.set_status(rt, job_id, job_lib.JobStatus.SETTING_UP)
        rc = run.run_phase(setup_cmd, 'setup')
        if rc != 0:
            job_lib.set_status(rt, job_id, job_lib.JobStatus.FAILED_SETUP,
                               exit_code=rc)
            return rc

    run_cmd = spec.get('run')
    if not run_cmd:
        job_lib.set_status(rt, job_id, job_lib.JobStatus.SUCCEEDED,
                           exit_code=0)
        return 0
    job_lib.set_status(rt, job_id, job_lib.JobStatus.RUNNING)
    rc = run.run_phase(run_cmd, 'run')
    job_lib.set_status(
        rt, job_id,
        job_lib.JobStatus.SUCCEEDED if rc == 0 else job_lib.JobStatus.FAILED,
        exit_code=rc)
    return rc


if __name__ == '__main__':
    sys.exit(main())
