"""Orphan reaper: kills a process tree once its parent is gone.

Reference analog: sky/skylet/subprocess_daemon.py (108 LoC). The gang
driver spawns one reaper per host process; if the driver dies (crash,
OOM, operator kill -9) the reaper notices within a second and tears
down the orphaned process group — user jobs and their SSH sessions
never outlive their driver.

    python -m skypilot_tpu.skylet.subprocess_daemon \
        --parent-pid <driver> --proc-pid <child>
"""
import argparse
import os
import signal
import sys
import time


def _start_time(pid: int):
    """Kernel start time of `pid` (field 22 of /proc/<pid>/stat), or
    None when the process is gone. /proc is used instead of
    os.kill(pid, 0) because the latter only works on processes we may
    signal; liveness of an arbitrary pid must not depend on that."""
    try:
        with open(f'/proc/{pid}/stat', 'rb') as f:
            stat = f.read()
    except OSError:
        return None
    # comm can contain spaces/parens: split after the LAST ')'.
    fields = stat.rsplit(b')', 1)[-1].split()
    return fields[19] if len(fields) > 19 else None


def _alive(pid: int, expected_start=None) -> bool:
    start = _start_time(pid)
    if start is None:
        return False
    if expected_start is not None and start != expected_start:
        return False  # pid was reused by an unrelated process
    return True


def _kill_tree(pid: int) -> None:
    """SIGTERM the process group, grace period, then SIGKILL."""
    try:
        pgid = os.getpgid(pid)
    except ProcessLookupError:
        return
    try:
        os.killpg(pgid, signal.SIGTERM)
    except (ProcessLookupError, PermissionError):
        return
    deadline = time.time() + 5.0
    while time.time() < deadline:
        if not _alive(pid):
            return
        time.sleep(0.2)
    try:
        os.killpg(pgid, signal.SIGKILL)
    except (ProcessLookupError, PermissionError):
        pass


def main(argv=None) -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument('--parent-pid', type=int, required=True)
    parser.add_argument('--proc-pid', type=int, required=True)
    parser.add_argument('--poll-seconds', type=float, default=1.0)
    args = parser.parse_args(argv)

    parent_start = _start_time(args.parent_pid)
    proc_start = _start_time(args.proc_pid)
    while True:
        if not _alive(args.proc_pid, proc_start):
            return 0  # target finished normally: nothing to reap
        if not _alive(args.parent_pid, parent_start):
            _kill_tree(args.proc_pid)
            return 0
        time.sleep(args.poll_seconds)


if __name__ == '__main__':
    sys.exit(main())
