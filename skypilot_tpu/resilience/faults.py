"""Deterministic fault injection: named points the stack checks inline.

Chaos testing without a chaos fleet: hot paths call
`faults.inject('<point>')` at the moments that fail in production
(cloud launch, readiness probe, upstream proxy hop, checkpoint write,
heartbeat receipt). Unarmed, an inject is a dict lookup — effectively
free. Armed (by a test, or by the SKYTPU_FAULTS env var on a live
process), it raises a configured exception and/or adds latency for a
bounded number of hits, so failure-handling paths run as ordinary,
deterministic tier-1 unit tests.

The point catalog below is the single source of truth:
tests/unit/test_fault_points_lint.py asserts every name matches the
naming regex, is unique, and is documented in
docs/guides/resilience.md — injection points stay discoverable as
they spread.

Arming from a test:

    faults.arm('lb.upstream', times=1, exc=OSError('injected'))
    ...
    faults.reset()   # in teardown

Arming from the environment (read at inject time, so a late export
still takes effect — no import-order trap):

    SKYTPU_FAULTS='checkpoint.save:2,probe.http:forever'

Env grammar: comma-separated `point[:times[:latency_seconds]]` where
times is an int or `forever`. Env-armed faults raise FaultInjected.
"""
import re
import threading
import time
from typing import Callable, Dict, List, Optional

from skypilot_tpu import sky_logging
from skypilot_tpu import envs
from skypilot_tpu.observability import instruments as obs

logger = sky_logging.init_logger(__name__)

POINT_RE = re.compile(r'^[a-z][a-z0-9_]*(\.[a-z][a-z0-9_]*)+$')


class FaultInjected(Exception):
    """Default exception an armed fault raises (env-armed faults
    always raise this; tests usually arm the exception type the call
    site actually handles, e.g. OSError for the LB upstream hop)."""


# -- the fault-point catalog ----------------------------------------------
# Declared centrally (like observability/instruments.py) so the lint
# and the docs cover the whole namespace by importing one module.

_POINTS: Dict[str, str] = {}


def declare(name: str, description: str) -> str:
    if not POINT_RE.fullmatch(name):
        raise ValueError(
            f'fault point {name!r} must match {POINT_RE.pattern} '
            '(plane.operation, lowercase)')
    if name in _POINTS:
        raise ValueError(f'duplicate fault point {name!r}')
    if not description or len(description.strip()) < 10:
        raise ValueError(f'fault point {name!r} needs a description')
    _POINTS[name] = description
    return name


PROVISION_LAUNCH = declare(
    'provision.launch',
    'Launching a cluster/replica through the provision plane (cloud '
    'API create + execution.launch call sites).')
PROBE_HTTP = declare(
    'probe.http',
    'One readiness-probe HTTP round against a replica endpoint.')
LB_UPSTREAM = declare(
    'lb.upstream',
    'The load balancer contacting one upstream replica for a proxied '
    'request (fires before any response bytes are written).')
CHECKPOINT_SAVE = declare(
    'checkpoint.save',
    'Writing one training checkpoint (orbax save + completeness '
    'sentinel).')
HEARTBEAT_RECV = declare(
    'heartbeat.recv',
    'The API server accepting one skylet liveness heartbeat.')
LB_UPSTREAM_MIDSTREAM = declare(
    'lb.upstream_midstream',
    'The load balancer reading the NEXT body chunk from an upstream '
    'that already sent response bytes (fires mid-stream, after the '
    'client saw headers — failover is no longer possible).')
CONTROLLER_STEP = declare(
    'controller.step',
    'One serve-controller reconcile tick (probe -> autoscale -> LB '
    'sync); arming with latency simulates a stalled controller, with '
    'an exception a crashed tick.')
FLEET_ZONE_LOSS = declare(
    'fleet.zone_loss',
    'One replica killed by a simulated zone outage (fleetsim chaos '
    'schedules arm this while a zone is marked lost; each firing is '
    'one replica down).')
FLEET_PREEMPTION_WAVE = declare(
    'fleet.preemption_wave',
    'One spot replica killed by a simulated preemption wave; the '
    'armed `times` bound IS the wave size, so '
    'SKYTPU_FAULTS=fleet.preemption_wave:300 preempts 300 replicas.')
REPLICA_PREEMPT = declare(
    'replica.preempt',
    'One replica receiving a preemption notice mid-decode (fleetsim '
    'chaos arms this to kill replicas that hold in-flight requests, '
    'exercising the drain -> snapshot -> migrate ladder).')
ENGINE_SNAPSHOT = declare(
    'engine.snapshot',
    'Serializing one in-flight request\'s KV pages + host state into '
    'a migration blob (fires before any device reads, so an armed '
    'fault models a snapshot that never materializes).')
LB_MIGRATE = declare(
    'lb.migrate',
    'The load balancer migrating one interrupted stream: snapshot '
    'fetch + restore re-route (fires once per interrupted request, '
    'before the first restore attempt).')
LB_HANDOFF = declare(
    'lb.handoff',
    'The load balancer walking the planned prefill->decode handoff '
    'ladder for one request (fires once per handoff frame, before '
    'the first decode-pool restore attempt); an armed fault forces '
    'the co-located /internal/resume fallback.')
ENGINE_HANDOFF_LEASE = declare(
    'engine.handoff_lease',
    'The engine granting a handoff lease — pausing a request at the '
    'prefill->decode boundary with its slot held live; an armed '
    'fault refuses the lease, so the request decodes co-located and '
    'no handoff frame is exported.')


def registered_points() -> Dict[str, str]:
    return dict(_POINTS)


# -- arming ----------------------------------------------------------------

# Default-exception sentinel: distinct from None (None = latency-only
# fault). A fresh FaultInjected is constructed per firing — a shared
# instance raised concurrently would race on __traceback__.
_DEFAULT_EXC = object()


class _Arm:
    __slots__ = ('times', 'exc', 'latency', 'hits', 'from_env')

    def __init__(self, times: Optional[int], exc,
                 latency: float, from_env: bool = False):
        self.times = times          # None = forever
        self.exc = exc              # None = latency-only fault
        self.latency = latency
        self.hits = 0
        # Env-armed faults carry no exception type of their own: the
        # call site supplies one via inject(env_exc=...) so the
        # failure looks like the real thing to its handlers.
        self.from_env = from_env


_lock = threading.Lock()
_armed: Dict[str, _Arm] = {}
_env_cache_raw: Optional[str] = None


def arm(point: str, times: Optional[int] = 1,
        exc=_DEFAULT_EXC,
        latency: float = 0.0) -> None:
    """Arm `point` to fail the next `times` injections (None=forever)
    with `exc` (None = add latency only), after `latency` seconds."""
    if point not in _POINTS:
        raise ValueError(f'unknown fault point {point!r}; declared: '
                         f'{sorted(_POINTS)}')
    if times is not None and times < 1:
        raise ValueError('times must be >= 1 or None (forever)')
    with _lock:
        _armed[point] = _Arm(times, exc, latency)


def disarm(point: str) -> None:
    with _lock:
        _armed.pop(point, None)


def reset() -> None:
    """Disarm everything (test teardown)."""
    global _env_cache_raw
    with _lock:
        _armed.clear()
        _env_cache_raw = None


def hits(point: str) -> int:
    """How many times `point` actually fired (test assertions)."""
    with _lock:
        a = _armed.get(point)
        return a.hits if a is not None else 0


def _load_env_locked() -> None:
    """Re-parse SKYTPU_FAULTS whenever its raw value changes: read at
    inject time, never cached at import (the import-time-env trap that
    bit SKYTPU_JOBS_RETRY_GAP)."""
    global _env_cache_raw
    raw = envs.SKYTPU_FAULTS.get()
    if raw == _env_cache_raw:
        return
    _env_cache_raw = raw
    # The env var is authoritative for env-armed points: a changed or
    # unset value must DISARM what it no longer lists (a chaos drill
    # must end when the operator unsets the variable).
    for point in [p for p, a in _armed.items() if a.from_env]:
        del _armed[point]
    for spec in filter(None, (s.strip() for s in raw.split(','))):
        parts = spec.split(':')
        point = parts[0]
        if point not in _POINTS:
            logger.warning('SKYTPU_FAULTS: unknown point %r ignored',
                           point)
            continue
        try:
            times: Optional[int] = 1
            if len(parts) > 1:
                times = (None if parts[1] == 'forever'
                         else int(parts[1]))
            latency = float(parts[2]) if len(parts) > 2 else 0.0
        except ValueError:
            # A typo'd env var must never take down the hot path it
            # was meant to test.
            logger.warning('SKYTPU_FAULTS: malformed spec %r ignored',
                           spec)
            continue
        existing = _armed.get(point)
        if existing is not None and not existing.from_env:
            # arm() (a test's explicit choice) outranks the env.
            continue
        _armed[point] = _Arm(times, _DEFAULT_EXC, latency,
                             from_env=True)


def inject(point: str,
           sleep_fn: Callable[[float], None] = time.sleep,
           env_exc: Optional[type] = None) -> None:
    """The hot-path hook: no-op unless `point` is armed.

    `env_exc` is the exception type an ENV-armed fault raises at this
    call site — the type the surrounding handlers treat as a real
    failure (e.g. OSError on the LB upstream hop), so chaos armed via
    SKYTPU_FAULTS exercises the recovery path instead of crashing it.
    Code-armed faults always raise exactly what the test supplied.
    """
    with _lock:
        _load_env_locked()
        a = _armed.get(point)
        if a is None:
            return
        if a.times is not None and a.hits >= a.times:
            return
        a.hits += 1
        latency, exc = a.latency, a.exc
        if exc is _DEFAULT_EXC:
            exc_type = (env_exc if (a.from_env and env_exc is not None)
                        else FaultInjected)
            exc = exc_type(f'injected fault at {point}')
    obs.FAULTS_INJECTED.labels(point=point).inc()
    logger.warning('fault injected at %s (latency=%.2fs, exc=%r)',
                   point, latency, exc)
    if latency > 0:
        sleep_fn(latency)
    if exc is not None:
        raise exc


def armed_points() -> List[str]:
    with _lock:
        _load_env_locked()
        return sorted(_armed)
