"""Circuit breakers keyed by target (closed -> open -> half-open).

A breaker protects callers from hammering a target that keeps failing
(a flapping replica, a wedged probe endpoint): after
`failure_threshold` consecutive failures the circuit OPENS and calls
are rejected without touching the target; after `recovery_timeout`
the circuit goes HALF-OPEN and admits a bounded number of trial calls
— one success re-closes it, one failure re-opens it (and restarts the
timer).

State is exported through the PR-1 observability registry
(`skytpu_circuit_state`, `skytpu_circuit_open_total`) so an open
circuit shows up in any /metrics scrape, not just in logs.

Thread-safe: the serve controller probes from its tick thread while
the load balancer records outcomes from its asyncio thread.
"""
import enum
import threading
import time
from typing import Callable, Dict, Optional

from skypilot_tpu import sky_logging
from skypilot_tpu.observability import instruments as obs

logger = sky_logging.init_logger(__name__)


class State(enum.IntEnum):
    """Gauge encoding (documented in the metric help string)."""
    CLOSED = 0
    OPEN = 1
    HALF_OPEN = 2


class _Target:
    __slots__ = ('state', 'failures', 'opened_at', 'half_open_inflight',
                 'half_open_since')

    def __init__(self):
        self.state = State.CLOSED
        self.failures = 0
        self.opened_at = 0.0
        self.half_open_inflight = 0
        self.half_open_since = 0.0


class CircuitBreaker:
    """One named breaker group; per-target independent circuits."""

    def __init__(self, name: str,
                 failure_threshold: int = 3,
                 recovery_timeout: float = 30.0,
                 half_open_max_calls: int = 1,
                 now_fn: Callable[[], float] = time.monotonic,
                 on_open: Optional[Callable[[str], None]] = None):
        if failure_threshold < 1:
            raise ValueError('failure_threshold must be >= 1')
        if recovery_timeout < 0:
            raise ValueError('recovery_timeout must be >= 0')
        self.name = name
        self.failure_threshold = failure_threshold
        self.recovery_timeout = recovery_timeout
        self.half_open_max_calls = half_open_max_calls
        # Fired (outside the breaker lock) each time a target's
        # circuit transitions to OPEN — the LB hooks its trace
        # flight-recorder dump here, so the evidence of WHAT was
        # failing ships the moment the breaker gives up on a target.
        self._on_open = on_open
        self._now = now_fn
        self._lock = threading.Lock()
        self._targets: Dict[str, _Target] = {}

    # -- queries -------------------------------------------------------------

    def allow(self, target: str) -> bool:
        """May the caller contact `target` now? Drives the open ->
        half-open transition as a side effect of asking."""
        with self._lock:
            t = self._targets.get(target)
            if t is None or t.state == State.CLOSED:
                return True
            now = self._now()
            if t.state == State.OPEN:
                if now - t.opened_at < self.recovery_timeout:
                    return False
                self._set_state(t, target, State.HALF_OPEN)
                t.half_open_inflight = 0
                t.half_open_since = now
            # HALF_OPEN: admit a bounded number of trial calls. Trial
            # slots EXPIRE after another recovery window — a trial
            # whose caller never reported an outcome (client vanished
            # mid-proxy) must not wedge the target rejected forever.
            if t.half_open_inflight >= self.half_open_max_calls:
                if now - t.half_open_since < self.recovery_timeout:
                    return False
                t.half_open_inflight = 0
                t.half_open_since = now
            t.half_open_inflight += 1
            return True

    def state(self, target: str) -> State:
        with self._lock:
            t = self._targets.get(target)
            return t.state if t is not None else State.CLOSED

    def snapshot(self) -> Dict[str, State]:
        """Target -> state for every tracked target, WITHOUT driving
        the open -> half-open transition (allow() mutates; a stats
        endpoint polled by dashboards must not burn half-open trial
        slots)."""
        with self._lock:
            return {target: t.state
                    for target, t in self._targets.items()}

    # -- outcome feedback ----------------------------------------------------

    def record_success(self, target: str) -> None:
        with self._lock:
            t = self._targets.get(target)
            if t is None:
                return
            if t.state != State.CLOSED:
                self._set_state(t, target, State.CLOSED)
            t.failures = 0
            t.half_open_inflight = 0

    def record_failure(self, target: str) -> None:
        opened = False
        with self._lock:
            t = self._targets.setdefault(target, _Target())
            t.failures += 1
            if t.state == State.HALF_OPEN or (
                    t.state == State.CLOSED and
                    t.failures >= self.failure_threshold):
                self._set_state(t, target, State.OPEN)
                t.opened_at = self._now()
                t.half_open_inflight = 0
                opened = True
                obs.CIRCUIT_OPEN.labels(breaker=self.name,
                                        target=target).inc()
                logger.warning(
                    'circuit %s/%s OPEN after %d consecutive '
                    'failure(s); retry in %.0fs', self.name, target,
                    t.failures, self.recovery_timeout)
        if opened and self._on_open is not None:
            # Outside the lock: the callback may query this breaker
            # (or do slow I/O like a trace dump) without deadlocking
            # the record path.
            try:
                self._on_open(target)
            except Exception:  # diagnostics must never break serving
                logger.warning('on_open callback failed for %s/%s',
                               self.name, target, exc_info=True)

    def forget(self, target: str) -> None:
        """Drop a target (replica scaled down): its gauge reads closed
        so a dead endpoint never looks permanently broken."""
        with self._lock:
            t = self._targets.pop(target, None)
            if t is not None:
                obs.CIRCUIT_STATE.labels(
                    breaker=self.name, target=target).set(
                        float(State.CLOSED))

    # -- internals -----------------------------------------------------------

    def _set_state(self, t: _Target, target: str, state: State) -> None:
        t.state = state
        obs.CIRCUIT_STATE.labels(breaker=self.name,
                                 target=target).set(float(state))
