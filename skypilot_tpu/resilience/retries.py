"""One retry policy for every plane: backoff + jitter + deadline budget.

Reference analog: the reference scatters `time.sleep(gap * attempt)`
loops through jobs/recovery_strategy.py, provision/provisioner.py and
serve/replica_managers.py; here the policy is a value object so call
sites share semantics and tests inject fake clocks instead of sleeping.

Semantics:
- exponential backoff with FULL jitter (AWS architecture-blog style):
  delay = uniform(0, min(max_delay, base_delay * 2**attempt)). Full
  jitter de-synchronizes thundering herds — after a TPU-pod preemption
  every recovering job hits the same regional API at once.
- `deadline` is an overall elapsed-time budget across all attempts:
  recovery must bound time-to-give-up, not just attempt counts (a
  15-minute provision hang x 3 attempts is not "3 quick retries").
- `attempt_timeout` bounds one attempt by running it on a worker
  thread; a timed-out attempt counts as a failure (the thread is
  abandoned — best effort, sufficient for I/O-bound attempts).

Usage — explicit call:

    policy = retries.RetryPolicy(max_attempts=3, base_delay=10.0)
    retries.call(launch_once, policy=policy,
                 retry_on=(ResourcesUnavailableError,))

or decorator:

    @retries.retrying(RetryPolicy(max_attempts=5), retry_on=(OSError,))
    def flaky(): ...

Determinism for tests: `sleep_fn`, `now_fn` and `rng` are injectable;
a fake clock advanced by the fake sleep makes every schedule exact.
"""
import dataclasses
import functools
import random
import time
from typing import Callable, Optional, Tuple, Type

from skypilot_tpu import sky_logging

logger = sky_logging.init_logger(__name__)


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """How to retry: attempt count, backoff shape, time budgets.

    max_attempts=None means attempts are unbounded and only `deadline`
    stops the loop (polling loops like wait-for-SSH).
    """
    max_attempts: Optional[int] = 3
    base_delay: float = 1.0
    max_delay: float = 60.0
    deadline: Optional[float] = None
    attempt_timeout: Optional[float] = None
    exponential: bool = True
    jitter: bool = True

    def __post_init__(self):
        if self.max_attempts is not None and self.max_attempts < 1:
            raise ValueError('max_attempts must be >= 1 (or None)')
        if self.base_delay < 0 or self.max_delay < self.base_delay:
            raise ValueError('need 0 <= base_delay <= max_delay')
        if self.max_attempts is None and self.deadline is None:
            raise ValueError(
                'unbounded attempts require a deadline budget')

    def delay(self, attempt: int, rng: Callable[[], float]) -> float:
        """Backoff before attempt `attempt + 1` (0-based)."""
        if self.exponential:
            cap = min(self.max_delay,
                      self.base_delay * (2.0 ** attempt))
        else:
            cap = min(self.max_delay, self.base_delay)
        if self.jitter:
            return rng() * cap
        return cap


def call(fn: Callable,
         policy: RetryPolicy,
         retry_on: Tuple[Type[BaseException], ...] = (Exception,),
         on_retry: Optional[Callable[[BaseException, int], None]] = None,
         describe: str = '',
         sleep_fn: Callable[[float], None] = time.sleep,
         now_fn: Callable[[], float] = time.monotonic,
         rng: Callable[[], float] = random.random):
    """Run `fn()` under `policy`; re-raise the last error on exhaustion.

    `on_retry(exc, attempt)` fires between attempts — the hook where a
    caller tears down partial state (e.g. terminate a half-launched
    cluster) before the relaunch.
    """
    start = now_fn()
    what = describe or getattr(fn, '__name__', 'operation')
    attempt = 0
    while True:
        try:
            return _one_attempt(fn, policy)
        except retry_on as e:
            attempt += 1
            out_of_attempts = (policy.max_attempts is not None and
                               attempt >= policy.max_attempts)
            delay = policy.delay(attempt - 1, rng)
            over_budget = (policy.deadline is not None and
                           now_fn() - start + delay > policy.deadline)
            if out_of_attempts or over_budget:
                reason = ('budget exhausted' if over_budget
                          else 'attempts exhausted')
                logger.warning('%s failed (%s after %d attempt(s)): %s',
                               what, reason, attempt, e)
                raise
            logger.debug('%s attempt %d failed (%s); retrying in '
                         '%.1fs', what, attempt, e, delay)
            if on_retry is not None:
                on_retry(e, attempt)
            if delay > 0:
                sleep_fn(delay)


def _one_attempt(fn: Callable, policy: RetryPolicy):
    if policy.attempt_timeout is None:
        return fn()
    import concurrent.futures
    # One worker per attempt: the pool must not serialize a fresh
    # attempt behind an abandoned (still-running) timed-out one.
    pool = concurrent.futures.ThreadPoolExecutor(max_workers=1)
    try:
        fut = pool.submit(fn)
        try:
            return fut.result(timeout=policy.attempt_timeout)
        except concurrent.futures.TimeoutError:
            raise TimeoutError(
                f'attempt exceeded {policy.attempt_timeout:.1f}s')
    finally:
        pool.shutdown(wait=False)


def retrying(policy: RetryPolicy,
             retry_on: Tuple[Type[BaseException], ...] = (Exception,),
             **call_kwargs):
    """Decorator form of `call` for functions that own their policy."""
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            return call(functools.partial(fn, *args, **kwargs),
                        policy=policy, retry_on=retry_on,
                        describe=fn.__name__, **call_kwargs)
        return wrapper
    return deco
