"""Resilience subsystem: retries, circuit breakers, fault injection.

TPU capacity is the most preemption-prone in the fleet, so recovery is
the product, not an edge case. This package is the single place the
stack's failure handling lives:

- `retries`: one retry policy (exponential backoff, full jitter,
  per-attempt timeout, overall deadline budget) replacing ad-hoc
  sleep loops in the recovery, provision, and serve planes.
- `circuit`: thread-safe circuit breakers keyed by target (replica
  endpoints, probe URLs), exported as `skytpu_circuit_*` series.
- `faults`: a deterministic fault-injection registry — named fault
  points that tests arm with fail-N-times / latency / fail-forever
  behaviors, so chaos scenarios run as ordinary tier-1 unit tests.
"""
from skypilot_tpu.resilience import circuit
from skypilot_tpu.resilience import faults
from skypilot_tpu.resilience import retries

__all__ = ['circuit', 'faults', 'retries']
