"""Role policy: which API commands each role may execute.

Reference analog: sky/users/permission.py (casbin model + policy; the
reference's policy boils down to the same read/write/admin split).
"""
from typing import FrozenSet

from skypilot_tpu import users

# Read-only commands: cluster/job/service introspection.
READ_COMMANDS: FrozenSet[str] = frozenset({
    'status', 'queue', 'cost_report', 'check', 'optimize', 'logs',
    'jobs_queue', 'jobs_logs', 'serve_status', 'serve_logs',
    'storage_ls', 'accelerators',
})

# Mutating commands available to ROLE_USER and above.
WRITE_COMMANDS: FrozenSet[str] = frozenset({
    'launch', 'exec', 'start', 'stop', 'down', 'autostop', 'cancel',
    'jobs_launch', 'jobs_cancel', 'serve_up', 'serve_down',
    'serve_update', 'storage_delete',
})


def allowed(user: 'users.User', command: str) -> bool:
    if user.role == users.ROLE_ADMIN:
        return True
    if user.role == users.ROLE_USER:
        return command in READ_COMMANDS or command in WRITE_COMMANDS
    if user.role == users.ROLE_VIEWER:
        return command in READ_COMMANDS
    return False


def check(user: 'users.User', command: str) -> None:
    from skypilot_tpu import exceptions
    if not allowed(user, command):
        raise exceptions.PermissionDeniedError(
            f'User {user.name!r} (role {user.role}) may not run '
            f'{command!r}.')
