"""Users, roles, and workspaces — API-server multi-tenancy.

Reference analog: sky/users/permission.py:8 (casbin RBAC enforcer),
sky/workspaces/. Ours is config-driven (no casbin dependency): the
`api_server.users` list in ~/.skytpu/config.yaml declares users with a
token, role, and optional workspace; role policy lives in
users/permission.py. With no users configured the server runs in open
local mode as user 'default' (admin), matching the reference's
no-auth-proxy default.

    api_server:
      auth: true
      users:
        - name: alice
          token: secret-a
          role: admin
        - name: bob
          token: secret-b
          role: user
          workspace: team-x
        - name: carol
          token: secret-c
          role: viewer
"""
import dataclasses
import hmac
from typing import Dict, List, Optional

from skypilot_tpu import envs

ROLE_ADMIN = 'admin'
ROLE_USER = 'user'
ROLE_VIEWER = 'viewer'
ROLES = (ROLE_ADMIN, ROLE_USER, ROLE_VIEWER)

DEFAULT_WORKSPACE = 'default'


@dataclasses.dataclass(frozen=True)
class User:
    name: str
    role: str = ROLE_ADMIN
    workspace: str = DEFAULT_WORKSPACE
    token: Optional[str] = None


DEFAULT_USER = User(name='default', role=ROLE_ADMIN)


def configured_users_from_config() -> List[User]:
    """Users declared in the config file only (no DB users)."""
    from skypilot_tpu import config as config_lib
    raw = config_lib.get_nested(('api_server', 'users'), default=None)
    users: List[User] = []
    for entry in raw or []:
        if not isinstance(entry, dict) or 'name' not in entry:
            continue
        role = entry.get('role', ROLE_USER)
        if role not in ROLES:
            role = ROLE_VIEWER  # unknown role: least privilege
        users.append(User(
            name=str(entry['name']), role=role,
            workspace=str(entry.get('workspace', DEFAULT_WORKSPACE)),
            token=entry.get('token')))
    return users


def bootstrap_admin() -> Optional[User]:
    """Deployment bootstrap credential: containerized servers (the Helm
    chart's auth Secret) inject SKYTPU_BOOTSTRAP_ADMIN_TOKEN so a fresh
    install has exactly one admin, who then creates real users over the
    API. Config/DB users named 'admin' shadow it."""
    token = envs.SKYTPU_BOOTSTRAP_ADMIN_TOKEN.get()
    if not token:
        return None
    return User(name='admin', role=ROLE_ADMIN, token=token)


def configured_users() -> List[User]:
    """All users the auth layer accepts: config-declared plus enabled
    DB users (users/store.py CRUD) plus the env bootstrap admin;
    config wins on name collisions."""
    users = configured_users_from_config()
    names = {u.name for u in users}
    from skypilot_tpu.users import store
    users.extend(u for u in store.enabled_db_users()
                 if u.name not in names)
    names = {u.name for u in users}
    boot = bootstrap_admin()
    if boot is not None and boot.name not in names:
        users.append(boot)
    return users


def auth_required() -> bool:
    """Auth posture comes from the CONFIG (the flag or declared users)
    or a deployment bootstrap token. API-created DB users deliberately
    don't flip it: an admin adding a user in open local mode must not
    lock every tokenless client (themselves included) out of the
    server."""
    from skypilot_tpu import config as config_lib
    if config_lib.get_nested(('api_server', 'auth'), default=False):
        return True
    if bootstrap_admin() is not None:
        return True
    return bool(configured_users_from_config())


def user_for_token(token: Optional[str]) -> Optional[User]:
    """Token → User; None when auth is on and the token is unknown."""
    if not auth_required():
        return DEFAULT_USER
    if not token:
        return None
    for user in configured_users():
        if user.token is not None and hmac.compare_digest(
                user.token, token):
            return user
    return None


def users_by_name() -> Dict[str, User]:
    return {u.name: u for u in configured_users()}
