"""DB-backed user management: add / rotate / disable / delete over
the API, next to the config-declared user list.

Reference analog: sky/users/server.py (user CRUD endpoints + service
accounts) and sky/global_user_state user tables. Two sources feed the
auth layer:

  1. config users (`api_server.users` in ~/.skytpu/config.yaml) —
     declarative, operator-managed, immutable through the API (the
     API answering "edit your config file" beats two writers fighting
     over one YAML document);
  2. DB users (this module) — created through `tsky user add` /
     POST /api/v1/users, with server-generated tokens, rotation, and
     disable without delete.

On a name collision the config entry wins (the operator's file is
the higher authority). Tokens are stored in the server's state DB the
same way the config stores them — the DB file lives under the
server's state dir with user-only permissions.
"""
import secrets
import sqlite3
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import state
from skypilot_tpu import users as users_lib


_table = state.TableOnce("""
    CREATE TABLE IF NOT EXISTS users (
        name TEXT PRIMARY KEY,
        token TEXT,
        role TEXT,
        workspace TEXT,
        disabled INTEGER DEFAULT 0,
        created_at INTEGER
    )""")
_ensure_table = _table.ensure


def _new_token() -> str:
    return f'sky-{secrets.token_urlsafe(24)}'


def _row_to_doc(row, with_token: bool = False) -> Dict[str, Any]:
    name, token, role, workspace, disabled, created_at = row
    doc = {'name': name, 'role': role, 'workspace': workspace,
           'disabled': bool(disabled), 'created_at': created_at,
           'source': 'db'}
    if with_token:
        doc['token'] = token
    return doc


def list_users() -> List[Dict[str, Any]]:
    """Merged listing: config users (tokens never echoed) + DB users
    (disabled ones included — the point of disable is to keep them
    visible)."""
    _ensure_table()
    conn = state.connection()
    db_rows = conn.execute(
        'SELECT name, token, role, workspace, disabled, created_at '
        'FROM users ORDER BY name').fetchall()
    config_names = set()
    out = []
    for u in users_lib.configured_users_from_config():
        config_names.add(u.name)
        out.append({'name': u.name, 'role': u.role,
                    'workspace': u.workspace, 'disabled': False,
                    'created_at': None, 'source': 'config'})
    for row in db_rows:
        if row[0] in config_names:
            continue  # config wins on collisions
        out.append(_row_to_doc(row))
    return out


def get_user(name: str) -> Optional[Dict[str, Any]]:
    _ensure_table()
    conn = state.connection()
    row = conn.execute(
        'SELECT name, token, role, workspace, disabled, created_at '
        'FROM users WHERE name=?', (name,)).fetchone()
    return _row_to_doc(row) if row else None


def enabled_db_users() -> List['users_lib.User']:
    """The DB users the auth layer accepts tokens from."""
    _ensure_table()
    conn = state.connection()
    rows = conn.execute(
        'SELECT name, token, role, workspace FROM users '
        'WHERE disabled=0').fetchall()
    return [users_lib.User(name=r[0], token=r[1], role=r[2],
                           workspace=r[3] or users_lib.DEFAULT_WORKSPACE)
            for r in rows]


def _check_name_free(name: str) -> None:
    if any(u.name == name
           for u in users_lib.configured_users_from_config()):
        raise ValueError(
            f'User {name!r} is declared in the server config file; '
            'manage it by editing api_server.users there.')


def create_user(name: str, role: str = users_lib.ROLE_USER,
                workspace: str = users_lib.DEFAULT_WORKSPACE
                ) -> Dict[str, Any]:
    """Add a user; returns the doc INCLUDING the generated token —
    the only time it is ever echoed."""
    _ensure_table()
    if not state.valid_identifier(name):
        raise ValueError(f'User name {name!r} must be alphanumeric '
                         'with - or _')
    if role not in users_lib.ROLES:
        raise ValueError(f'Unknown role {role!r} '
                         f'(one of {users_lib.ROLES})')
    _check_name_free(name)
    if get_user(name) is not None:
        raise ValueError(f'User {name!r} already exists.')
    token = _new_token()
    with state.write_lock():
        conn = state.connection()
        try:
            conn.execute(
                'INSERT INTO users (name, token, role, workspace, '
                'disabled, created_at) VALUES (?, ?, ?, ?, 0, ?)',
                (name, token, role, workspace, int(time.time())))
            conn.commit()
        except sqlite3.IntegrityError as e:
            # Concurrent create raced the pre-check; same error as the
            # pre-check, not a raw 500. Rollback releases the implicit
            # write transaction; the write_lock hold is what makes it
            # safe (it can't discard another thread's pending write).
            conn.rollback()
            raise ValueError(f'User {name!r} already exists.') from e
        # Re-read INSIDE the hold: after release, a concurrent delete
        # could make this None and turn success into a 500.
        doc = get_user(name)
    doc['token'] = token
    return doc


def rotate_token(name: str) -> Dict[str, Any]:
    """Invalidate the old token, return the new one (once)."""
    _require_db_user(name)
    token = _new_token()
    with state.write_lock():
        conn = state.connection()
        conn.execute('UPDATE users SET token=? WHERE name=?',
                     (token, name))
        conn.commit()
        doc = get_user(name)
    if doc is None:
        raise ValueError(f'User {name!r} was deleted concurrently.')
    doc['token'] = token
    return doc


def update_user(name: str, role: Optional[str] = None,
                workspace: Optional[str] = None,
                disabled: Optional[bool] = None) -> Dict[str, Any]:
    _require_db_user(name)
    if role is not None and role not in users_lib.ROLES:
        raise ValueError(f'Unknown role {role!r} '
                         f'(one of {users_lib.ROLES})')
    with state.write_lock():
        conn = state.connection()
        if role is not None:
            conn.execute('UPDATE users SET role=? WHERE name=?',
                         (role, name))
        if workspace is not None:
            conn.execute('UPDATE users SET workspace=? WHERE name=?',
                         (workspace, name))
        if disabled is not None:
            conn.execute('UPDATE users SET disabled=? WHERE name=?',
                         (1 if disabled else 0, name))
        conn.commit()
        doc = get_user(name)
    if doc is None:
        raise ValueError(f'User {name!r} was deleted concurrently.')
    return doc


def delete_user(name: str) -> None:
    _require_db_user(name)
    with state.write_lock():
        conn = state.connection()
        conn.execute('DELETE FROM users WHERE name=?', (name,))
        conn.commit()


def _require_db_user(name: str) -> None:
    _check_name_free(name)
    if get_user(name) is None:
        raise ValueError(f'No such user {name!r}.')
