"""Training: sharded trainer + MFU accounting."""
from skypilot_tpu.train import trainer

__all__ = ['trainer']
