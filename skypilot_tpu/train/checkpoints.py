"""Checkpointing: orbax-backed sharded save/restore + job-level resume.

The reference has NO tensor checkpointing (it is an orchestrator; user
ckpts go to storage mounts — SURVEY.md §5 'Checkpoint/resume'). Here it
is first-class: train state (params + opt state + step) saves
asynchronously from every host of a sharded run, and restores onto a
DIFFERENT mesh shape (orbax resharding), which is what makes managed-job
recovery after preemption resume training instead of restarting.
"""
from __future__ import annotations

import os
import threading
from typing import Any, Dict, List, Optional

import jax

from skypilot_tpu.resilience import faults

# Completeness sentinel: written only AFTER orbax's async write fully
# flushed. latest_step requires it, so a host killed mid-save can
# never be resumed from a torn checkpoint — the orbax tmp marker alone
# does not cover the window between array commit and metadata flush.
COMPLETE_SENTINEL = '.skytpu-complete'

_pending_lock = threading.Lock()
_pending: List[threading.Thread] = []


def _checkpointer():
    import orbax.checkpoint as ocp
    return ocp.StandardCheckpointer()


def _mark_complete(path: str) -> None:
    with open(os.path.join(path, COMPLETE_SENTINEL), 'w',
              encoding='utf-8') as f:
        f.write('complete\n')


def save_train_state(ckpt_dir: str, state: Dict[str, Any],
                     step: Optional[int] = None,
                     wait: bool = True) -> str:
    """Save {params, opt_state, step} under ckpt_dir/<step>.

    wait=False returns once the async write is dispatched; the
    completeness sentinel is written by a background finalizer after
    the write flushes (join it with `flush()`), so the checkpoint
    becomes visible to latest_step only when it is actually durable.
    """
    if step is None:
        step = int(jax.device_get(state.get('step', 0)))
    path = os.path.join(os.path.abspath(os.path.expanduser(ckpt_dir)),
                        str(step))
    faults.inject('checkpoint.save')
    ckptr = _checkpointer()
    ckptr.save(path, state, force=True)
    if wait:
        ckptr.wait_until_finished()
        _mark_complete(path)
        return path

    def _finalize():
        ckptr.wait_until_finished()
        _mark_complete(path)

    thread = threading.Thread(target=_finalize, daemon=True)
    with _pending_lock:
        # Prune finished finalizers: periodic async savers must not
        # grow this list for the life of the process.
        _pending[:] = [t for t in _pending if t.is_alive()]
        _pending.append(thread)
    thread.start()
    return path


def flush() -> None:
    """Join every in-flight async save (end-of-run barrier; tests)."""
    with _pending_lock:
        threads, _pending[:] = list(_pending), []
    for t in threads:
        t.join()


def latest_step(ckpt_dir: str) -> Optional[int]:
    """Newest COMPLETE step. Torn checkpoints — orbax tmp marker
    present, or completeness sentinel missing (killed mid-save, or an
    async save still flushing) — are never resume candidates."""
    ckpt_dir = os.path.abspath(os.path.expanduser(ckpt_dir))
    if not os.path.isdir(ckpt_dir):
        return None
    steps = []
    for name in os.listdir(ckpt_dir):
        full = os.path.join(ckpt_dir, name)
        if (name.isdigit() and os.path.isdir(full) and
                not os.path.exists(
                    os.path.join(full, '.orbax-checkpoint-tmp')) and
                os.path.exists(os.path.join(full, COMPLETE_SENTINEL))):
            steps.append(int(name))
    return max(steps) if steps else None


def restore_train_state(ckpt_dir: str, abstract_state: Dict[str, Any],
                        step: Optional[int] = None) -> Dict[str, Any]:
    """Restore onto the shardings/dtypes described by `abstract_state`
    (a pytree of jax.ShapeDtypeStruct with .sharding — orbax reshards
    across mesh shapes)."""
    if step is None:
        step = latest_step(ckpt_dir)
        if step is None:
            raise FileNotFoundError(
                f'No checkpoint found under {ckpt_dir!r}')
    path = os.path.join(os.path.abspath(os.path.expanduser(ckpt_dir)),
                        str(step))
    return _checkpointer().restore(path, abstract_state)


def abstract_train_state(cfg, mesh) -> Dict[str, Any]:
    """ShapeDtypeStruct pytree for a TrainerConfig on a mesh — the
    restore target, built WITHOUT materializing any arrays."""
    from skypilot_tpu.train import trainer as trainer_lib

    def _make():
        state = trainer_lib.make_train_state(cfg, mesh)
        return state
    return jax.eval_shape(_make)


def restore_params(ckpt_dir: str, config,
                   mesh: Optional[Any] = None) -> Dict[str, Any]:
    """Restore just model params (inference path). Accepts checkpoints
    saved either as bare params or as full train state — and, via
    auto-detection, an HF safetensors dir: a pretrained download
    passed where an Orbax dir was expected streams in through the
    importer (with the geometry its own config.json declares) instead
    of dying in FileNotFoundError."""
    from skypilot_tpu import checkpoints as hf_ckpts
    if hf_ckpts.is_hf_checkpoint(ckpt_dir):
        params, _detected, _stats = hf_ckpts.load_params(ckpt_dir,
                                                         mesh=mesh)
        return params
    del config  # shapes come from checkpoint metadata
    step = latest_step(ckpt_dir)
    if step is None:
        raise FileNotFoundError(f'No checkpoint under {ckpt_dir!r}')
    path = os.path.join(os.path.abspath(os.path.expanduser(ckpt_dir)),
                        str(step))
    restored = _checkpointer().restore(path)
    if isinstance(restored, dict) and 'params' in restored:
        return restored['params']
    return restored
