"""Checkpointing: orbax-backed sharded save/restore + job-level resume.

The reference has NO tensor checkpointing (it is an orchestrator; user
ckpts go to storage mounts — SURVEY.md §5 'Checkpoint/resume'). Here it
is first-class: train state (params + opt state + step) saves
asynchronously from every host of a sharded run, and restores onto a
DIFFERENT mesh shape (orbax resharding), which is what makes managed-job
recovery after preemption resume training instead of restarting.
"""
from __future__ import annotations

import os
from typing import Any, Dict, Optional

import jax


def _checkpointer():
    import orbax.checkpoint as ocp
    return ocp.StandardCheckpointer()


def save_train_state(ckpt_dir: str, state: Dict[str, Any],
                     step: Optional[int] = None,
                     wait: bool = True) -> str:
    """Save {params, opt_state, step} under ckpt_dir/<step>."""
    if step is None:
        step = int(jax.device_get(state.get('step', 0)))
    path = os.path.join(os.path.abspath(os.path.expanduser(ckpt_dir)),
                        str(step))
    ckptr = _checkpointer()
    ckptr.save(path, state, force=True)
    if wait:
        ckptr.wait_until_finished()
    return path


def latest_step(ckpt_dir: str) -> Optional[int]:
    ckpt_dir = os.path.abspath(os.path.expanduser(ckpt_dir))
    if not os.path.isdir(ckpt_dir):
        return None
    steps = []
    for name in os.listdir(ckpt_dir):
        full = os.path.join(ckpt_dir, name)
        if name.isdigit() and os.path.isdir(full) and not os.path.exists(
                os.path.join(full, '.orbax-checkpoint-tmp')):
            steps.append(int(name))
    return max(steps) if steps else None


def restore_train_state(ckpt_dir: str, abstract_state: Dict[str, Any],
                        step: Optional[int] = None) -> Dict[str, Any]:
    """Restore onto the shardings/dtypes described by `abstract_state`
    (a pytree of jax.ShapeDtypeStruct with .sharding — orbax reshards
    across mesh shapes)."""
    if step is None:
        step = latest_step(ckpt_dir)
        if step is None:
            raise FileNotFoundError(
                f'No checkpoint found under {ckpt_dir!r}')
    path = os.path.join(os.path.abspath(os.path.expanduser(ckpt_dir)),
                        str(step))
    return _checkpointer().restore(path, abstract_state)


def abstract_train_state(cfg, mesh) -> Dict[str, Any]:
    """ShapeDtypeStruct pytree for a TrainerConfig on a mesh — the
    restore target, built WITHOUT materializing any arrays."""
    from skypilot_tpu.train import trainer as trainer_lib

    def _make():
        state = trainer_lib.make_train_state(cfg, mesh)
        return state
    return jax.eval_shape(_make)


def restore_params(ckpt_dir: str, config,
                   mesh: Optional[Any] = None) -> Dict[str, Any]:
    """Restore just model params (inference path). Accepts checkpoints
    saved either as bare params or as full train state."""
    del config  # shapes come from checkpoint metadata
    step = latest_step(ckpt_dir)
    if step is None:
        raise FileNotFoundError(f'No checkpoint under {ckpt_dir!r}')
    path = os.path.join(os.path.abspath(os.path.expanduser(ckpt_dir)),
                        str(step))
    restored = _checkpointer().restore(path)
    if isinstance(restored, dict) and 'params' in restored:
        return restored['params']
    return restored
