"""Training loop: sharded init, jitted train step, MFU accounting.

The TPU-native replacement for the reference's 'finetuning recipe shells
out to MaxText/DeepSpeed' pattern (reference: llm/llama-3_1-finetuning,
examples/deepspeed-multinode — orchestration-only, SURVEY.md §2.11).
Everything here is mesh-parametric: the same step runs single-chip, a
v5p pod (FSDP+TP), or multi-slice (hybrid mesh, DP over DCN).
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from skypilot_tpu.models import llama
import skypilot_tpu.parallel as parallel
from skypilot_tpu.parallel import sharding


@dataclasses.dataclass
class TrainerConfig:
    model: str = 'tiny'
    learning_rate: float = 3e-4
    weight_decay: float = 0.1
    warmup_steps: int = 100
    max_steps: int = 1000
    batch_size: int = 8          # global
    seq_len: int = 512
    grad_clip: float = 1.0
    # Adam first moment dtype: 'bfloat16' halves its HBM footprint
    # (standard large-model practice); None keeps f32.
    mu_dtype: Optional[str] = None
    # Override the preset's attention impl (dense/blockwise/ring/
    # flash) — e.g. ring for context-parallel long-sequence runs.
    attention_impl: Optional[str] = None

    def model_config(self):
        import dataclasses as _dc

        import skypilot_tpu.models as models_lib
        cfg = models_lib.resolve(self.model)[1]
        if self.attention_impl is not None:
            if not hasattr(cfg, 'attention_impl'):
                # Never drop the override silently: running a
                # long-context job with dense attention because the
                # flag didn't apply is an OOM or a perf cliff.
                raise ValueError(
                    f'Model {self.model!r} does not support an '
                    'attention override.')
            cfg = _dc.replace(cfg, attention_impl=self.attention_impl)
        return cfg

    def model_family(self):
        import skypilot_tpu.models as models_lib
        return models_lib.resolve(self.model)[0]


def make_optimizer(cfg: TrainerConfig):
    import optax
    schedule = optax.warmup_cosine_decay_schedule(
        init_value=0.0, peak_value=cfg.learning_rate,
        warmup_steps=cfg.warmup_steps,
        decay_steps=max(cfg.max_steps, cfg.warmup_steps + 1))
    mu_dtype = jnp.bfloat16 if cfg.mu_dtype == 'bfloat16' else None
    return optax.chain(
        optax.clip_by_global_norm(cfg.grad_clip),
        optax.adamw(schedule, b1=0.9, b2=0.95,
                    weight_decay=cfg.weight_decay, mu_dtype=mu_dtype),
    )


def batch_shardings(mesh: Any) -> Dict[str, Any]:
    return {
        'tokens': sharding.named_sharding(mesh, ('batch', 'seq')),
        'mask': sharding.named_sharding(mesh, ('batch', 'seq')),
    }


def make_train_state(cfg: TrainerConfig, mesh: Any,
                     key: Optional[jax.Array] = None) -> Dict[str, Any]:
    """Init params + opt state DIRECTLY sharded (never materialized on
    one device): jit with out_shardings does the placement."""
    mcfg = cfg.model_config()
    key = key if key is not None else jax.random.key(0)
    optimizer = make_optimizer(cfg)

    family = cfg.model_family()
    logical = family.param_logical_axes(mcfg)
    param_sh = sharding.tree_shardings(mesh, logical)

    with parallel.use_mesh(mesh):
        params = jax.jit(
            functools.partial(family.init_params, mcfg),
            out_shardings=param_sh)(key)
        opt_state = jax.jit(
            optimizer.init,
            # optimizer state mirrors param sharding where shaped like
            # params; scalars replicate (jit infers from input sharding).
        )(params)
        step = jax.jit(
            lambda: jnp.zeros((), jnp.int32),
            out_shardings=sharding.named_sharding(mesh, ()))()
    return {'params': params, 'opt_state': opt_state, 'step': step}


def make_train_step(cfg: TrainerConfig,
                    mesh: Any) -> Callable[[Dict[str, Any], Dict[str, Any]],
                                           Tuple[Dict[str, Any], Dict[str, Any]]]:
    """Returns jitted (state, batch) → (state, metrics)."""
    mcfg = cfg.model_config()
    family = cfg.model_family()
    optimizer = make_optimizer(cfg)

    def step_fn(state, batch):
        import optax
        params = state['params']
        loss, grads = jax.value_and_grad(family.loss_fn)(
            params, batch, mcfg, mesh)
        updates, opt_state = optimizer.update(
            grads, state['opt_state'], params)
        params = optax.apply_updates(params, updates)
        metrics = {
            'loss': loss,
            'grad_norm': optax.global_norm(grads),
            'step': state['step'] + 1,
        }
        return {'params': params, 'opt_state': opt_state,
                'step': state['step'] + 1}, metrics

    return jax.jit(step_fn, donate_argnums=(0,))


def synthetic_batch(cfg: TrainerConfig, mesh: Any,
                    key: Optional[jax.Array] = None) -> Dict[str, Any]:
    """Random-token batch laid out with the right sharding (bench/tests)."""
    mcfg = cfg.model_config()
    key = key if key is not None else jax.random.key(1)
    sh = batch_shardings(mesh)
    with parallel.use_mesh(mesh):
        tokens = jax.jit(
            lambda k: jax.random.randint(
                k, (cfg.batch_size, cfg.seq_len), 0, mcfg.vocab_size,
                jnp.int32),
            out_shardings=sh['tokens'])(key)
        mask = jax.jit(
            lambda: jnp.ones((cfg.batch_size, cfg.seq_len), jnp.float32),
            out_shardings=sh['mask'])()
    return {'tokens': tokens, 'mask': mask}


def mfu(tokens_per_sec: float, config: llama.LlamaConfig, seq_len: int,
        peak_flops_per_chip: float, num_chips: int = 1) -> float:
    """Model FLOPs utilization against the chip's peak."""
    achieved = tokens_per_sec * config.flops_per_token(seq_len)
    return achieved / (peak_flops_per_chip * num_chips)


# Peak bf16 FLOPs/s per chip (public spec sheets).
PEAK_FLOPS = {
    'v4': 275e12,
    'v5e': 197e12,
    'v5p': 459e12,
    'v6e': 918e12,
    'cpu': 1e12,  # arbitrary for tests
}


def detect_chip() -> str:
    d = jax.devices()[0]
    kind = getattr(d, 'device_kind', '').lower()
    for name in ('v6e', 'v5p', 'v5e', 'v4'):
        if name in kind:
            return name
    if 'tpu v6' in kind:
        return 'v6e'
    if 'tpu v5 lite' in kind or 'v5litepod' in kind:
        return 'v5e'
    return 'cpu' if d.platform == 'cpu' else 'v5e'
