"""fit(): the mesh-parametric training loop with checkpoint/resume.

This is what a finetune recipe's `run:` invokes
(`python -m skypilot_tpu.train.loop --model llama3-8b ...`) — the
TPU-native analog of the reference recipes that shell out to
MaxText/axolotl (llm/llama-3_1-finetuning). Resume-after-preemption:
managed jobs relaunch this program; it finds the latest checkpoint in
--checkpoint-dir (a GCS mount in real runs) and continues.
"""
from __future__ import annotations

import argparse
import time
from typing import Any, Callable, Dict, Optional

import jax

from skypilot_tpu import envs
from skypilot_tpu.observability import instruments as obs
from skypilot_tpu.parallel import mesh as mesh_lib
from skypilot_tpu.resilience import retries
from skypilot_tpu.train import checkpoints
from skypilot_tpu.train import trainer as trainer_lib


def _save_with_retries(checkpoint_dir: str, state: Dict[str, Any],
                       step: int) -> None:
    """A transient save failure (GCS blip, FUSE hiccup) must not kill
    a multi-hour run — retry under the shared policy; give up only
    after the budget and let the caller's exception surface."""
    retries.call(
        lambda: checkpoints.save_train_state(checkpoint_dir, state,
                                             step=step),
        policy=retries.RetryPolicy(
            max_attempts=3,
            base_delay=envs.SKYTPU_CKPT_RETRY_GAP.get(),
            max_delay=30.0),
        retry_on=(Exception,),
        describe=f'checkpoint save step {step}')


def fit(cfg: trainer_lib.TrainerConfig,
        mesh: Any,
        batch_fn: Optional[Callable[[int], Dict[str, Any]]] = None,
        checkpoint_dir: Optional[str] = None,
        checkpoint_every: int = 100,
        log_every: int = 10,
        init_checkpoint: Optional[str] = None,
        log_fn=print) -> Dict[str, Any]:
    """Train to cfg.max_steps; resume from checkpoint_dir if present.

    `init_checkpoint` seeds the STARTING params (the finetune case):
    an HF safetensors dir streams in through the importer, an Orbax
    dir restores params — auto-detected either way. A resume
    checkpoint in `checkpoint_dir` wins over it (mid-run preemption
    recovery must continue the finetune, not restart it)."""
    state = trainer_lib.make_train_state(cfg, mesh)
    start_step = 0
    if checkpoint_dir is not None:
        step = checkpoints.latest_step(checkpoint_dir)
        if step is not None:
            abstract = jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                               sharding=x.sharding),
                state)
            state = checkpoints.restore_train_state(
                checkpoint_dir, abstract, step=step)
            # Restored arrays are COMMITTED to their shardings. Fresh
            # state may carry leaves jit left on one device
            # (optimizer.init without out_shardings) — harmless while
            # uncommitted, but restored-committed, a mixed device set
            # fails the next jitted step. Replicate any narrow leaf
            # across the full mesh so resume == fresh behavior.
            from jax.sharding import NamedSharding, PartitionSpec
            full_set = set(mesh.devices.flat)
            state = jax.tree.map(
                lambda x: x if set(x.sharding.device_set) == full_set
                else jax.device_put(
                    x, NamedSharding(mesh, PartitionSpec())),
                state)
            start_step = step
            log_fn(f'[fit] resumed from step {step}')

    if init_checkpoint is not None and start_step == 0:
        import jax.numpy as jnp
        loaded = checkpoints.restore_params(
            init_checkpoint, cfg.model_config(), mesh=mesh)
        # Land every leaf on the train state's sharding/dtype: the
        # tree.map fails LOUDLY on a structure or shape mismatch
        # (wrong family/geometry for this TrainerConfig), instead of
        # training a silently half-initialized model.
        def _adopt(cur, new):
            if cur.shape != new.shape:
                raise ValueError(
                    f'--checkpoint geometry mismatch: leaf shape '
                    f'{new.shape} vs model {cur.shape} — does '
                    f'--model match the checkpoint?')
            return jax.device_put(jnp.asarray(new, cur.dtype),
                                  cur.sharding)

        try:
            state['params'] = jax.tree.map(_adopt, state['params'],
                                           loaded)
        except ValueError as e:
            # jax's pytree structure errors dump whole arrays; keep
            # the detail but lead with what the operator must fix.
            raise ValueError(
                f'--checkpoint geometry mismatch: {init_checkpoint!r} '
                f'does not hold params for model {cfg.model!r} '
                '(different family knobs — tied embeddings, biases, '
                f'post-norms — or sizes): {str(e)[:500]}') from None
        log_fn(f'[fit] initialized params from {init_checkpoint}')

    step_fn = trainer_lib.make_train_step(cfg, mesh)
    if batch_fn is None:
        fixed = trainer_lib.synthetic_batch(cfg, mesh)
        batch_fn = lambda i: fixed  # noqa: E731

    mcfg = cfg.model_config()
    chip = trainer_lib.detect_chip()
    peak = trainer_lib.PEAK_FLOPS[chip]
    tokens_per_step = cfg.batch_size * cfg.seq_len
    t_last = time.perf_counter()
    metrics = {}
    with mesh_lib.use_mesh(mesh):
        t_step = time.perf_counter()
        for i in range(start_step, cfg.max_steps):
            state, metrics = step_fn(state, batch_fn(i))
            # Same registry the serving planes scrape: per-step wall
            # time (async dispatch included — the loss read below is
            # the sync point each log window), token count, and step
            # progress, so a training replica's /metrics (or a test)
            # yields tokens/sec/chip from two scrapes.
            now = time.perf_counter()
            obs.TRAIN_STEP_SECONDS.observe(now - t_step)
            t_step = now
            obs.TRAIN_TOKENS.inc(tokens_per_step)
            obs.TRAIN_STEP.set(i + 1)
            if (i + 1) % log_every == 0:
                loss = float(metrics['loss'])
                dt = time.perf_counter() - t_last
                t_last = time.perf_counter()
                tps = tokens_per_step * log_every / dt
                mfu = trainer_lib.mfu(tps, mcfg, cfg.seq_len, peak,
                                      jax.device_count())
                obs.TRAIN_MFU.set(mfu)
                obs.TRAIN_LOSS.set(loss)
                log_fn(f'[fit] step {i + 1}/{cfg.max_steps} '
                       f'loss={loss:.4f} tokens/s={tps:.0f} '
                       f'mfu={mfu:.2%}')
            if checkpoint_dir is not None and \
                    (i + 1) % checkpoint_every == 0:
                _save_with_retries(checkpoint_dir, state, step=i + 1)
    if checkpoint_dir is not None and \
            checkpoints.latest_step(checkpoint_dir) != cfg.max_steps:
        _save_with_retries(checkpoint_dir, state, step=cfg.max_steps)
    return {'state': state, 'metrics': metrics,
            'final_step': cfg.max_steps}


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--model', default='tiny')
    parser.add_argument('--batch-size', type=int, default=8)
    parser.add_argument('--seq-len', type=int, default=512)
    parser.add_argument('--max-steps', type=int, default=100)
    parser.add_argument('--learning-rate', type=float, default=3e-4)
    parser.add_argument('--checkpoint-dir', default=None)
    parser.add_argument('--checkpoint-every', type=int, default=100)
    parser.add_argument('--checkpoint', default=None,
                        help='Initial weights for a finetune: an HF '
                             'safetensors dir (streamed import) or '
                             'an Orbax params checkpoint — layout '
                             'auto-detected. A resume checkpoint in '
                             '--checkpoint-dir takes precedence.')
    parser.add_argument('--mesh', default='fsdp=-1',
                        help='Comma-separated axis=size, e.g. '
                        'data=2,fsdp=4,tensor=2 (-1 fills).')
    parser.add_argument('--attention', default=None,
                        choices=['dense', 'blockwise', 'ring', 'flash'],
                        help='Override the preset attention impl '
                        '(ring = context-parallel long sequences).')
    args = parser.parse_args()

    spec = mesh_lib.MeshSpec.from_dict(dict(
        kv.split('=') for kv in args.mesh.split(',')))
    mesh = mesh_lib.mesh_from_env(spec)
    cfg = trainer_lib.TrainerConfig(
        model=args.model, batch_size=args.batch_size,
        seq_len=args.seq_len, max_steps=args.max_steps,
        learning_rate=args.learning_rate,
        attention_impl=args.attention)
    fit(cfg, mesh, checkpoint_dir=args.checkpoint_dir,
        checkpoint_every=args.checkpoint_every,
        init_checkpoint=args.checkpoint)


if __name__ == '__main__':
    main()
