"""Client-side cluster/state database (sqlite3, WAL).

Reference analog: sky/global_user_state.py (SQLAlchemy tables :55-150,
pickled handles). Ours uses stdlib sqlite3 with the same lock discipline
(WAL + busy timeout) and pickles the backend's ResourceHandle the same way.
"""
import enum
import json
import os
import pickle
import sqlite3
import threading
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import envs
from skypilot_tpu.utils import paths

# Reentrant: sibling stores hold write_lock() around their
# execute+commit/rollback sequences and resolve the connection INSIDE
# the hold (connection() re-takes this lock).
_lock = threading.RLock()


def _after_fork_in_child() -> None:
    global _lock, _conn, _conn_path
    _lock = threading.RLock()
    _conn = None
    _conn_path = None


os.register_at_fork(after_in_child=_after_fork_in_child)
_conn: Optional[sqlite3.Connection] = None
_conn_path: Optional[str] = None


class ClusterStatus(enum.Enum):
    INIT = 'INIT'          # provisioning in progress / unknown health
    UP = 'UP'              # provisioned + runtime healthy
    STOPPED = 'STOPPED'    # instances stopped, disk kept

    def colored(self) -> str:
        return self.value


def _get_conn() -> sqlite3.Connection:
    global _conn, _conn_path
    path = paths.state_db_path()
    with _lock:
        if _conn is None or _conn_path != path:
            _conn = sqlite3.connect(path, check_same_thread=False,
                                    timeout=30.0)
            _conn.execute('PRAGMA journal_mode=WAL')
            _create_tables_locked(_conn)
            _conn_path = path
        return _conn


def connection() -> sqlite3.Connection:
    """The shared state-DB connection, for sibling stores (workspaces,
    users) that live in the same sqlite file and want the same WAL /
    busy-timeout discipline."""
    return _get_conn()


def write_lock() -> threading.RLock:
    """Serializes writes on the shared connection. Two threads
    interleaving execute/commit/rollback on ONE sqlite3 connection
    share its implicit transaction: thread B's rollback (e.g. on an
    IntegrityError from a racing duplicate create) would discard
    thread A's executed-but-uncommitted INSERT. Sibling stores hold
    this around every write sequence; reentrant so connection() can be
    resolved inside the hold."""
    return _lock


def valid_identifier(name: str) -> bool:
    """One naming rule for API-created entities (workspaces, users)."""
    return bool(name) and \
        name.replace('-', '').replace('_', '').isalnum()


class TableOnce:
    """Run a sibling store's DDL once per process per DB path (tests
    re-point the state dir). DDL + commit per request would serialize
    the API server on sqlite write locks."""

    def __init__(self, ddl: str) -> None:
        self._ddl = ddl
        self._ready_for: Optional[str] = None

    def ensure(self) -> None:
        path = paths.state_db_path()
        if self._ready_for == path:
            return
        # Under the module lock: a bare execute+commit on the shared
        # connection would commit another thread's half-done write
        # sequence (the exact interleave write_lock() exists to stop).
        with _lock:
            conn = _get_conn()
            conn.execute(self._ddl)
            conn.commit()
        self._ready_for = path


def reset_for_tests() -> None:
    global _conn, _conn_path
    with _lock:
        if _conn is not None:
            _conn.close()
        _conn = None
        _conn_path = None


def _create_tables_locked(conn: sqlite3.Connection) -> None:
    """Caller holds `_lock` (_get_conn does): DDL + migrations
    write on the shared connection."""
    conn.execute("""
        CREATE TABLE IF NOT EXISTS clusters (
            name TEXT PRIMARY KEY,
            launched_at INTEGER,
            handle BLOB,
            last_use TEXT,
            status TEXT,
            autostop_json TEXT,
            owner TEXT,
            workspace TEXT DEFAULT 'default',
            cluster_hash TEXT,
            resources_json TEXT,
            num_nodes INTEGER,
            to_down INTEGER DEFAULT 0
        )""")
    conn.execute("""
        CREATE TABLE IF NOT EXISTS cluster_history (
            cluster_hash TEXT,
            name TEXT,
            launched_at INTEGER,
            duration_s REAL,
            resources_json TEXT,
            num_nodes INTEGER,
            usage_intervals TEXT,
            PRIMARY KEY (cluster_hash, launched_at)
        )""")
    conn.execute("""
        CREATE TABLE IF NOT EXISTS config (
            key TEXT PRIMARY KEY,
            value TEXT
        )""")
    conn.execute("""
        CREATE TABLE IF NOT EXISTS storage (
            name TEXT PRIMARY KEY,
            store TEXT,
            source TEXT,
            launched_at INTEGER,
            last_use TEXT,
            workspace TEXT DEFAULT 'default'
        )""")
    conn.execute("""
        CREATE TABLE IF NOT EXISTS heartbeats (
            cluster_name TEXT PRIMARY KEY,
            last_seen REAL,
            epoch TEXT,
            payload TEXT
        )""")
    # Migrations for pre-workspace / pre-heartbeat DBs.
    cols = [r[1] for r in conn.execute('PRAGMA table_info(clusters)')]
    if 'workspace' not in cols:
        conn.execute(
            "ALTER TABLE clusters ADD COLUMN workspace TEXT "
            "DEFAULT 'default'")
    if 'epoch' not in cols:
        conn.execute('ALTER TABLE clusters ADD COLUMN epoch TEXT')
    conn.commit()


# --- clusters ---------------------------------------------------------------

def add_or_update_cluster(cluster_name: str, handle: Any,
                          requested_resources_str: str, num_nodes: int,
                          ready: bool,
                          autostop: Optional[Dict[str, Any]] = None,
                          cluster_hash: Optional[str] = None,
                          epoch: Optional[str] = None) -> None:
    conn = _get_conn()
    status = ClusterStatus.UP if ready else ClusterStatus.INIT
    now = int(time.time())
    with _lock:
        existing = conn.execute(
            'SELECT launched_at, epoch FROM clusters WHERE name=?',
            (cluster_name,)).fetchone()
        launched_at = existing[0] if existing else now
        # Keep a known epoch when the caller has none (e.g. a status
        # update that didn't re-run provisioning).
        epoch = epoch or (existing[1] if existing else None)
        conn.execute(
            """INSERT INTO clusters
               (name, launched_at, handle, last_use, status, autostop_json,
                owner, workspace, cluster_hash, resources_json, num_nodes,
                to_down, epoch)
               VALUES (?,?,?,?,?,?,?,?,?,?,?,?,?)
               ON CONFLICT(name) DO UPDATE SET
                 handle=excluded.handle, last_use=excluded.last_use,
                 status=excluded.status,
                 autostop_json=excluded.autostop_json,
                 cluster_hash=excluded.cluster_hash,
                 resources_json=excluded.resources_json,
                 num_nodes=excluded.num_nodes,
                 epoch=excluded.epoch""",
            (cluster_name, launched_at, pickle.dumps(handle),
             str(int(now)), status.value,
             json.dumps(autostop) if autostop else None,
             envs.SKYTPU_USER.get() or os.environ.get(
                 'USER', 'unknown'),
             active_workspace(), cluster_hash,
             requested_resources_str, num_nodes, 0, epoch))
        conn.commit()


def update_cluster_status(cluster_name: str,
                          status: ClusterStatus) -> None:
    conn = _get_conn()
    with _lock:
        conn.execute('UPDATE clusters SET status=? WHERE name=?',
                     (status.value, cluster_name))
        if status != ClusterStatus.UP:
            # A stopped cluster's silence is expected: drop the beat so
            # status shows '-' instead of an ever-growing age.
            conn.execute('DELETE FROM heartbeats WHERE cluster_name=?',
                         (cluster_name,))
        conn.commit()


def update_cluster_handle(cluster_name: str, handle: Any) -> None:
    conn = _get_conn()
    with _lock:
        conn.execute('UPDATE clusters SET handle=? WHERE name=?',
                     (pickle.dumps(handle), cluster_name))
        conn.commit()


def update_last_use(cluster_name: str) -> None:
    conn = _get_conn()
    with _lock:
        conn.execute('UPDATE clusters SET last_use=? WHERE name=?',
                     (str(int(time.time())), cluster_name))
        conn.commit()


def set_autostop(cluster_name: str,
                 autostop: Optional[Dict[str, Any]]) -> None:
    conn = _get_conn()
    with _lock:
        conn.execute('UPDATE clusters SET autostop_json=? WHERE name=?',
                     (json.dumps(autostop) if autostop else None,
                      cluster_name))
        conn.commit()


def remove_cluster(cluster_name: str, terminate: bool) -> None:
    conn = _get_conn()
    with _lock:
        if terminate:
            row = conn.execute(
                'SELECT launched_at, cluster_hash, resources_json, num_nodes'
                ' FROM clusters WHERE name=?', (cluster_name,)).fetchone()
            if row is not None and row[1] is not None:
                conn.execute(
                    """INSERT OR REPLACE INTO cluster_history
                       (cluster_hash, name, launched_at, duration_s,
                        resources_json, num_nodes, usage_intervals)
                       VALUES (?,?,?,?,?,?,?)""",
                    (row[1], cluster_name, row[0],
                     time.time() - (row[0] or time.time()), row[2], row[3],
                     None))
            conn.execute('DELETE FROM clusters WHERE name=?',
                         (cluster_name,))
        else:
            conn.execute(
                'UPDATE clusters SET status=?, handle=handle WHERE name=?',
                (ClusterStatus.STOPPED.value, cluster_name))
        # Either way the skylet is gone (or expected silent): drop the
        # beat so status shows '-' instead of an ever-growing age.
        conn.execute('DELETE FROM heartbeats WHERE cluster_name=?',
                     (cluster_name,))
        conn.commit()


def active_workspace() -> str:
    """The workspace this request acts in (set by the API server from
    the authenticated user; 'default' in open local mode)."""
    return envs.SKYTPU_WORKSPACE.get()


def _row_to_record(row) -> Dict[str, Any]:
    (name, launched_at, handle_blob, last_use, status, autostop_json,
     owner, workspace, cluster_hash, resources_json, num_nodes,
     to_down) = row
    return {
        'name': name,
        'launched_at': launched_at,
        'handle': pickle.loads(handle_blob) if handle_blob else None,
        'last_use': last_use,
        'status': ClusterStatus(status),
        'autostop': json.loads(autostop_json) if autostop_json else None,
        'owner': owner,
        'workspace': workspace,
        'cluster_hash': cluster_hash,
        'resources_str': resources_json,
        'num_nodes': num_nodes,
        'to_down': bool(to_down),
    }


_COLS = ('name, launched_at, handle, last_use, status, autostop_json, '
         'owner, workspace, cluster_hash, resources_json, num_nodes, '
         'to_down')


def get_cluster_from_name(cluster_name: str) -> Optional[Dict[str, Any]]:
    conn = _get_conn()
    row = conn.execute(f'SELECT {_COLS} FROM clusters WHERE name=?',
                       (cluster_name,)).fetchone()
    return _row_to_record(row) if row else None


def get_clusters(all_workspaces: bool = False) -> List[Dict[str, Any]]:
    """Clusters in the active workspace (all of them when asked)."""
    conn = _get_conn()
    if all_workspaces:
        rows = conn.execute(
            f'SELECT {_COLS} FROM clusters '
            'ORDER BY launched_at DESC').fetchall()
    else:
        rows = conn.execute(
            f'SELECT {_COLS} FROM clusters WHERE workspace=? '
            'ORDER BY launched_at DESC',
            (active_workspace(),)).fetchall()
    return [_row_to_record(r) for r in rows]


def get_cluster_history() -> List[Dict[str, Any]]:
    conn = _get_conn()
    rows = conn.execute(
        'SELECT cluster_hash, name, launched_at, duration_s, resources_json,'
        ' num_nodes FROM cluster_history ORDER BY launched_at DESC'
    ).fetchall()
    return [{'cluster_hash': r[0], 'name': r[1], 'launched_at': r[2],
             'duration_s': r[3], 'resources_str': r[4], 'num_nodes': r[5]}
            for r in rows]


# --- cluster liveness heartbeats (reference skylet events.py:94
# UsageHeartbeatReportEvent; ours lands in the state DB so status/
# dashboard can tell a live cluster record from a stale one) -----------------

def record_heartbeat(cluster_name: str, epoch: Optional[str],
                     payload: Optional[Dict[str, Any]] = None) -> bool:
    """Record a liveness heartbeat. Only known, non-STOPPED clusters
    are accepted (a skylet outliving `tsky stop` by a couple of minutes
    must not resurrect the beat the stop just dropped), and when the
    cluster record carries a provision epoch the beat must match it —
    a leaked skylet from a previous incarnation of a same-named cluster
    (or a forger on the unauthenticated endpoint, who can't know the
    random epoch) must not keep the record looking live. Pre-epoch
    records (migrated DBs) accept any beat but do NOT adopt its epoch:
    trust-on-first-use would let whoever posts first (possibly a
    forger) define the epoch and lock out the real skylet; the
    protection instead begins at the cluster's next provision, which
    records a genuine epoch.
    Returns False when refused."""
    conn = _get_conn()
    with _lock:
        known = conn.execute(
            'SELECT epoch, status FROM clusters WHERE name=?',
            (cluster_name,)).fetchone()
        if not known:
            return False
        expected_epoch, status = known
        if status == ClusterStatus.STOPPED.value:
            return False
        if expected_epoch and epoch != expected_epoch:
            return False
        conn.execute(
            """INSERT INTO heartbeats (cluster_name, last_seen, epoch,
                                       payload)
               VALUES (?,?,?,?)
               ON CONFLICT(cluster_name) DO UPDATE SET
                 last_seen=excluded.last_seen, epoch=excluded.epoch,
                 payload=excluded.payload""",
            (cluster_name, time.time(), epoch,
             json.dumps(payload) if payload else None))
        conn.commit()
    return True


def get_heartbeats() -> Dict[str, Dict[str, Any]]:
    """cluster_name -> {last_seen, age_s, epoch, payload}."""
    conn = _get_conn()
    rows = conn.execute(
        'SELECT cluster_name, last_seen, epoch, payload '
        'FROM heartbeats').fetchall()
    now = time.time()
    out = {}
    for name, last_seen, epoch, payload in rows:
        out[name] = {
            'last_seen': last_seen,
            'age_s': max(0.0, now - last_seen),
            'epoch': epoch,
            'payload': json.loads(payload) if payload else None,
        }
    return out


# --- storage registry (reference global_user_state storage table :104) ------

def add_or_update_storage(name: str, store: str,
                          source: Optional[str] = None) -> None:
    conn = _get_conn()
    now = int(time.time())
    with _lock:
        conn.execute(
            """INSERT INTO storage (name, store, source, launched_at,
                                    last_use, workspace)
               VALUES (?,?,?,?,?,?)
               ON CONFLICT(name) DO UPDATE SET
                 store=excluded.store, source=excluded.source,
                 last_use=excluded.last_use,
                 workspace=excluded.workspace""",
            (name, store, source, now, str(now), active_workspace()))
        conn.commit()


def get_storage(all_workspaces: bool = False) -> List[Dict[str, Any]]:
    conn = _get_conn()
    q = ('SELECT name, store, source, launched_at, last_use, workspace '
         'FROM storage')
    if all_workspaces:
        rows = conn.execute(q + ' ORDER BY launched_at DESC').fetchall()
    else:
        rows = conn.execute(
            q + ' WHERE workspace=? ORDER BY launched_at DESC',
            (active_workspace(),)).fetchall()
    return [{'name': r[0], 'store': r[1], 'source': r[2],
             'launched_at': r[3], 'last_use': r[4], 'workspace': r[5]}
            for r in rows]


def remove_storage(name: str) -> None:
    conn = _get_conn()
    with _lock:
        conn.execute('DELETE FROM storage WHERE name=?', (name,))
        conn.commit()
