"""TPU parallelism layer: mesh construction + logical sharding rules.

First-class in this framework (the reference delegates all parallelism to
user recipes via env vars — SURVEY.md §2.11).
"""
from skypilot_tpu.parallel.mesh import (AXIS_ORDER, MeshSpec, use_mesh,
                                        initialize_distributed,
                                        make_hybrid_mesh, make_mesh,
                                        mesh_from_env)
from skypilot_tpu.parallel.sharding import (DEFAULT_RULES, named_sharding,
                                            shard, spec_for, tree_shardings)

__all__ = [
    'AXIS_ORDER', 'MeshSpec', 'initialize_distributed', 'make_hybrid_mesh',
    'make_mesh', 'mesh_from_env', 'use_mesh', 'DEFAULT_RULES', 'named_sharding', 'shard',
    'spec_for', 'tree_shardings',
]
