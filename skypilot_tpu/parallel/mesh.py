"""Device-mesh construction for TPU-first parallelism.

The reference (SkyPilot) has no in-tree parallelism machinery — its
recipes export `SKYPILOT_NODE_*` env vars and let torchrun/NCCL assemble
the job (reference: sky/backends/cloud_vm_ray_backend.py:606-670). Here
parallelism is a first-class library: a `MeshSpec` names the axes, this
module turns it into a `jax.sharding.Mesh` laid out so that the
bandwidth-hungry axes (tensor, context) ride ICI and only the data axis
crosses DCN slice boundaries.

Axes (in fixed order, outermost → innermost):
  data    — pure data parallel; gradients all-reduced.
  pipe    — pipeline parallel (GPipe microbatching; stage-to-stage
            ppermute — tolerates slow links, so it sits outer).
  fsdp    — data parallel with fully-sharded params (ZeRO-3 style).
  expert  — expert parallel for MoE layers (all_to_all dispatch).
  context — sequence/context parallel (ring attention over this axis).
  tensor  — megatron-style tensor parallel (activations all-reduced).

The innermost axes get the most ICI locality from
`mesh_utils.create_device_mesh`, which is why tensor/context sit last.
"""
from __future__ import annotations

import dataclasses
import math
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

AXIS_ORDER = ('data', 'pipe', 'fsdp', 'expert', 'context', 'tensor')

# Aliases accepted from YAML / CLI knobs.
_AXIS_ALIASES = {
    'dp': 'data',
    'data_parallel': 'data',
    'pp': 'pipe',
    'pipeline': 'pipe',
    'pipeline_parallel': 'pipe',
    'stage': 'pipe',
    'zero': 'fsdp',
    'fsdp_parallel': 'fsdp',
    'ep': 'expert',
    'expert_parallel': 'expert',
    'sp': 'context',
    'cp': 'context',
    'sequence': 'context',
    'context_parallel': 'context',
    'ring': 'context',
    'tp': 'tensor',
    'model': 'tensor',
    'tensor_parallel': 'tensor',
}


def canonical_axis(name: str) -> str:
    name = name.lower()
    name = _AXIS_ALIASES.get(name, name)
    if name not in AXIS_ORDER:
        raise ValueError(
            f'Unknown mesh axis {name!r}; valid: {AXIS_ORDER} '
            f'(aliases: {sorted(_AXIS_ALIASES)})')
    return name


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Named parallelism degrees. -1 on at most one axis means "fill".

    Examples:
        MeshSpec(fsdp=-1)                      # pure FSDP over all chips
        MeshSpec(data=2, fsdp=4, tensor=4)     # 32-chip 3D mesh
        MeshSpec.from_dict({'dp': 2, 'tp': 8})
    """
    data: int = 1
    pipe: int = 1
    fsdp: int = -1
    expert: int = 1
    context: int = 1
    tensor: int = 1

    @classmethod
    def from_dict(cls, d: Dict[str, int]) -> 'MeshSpec':
        kwargs: Dict[str, int] = {}
        for key, value in d.items():
            axis = canonical_axis(key)
            if axis in kwargs and kwargs[axis] != int(value):
                raise ValueError(f'Axis {axis!r} specified twice via aliases')
            kwargs[axis] = int(value)
        return cls(**kwargs)

    def sizes(self) -> Dict[str, int]:
        return {axis: getattr(self, axis) for axis in AXIS_ORDER}

    def resolve(self, n_devices: int) -> 'MeshSpec':
        """Fill the single -1 axis so the product equals n_devices."""
        sizes = self.sizes()
        fill_axes = [a for a, s in sizes.items() if s == -1]
        if len(fill_axes) > 1:
            raise ValueError(f'At most one -1 axis allowed, got {fill_axes}')
        fixed = math.prod(s for s in sizes.values() if s != -1)
        if fill_axes:
            if n_devices % fixed != 0:
                raise ValueError(
                    f'{n_devices} devices not divisible by fixed axes '
                    f'product {fixed} ({sizes})')
            sizes[fill_axes[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(
                f'Mesh {sizes} needs {fixed} devices, have {n_devices}')
        return MeshSpec(**sizes)

    def axis_names(self) -> Tuple[str, ...]:
        return AXIS_ORDER

    def shape(self) -> Tuple[int, ...]:
        sizes = self.sizes()
        if any(s == -1 for s in sizes.values()):
            raise ValueError('Call resolve() before shape()')
        return tuple(sizes[a] for a in AXIS_ORDER)


def make_mesh(spec: MeshSpec,
              devices: Optional[Sequence[Any]] = None) -> Any:
    """Build a `jax.sharding.Mesh` honoring TPU ICI topology.

    `mesh_utils.create_device_mesh` places the trailing (fastest-varying)
    mesh axes on physically adjacent chips, so tensor/context collectives
    ride ICI neighbors. Falls back to a plain reshape off-TPU (CPU test
    meshes have no topology).
    """
    import jax
    from jax.experimental import mesh_utils

    if devices is None:
        devices = jax.devices()
    spec = spec.resolve(len(devices))
    shape = spec.shape()
    try:
        device_array = mesh_utils.create_device_mesh(
            shape, devices=list(devices))
    except (ValueError, AssertionError, NotImplementedError):
        import numpy as np
        device_array = np.asarray(list(devices)).reshape(shape)
    return jax.sharding.Mesh(device_array, spec.axis_names())


def make_hybrid_mesh(spec: MeshSpec,
                     num_slices: int,
                     devices: Optional[Sequence[Any]] = None) -> Any:
    """Multi-slice mesh: `data` spans DCN (slices), the rest stay on ICI.

    Mirrors `mesh_utils.create_hybrid_device_mesh`: the data axis is the
    only one allowed to cross the slow DCN boundary, matching how the
    provisioner wires MEGASCALE_* coordinates (skylet/constants.py:28).
    """
    import jax
    from jax.experimental import mesh_utils

    if devices is None:
        devices = jax.devices()
    spec = spec.resolve(len(devices))
    sizes = spec.sizes()
    if sizes['data'] % num_slices != 0:
        raise ValueError(
            f"data axis ({sizes['data']}) must be a multiple of "
            f'num_slices ({num_slices}) — only data parallel crosses DCN')
    ici_shape = list(spec.shape())
    dcn_shape = [1] * len(ici_shape)
    dcn_shape[0] = num_slices
    ici_shape[0] = sizes['data'] // num_slices
    try:
        device_array = mesh_utils.create_hybrid_device_mesh(
            tuple(ici_shape), tuple(dcn_shape), devices=list(devices))
    except (ValueError, AssertionError, NotImplementedError, KeyError):
        import numpy as np
        device_array = np.asarray(list(devices)).reshape(spec.shape())
    return jax.sharding.Mesh(device_array, spec.axis_names())


def use_mesh(mesh: Any):
    """Context manager setting the ambient mesh (jax version compat)."""
    import jax
    if hasattr(jax.sharding, 'use_mesh'):
        return jax.sharding.use_mesh(mesh)
    if hasattr(jax, 'set_mesh'):
        return jax.set_mesh(mesh)  # jax>=0.7: context manager form
    return mesh  # Mesh is itself a context manager


def initialize_distributed(coordinator: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> bool:
    """`jax.distributed.initialize` from SKYTPU_* gang coordinates.

    The gang driver (skylet/gang.py) injects SKYTPU_COORDINATOR_ADDR /
    NUM_PROCESSES / PROCESS_ID on every host — the TPU-native analog of
    the reference's SKYPILOT_NODE_RANK-for-torchrun contract. Returns
    False (no-op) for single-process jobs so the same program runs
    unmodified on one host.
    """
    from skypilot_tpu import envs

    coordinator = coordinator or envs.SKYTPU_COORDINATOR_ADDR.get()
    # strict: these are the gang IDENTITY contract, not tuning knobs —
    # a corrupted SKYTPU_PROCESS_ID silently parsing to the default 0
    # would put two hosts at process_id=0 (hung rendezvous) or run a
    # multi-host job un-distributed (wrong results). Fail loud.
    if num_processes is None:
        num_processes = envs.SKYTPU_NUM_PROCESSES.get(strict=True)
    if process_id is None:
        process_id = envs.SKYTPU_PROCESS_ID.get(strict=True)
    if num_processes <= 1 or not coordinator:
        return False
    import jax
    # Idempotent: mesh_from_env and user code may both bootstrap.
    state = getattr(getattr(jax._src, 'distributed', None),  # noqa: SLF001
                    'global_state', None)
    if state is not None and getattr(state, 'client', None) is not None:
        return True
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)
    return True


def mesh_from_env(spec: Optional[MeshSpec] = None) -> Any:
    """One-call bootstrap: init jax.distributed (if gang) then build the
    mesh over all global devices, hybrid across slices when MEGASCALE
    coordinates are present."""
    from skypilot_tpu.skylet import constants

    initialize_distributed()
    import jax
    spec = spec or MeshSpec()
    num_slices = int(os.environ.get(constants.ENV_MEGASCALE_NUM_SLICES, '1'))
    if num_slices > 1:
        return make_hybrid_mesh(spec, num_slices)
    return make_mesh(spec)
