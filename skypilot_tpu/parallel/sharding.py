"""Logical-axis sharding rules (the pjit/GSPMD idiom).

Model code annotates arrays with *logical* axis names ('batch', 'embed',
'heads', …); a rule table maps logical names to mesh axes. Swapping the
rule table re-shards the whole model — DP↔FSDP↔TP↔ring-attention — with
zero model-code changes. This replaces nothing in the reference (SkyPilot
ships no sharding machinery; see SURVEY.md §2.11) and is the TPU-native
contract its torchrun/NCCL recipes compiled down to.
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple, Union

MeshAxes = Union[None, str, Tuple[str, ...]]
Rules = Dict[str, MeshAxes]

# Default rules: FSDP shards params + optimizer state over ('data','fsdp'),
# tensor parallel splits heads/mlp, context parallel splits sequence.
DEFAULT_RULES: Rules = {
    'batch': ('data', 'fsdp'),
    'seq': 'context',
    'embed': ('fsdp',),
    'heads': 'tensor',
    'kv_heads': 'tensor',
    'head_dim': None,
    'mlp': 'tensor',
    'vocab': 'tensor',
    'expert': 'expert',
    'layers': None,
}


def spec_for(logical_axes: Sequence[Optional[str]],
             rules: Optional[Rules] = None) -> Any:
    """logical axis names → jax.sharding.PartitionSpec."""
    from jax.sharding import PartitionSpec
    rules = DEFAULT_RULES if rules is None else rules
    entries = []
    used: set = set()
    for name in logical_axes:
        if name is None:
            entries.append(None)
            continue
        axes = rules.get(name)
        if axes is None:
            entries.append(None)
            continue
        if isinstance(axes, str):
            axes = (axes,)
        # A mesh axis may appear only once in a PartitionSpec; drop dups
        # (e.g. batch=('data','fsdp') while embed=('fsdp',) on weights
        # is fine — dup checks apply per-array).
        axes = tuple(a for a in axes if a not in used)
        used.update(axes)
        if not axes:
            entries.append(None)
        elif len(axes) == 1:
            entries.append(axes[0])
        else:
            entries.append(axes)
    return PartitionSpec(*entries)


def shard(x: Any,
          logical_axes: Sequence[Optional[str]],
          rules: Optional[Rules] = None) -> Any:
    """`with_sharding_constraint` by logical axes; no-op outside jit/mesh."""
    import jax
    try:
        return jax.lax.with_sharding_constraint(
            x, spec_for(logical_axes, rules))
    except (ValueError, RuntimeError):
        return x


def named_sharding(mesh: Any,
                   logical_axes: Sequence[Optional[str]],
                   rules: Optional[Rules] = None) -> Any:
    import jax
    return jax.sharding.NamedSharding(mesh, spec_for(logical_axes, rules))


def tree_shardings(mesh: Any,
                   logical_tree: Any,
                   rules: Optional[Rules] = None) -> Any:
    """Map a pytree of logical-axis tuples → pytree of NamedShardings."""
    import jax
    return jax.tree.map(
        lambda axes: named_sharding(mesh, axes, rules),
        logical_tree,
        is_leaf=lambda x: isinstance(x, tuple) and all(
            a is None or isinstance(a, str) for a in x))
