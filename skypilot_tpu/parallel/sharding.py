"""Logical-axis sharding rules (the pjit/GSPMD idiom).

Model code annotates arrays with *logical* axis names ('batch', 'embed',
'heads', …); a rule table maps logical names to mesh axes. Swapping the
rule table re-shards the whole model — DP↔FSDP↔TP↔ring-attention — with
zero model-code changes. This replaces nothing in the reference (SkyPilot
ships no sharding machinery; see SURVEY.md §2.11) and is the TPU-native
contract its torchrun/NCCL recipes compiled down to.
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple, Union

MeshAxes = Union[None, str, Tuple[str, ...]]
Rules = Dict[str, MeshAxes]

# Default rules: FSDP shards params + optimizer state over ('data','fsdp'),
# tensor parallel splits heads/mlp, context parallel splits sequence.
DEFAULT_RULES: Rules = {
    'batch': ('data', 'fsdp'),
    'seq': 'context',
    'embed': ('fsdp',),
    'heads': 'tensor',
    'kv_heads': 'tensor',
    'head_dim': None,
    'mlp': 'tensor',
    'vocab': 'tensor',
    'expert': 'expert',
    'layers': None,
}


def spec_for(logical_axes: Sequence[Optional[str]],
             rules: Optional[Rules] = None) -> Any:
    """logical axis names → jax.sharding.PartitionSpec."""
    from jax.sharding import PartitionSpec
    rules = DEFAULT_RULES if rules is None else rules
    entries = []
    used: set = set()
    for name in logical_axes:
        if name is None:
            entries.append(None)
            continue
        axes = rules.get(name)
        if axes is None:
            entries.append(None)
            continue
        if isinstance(axes, str):
            axes = (axes,)
        # A mesh axis may appear only once in a PartitionSpec; drop dups
        # (e.g. batch=('data','fsdp') while embed=('fsdp',) on weights
        # is fine — dup checks apply per-array).
        axes = tuple(a for a in axes if a not in used)
        used.update(axes)
        if not axes:
            entries.append(None)
        elif len(axes) == 1:
            entries.append(axes[0])
        else:
            entries.append(axes)
    return PartitionSpec(*entries)


def shard(x: Any,
          logical_axes: Sequence[Optional[str]],
          rules: Optional[Rules] = None) -> Any:
    """`with_sharding_constraint` by logical axes; no-op outside jit/mesh."""
    import jax
    try:
        return jax.lax.with_sharding_constraint(
            x, spec_for(logical_axes, rules))
    except (ValueError, RuntimeError):
        return x


def kv_page_axes(ndim: int, stacked: bool = False
                 ) -> Tuple[Optional[str], ...]:
    """Logical axes of a paged-KV pool leaf (or its per-slot gathered
    view) — ONE construction site for the pool's sharding story.

    The pool shards its KV-HEADS axis over 'tensor' (the same rule the
    dense cache uses) and nothing else: page/position axes stay
    replicated because the block tables and gather indices are
    host-built and identical on every chip, so the page gather/scatter
    partitions trivially — each chip touches its own head-slice of the
    same pages, no all-gather of the pool.

    Leaf ranks covered (quantized scale leaves drop the trailing D):
      stacked pool      [L, P, page, KV(, D)]  -> stacked=True
      per-layer pool    [P, page, KV(, D)]     -> stacked=False
      gathered view     [B, S, KV(, D)]        -> stacked=False
    """
    lead = 3 if stacked else 2
    if ndim not in (lead + 1, lead + 2):
        raise ValueError(
            f'kv_page_axes: rank-{ndim} leaf does not look like a '
            f'{"stacked " if stacked else ""}page-pool leaf')
    axes: Tuple[Optional[str], ...] = (None,) * lead + ('kv_heads',)
    if ndim == lead + 2:
        axes += (None,)
    return axes


def named_sharding(mesh: Any,
                   logical_axes: Sequence[Optional[str]],
                   rules: Optional[Rules] = None) -> Any:
    import jax
    return jax.sharding.NamedSharding(mesh, spec_for(logical_axes, rules))


def tree_shardings(mesh: Any,
                   logical_tree: Any,
                   rules: Optional[Rules] = None) -> Any:
    """Map a pytree of logical-axis tuples → pytree of NamedShardings."""
    import jax
    return jax.tree.map(
        lambda axes: named_sharding(mesh, axes, rules),
        logical_tree,
        is_leaf=lambda x: isinstance(x, tuple) and all(
            a is None or isinstance(a, str) for a in x))
