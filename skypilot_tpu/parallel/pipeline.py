"""Pipeline parallelism: GPipe microbatching over the `pipe` mesh axis.

The reference has no in-tree pipeline machinery (SURVEY.md §2.11 —
reached only through user DeepSpeed recipes). TPU-native design:

- The model's layer stack is already a STACKED pytree (leading dim =
  layers, lax.scan'd); sharding that leading dim over `pipe` gives each
  stage a contiguous chunk of layers with zero repacking.
- Inside `jax.shard_map` every stage runs the same program: process the
  activation it holds through its local layers (an inner scan), then
  `lax.ppermute` it to the next stage. Stage 0 injects a fresh
  microbatch each step; the last stage records finished microbatches.
  After M + S - 1 steps every microbatch has crossed all S stages —
  the classic GPipe schedule, with the bubble fraction (S-1)/(M+S-1).
- ppermute is neighbor-only, so stage traffic rides ICI (or tolerates
  DCN — `pipe` sits outer in the mesh for exactly that reason), and it
  is differentiable: jax.grad produces the reverse schedule without a
  hand-written backward pass.
- The shard_map is PARTIAL-MANUAL (`axis_names={'pipe'}`): only the
  pipeline axis is manual; every other mesh axis (data/fsdp/tensor/
  context) stays in GSPMD auto mode INSIDE the stage program, so
  layer_fn's sharding constraints partition each stage's compute over
  tensor/context and its microbatch over data/fsdp — the full
  pp x tp x sp x dp factorization of a 405B-class run in one mesh,
  with XLA inserting the intra-stage collectives.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax


def pipeline_apply(layer_fn: Callable[[Any, jax.Array], jax.Array],
                   stacked_params: Any,
                   x: jax.Array,
                   mesh: Any,
                   num_microbatches: Optional[int] = None) -> jax.Array:
    """Run `x` through the stacked layers, pipelined over `pipe`.

    layer_fn(single_layer_params, activation) -> activation
    stacked_params: pytree, every leaf with leading dim = num_layers
                    (num_layers % pipe == 0).
    x: [batch, ...] activations entering layer 0.
    Returns activations after the last layer, same shape as x.
    """
    num_stages = dict(mesh.shape).get('pipe', 1)
    if num_stages == 1:
        def scan_all(carry, layer_params):
            return layer_fn(layer_params, carry), None
        out, _ = lax.scan(scan_all, x, stacked_params)
        return out

    batch = x.shape[0]
    m = num_microbatches or num_stages
    if batch % m:
        raise ValueError(f'batch {batch} % microbatches {m} != 0')
    num_layers = jax.tree.leaves(stacked_params)[0].shape[0]
    if num_layers % num_stages:
        raise ValueError(
            f'layers {num_layers} % stages {num_stages} != 0')

    # [M, mb, ...] microbatch-major view.
    x_mb = x.reshape(m, batch // m, *x.shape[1:])

    from jax.sharding import PartitionSpec as P
    # Partial-manual: specs only place the MANUAL `pipe` axis (params'
    # stacked layer dim; the output's per-stage slot dim). Every other
    # mesh axis stays auto — GSPMD propagates/constrains data/fsdp/
    # tensor/context shardings straight through the stage program.
    param_spec = jax.tree.map(lambda _: P('pipe'), stacked_params)
    # Output gains a leading `pipe` dim (one slot per stage); only the
    # last stage's slot holds finished microbatches — sliced below,
    # which avoids an all_gather inside the pipeline body.
    fn = functools.partial(_stage_program, layer_fn=layer_fn,
                           num_stages=num_stages, num_microbatches=m)
    mapped = jax.shard_map(
        fn, mesh=mesh, axis_names=frozenset({'pipe'}),
        in_specs=(param_spec, P()),
        out_specs=P('pipe'))
    out_mb = mapped(stacked_params, x_mb)[num_stages - 1]
    return out_mb.reshape(batch, *x.shape[1:])


def _stage_program(local_params: Any, x_mb: jax.Array, *,
                   layer_fn: Callable, num_stages: int,
                   num_microbatches: int) -> jax.Array:
    """Per-stage body (runs under shard_map, manual over every axis)."""
    stage = lax.axis_index('pipe')
    m = num_microbatches

    def local_layers(state):
        def body(carry, layer_params):
            return layer_fn(layer_params, carry), None
        out, _ = lax.scan(body, state, local_params)
        return out

    perm = [(i, (i + 1) % num_stages) for i in range(num_stages)]

    def step(carry, t):
        state, collected = carry
        # Stage 0 ingests microbatch t (clipped to stay in range during
        # the drain phase — the injected value is ignored then).
        inject = lax.dynamic_index_in_dim(
            x_mb, jnp.clip(t, 0, m - 1), 0, keepdims=False)
        state = jnp.where(stage == 0, inject, state)
        state = local_layers(state)
        # The last stage records microbatch (t - (S-1)) once it has
        # crossed every stage.
        out_idx = t - (num_stages - 1)
        record = jnp.logical_and(
            stage == num_stages - 1,
            jnp.logical_and(out_idx >= 0, out_idx < m))
        updated = lax.dynamic_update_index_in_dim(
            collected, state, jnp.clip(out_idx, 0, m - 1), 0)
        collected = jnp.where(record, updated, collected)
        state = lax.ppermute(state, 'pipe', perm)
        return (state, collected), None

    # The carry BECOMES pipe-varying (axis_index + ppermute) even
    # though x_mb enters replicated over 'pipe' — type the zeros to
    # match the steady state.
    zero_state = _pvary_like(jnp.zeros_like(x_mb[0]), x_mb,
                             extra=('pipe',))
    zero_out = _pvary_like(jnp.zeros_like(x_mb), x_mb, extra=('pipe',))
    (_, collected), _ = lax.scan(
        step, (zero_state, zero_out),
        jnp.arange(m + num_stages - 1))
    # [1, M, mb, ...] per stage — concatenated over `pipe` by the
    # out_spec; the caller slices the last stage's slot.
    return collected[None]


def _pvary_like(zeros: jax.Array, ref: jax.Array,
                extra: tuple = ()) -> jax.Array:
    """Match scan-carry device-variance typing (jax>=0.7
    varying-manual-axes; no-op on older versions): the input's varying
    axes plus `extra` ones the loop body introduces."""
    try:
        vary = tuple(ref.aval.vma)  # type: ignore[attr-defined]
    except AttributeError:
        return zeros
    vary = tuple(dict.fromkeys(vary + extra))
    have = tuple(getattr(zeros.aval, 'vma', ()))
    need = tuple(a for a in vary if a not in have)
    if not need:
        return zeros
    return lax.pvary(zeros, need)


# --- llama convenience ------------------------------------------------------

def llama_pipeline_forward(params: Any, tokens: jax.Array, config: Any,
                           mesh: Any,
                           num_microbatches: Optional[int] = None
                           ) -> jax.Array:
    """llama.forward with the layer stack pipelined over `pipe`.

    Embedding / final norm / lm_head are tiny next to the layer stack
    and run replicated on every stage. Inside a stage the layer runs
    with its normal sharding constraints over the mesh's AUTO axes
    (partial-manual shard_map), so pp composes with tensor/context/
    data/fsdp parallelism in one mesh — the 405B factorization.
    """
    from skypilot_tpu.models import llama

    c = config
    positions = jnp.arange(tokens.shape[1])
    x = llama._embed_lookup(  # noqa: SLF001
        params['embed'].astype(c.dtype), tokens, None)
    # Non-pipe axes are auto inside the stage program: hand the mesh to
    # the layer so attention/mlp keep their tensor/context constraints.
    inner_mesh = mesh if len(dict(mesh.shape)) > 1 else None

    def layer_fn(layer_params, h):
        return llama._layer(h, layer_params, config=c,  # noqa: SLF001
                            positions=positions, mesh=inner_mesh)

    if c.remat:
        layer_fn_wrapped = jax.checkpoint(
            layer_fn,
            policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable)
    else:
        layer_fn_wrapped = layer_fn
    x = pipeline_apply(layer_fn_wrapped, params['layers'], x, mesh,
                       num_microbatches=num_microbatches)
    x = llama._rms_norm(x, params['final_norm'],  # noqa: SLF001
                        c.rms_norm_eps)
    return jnp.einsum('bse,ev->bsv', x, params['lm_head'],
                      preferred_element_type=jnp.float32)
