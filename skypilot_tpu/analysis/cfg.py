"""Per-function control-flow graphs for flow-aware skytpu-lint rules.

The graph is STATEMENT-granular: one node per executed statement (or
per compound-statement HEADER — an `if`/`while` node models only its
test, a `with` node only its context expressions; their bodies are
separate nodes). Three synthetic nodes complete every graph: `entry`,
`exit` (normal completion / return) and `raise_exit` (an exception
escaping the function).

Edges carry a kind:

  normal     sequential flow, branch arms, loop entry/exit
  exception  a statement that can raise, to the innermost handler
             (or `raise_exit`); assert-failure; unmatched-handler
             dispatch

What the model gets right, because the checkers need it:

  * `try`/`except`/`else`: every can-raise statement in the try body
    has an exception edge to each handler AND (unmatched case) onward
    to the outer handler / `raise_exit`.
  * `finally`: the final body is DUPLICATED per continuation (normal,
    exception, return, break, continue), so a release that lives in a
    `finally` satisfies resource-pairing on the exception path too —
    no merged over-approximation that would let a leak hide.
  * `with`: the header can raise; body exceptions still propagate
    (``__exit__`` observes, it does not swallow) — lexical lock
    coverage is the With body's job, not the graph's.
  * loops: back edges exist (body tail -> header), so cycle queries
    (`host-sync-budget`'s sync-in-loop rule) see them; `break` skips
    the `else:` clause, `continue` returns to the header.

Can-raise is deliberately coarse-but-calibrated: a statement gets an
exception edge iff it contains a call/await (or IS a raise/assert).
Pure name/constant shuffling does not fork the graph — that keeps
resource-pairing findings about real raise sites, not `x = y`.
"""
import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

# Bench hook: total build() invocations. core.ParsedFile memoizes per
# function node, so over a full check_project run this must equal the
# number of DISTINCT functions whose CFG any checker asked for — the
# committed lint bench asserts exactly that (memoize per file, not per
# checker).
BUILD_CALLS = 0

NORMAL = 'normal'
EXCEPTION = 'exception'

# Finally-duplication guard: nested finally bodies multiply; past this
# depth the builder reuses the normal-continuation copy for every exit
# kind (an over-approximation no real code in this tree reaches).
_MAX_FINALLY_DEPTH = 8


class Node:
    """One executed statement (or a synthetic entry/exit/raise node).
    A statement can be wrapped by SEVERAL nodes when it sits in a
    `finally` body (one copy per continuation)."""

    __slots__ = ('stmt', 'kind', 'succs', 'index')

    def __init__(self, stmt: Optional[ast.stmt], kind: str,
                 index: int) -> None:
        self.stmt = stmt
        self.kind = kind          # 'entry' | 'exit' | 'raise' | 'stmt'
        self.succs: List[Tuple['Node', str]] = []
        self.index = index        # creation order; stable for sorting

    @property
    def lineno(self) -> int:
        return getattr(self.stmt, 'lineno', 0)

    def add(self, target: Optional['Node'], kind: str) -> None:
        if target is None:
            return
        for t, k in self.succs:
            if t is target and k == kind:
                return
        self.succs.append((target, kind))

    def __repr__(self) -> str:  # pragma: no cover — debugging aid
        what = (f'{type(self.stmt).__name__}@{self.lineno}'
                if self.stmt is not None else self.kind)
        return f'<Node {self.index} {what}>'


class CFG:
    def __init__(self, fn: ast.AST) -> None:
        self.fn = fn
        self.nodes: List[Node] = []
        self.entry = self._node(None, 'entry')
        self.exit = self._node(None, 'exit')
        self.raise_exit = self._node(None, 'raise')
        self._by_stmt: Dict[int, List[Node]] = {}
        self._cyclic: Optional[Set[int]] = None

    def _node(self, stmt: Optional[ast.stmt], kind: str = 'stmt'
              ) -> Node:
        n = Node(stmt, kind, len(self.nodes))
        self.nodes.append(n)
        if stmt is not None:
            self._by_stmt.setdefault(id(stmt), []).append(n)
        return n

    def nodes_for(self, stmt: ast.stmt) -> List[Node]:
        """Every node wrapping `stmt` (finally bodies duplicate)."""
        return self._by_stmt.get(id(stmt), [])

    def terminals(self) -> Tuple[Node, Node]:
        return self.exit, self.raise_exit

    # -- cycle queries (loop back edges) ---------------------------------

    def cyclic_nodes(self) -> Set[int]:
        """Indices of nodes on some cycle (loop bodies): SCCs of size
        > 1 plus self-loops, via iterative Tarjan."""
        if self._cyclic is not None:
            return self._cyclic
        index_of: Dict[int, int] = {}
        low: Dict[int, int] = {}
        on_stack: Set[int] = set()
        stack: List[int] = []
        cyclic: Set[int] = set()
        counter = [0]

        for root in self.nodes:
            if root.index in index_of:
                continue
            work: List[Tuple[Node, int]] = [(root, 0)]
            while work:
                node, si = work[-1]
                if si == 0:
                    index_of[node.index] = low[node.index] = counter[0]
                    counter[0] += 1
                    stack.append(node.index)
                    on_stack.add(node.index)
                recursed = False
                succs = node.succs
                while si < len(succs):
                    child = succs[si][0]
                    si += 1
                    if child.index not in index_of:
                        work[-1] = (node, si)
                        work.append((child, 0))
                        recursed = True
                        break
                    if child.index in on_stack:
                        low[node.index] = min(low[node.index],
                                              index_of[child.index])
                if recursed:
                    continue
                work[-1] = (node, si)
                if si >= len(succs):
                    work.pop()
                    if low[node.index] == index_of[node.index]:
                        comp: List[int] = []
                        while True:
                            w = stack.pop()
                            on_stack.discard(w)
                            comp.append(w)
                            if w == node.index:
                                break
                        if len(comp) > 1:
                            cyclic.update(comp)
                        elif any(t.index == node.index
                                 for t, _ in node.succs):
                            cyclic.add(node.index)
                    if work:
                        parent = work[-1][0]
                        low[parent.index] = min(low[parent.index],
                                                low[node.index])
        self._cyclic = cyclic
        return cyclic


class _Frame:
    """Where control goes from inside the statement list being built:
    fall-through, break, continue, return, and raised exceptions."""

    __slots__ = ('follow', 'brk', 'cont', 'ret', 'exc', 'fin_depth')

    def __init__(self, follow: Node, brk: Optional[Node],
                 cont: Optional[Node], ret: Node, exc: Node,
                 fin_depth: int = 0) -> None:
        self.follow = follow
        self.brk = brk
        self.cont = cont
        self.ret = ret
        self.exc = exc
        self.fin_depth = fin_depth

    def at(self, **kw) -> '_Frame':
        f = _Frame(self.follow, self.brk, self.cont, self.ret,
                   self.exc, self.fin_depth)
        for k, v in kw.items():
            setattr(f, k, v)
        return f


# Builtins whose calls the graph treats as non-raising — `if x >
# len(self._q):` forking an exception edge would drown resource-
# pairing in paths no real program takes.
_SAFE_BUILTINS = {'len', 'isinstance', 'issubclass', 'range', 'id',
                  'hasattr'}


def _raising_call(n: ast.AST) -> bool:
    if isinstance(n, ast.Await):
        return True
    if not isinstance(n, ast.Call):
        return False
    return not (isinstance(n.func, ast.Name)
                and n.func.id in _SAFE_BUILTINS)


def _catches_all(handler: ast.ExceptHandler) -> bool:
    """Bare `except:` or `except BaseException:` — guaranteed to
    match, so nothing escapes the dispatch to the enclosing scope."""
    if handler.type is None:
        return True
    types = handler.type.elts if isinstance(handler.type, ast.Tuple) \
        else [handler.type]
    for t in types:
        name = t.attr if isinstance(t, ast.Attribute) else (
            t.id if isinstance(t, ast.Name) else None)
        if name == 'BaseException':
            return True
    return False


def _contains_call(exprs: Iterable[Optional[ast.AST]]) -> bool:
    for expr in exprs:
        if expr is None:
            continue
        for n in ast.walk(expr):
            if _raising_call(n):
                return True
    return False


def _stmt_can_raise(stmt: ast.stmt) -> bool:
    """Coarse: the statement contains a call/await outside any nested
    function/lambda body (nested bodies do not run here)."""
    stack: List[ast.AST] = [stmt]
    while stack:
        n = stack.pop()
        if _raising_call(n):
            return True
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)) and n is not stmt:
            continue
        stack.extend(ast.iter_child_nodes(n))
    return False


class _Builder:
    def __init__(self, fn: ast.AST) -> None:
        self.cfg = CFG(fn)

    def build(self) -> CFG:
        cfg = self.cfg
        frame = _Frame(follow=cfg.exit, brk=None, cont=None,
                       ret=cfg.exit, exc=cfg.raise_exit)
        first = self._stmts(self.cfg.fn.body, frame)
        cfg.entry.add(first, NORMAL)
        return cfg

    def _stmts(self, stmts: Sequence[ast.stmt], frame: _Frame) -> Node:
        nxt = frame.follow
        for stmt in reversed(stmts):
            nxt = self._stmt(stmt, frame.at(follow=nxt))
        return nxt

    def _stmt(self, stmt: ast.stmt, frame: _Frame) -> Node:
        if isinstance(stmt, ast.If):
            return self._if(stmt, frame)
        if isinstance(stmt, (ast.While,)):
            return self._while(stmt, frame)
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            return self._for(stmt, frame)
        if isinstance(stmt, ast.Try) or (
                hasattr(ast, 'TryStar')
                and isinstance(stmt, getattr(ast, 'TryStar'))):
            return self._try(stmt, frame)
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            return self._with(stmt, frame)
        if isinstance(stmt, ast.Return):
            node = self.cfg._node(stmt)
            if _contains_call([stmt.value]):
                node.add(frame.exc, EXCEPTION)
            node.add(frame.ret, NORMAL)
            return node
        if isinstance(stmt, ast.Raise):
            node = self.cfg._node(stmt)
            node.add(frame.exc, EXCEPTION)
            return node
        if isinstance(stmt, ast.Break):
            node = self.cfg._node(stmt)
            node.add(frame.brk or frame.follow, NORMAL)
            return node
        if isinstance(stmt, ast.Continue):
            node = self.cfg._node(stmt)
            node.add(frame.cont or frame.follow, NORMAL)
            return node
        if isinstance(stmt, ast.Assert):
            node = self.cfg._node(stmt)
            node.add(frame.follow, NORMAL)
            node.add(frame.exc, EXCEPTION)
            return node
        if hasattr(ast, 'Match') and isinstance(
                stmt, getattr(ast, 'Match')):
            return self._match(stmt, frame)
        # Simple statement (incl. nested def/class, import, expr,
        # assignments, global/nonlocal, pass, delete).
        node = self.cfg._node(stmt)
        node.add(frame.follow, NORMAL)
        if _stmt_can_raise(stmt):
            node.add(frame.exc, EXCEPTION)
        return node

    def _if(self, stmt: ast.If, frame: _Frame) -> Node:
        node = self.cfg._node(stmt)
        then_entry = self._stmts(stmt.body, frame)
        else_entry = self._stmts(stmt.orelse, frame) \
            if stmt.orelse else frame.follow
        node.add(then_entry, NORMAL)
        node.add(else_entry, NORMAL)
        if _contains_call([stmt.test]):
            node.add(frame.exc, EXCEPTION)
        return node

    def _while(self, stmt: ast.While, frame: _Frame) -> Node:
        header = self.cfg._node(stmt)
        orelse_entry = self._stmts(stmt.orelse, frame) \
            if stmt.orelse else frame.follow
        body_entry = self._stmts(
            stmt.body,
            frame.at(follow=header, brk=frame.follow, cont=header))
        header.add(body_entry, NORMAL)
        header.add(orelse_entry, NORMAL)
        if _contains_call([stmt.test]):
            header.add(frame.exc, EXCEPTION)
        return header

    def _for(self, stmt: ast.stmt, frame: _Frame) -> Node:
        header = self.cfg._node(stmt)
        orelse_entry = self._stmts(stmt.orelse, frame) \
            if stmt.orelse else frame.follow
        body_entry = self._stmts(
            stmt.body,
            frame.at(follow=header, brk=frame.follow, cont=header))
        header.add(body_entry, NORMAL)
        header.add(orelse_entry, NORMAL)
        # Iterator construction/advancement can raise.
        header.add(frame.exc, EXCEPTION)
        return header

    def _with(self, stmt: ast.stmt, frame: _Frame) -> Node:
        header = self.cfg._node(stmt)
        body_entry = self._stmts(stmt.body, frame)
        header.add(body_entry, NORMAL)
        # __enter__ / the context expressions can raise.
        header.add(frame.exc, EXCEPTION)
        return header

    def _match(self, stmt: ast.stmt, frame: _Frame) -> Node:
        header = self.cfg._node(stmt)
        for case in stmt.cases:
            header.add(self._stmts(case.body, frame), NORMAL)
        header.add(frame.follow, NORMAL)  # no case matched
        if _contains_call([stmt.subject]):
            header.add(frame.exc, EXCEPTION)
        return header

    def _try(self, stmt: ast.stmt, frame: _Frame) -> Node:
        if stmt.finalbody:
            depth = frame.fin_depth + 1
            if depth > _MAX_FINALLY_DEPTH:
                # Pathological nesting: stop duplicating, route every
                # continuation through one copy (over-approximation).
                fin = self._stmts(stmt.finalbody,
                                  frame.at(fin_depth=depth))
                inner = frame.at(follow=fin, exc=fin, ret=fin,
                                 brk=fin if frame.brk else None,
                                 cont=fin if frame.cont else None,
                                 fin_depth=depth)
                return self._try_core(stmt, inner, frame)
            base = frame.at(fin_depth=depth)
            fin_follow = self._stmts(stmt.finalbody, base)
            fin_exc = self._stmts(stmt.finalbody,
                                  base.at(follow=frame.exc))
            fin_ret = self._stmts(stmt.finalbody,
                                  base.at(follow=frame.ret))
            fin_brk = self._stmts(stmt.finalbody,
                                  base.at(follow=frame.brk)) \
                if frame.brk is not None else None
            fin_cont = self._stmts(stmt.finalbody,
                                   base.at(follow=frame.cont)) \
                if frame.cont is not None else None
            inner = frame.at(follow=fin_follow, exc=fin_exc,
                             ret=fin_ret, brk=fin_brk, cont=fin_cont,
                             fin_depth=depth)
            return self._try_core(stmt, inner, frame)
        return self._try_core(stmt, frame, frame)

    def _try_core(self, stmt: ast.stmt, inner: _Frame,
                  outer: _Frame) -> Node:
        """Build try/except/else with `inner` as the continuation set
        (already routed through finally copies when one exists)."""
        # Unmatched-exception dispatch: raising statements in the try
        # body reach each handler, and — no handler guaranteed to
        # match — continue to the enclosing handler too.
        if stmt.handlers:
            disp = self.cfg._node(None, 'dispatch')
            for handler in stmt.handlers:
                h_entry = self._stmts(handler.body, inner)
                disp.add(h_entry, NORMAL)
            if not any(_catches_all(h) for h in stmt.handlers):
                disp.add(inner.exc, EXCEPTION)
            body_exc: Node = disp
        else:
            body_exc = inner.exc
        orelse_entry = self._stmts(stmt.orelse, inner) \
            if stmt.orelse else inner.follow
        body_entry = self._stmts(
            stmt.body, inner.at(follow=orelse_entry, exc=body_exc))
        return body_entry


def build(fn: ast.AST) -> CFG:
    """CFG for one FunctionDef/AsyncFunctionDef (or Lambda: trivial).
    Nested function bodies are opaque single statements — ask for
    their own CFG."""
    global BUILD_CALLS
    BUILD_CALLS += 1
    if isinstance(fn, ast.Lambda):
        cfg = CFG(fn)
        cfg.entry.add(cfg.exit, NORMAL)
        return cfg
    return _Builder(fn).build()
