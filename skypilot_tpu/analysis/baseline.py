"""Committed-baseline handling: pre-existing debt must not block the
gate, new findings must.

The baseline maps content fingerprints (check|rule|path|normalized
STATEMENT text — no line numbers, no single-physical-line coupling)
to an allowed count. A finding is 'baselined' while occurrences of
its fingerprint stay within that count; the excess — and any unknown
fingerprint — is NEW and fails the gate. Fixing a baselined finding
never breaks the gate (stale entries are just dead weight;
`--write-baseline` prunes them).

Version 2 moved the fingerprint basis from one stripped source line
to the whole normalized statement: a v1 baseline entry resurrected
the moment black-style rewrapping moved part of a multi-line call
onto another physical line. `migrate()` rewrites a v1 file in place,
carrying counts over by matching the CURRENT findings' v1-style
fingerprints against the old entries — exact, no heuristics — and
dropping entries that match nothing (they were stale anyway).
"""
import collections
import json
import os
from typing import Dict, List, Sequence, Tuple

from skypilot_tpu.analysis.core import Finding

DEFAULT_BASENAME = '.skytpu-lint-baseline.json'
_VERSION = 2


def default_path(root: str) -> str:
    return os.path.join(root, DEFAULT_BASENAME)


def load(path: str) -> Dict[str, Dict[str, object]]:
    """fingerprint -> entry ({check, rule, path, statement, count})."""
    if not os.path.exists(path):
        return {}
    with open(path, encoding='utf-8') as f:
        doc = json.load(f)
    version = doc.get('version')
    if version == 1:
        raise ValueError(
            f'{path} is a v1 (line-snippet) baseline; run '
            '`python -m skypilot_tpu.analysis --migrate-baseline` '
            'to rewrite it in place')
    if version != _VERSION:
        raise ValueError(
            f'{path}: unsupported baseline version {version!r}')
    entries = doc.get('entries', {})
    if not isinstance(entries, dict):
        raise ValueError(f'{path}: entries must be a mapping')
    return entries


def _entries_for(findings: Sequence[Finding],
                 counts: Dict[str, int]) -> Dict[str, Dict[str, object]]:
    entries: Dict[str, Dict[str, object]] = {}
    for f in findings:
        fp = f.fingerprint()
        if fp in entries:
            continue
        entries[fp] = {
            'check': f.check,
            'rule': f.rule,
            'path': f.path,
            'statement': f.statement or f.snippet or f.message,
            'count': counts[fp],
        }
    return entries


def write(path: str, findings: Sequence[Finding]) -> None:
    counts: Dict[str, int] = collections.Counter(
        f.fingerprint() for f in findings)
    doc = {'version': _VERSION,
           'entries': dict(sorted(_entries_for(findings,
                                               counts).items()))}
    with open(path, 'w', encoding='utf-8') as out:
        json.dump(doc, out, indent=1, sort_keys=False)
        out.write('\n')


def migrate(path: str, findings: Sequence[Finding]) -> int:
    """Rewrite a v1 baseline as v2 in place, preserving each entry's
    count by matching the current findings' v1 fingerprints. Returns
    the number of entries carried over; no-op (returning -1) when the
    file is already v2 or absent."""
    if not os.path.exists(path):
        return -1
    with open(path, encoding='utf-8') as f:
        doc = json.load(f)
    if doc.get('version') == _VERSION:
        return -1
    if doc.get('version') != 1:
        raise ValueError(
            f'{path}: cannot migrate version {doc.get("version")!r}')
    old_entries = doc.get('entries', {})

    kept: List[Finding] = []
    counts: Dict[str, int] = {}
    for f in findings:
        old = old_entries.get(f.legacy_fingerprint())
        if old is None:
            continue
        fp = f.fingerprint()
        if fp not in counts:
            kept.append(f)
        # The old COUNT is the accepted debt level; distinct current
        # findings sharing one new fingerprint still only get the old
        # budget, not one budget each.
        counts[fp] = max(counts.get(fp, 0), int(old.get('count', 1)))
    new_doc = {'version': _VERSION,
               'entries': dict(sorted(_entries_for(kept,
                                                   counts).items()))}
    with open(path, 'w', encoding='utf-8') as out:
        json.dump(new_doc, out, indent=1, sort_keys=False)
        out.write('\n')
    return len(kept)


def partition(findings: Sequence[Finding],
              entries: Dict[str, Dict[str, object]],
              ) -> Tuple[List[Finding], List[Finding]]:
    """Split into (new, baselined): each fingerprint absorbs up to its
    baseline count, in file order; the rest is new."""
    budget = {fp: int(e.get('count', 1)) for fp, e in entries.items()}
    new: List[Finding] = []
    baselined: List[Finding] = []
    for f in findings:
        fp = f.fingerprint()
        if budget.get(fp, 0) > 0:
            budget[fp] -= 1
            baselined.append(f)
        else:
            new.append(f)
    return new, baselined
