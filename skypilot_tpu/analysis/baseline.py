"""Committed-baseline handling: pre-existing debt must not block the
gate, new findings must.

The baseline maps content fingerprints (check|rule|path|source-line,
no line numbers) to an allowed count. A finding is 'baselined' while
occurrences of its fingerprint stay within that count; the excess —
and any unknown fingerprint — is NEW and fails the gate. Fixing a
baselined finding never breaks the gate (stale entries are just dead
weight; `--write-baseline` prunes them).
"""
import collections
import json
import os
from typing import Dict, List, Sequence, Tuple

from skypilot_tpu.analysis.core import Finding

DEFAULT_BASENAME = '.skytpu-lint-baseline.json'
_VERSION = 1


def default_path(root: str) -> str:
    return os.path.join(root, DEFAULT_BASENAME)


def load(path: str) -> Dict[str, Dict[str, object]]:
    """fingerprint -> entry ({check, rule, path, snippet, count})."""
    if not os.path.exists(path):
        return {}
    with open(path, encoding='utf-8') as f:
        doc = json.load(f)
    if doc.get('version') != _VERSION:
        raise ValueError(
            f'{path}: unsupported baseline version {doc.get("version")!r}')
    entries = doc.get('entries', {})
    if not isinstance(entries, dict):
        raise ValueError(f'{path}: entries must be a mapping')
    return entries


def write(path: str, findings: Sequence[Finding]) -> None:
    counts: Dict[str, int] = collections.Counter(
        f.fingerprint() for f in findings)
    entries = {}
    for f in findings:
        fp = f.fingerprint()
        if fp in entries:
            continue
        entries[fp] = {
            'check': f.check,
            'rule': f.rule,
            'path': f.path,
            'snippet': f.snippet or f.message,
            'count': counts[fp],
        }
    doc = {'version': _VERSION,
           'entries': dict(sorted(entries.items()))}
    with open(path, 'w', encoding='utf-8') as out:
        json.dump(doc, out, indent=1, sort_keys=False)
        out.write('\n')


def partition(findings: Sequence[Finding],
              entries: Dict[str, Dict[str, object]],
              ) -> Tuple[List[Finding], List[Finding]]:
    """Split into (new, baselined): each fingerprint absorbs up to its
    baseline count, in file order; the rest is new."""
    budget = {fp: int(e.get('count', 1)) for fp, e in entries.items()}
    new: List[Finding] = []
    baselined: List[Finding] = []
    for f in findings:
        fp = f.fingerprint()
        if budget.get(fp, 0) > 0:
            budget[fp] -= 1
            baselined.append(f)
        else:
            new.append(f)
    return new, baselined
