"""skytpu-lint: AST-based static analysis as a CI gate.

The repo proved mechanical enforcement twice before this package
existed (the metrics-namespace and fault-point lint tests); this
unifies them behind one checker plugin API and adds the checks that
guard the ROADMAP's trace-correctness and concurrency refactors:

  trace-safety      host effects / tracer coercions / closure mutation
                    inside jax.jit / shard_map / lax control-flow
  env-registry      every SKYTPU_* var declared once in
                    skypilot_tpu/envs.py; env read at call time only
  async-discipline  no blocking calls inside `async def`; no
                    leak-prone bare asyncio.gather fan-outs
  lock-discipline   shared module state mutated only under the
                    module's lock
  metrics-names     the skytpu_* metric naming/help/bucket contract
  fault-points      the chaos-injection catalog contract

CLI:  python -m skypilot_tpu.analysis [paths...]
          --checks a,b --format text|json
          --baseline PATH --write-baseline

Pre-existing debt lives in a committed baseline file
(.skytpu-lint-baseline.json) so the gate fails only on NEW findings;
see docs/guides/static-analysis.md.
"""
from skypilot_tpu.analysis.core import (Checker, Finding, all_checkers,
                                        register, run)

__all__ = ['Checker', 'Finding', 'all_checkers', 'register', 'run']
