"""lock-coverage: per-class inference of which attributes a lock
guards, then flagging mutations that skip the lock.

lock-discipline covers MODULE-level lock/state pairs; this checker
covers the threaded-CLASS pattern (SpanCollector, LoadBalancer,
executors, adaptor caches): a class that creates `self._lock =
threading.Lock()` and mutates shared attributes under `with
self._lock:` has declared, implicitly, that those attributes are
lock-guarded everywhere. The PR 16/17 bugs were exactly a mutation
added later on a path that skipped the lock.

Inference: for each class owning a Lock/RLock/Condition attribute,
the GUARDED set is every `self.X` mutated (assigned, aug-assigned,
deleted, or hit with a mutator method like .append/.pop/.update)
inside any `with self.<lock>:` body in the class. A mutation of a
guarded attribute elsewhere must then be covered by one of:

  * lexical containment in a `with self.<lock>:` body
  * the enclosing method being named `*_locked` (the repo's
    caller-holds-the-lock convention)
  * `__init__`/`__new__` (the object is not yet shared)
  * flow-sensitive coverage: a `self.<lock>.acquire()` dominating the
    mutation with no intervening release (must-hold dataflow over the
    method's CFG — the try/finally acquire pattern)

Everything else flags `unguarded-mutation`. Single-threaded-by-
construction classes (EngineLoop's queue ownership) simply have no
lock attribute and are never visited.
"""
import ast
from typing import Dict, FrozenSet, Iterable, List, Optional, Set, \
    Tuple

from skypilot_tpu.analysis import core, dataflow
from skypilot_tpu.analysis.core import Checker, Finding, register

_LOCK_TYPES = {'Lock', 'RLock', 'Condition'}
_MUTATOR_METHODS = {'append', 'extend', 'insert', 'remove', 'pop',
                    'clear', 'add', 'discard', 'popitem',
                    'setdefault', 'update'}
_EXEMPT_METHODS = {'__init__', '__new__', '__del__'}


def _self_attr(node: ast.AST) -> Optional[str]:
    """'X' when node is exactly `self.X` (or a subscript/attribute
    chain rooted there: self.X[k], self.X.y -> 'X')."""
    while isinstance(node, (ast.Subscript, ast.Attribute)):
        if isinstance(node, ast.Attribute) and isinstance(
                node.value, ast.Name) and node.value.id == 'self':
            return node.attr
        node = node.value
    return None


def _lock_attrs(cls: ast.ClassDef) -> Set[str]:
    locks: Set[str] = set()
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign):
            continue
        if not isinstance(node.value, ast.Call):
            continue
        name = core.dotted_name(node.value.func)
        if name is None or name.split('.')[-1] not in _LOCK_TYPES:
            continue
        for t in node.targets:
            attr = _self_attr(t)
            if attr is not None:
                locks.add(attr)
    return locks


def _mutations(root: ast.AST) -> List[Tuple[ast.AST, str]]:
    """(node, attr) for every self.<attr> mutation under `root`.
    Nested functions still mutate the same object (often from yet
    another thread), so they are walked; only nested CLASS bodies —
    a different `self` — are skipped."""
    out: List[Tuple[ast.AST, str]] = []
    stack: List[ast.AST] = [root]
    while stack:
        node = stack.pop()
        if isinstance(node, ast.ClassDef) and node is not root:
            continue
        stack.extend(ast.iter_child_nodes(node))
        if isinstance(node, ast.Assign):
            for t in node.targets:
                targets = t.elts if isinstance(
                    t, (ast.Tuple, ast.List)) else [t]
                for tt in targets:
                    # Plain rebinding `self.X = ...` of the whole
                    # attribute is a single store; item/field writes
                    # through it are the racy shape too.
                    attr = _self_attr(tt)
                    if attr is not None:
                        out.append((node, attr))
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            attr = _self_attr(node.target)
            if attr is not None and (
                    not isinstance(node, ast.AnnAssign)
                    or node.value is not None):
                out.append((node, attr))
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                attr = _self_attr(t)
                if attr is not None:
                    out.append((node, attr))
        elif isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute) and \
                node.func.attr in _MUTATOR_METHODS:
            attr = _self_attr(node.func.value)
            if attr is not None:
                out.append((node, attr))
    return out


def _with_locks(stmt: ast.AST, locks: Set[str]) -> Set[str]:
    """Lock attrs entered by a With statement's items."""
    held: Set[str] = set()
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        for item in stmt.items:
            attr = _self_attr(item.context_expr)
            if attr in locks:
                held.add(attr)
    return held


def _lexically_locked(node: ast.AST, locks: Set[str]) -> bool:
    cur = getattr(node, 'skytpu_parent', None)
    while cur is not None:
        if _with_locks(cur, locks):
            return True
        cur = getattr(cur, 'skytpu_parent', None)
    return False


def _enclosing_method(node: ast.AST) -> Optional[ast.AST]:
    cur = getattr(node, 'skytpu_parent', None)
    while cur is not None and not isinstance(
            cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
        cur = getattr(cur, 'skytpu_parent', None)
    return cur


def _lock_call_attr(stmt: ast.stmt, locks: Set[str],
                    verb: str) -> FrozenSet[str]:
    """Lock attrs on which `stmt` calls self.<lock>.<verb>()."""
    hit: Set[str] = set()
    for node in ast.walk(stmt):
        if isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute) and node.func.attr == verb:
            attr = _self_attr(node.func.value)
            if attr in locks:
                hit.add(attr)
    return frozenset(hit)


@register
class LockCoverageChecker(Checker):
    name = 'lock-coverage'
    description = ('attributes a class mutates under `with self._lock:`'
                   ' are mutated under it everywhere')

    def check_file(self, pf: core.ParsedFile) -> Iterable[Finding]:
        findings: List[Finding] = []
        for cls in ast.walk(pf.tree):
            if isinstance(cls, ast.ClassDef):
                findings.extend(self._check_class(pf, cls))
        return findings

    def _check_class(self, pf: core.ParsedFile,
                     cls: ast.ClassDef) -> Iterable[Finding]:
        locks = _lock_attrs(cls)
        if not locks:
            return
        guarded: Set[str] = set()
        for node in ast.walk(cls):
            if _with_locks(node, locks):
                for _, attr in _mutations(node):
                    guarded.add(attr)
        guarded -= locks
        if not guarded:
            return

        # Flow-held cache: method node -> must-hold state (built only
        # for methods that call .acquire() on a class lock).
        held_cache: Dict[int, Optional[Dict[int, FrozenSet[str]]]] = {}

        reported: Set[Tuple[int, str]] = set()
        for method in cls.body:
            if not isinstance(method, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                continue
            if method.name in _EXEMPT_METHODS or \
                    method.name.endswith('_locked'):
                continue
            for node, attr in _mutations(method):
                if attr not in guarded:
                    continue
                if _lexically_locked(node, locks):
                    continue
                if self._flow_held(pf, method, node, locks,
                                   held_cache):
                    continue
                key = (node.lineno, attr)
                if key in reported:
                    continue
                reported.add(key)
                yield pf.finding(
                    self.name, 'unguarded-mutation', node,
                    f'`self.{attr}` is lock-guarded in '
                    f'`{cls.name}` (mutated under `with self.'
                    f'{sorted(locks)[0]}:` elsewhere) but mutated '
                    f'here in `{method.name}` without the lock — '
                    'take the lock, or rename the method *_locked '
                    'if every caller already holds it')

    def _flow_held(self, pf: core.ParsedFile, method: ast.AST,
                   node: ast.AST, locks: Set[str],
                   cache: Dict[int, Optional[Dict[int,
                                                  FrozenSet[str]]]],
                   ) -> bool:
        """Is some class lock guaranteed held at `node` via explicit
        acquire()/release() calls (the try/finally pattern)?"""
        key = id(method)
        if key not in cache:
            uses_acquire = any(
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr == 'acquire'
                and _self_attr(n.func.value) in locks
                for n in ast.walk(method))
            if not uses_acquire:
                cache[key] = None
            else:
                graph = pf.cfg(method)
                state = dataflow.must_hold(
                    graph,
                    acquires=lambda nd: _lock_call_attr(
                        nd.stmt, locks, 'acquire')
                    if nd.stmt is not None else frozenset(),
                    releases=lambda nd: _lock_call_attr(
                        nd.stmt, locks, 'release')
                    if nd.stmt is not None else frozenset(),
                    universe=frozenset(locks))
                # Collapse to stmt-id -> held (any CFG copy).
                by_stmt: Dict[int, FrozenSet[str]] = {}
                for g_node in graph.nodes:
                    if g_node.stmt is None:
                        continue
                    prev = by_stmt.get(id(g_node.stmt))
                    cur = state[g_node.index]
                    by_stmt[id(g_node.stmt)] = (
                        cur if prev is None else (prev & cur))
                cache[key] = by_stmt
        by_stmt = cache[key]
        if by_stmt is None:
            return False
        stmt = pf.statement_of(node)
        if stmt is None:
            return False
        held = by_stmt.get(id(stmt), frozenset())
        # The acquiring statement itself: held-on-entry is empty but
        # the mutation runs after acquire() only if it IS the acquire
        # statement — rare; treat entry-state as the answer.
        return bool(held)
