"""donation-discipline: a buffer donated to a jit call is DEAD until
rebound; any later read on a downstream path is a use-after-free.

With `donate_argnums`/`donate_argnames`, XLA is free to alias the
donated input's memory for the outputs — reading the Python handle
afterwards observes whatever the kernel scribbled there (on TPU:
garbage that often LOOKS plausible; the PR 13 dual-cache lesson was
exactly this, fixed by threading the returned cache back instead of
touching the argument again).

Statically: collect the file's donating callables —

  @functools.partial(jax.jit, donate_argnums=(0,))
  def step_fn(cache, x): ...
  fast = jax.jit(step_fn, donate_argnums=(0,))

— then at every bare-name call site of one, resolve the donated
argument expressions (name or attribute chain: `cache`,
`self.state.cache`) and walk the CFG forward from the call statement.
A statement that rebinds the chain (or a prefix — rebinding
`self.state` rebinds `self.state.cache`) kills the walk on that path;
a statement that READS the chain (or anything under it) first flags
`use-after-donate`. The donating statement itself rebinding the chain
(`cache = fast(cache, x)`) is the blessed pattern and exempt, unless
a loop back-edge brings execution back to it with the chain still
dead.
"""
import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set, \
    Tuple

from skypilot_tpu.analysis import core, dataflow
from skypilot_tpu.analysis.core import Checker, Finding, register


class _Donor:
    """One donating callable: positional indices and keyword names
    whose call-site arguments die."""

    __slots__ = ('argnums', 'argnames')

    def __init__(self, argnums: Set[int], argnames: Set[str]) -> None:
        self.argnums = argnums
        self.argnames = argnames


def _literal_ints(node: ast.AST) -> Set[int]:
    out: Set[int] = set()
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        out.add(node.value)
    elif isinstance(node, (ast.Tuple, ast.List)):
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value,
                                                          int):
                out.add(e.value)
    return out


def _literal_strs(node: ast.AST) -> Set[str]:
    out: Set[str] = set()
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        out.add(node.value)
    elif isinstance(node, (ast.Tuple, ast.List)):
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value,
                                                          str):
                out.add(e.value)
    return out


def _donation_kwargs(call: ast.Call) -> Optional[_Donor]:
    argnums: Set[int] = set()
    argnames: Set[str] = set()
    for kw in call.keywords:
        if kw.arg == 'donate_argnums':
            argnums |= _literal_ints(kw.value)
        elif kw.arg == 'donate_argnames':
            argnames |= _literal_strs(kw.value)
    if argnums or argnames:
        return _Donor(argnums, argnames)
    return None


def _is_jit(func: ast.AST) -> bool:
    name = core.dotted_name(func)
    if name is None:
        return False
    parts = name.split('.')
    return parts[-1] in ('jit', 'pjit') and (
        len(parts) == 1 or 'jax' in parts or 'pjit' in parts[:-1])


def collect_donors(tree: ast.AST) -> Dict[str, _Donor]:
    """name -> donation spec, for names callable in this file."""
    donors: Dict[str, _Donor] = {}
    for node in ast.walk(tree):
        # @functools.partial(jax.jit, donate_argnums=...) / @jax.jit(...)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for deco in node.decorator_list:
                if not isinstance(deco, ast.Call):
                    continue
                deco_name = core.dotted_name(deco.func)
                is_partial_jit = (
                    deco_name in ('functools.partial', 'partial')
                    and deco.args and _is_jit(deco.args[0]))
                if is_partial_jit or _is_jit(deco.func):
                    donor = _donation_kwargs(deco)
                    if donor is not None:
                        donors[node.name] = donor
        # fast = jax.jit(fn, donate_argnums=...)
        if isinstance(node, ast.Assign) and isinstance(node.value,
                                                       ast.Call):
            call = node.value
            inner: Optional[ast.Call] = None
            if _is_jit(call.func):
                inner = call
            elif (core.dotted_name(call.func) in ('functools.partial',
                                                  'partial')
                  and call.args and _is_jit(call.args[0])):
                inner = call
            if inner is None:
                continue
            donor = _donation_kwargs(inner)
            if donor is None:
                continue
            for t in node.targets:
                tname = core.dotted_name(t)
                if tname is not None:
                    donors[tname] = donor
    return donors


def _assigned_chains(stmt: ast.stmt) -> Set[str]:
    """Dotted chains (re)bound by `stmt` — plain names and attribute
    chains; tuple targets are unpacked."""
    chains: Set[str] = set()

    def target(t: ast.AST) -> None:
        if isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                target(e)
            return
        if isinstance(t, ast.Starred):
            target(t.value)
            return
        name = core.dotted_name(t)
        if name is not None:
            chains.add(name)

    if isinstance(stmt, ast.Assign):
        for t in stmt.targets:
            target(t)
    elif isinstance(stmt, ast.AnnAssign):
        target(stmt.target)
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        target(stmt.target)
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        for item in stmt.items:
            if item.optional_vars is not None:
                target(item.optional_vars)
    return chains


def _kills(chains: Set[str], dead: str) -> bool:
    """Does rebinding any of `chains` resurrect `dead`? True when a
    chain equals the dead chain or is a strict prefix of it."""
    for c in chains:
        if c == dead or dead.startswith(c + '.'):
            return True
    return False


def _reads_of(stmt: ast.stmt, dead: str,
              skip_call: Optional[ast.Call] = None) -> List[ast.AST]:
    """Load-context references to `dead` (or anything under it) in the
    expressions `stmt` evaluates. `skip_call` exempts the donating
    call's own arguments (they are the donation, not a use-after)."""
    hits: List[ast.AST] = []
    stack: List[ast.AST] = list(_scan_roots(stmt))
    while stack:
        node = stack.pop()
        if node is skip_call:
            continue
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        if isinstance(node, (ast.Name, ast.Attribute)) and isinstance(
                getattr(node, 'ctx', None), ast.Load):
            name = core.dotted_name(node)
            if name is not None and (name == dead
                                     or name.startswith(dead + '.')):
                hits.append(node)
                continue  # children are part of the same chain
        stack.extend(ast.iter_child_nodes(node))
    return hits


def _scan_roots(stmt: ast.stmt) -> List[ast.AST]:
    """Expressions this statement's CFG node evaluates (headers only
    for compound statements)."""
    if isinstance(stmt, ast.If):
        return [stmt.test]
    if isinstance(stmt, ast.While):
        return [stmt.test]
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        return [stmt.iter]
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        return [item.context_expr for item in stmt.items]
    if isinstance(stmt, ast.Try) or (
            hasattr(ast, 'TryStar')
            and isinstance(stmt, getattr(ast, 'TryStar'))):
        return []
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef)):
        return []
    return [stmt]


def _own_statements(fn: ast.AST) -> Iterable[ast.stmt]:
    """Statements executed in `fn`'s own frame (nested defs opaque)."""
    stack: List[ast.stmt] = list(fn.body)
    while stack:
        stmt = stack.pop()
        yield stmt
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for field in ('body', 'orelse', 'finalbody'):
            stack.extend(getattr(stmt, field, ()))
        for handler in getattr(stmt, 'handlers', ()):
            stack.extend(handler.body)
        for case in getattr(stmt, 'cases', ()):
            stack.extend(case.body)


@register
class DonationDisciplineChecker(Checker):
    name = 'donation-discipline'
    description = ('arguments donated to a jit call are dead until '
                   'rebound; downstream reads flag')

    def check_file(self, pf: core.ParsedFile) -> Iterable[Finding]:
        donors = collect_donors(pf.tree)
        if not donors:
            return ()
        findings: List[Finding] = []
        for fn in ast.walk(pf.tree):
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                findings.extend(self._check_fn(pf, fn, donors))
        return findings

    def _check_fn(self, pf: core.ParsedFile, fn: ast.AST,
                  donors: Dict[str, _Donor]) -> Iterable[Finding]:
        sites: List[Tuple[ast.stmt, ast.Call, str]] = []
        for stmt in _own_statements(fn):
            for node in _scan_roots(stmt):
                for sub in ast.walk(node):
                    if not isinstance(sub, ast.Call):
                        continue
                    callee = core.dotted_name(sub.func)
                    donor = donors.get(callee or '')
                    if donor is None:
                        continue
                    for dead in self._donated_chains(sub, donor):
                        sites.append((stmt, sub, dead))
        if not sites:
            return

        graph: Optional[object] = None
        reported: Set[Tuple[int, str]] = set()
        for stmt, call, dead in sites:
            # `cache = fast(cache, x)` — the donating statement itself
            # rebinds the chain, so it is alive again at every
            # successor (including its own loop back edge). Nothing
            # downstream can read the dead handle.
            if _kills(_assigned_chains(stmt), dead):
                continue
            if graph is None:
                graph = pf.cfg(fn)
            for start in graph.nodes_for(stmt):
                # Walk the call statement's SUCCESSORS: the donating
                # statement's own argument reads are the donation.
                for node in dataflow.forward_reach(
                        start,
                        stop=lambda n: n.stmt is not None and _kills(
                            _assigned_chains(n.stmt), dead)):
                    if node.stmt is None:
                        continue
                    # Reaching the donating statement AGAIN (loop
                    # back edge) donates an already-dead buffer — its
                    # argument reads are genuine findings, so no
                    # skip_call here.
                    reads = _reads_of(node.stmt, dead)
                    if not reads:
                        continue
                    key = (node.stmt.lineno, dead)
                    if key in reported:
                        continue
                    reported.add(key)
                    yield pf.finding(
                        self.name, 'use-after-donate', node.stmt,
                        f'`{dead}` was donated to `'
                        f'{core.dotted_name(call.func)}` on line '
                        f'{stmt.lineno} (donate_argnums aliases its '
                        'buffer for the outputs) and is read here '
                        'before being rebound — thread the returned '
                        'value instead of the dead handle')

    @staticmethod
    def _donated_chains(call: ast.Call, donor: _Donor) -> List[str]:
        chains: List[str] = []
        for i in donor.argnums:
            if i < len(call.args):
                name = core.dotted_name(call.args[i])
                if name is not None:
                    chains.append(name)
        for kw in call.keywords:
            if kw.arg in donor.argnames:
                name = core.dotted_name(kw.value)
                if name is not None:
                    chains.append(name)
        return chains
