"""async-discipline: the event loop must never block, tasks must not
leak.

  blocking-call   time.sleep / requests.* / urllib / sync sockets /
                  subprocess / open() directly inside an `async def`
                  body stalls EVERY in-flight request on that loop —
                  on the serve planes that is every token stream
                  behind the LB. Use asyncio.sleep, aiohttp, or
                  asyncio.to_thread.
  task-leak       `asyncio.gather(*<freshly created coroutines>)`
                  without return_exceptions=True: when one coroutine
                  raises, gather returns immediately but the SIBLING
                  coroutines keep running detached — nothing holds a
                  handle to cancel them (the openai_api _collect leak,
                  ADVICE.md round 5). Either pass
                  return_exceptions=True, or create named tasks first
                  (asyncio.ensure_future/create_task) and cancel the
                  survivors in the error path.

Nested synchronous `def`s inside an async function are exempt from
blocking-call: they run wherever they are called (often under
to_thread / run_in_executor).
"""
import ast
from typing import Iterable, List, Optional, Set

from skypilot_tpu.analysis import core
from skypilot_tpu.analysis.core import Checker, Finding, register

_BLOCKING_CALLS = {
    'time.sleep',
    'urllib.request.urlopen',
    'socket.create_connection',
    'subprocess.run', 'subprocess.call', 'subprocess.check_call',
    'subprocess.check_output', 'subprocess.Popen',
    'os.system', 'os.wait', 'os.waitpid',
    'open',
}
_BLOCKING_PREFIXES = ('requests.',)


def _blocking_name(node: ast.Call) -> Optional[str]:
    name = core.dotted_name(node.func)
    if name is None:
        return None
    if name in _BLOCKING_CALLS or name.startswith(_BLOCKING_PREFIXES):
        return name
    return None


def _async_body_nodes(fn: ast.AsyncFunctionDef) -> Iterable[ast.AST]:
    """Walk fn's body, skipping nested (a)sync function/lambda
    subtrees — nested async defs are visited in their own right by the
    outer loop; nested sync defs run off-loop."""
    stack: List[ast.AST] = []
    for stmt in fn.body:
        stack.append(stmt)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _is_spawned_coroutine(arg: ast.AST) -> bool:
    """True when a gather argument is a coroutine created in place —
    the shapes that leave no cancellable handle behind: f(x),
    *map(f, xs), *[f(x) for x in xs], *(f(x) for x in xs)."""
    if isinstance(arg, ast.Starred):
        inner = arg.value
        return isinstance(inner, (ast.Call, ast.ListComp,
                                  ast.GeneratorExp))
    return isinstance(arg, (ast.Call, ast.Await))


@register
class AsyncDisciplineChecker(Checker):
    name = 'async-discipline'
    description = ('no blocking calls inside async def; no leak-prone '
                   'bare asyncio.gather fan-outs')

    def check_file(self, pf: core.ParsedFile) -> Iterable[Finding]:
        findings: List[Finding] = []
        seen: Set[int] = set()

        def emit(node: ast.AST, rule: str, message: str) -> None:
            if (node.lineno, rule) in seen:
                return
            seen.add((node.lineno, rule))
            findings.append(pf.finding(self.name, rule, node, message))

        for fn in ast.walk(pf.tree):
            if not isinstance(fn, ast.AsyncFunctionDef):
                continue
            for node in _async_body_nodes(fn):
                if not isinstance(node, ast.Call):
                    continue
                blocking = _blocking_name(node)
                if blocking is not None:
                    emit(node, 'blocking-call',
                         f'{blocking}() blocks the event loop inside '
                         f'async `{fn.name}` — every in-flight '
                         'request on this loop stalls; use the async '
                         'equivalent or asyncio.to_thread')
                name = core.dotted_name(node.func)
                if name in ('asyncio.gather', 'gather'):
                    has_re = any(kw.arg == 'return_exceptions'
                                 for kw in node.keywords)
                    if not has_re and any(_is_spawned_coroutine(a)
                                          for a in node.args):
                        emit(node, 'task-leak',
                             'asyncio.gather over in-place coroutines '
                             'without return_exceptions=True: when '
                             'one raises, the siblings keep running '
                             'with no handle left to cancel them — '
                             'create tasks first and cancel survivors '
                             'on error, or pass '
                             'return_exceptions=True')
        return findings
