"""resource-pairing: every acquire reaches a release on every
outgoing path — exception edges included.

The prefix cache pins pages by refcount (`RadixPrefixCache.acquire`)
and the page allocator hands out reservations; a path that leaves the
function without releasing or publishing them leaks the pin forever —
under load the allocator then OOMs slots that are actually free (the
PR 11/17 class). The flow check: from each acquire statement, can the
function's exit — or, the case unit tests must catch, its
RAISE exit — be reached without passing a satisfying statement?

Satisfying statements, per acquire:

  * a release-verb call (`release`/`free`/`free_pages`/
    `release_pages`/`unpin`) on the SAME receiver chain
    (`self._prefix.acquire(...)` pairs with `self._prefix.release(...)`)
  * ownership transfer: a `return` whose value mentions the
    acquire's bound name(s) (the caller now owns the pin), or the
    acquire statement itself being a `return`
  * publish: an assignment that stores a bound name into an
    attribute/subscript (e.g. `self._slot_pages[slot] = pages` — the
    tracked structure now owns the pages and frees them on its own
    path)
  * an explicit annotation on a line: `# skytpu-lint:
    releases[<receiver>]` for hand-off shapes the matcher cannot see

Lock-shaped receivers (`lock`/`sem`/`cond` in the chain) are excluded
— lock.acquire pairing is lock-coverage's domain, and `with` handles
it anyway.
"""
import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set, \
    Tuple

from skypilot_tpu.analysis import core, dataflow
from skypilot_tpu.analysis.core import Checker, Finding, register

RELEASE_MARKER = 'skytpu-lint: releases['

_ACQUIRE_VERBS = {'acquire', 'reserve', 'reserve_pages'}
_RELEASE_VERBS = {'release', 'free', 'free_pages', 'release_pages',
                  'unpin', 'publish'}
_LOCKISH = ('lock', 'sem', 'cond', 'mutex')


def _receiver_of(call: ast.Call, verbs: Set[str]) -> Optional[str]:
    if not isinstance(call.func, ast.Attribute):
        return None
    if call.func.attr not in verbs:
        return None
    return core.dotted_name(call.func.value)


def _is_lockish(receiver: str) -> bool:
    low = receiver.lower()
    return any(token in low for token in _LOCKISH)


def _marker_releases(line: str) -> Set[str]:
    """Receivers named by `# skytpu-lint: releases[a, b]` on a line."""
    start = line.find(RELEASE_MARKER)
    if start < 0:
        return set()
    start += len(RELEASE_MARKER)
    end = line.find(']', start)
    if end < 0:
        return set()
    return {n.strip() for n in line[start:end].split(',') if n.strip()}


def _walk_shallow(root: ast.AST) -> Iterable[ast.AST]:
    """Walk skipping nested function/lambda bodies."""
    stack: List[ast.AST] = [root]
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)) and node is not root:
            continue
        stack.extend(ast.iter_child_nodes(node))


def _stmt_header(stmt: ast.stmt) -> List[ast.AST]:
    if isinstance(stmt, (ast.If, ast.While)):
        return [stmt.test]
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        return [stmt.iter]
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        return [item.context_expr for item in stmt.items]
    if isinstance(stmt, ast.Try) or (
            hasattr(ast, 'TryStar')
            and isinstance(stmt, getattr(ast, 'TryStar'))):
        return []
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef)):
        return []
    return [stmt]


class _Acquire:
    __slots__ = ('stmt', 'call', 'receiver', 'bound')

    def __init__(self, stmt: ast.stmt, call: ast.Call, receiver: str,
                 bound: Set[str]) -> None:
        self.stmt = stmt
        self.call = call
        self.receiver = receiver
        self.bound = bound  # names the acquire's result binds


def _mentions(expr: Optional[ast.AST], names: Set[str]) -> bool:
    if expr is None or not names:
        return False
    for n in ast.walk(expr):
        if isinstance(n, ast.Name) and n.id in names:
            return True
    return False


def _has_release_call(root: ast.AST, receiver: str) -> bool:
    for sub in ast.walk(root):
        if isinstance(sub, ast.Call):
            recv = _receiver_of(sub, _RELEASE_VERBS)
            if recv is not None and recv == receiver:
                return True
    return False


def _satisfies(stmt: ast.stmt, acq: _Acquire,
               line_text: str) -> bool:
    """Does executing `stmt` discharge the acquire's obligation?"""
    if acq.receiver in _marker_releases(line_text):
        return True
    # An `if` whose subtree releases the receiver counts as the
    # discharge ATTEMPT: the guard (`if pinned:` / `if matched.pages:`)
    # is usually correlated with whether the acquire ran at all —
    # branch-sensitivity the CFG cannot express. The path-blindness
    # tradeoff (a release hidden behind an unrelated rare condition
    # also satisfies) is documented; the exception-edge cases the
    # rule exists for never involve such a guard.
    if isinstance(stmt, ast.If) and _has_release_call(stmt,
                                                      acq.receiver):
        return True
    for node in _stmt_header(stmt):
        if _has_release_call(node, acq.receiver):
            return True
        # Hand-off into a callee that takes ownership by name:
        # cache.insert(..., pages) etc. is NOT assumed; use the
        # releases[...] marker for those.
    if isinstance(stmt, ast.Return) and _mentions(stmt.value,
                                                  acq.bound):
        return True
    if isinstance(stmt, ast.Assign):
        stores_tracked = any(
            isinstance(t, (ast.Attribute, ast.Subscript))
            for t in stmt.targets)
        if stores_tracked and _mentions(stmt.value, acq.bound):
            return True
    return False


@register
class ResourcePairingChecker(Checker):
    name = 'resource-pairing'
    description = ('acquire/reserve calls reach a release/publish on '
                   'every outgoing path, exception edges included')

    def check_file(self, pf: core.ParsedFile) -> Iterable[Finding]:
        findings: List[Finding] = []
        for fn in ast.walk(pf.tree):
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                findings.extend(self._check_fn(pf, fn))
        return findings

    def _line(self, pf: core.ParsedFile, stmt: ast.stmt) -> str:
        end = getattr(stmt, 'end_lineno', stmt.lineno)
        return ' '.join(pf.lines[stmt.lineno - 1:end])

    def _check_fn(self, pf: core.ParsedFile,
                  fn: ast.AST) -> Iterable[Finding]:
        acquires: List[_Acquire] = []
        for stmt in self._own_statements(fn):
            for root in _stmt_header(stmt):
                for sub in ast.walk(root):
                    if not isinstance(sub, ast.Call):
                        continue
                    recv = _receiver_of(sub, _ACQUIRE_VERBS)
                    if recv is None or _is_lockish(recv):
                        continue
                    bound = dataflow.assigned_names(stmt)
                    acquires.append(_Acquire(stmt, sub, recv, bound))
        if not acquires:
            return

        graph = pf.cfg(fn)
        for acq in acquires:
            line_of: Dict[int, str] = {}

            def satisfied(node) -> bool:
                if node.stmt is None:
                    return False
                text = line_of.get(node.index)
                if text is None:
                    text = self._line(pf, node.stmt)
                    line_of[node.index] = text
                return _satisfies(node.stmt, acq, text)

            # The acquire statement may itself discharge (same-line
            # release, `return self._alloc.reserve(n)`).
            if isinstance(acq.stmt, ast.Return) or _satisfies(
                    acq.stmt, acq, self._line(pf, acq.stmt)):
                continue
            exit_node, raise_node = graph.terminals()
            for start in graph.nodes_for(acq.stmt):
                # The acquire's OWN exception edge is exempt: if
                # acquire() raises, the pin was never taken.
                hit = dataflow.reach_avoiding(
                    start, {exit_node.index, raise_node.index},
                    blocked=satisfied, skip_start_exception=True)
                if hit is None:
                    continue
                via = ('an exception path'
                       if hit.index == raise_node.index
                       else 'a normal path')
                yield pf.finding(
                    self.name, 'unreleased-acquire', acq.stmt,
                    f'`{acq.receiver}.{acq.call.func.attr}(...)` can '
                    f'leave `{fn.name}` via {via} without a matching '
                    f'release/publish on `{acq.receiver}` — wrap the '
                    'region in try/except (releasing on error), move '
                    'the release into a finally, or annotate the '
                    f'hand-off line with `# skytpu-lint: '
                    f'releases[{acq.receiver}]`')
                break
        return

    @staticmethod
    def _own_statements(fn: ast.AST) -> Iterable[ast.stmt]:
        stack: List[ast.stmt] = list(fn.body)
        while stack:
            stmt = stack.pop()
            yield stmt
            if isinstance(stmt, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                continue
            for field in ('body', 'orelse', 'finalbody'):
                stack.extend(getattr(stmt, field, ()))
            for handler in getattr(stmt, 'handlers', ()):
                stack.extend(handler.body)
            for case in getattr(stmt, 'cases', ()):
                stack.extend(case.body)
