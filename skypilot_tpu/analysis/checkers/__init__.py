"""Built-in checkers. Importing this package registers all of them."""
from skypilot_tpu.analysis.checkers import async_discipline  # noqa: F401
from skypilot_tpu.analysis.checkers import donation_discipline  # noqa: F401
from skypilot_tpu.analysis.checkers import env_registry  # noqa: F401
from skypilot_tpu.analysis.checkers import fault_points  # noqa: F401
from skypilot_tpu.analysis.checkers import host_sync_budget  # noqa: F401
from skypilot_tpu.analysis.checkers import lock_coverage  # noqa: F401
from skypilot_tpu.analysis.checkers import lock_discipline  # noqa: F401
from skypilot_tpu.analysis.checkers import metrics_names  # noqa: F401
from skypilot_tpu.analysis.checkers import resource_pairing  # noqa: F401
from skypilot_tpu.analysis.checkers import trace_safety  # noqa: F401
