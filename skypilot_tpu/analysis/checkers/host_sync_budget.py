"""host-sync-budget: device->host syncs on annotated hot paths stay
within a declared per-function budget.

Opt-in via annotation — on the `def` line or the line directly above
it:

    # skytpu-lint: hot-path[1]
    def step(self): ...

Every device->host synchronization point inside the function then
counts against budget N along the WORST single execution path (CFG
acyclic max-path — branches don't double count, `if/else` with one
sync per arm costs 1, not 2):

  sync-budget   the worst path through the function performs more
                than N syncs — the PR 13 regression class (engine
                step must drain tokens+logprobs+emitted in exactly
                ONE jax.device_get; the runtime transfer-count tests
                catch it on the live path, this catches it in review).
  sync-in-loop  a sync inside a loop body: per-iteration cost is
                unbounded, no budget covers it.

What counts as a sync: jax.device_get, .item()/.tolist(),
.block_until_ready(), np.asarray/np.array on a non-literal, and
bool() of an array-shaped expression (name/attribute/subscript —
`bool(mask)` forces the value to host; `bool(flag_int)` inside a
hot-path function is noise worth renaming). Nested function bodies
are not counted — they do not run in this frame.
"""
import ast
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from skypilot_tpu.analysis import core, dataflow
from skypilot_tpu.analysis.core import Checker, Finding, register

HOT_PATH_RE = re.compile(r'skytpu-lint:\s*hot-path\[(\d+)\]')

_SYNC_METHODS = {'item', 'tolist', 'block_until_ready'}
_NUMPY_COERCIONS = {'np.asarray', 'np.array', 'numpy.asarray',
                    'numpy.array'}


def _sync_exprs(exprs: Iterable[ast.AST]) -> List[ast.AST]:
    """Sync points among `exprs` (nested function bodies excluded)."""
    out: List[ast.AST] = []
    stack: List[ast.AST] = [e for e in exprs if e is not None]
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))
        if not isinstance(node, ast.Call):
            continue
        name = core.dotted_name(node.func)
        if name is not None and name.split('.')[-1] == 'device_get':
            out.append(node)
        elif isinstance(node.func, ast.Attribute) and \
                node.func.attr in _SYNC_METHODS:
            out.append(node)
        elif name in _NUMPY_COERCIONS:
            if node.args and not isinstance(node.args[0],
                                            ast.Constant):
                out.append(node)
        elif name == 'bool' and len(node.args) == 1 and isinstance(
                node.args[0], (ast.Name, ast.Attribute,
                               ast.Subscript)):
            out.append(node)
    return out


def _stmt_scan_exprs(stmt: ast.stmt) -> List[ast.AST]:
    """The expressions a CFG node for `stmt` actually evaluates: the
    whole statement when simple, only the header when compound (the
    body belongs to other nodes)."""
    if isinstance(stmt, ast.If):
        return [stmt.test]
    if isinstance(stmt, ast.While):
        return [stmt.test]
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        return [stmt.iter]
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        return [item.context_expr for item in stmt.items]
    if isinstance(stmt, ast.Try) or (
            hasattr(ast, 'TryStar')
            and isinstance(stmt, getattr(ast, 'TryStar'))):
        return []
    if hasattr(ast, 'Match') and isinstance(stmt,
                                            getattr(ast, 'Match')):
        return [stmt.subject]
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef)):
        return []
    return [stmt]


def hot_path_budget(fn: ast.AST, lines: List[str]) -> Optional[int]:
    """The declared budget N when `fn` carries a hot-path[N]
    annotation on its def line or the line directly above."""
    lineno = getattr(fn, 'lineno', 0)
    for idx in (lineno - 1, lineno - 2):
        if 0 <= idx < len(lines):
            m = HOT_PATH_RE.search(lines[idx])
            if m:
                return int(m.group(1))
    return None


@register
class HostSyncBudgetChecker(Checker):
    name = 'host-sync-budget'
    description = ('device->host syncs on `# skytpu-lint: hot-path[N]`'
                   ' functions stay within the declared budget')

    def check_file(self, pf: core.ParsedFile) -> Iterable[Finding]:
        findings: List[Finding] = []
        for fn in ast.walk(pf.tree):
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                continue
            budget = hot_path_budget(fn, pf.lines)
            if budget is None:
                continue
            findings.extend(self._check_fn(pf, fn, budget))
        return findings

    def _check_fn(self, pf: core.ParsedFile, fn: ast.AST,
                  budget: int) -> Iterable[Finding]:
        graph = pf.cfg(fn)
        weight: Dict[int, int] = {}
        sync_stmts: Dict[int, ast.stmt] = {}
        for node in graph.nodes:
            if node.stmt is None:
                continue
            syncs = _sync_exprs(_stmt_scan_exprs(node.stmt))
            if syncs:
                weight[node.index] = len(syncs)
                sync_stmts[node.index] = node.stmt
        if not weight:
            return

        cyclic = graph.cyclic_nodes()
        looped: Set[int] = set()  # stmt ids already reported
        for idx, stmt in sync_stmts.items():
            if idx in cyclic and id(stmt) not in looped:
                looped.add(id(stmt))
                yield pf.finding(
                    self.name, 'sync-in-loop', stmt,
                    f'device->host sync inside a loop in hot-path '
                    f'`{fn.name}`: per-iteration cost is unbounded — '
                    'hoist the sync out of the loop (batch the '
                    'transfer) or drop the hot-path annotation')

        total, witness = dataflow.max_weight_path(graph, weight)
        if total > budget:
            sync_lines = sorted({n.lineno for n in witness})
            yield pf.finding(
                self.name, 'sync-budget', fn,
                f'hot-path `{fn.name}` declares budget '
                f'{budget} but its worst path performs {total} '
                f'device->host sync(s) (lines '
                f'{", ".join(map(str, sync_lines))}) — combine '
                'transfers into one jax.device_get of a tuple, or '
                'raise the declared budget if the cost is intended')
