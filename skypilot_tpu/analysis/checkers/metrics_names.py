"""metrics-names: the skytpu_* metric contract, migrated from the
bespoke tests/unit/test_metrics_lint.py into a checker.

Project-level (not AST): importing the instrument catalog registers
every hot-path metric in the default registry; the rules then assert
the naming/help/bucket contract over ALL of them, so a typo'd metric
name breaks CI instead of silently producing a series no alert
matches. test_metrics_lint.py remains as a thin wrapper so the
existing tier-1 test names survive.
"""
import math
import re
from typing import Iterable, List

from skypilot_tpu.analysis.core import Checker, Finding, Project, \
    register

_NAME_RE = re.compile(r'^skytpu_[a-z0-9_]+$')
_LABEL_RE = re.compile(r'^[a-z_][a-z0-9_]*$')
_CATALOG = 'skypilot_tpu/observability/instruments.py'


def findings_for_rule(rule: str) -> List[Finding]:
    """All findings for one sub-rule (the thin test wrappers key off
    this)."""
    project = Project(root='', files=[])
    return [f for f in MetricsNamesChecker().check_project(project)
            if f.rule == rule]


@register
class MetricsNamesChecker(Checker):
    name = 'metrics-names'
    description = ('skytpu_* metric naming/help/bucket/label contract '
                   'over the registered instrument catalog')

    def check_project(self, project: Project) -> Iterable[Finding]:
        from skypilot_tpu.observability import \
            instruments  # noqa: F401 — registers the catalog
        from skypilot_tpu.observability import metrics

        findings: List[Finding] = []

        def emit(rule: str, message: str) -> None:
            findings.append(Finding(
                check=self.name, rule=rule, path=_CATALOG, line=0,
                message=message, snippet=message))

        found = metrics.REGISTRY.metrics()
        if len(found) < 20:
            emit('catalog-present',
                 f'instrument catalog went missing ({len(found)} '
                 'metrics registered; expected >= 20)')
            return findings

        for m in found:
            if not _NAME_RE.fullmatch(m.name):
                emit('name-namespace',
                     f'{m.name}: metric names are skytpu_[a-z0-9_]+')
            if not (m.help and m.help.strip()) or \
                    len(m.help.strip()) < 10:
                emit('help-text',
                     f'{m.name}: help strings are sentences, not '
                     'stubs')
            if isinstance(m, metrics.Counter):
                if not m.name.endswith('_total'):
                    emit('counter-suffix',
                         f'{m.name}: Prometheus counters end in '
                         '_total')
            elif m.name.endswith('_total'):
                emit('counter-suffix',
                     f'{m.name}: _total is reserved for counters')
            if isinstance(m, metrics.Histogram):
                if not m.buckets:
                    emit('histogram-buckets',
                         f'{m.name}: histograms declare buckets')
                elif list(m.buckets) != sorted(set(m.buckets)):
                    emit('histogram-buckets',
                         f'{m.name}: buckets must be strictly '
                         'increasing')
                elif any(b == math.inf for b in m.buckets):
                    emit('histogram-buckets',
                         f'{m.name}: +Inf bucket is implicit')
                if not m.name.endswith(('_seconds', '_tokens',
                                        '_per_round')):
                    emit('histogram-buckets',
                         f'{m.name}: histograms name their unit '
                         'suffix (_seconds, _tokens, _per_round)')
            for label in m.labelnames:
                if not _LABEL_RE.fullmatch(label) or label == 'le':
                    emit('label-names',
                         f'{m.name}.{label}: invalid or reserved '
                         'label name')

        text = metrics.REGISTRY.generate_text()
        for line in text.strip().splitlines():
            if line.startswith('#'):
                if not re.match(
                        r'^# (HELP|TYPE) skytpu_[a-z0-9_]+ ', line):
                    emit('exposition', f'bad comment line: {line!r}')
                continue
            # Optional OpenMetrics exemplar suffix on histogram
            # bucket lines: `... 5 # {trace_id="<id>"} 0.042`.
            if not re.match(
                    r'^skytpu_[a-z0-9_]+(\{[^{}]*\})? '
                    r'([-+]?\d+(\.\d+)?([eE][-+]?\d+)?|\+Inf|-Inf|NaN)'
                    r'( # \{trace_id="[0-9a-zA-Z_-]+"\} '
                    r'([-+]?\d+(\.\d+)?([eE][-+]?\d+)?))?$',
                    line):
                emit('exposition', f'bad sample line: {line!r}')
            if ' # {' in line and '_bucket' not in line:
                emit('exposition',
                     f'exemplar on a non-bucket line: {line!r}')
        return findings
