"""env-registry: every SKYTPU_* knob declared once, read at call time.

Three rules:

  undeclared        a 'SKYTPU_*' string literal that names no variable
                    declared in skypilot_tpu/envs.py — knobs must be
                    enumerable (docs, tooling) from ONE place.
  import-time-read  any environment read executed at module scope.
                    Controllers are spawned and tests set env vars
                    after import; a module-level read freezes the
                    default forever (the SKYTPU_JOBS_RETRY_GAP trap).
  direct-read       os.environ/os.getenv with a SKYTPU_* literal
                    outside envs.py — the registry owns parsing and
                    defaults; ad-hoc reads reintroduce drift.

Declared names come from importing skypilot_tpu.envs (the registry is
the single source of truth, so the checker asks it, not a parallel
AST parse that could diverge).
"""
import ast
import re
from typing import FrozenSet, Iterable, List, Optional, Set

from skypilot_tpu.analysis import core
from skypilot_tpu.analysis.core import Checker, Finding, register

_ENV_NAME_RE = re.compile(r'^SKYTPU_[A-Z0-9_]+$')
_REGISTRY_REL = 'skypilot_tpu/envs.py'


def _declared_names() -> FrozenSet[str]:
    from skypilot_tpu import envs
    return envs.declared_names()


def _is_environ_read(node: ast.AST) -> Optional[ast.AST]:
    """The env-name argument node if `node` reads the environment
    (os.environ.get/os.getenv call, or os.environ[...] subscript in a
    load context), else None."""
    if isinstance(node, ast.Call):
        name = core.dotted_name(node.func)
        if name is None:
            return None
        if name.endswith('environ.get') or name.split('.')[-1] == \
                'getenv':
            return node.args[0] if node.args else node
        return None
    if isinstance(node, ast.Subscript) and isinstance(node.ctx,
                                                      ast.Load):
        name = core.dotted_name(node.value)
        if name is not None and name.endswith('environ'):
            return node.slice
        return None
    return None


def _is_registry_read(node: ast.AST) -> bool:
    """envs.SKYTPU_X.get(...) / .raw() / .is_set() call."""
    if not isinstance(node, ast.Call):
        return False
    name = core.dotted_name(node.func)
    if name is None:
        return False
    parts = name.split('.')
    return (len(parts) >= 3 and parts[-1] in ('get', 'raw', 'is_set')
            and _ENV_NAME_RE.fullmatch(parts[-2]) is not None)


def _module_scope_nodes(tree: ast.AST) -> Iterable[ast.AST]:
    """Every node reachable at import time: module-level statements
    and class bodies, but not function/lambda BODIES. Decorator
    expressions and parameter defaults DO execute at import — a read
    frozen into `def f(gap=envs.X.get())` is exactly the trap this
    rule exists for — so those subtrees are walked."""
    stack: List[ast.AST] = list(ast.iter_child_nodes(tree))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            if not isinstance(node, ast.Lambda):
                stack.extend(node.decorator_list)
            stack.extend(node.args.defaults)
            stack.extend(d for d in node.args.kw_defaults
                         if d is not None)
            continue  # the body itself is deferred to call time
        stack.extend(ast.iter_child_nodes(node))


def _docstring_linenos(tree: ast.AST) -> Set[int]:
    """Line spans of docstrings (their SKYTPU_ mentions are prose)."""
    out: Set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.Module, ast.FunctionDef,
                             ast.AsyncFunctionDef, ast.ClassDef)):
            body = node.body
            if body and isinstance(body[0], ast.Expr) and isinstance(
                    body[0].value, ast.Constant) and isinstance(
                    body[0].value.value, str):
                doc = body[0].value
                end = getattr(doc, 'end_lineno', doc.lineno)
                out.update(range(doc.lineno, end + 1))
    return out


@register
class EnvRegistryChecker(Checker):
    name = 'env-registry'
    description = ('SKYTPU_* vars declared once in envs.py and read '
                   'at call time through the registry')

    def check_file(self, pf: core.ParsedFile) -> Iterable[Finding]:
        tree = pf.tree
        findings: List[Finding] = []
        rel_posix = pf.rel.replace('\\', '/')
        in_registry = (rel_posix.endswith(_REGISTRY_REL)
                       or rel_posix == 'envs.py')
        declared = _declared_names()
        doc_lines = _docstring_linenos(tree)

        def emit(node: ast.AST, rule: str, message: str) -> None:
            findings.append(pf.finding(self.name, rule, node, message))

        # import-time-read: anything env-shaped at module scope.
        for node in _module_scope_nodes(tree):
            if _is_environ_read(node) is not None or \
                    _is_registry_read(node):
                emit(node, 'import-time-read',
                     'environment read at import time freezes the '
                     'value before controllers/tests can set it; '
                     'read inside the function that uses it')

        for node in ast.walk(tree):
            # undeclared: exact SKYTPU_* literals must be registered.
            if isinstance(node, ast.Constant) and isinstance(
                    node.value, str) and _ENV_NAME_RE.fullmatch(
                    node.value):
                if in_registry or node.lineno in doc_lines:
                    continue
                if node.value not in declared:
                    emit(node, 'undeclared',
                         f'{node.value} is not declared in '
                         'skypilot_tpu/envs.py; declare it (name, '
                         'type, default, doc) before reading it')
            # direct-read: SKYTPU literals must go through the
            # registry, which owns parsing and defaults.
            if not in_registry:
                arg = _is_environ_read(node)
                if arg is not None and isinstance(arg, ast.Constant) \
                        and isinstance(arg.value, str) and \
                        _ENV_NAME_RE.fullmatch(arg.value):
                    emit(node, 'direct-read',
                         f'read {arg.value} through '
                         f'envs.{arg.value}.get() so parsing and '
                         'defaults stay centralized')
        return findings
