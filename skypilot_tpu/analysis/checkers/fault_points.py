"""fault-points: the chaos-injection catalog contract, migrated from
the bespoke tests/unit/test_fault_points_lint.py into a checker.

Project-level: importing skypilot_tpu.resilience.faults registers the
whole catalog; the rules assert naming/documentation over ALL of it —
a typo'd point name would otherwise silently never fire, and an
undocumented one is undiscoverable to chaos drills.
test_fault_points_lint.py remains as a thin wrapper so the existing
tier-1 test names survive.
"""
import os
import re
from typing import Iterable, List

from skypilot_tpu.analysis.core import Checker, Finding, Project, \
    register

_CATALOG = 'skypilot_tpu/resilience/faults.py'
_GUIDE = os.path.join('docs', 'guides', 'resilience.md')


def findings_for_rule(rule: str, root: str) -> List[Finding]:
    """All findings for one sub-rule (the thin test wrappers key off
    this)."""
    project = Project(root=root, files=[])
    return [f for f in FaultPointsChecker().check_project(project)
            if f.rule == rule]


@register
class FaultPointsChecker(Checker):
    name = 'fault-points'
    description = ('fault-injection point naming + guide '
                   'documentation contract over the registered '
                   'catalog')

    def check_project(self, project: Project) -> Iterable[Finding]:
        from skypilot_tpu.resilience import faults
        root = project.root

        findings: List[Finding] = []

        def emit(rule: str, message: str, path: str = _CATALOG) -> None:
            findings.append(Finding(
                check=self.name, rule=rule, path=path, line=0,
                message=message, snippet=message))

        points = faults.registered_points()
        if len(points) < 5:
            emit('catalog-present',
                 f'fault-point catalog went missing ({len(points)} '
                 'points registered; expected >= 5)')
            return findings

        for name, desc in points.items():
            if not faults.POINT_RE.fullmatch(name):
                emit('point-name',
                     f'{name}: fault points are dotted '
                     'plane.operation names')
            if not desc or len(desc.strip()) < 10:
                emit('point-description',
                     f'{name}: describe the failure the point '
                     'injects')

        guide_path = os.path.join(root, _GUIDE)
        try:
            with open(guide_path, encoding='utf-8') as f:
                text = f.read()
        except OSError:
            emit('point-documented',
                 f'{_GUIDE} is missing; fault points must stay '
                 'discoverable', path=_GUIDE.replace(os.sep, '/'))
            return findings
        for point in points:
            if f'`{point}`' not in text:
                emit('point-documented',
                     f'{point} undocumented in {_GUIDE}; injection '
                     'points stay discoverable as they spread')
        table = re.findall(r'^\| `([a-z][a-z0-9_.]*)` \|', text,
                           flags=re.MULTILINE)
        if not table:
            emit('doc-ghost', 'guide lost its fault-point table',
                 path=_GUIDE.replace(os.sep, '/'))
        else:
            registered = set(points)
            for p in table:
                if '.' in p and p not in registered:
                    emit('doc-ghost',
                         f'guide documents unknown fault point {p}',
                         path=_GUIDE.replace(os.sep, '/'))
        return findings
