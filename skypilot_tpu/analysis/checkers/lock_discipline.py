"""lock-discipline: shared module state mutates only under the
module's lock.

Applies to modules that define a module-level threading.Lock/RLock
(the serve/controller state pattern: serve_state.py, jobs/state.py,
requests_db.py, state.py, ...). Two rules:

  sqlite-write-outside-lock  .execute()/.executemany() with a literal
                             write statement (INSERT/UPDATE/DELETE/
                             REPLACE/CREATE/ALTER/DROP) lexically
                             outside `with <lock>`. The connections are
                             shared across the API server's threads;
                             an unlocked write interleaves with
                             another thread's write+commit pair.
  global-write-outside-lock  a function rebinding module globals
                             (`global x; x = ...`) outside
                             `with <lock>`.

Functions that rebind the lock itself are exempt: you cannot hold a
lock you are replacing (the os.register_at_fork child handlers — the
child is single-threaded by construction).
"""
import ast
from typing import Iterable, List, Optional, Set

from skypilot_tpu.analysis import core
from skypilot_tpu.analysis.core import Checker, Finding, register

_WRITE_PREFIXES = ('INSERT', 'UPDATE', 'DELETE', 'REPLACE', 'CREATE',
                   'ALTER', 'DROP')


def _module_locks(tree: ast.Module) -> Set[str]:
    """Module-level names bound to threading.Lock()/RLock()."""
    locks: Set[str] = set()
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        value = node.value
        if not isinstance(value, ast.Call):
            continue
        name = core.dotted_name(value.func)
        if name is None or name.split('.')[-1] not in ('Lock', 'RLock'):
            continue
        for t in node.targets:
            if isinstance(t, ast.Name):
                locks.add(t.id)
    return locks


def _under_lock(node: ast.AST, locks: Set[str]) -> bool:
    """Is `node` lexically inside `with <lock>` for a module lock
    (directly, or via a local alias of self._lock-style attributes
    whose terminal name is a module lock name)?"""
    current = getattr(node, 'skytpu_parent', None)
    while current is not None:
        if isinstance(current, ast.With):
            for item in current.items:
                expr = item.context_expr
                # with _lock:  /  with _lock, other:  /  with x._lock:
                name = core.dotted_name(expr)
                if name is not None and name.split('.')[-1] in locks:
                    return True
        if isinstance(current, (ast.FunctionDef,
                                ast.AsyncFunctionDef)) and \
                _is_locked_helper(current):
            return True
        current = getattr(current, 'skytpu_parent', None)
    return False


def _is_locked_helper(fn: ast.AST) -> bool:
    """Helpers named *_locked declare (and document) that the caller
    holds the lock — the convention serve_state/usage_lib already
    use."""
    return getattr(fn, 'name', '').endswith('_locked')


@register
class LockDisciplineChecker(Checker):
    name = 'lock-discipline'
    description = ('shared module state (sqlite writes, globals) '
                   'mutated only under the module lock')

    def check_file(self, pf: core.ParsedFile) -> Iterable[Finding]:
        tree = pf.tree
        if not isinstance(tree, ast.Module):
            return ()
        locks = _module_locks(tree)
        if not locks:
            return ()
        findings: List[Finding] = []

        def emit(node: ast.AST, rule: str, message: str) -> None:
            findings.append(pf.finding(self.name, rule, node, message))

        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and isinstance(
                    node.func, ast.Attribute) and node.func.attr in (
                    'execute', 'executemany', 'executescript'):
                sql = node.args[0] if node.args else None
                if isinstance(sql, ast.Constant) and isinstance(
                        sql.value, str) and sql.value.lstrip().upper(
                        ).startswith(_WRITE_PREFIXES):
                    if not _under_lock(node, locks):
                        emit(node, 'sqlite-write-outside-lock',
                             'sqlite write outside `with '
                             f'{sorted(locks)[0]}`: the connection is '
                             'shared across server threads, so an '
                             'unlocked write interleaves with another '
                             "thread's write+commit")

        for fn in ast.walk(tree):
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                continue
            globals_declared: Set[str] = set()
            for node in fn.body:
                if isinstance(node, ast.Global):
                    globals_declared.update(node.names)
            if not globals_declared:
                continue
            if globals_declared & locks:
                # Rebinding the lock itself (fork-child handlers):
                # you cannot hold a lock you are replacing.
                continue
            stack: List[ast.AST] = list(fn.body)
            while stack:
                node = stack.pop()
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef,
                                     ast.Lambda)):
                    continue  # nested scope: its own global decls rule
                stack.extend(ast.iter_child_nodes(node))
                if isinstance(node, ast.Assign):
                    hit = [n.id for t in node.targets
                           for n in ast.walk(t)
                           if isinstance(n, ast.Name)
                           and n.id in globals_declared]
                    if hit and not _under_lock(node, locks):
                        emit(node, 'global-write-outside-lock',
                             f'global `{hit[0]}` rebound outside '
                             f'`with {sorted(locks)[0]}`; another '
                             'thread can observe the torn update')
        return findings
