"""trace-safety: what must not happen inside jax-traced code.

JAX runs the Python body of a jitted/shard_mapped/lax-control-flow
function ONCE, at trace time, with abstract tracers. Three classes of
bug follow, all silent until a recompile or a wrong number:

  host-call         print/time/file/network I/O runs at trace time
                    (once, not per step) or crashes under a tracer —
                    either way it is not doing what the author meant.
  tracer-coercion   .item()/.tolist()/float()/int()/np.asarray on a
                    traced value forces a host sync (or a trace-time
                    ConcretizationTypeError on data-dependent values).
  closure-mutation  assigning through a closed-over/global name from
                    inside traced code bakes the trace-time value in;
                    the mutation happens once, not per call.

Trace scopes are found statically: functions decorated with
jax.jit/pjit (directly or via functools.partial), functions passed to
jit/pjit/shard_map/vmap/pmap/grad, and bodies handed to
lax.scan/while_loop/fori_loop/cond/switch. Parameters named in literal
`static_argnames` are exempt from tracer-coercion (they are real
Python values, not tracers).
"""
import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from skypilot_tpu.analysis import core
from skypilot_tpu.analysis.core import Checker, Finding, register

# Terminal attribute names that mean "the callable argument(s) get
# traced". Value: positional indices of the traced callables.
_WRAPPERS: Dict[str, Tuple[int, ...]] = {
    'jit': (0,),
    'pjit': (0,),
    'shard_map': (0,),
    'vmap': (0,),
    'pmap': (0,),
    'grad': (0,),
    'value_and_grad': (0,),
    'remat': (0,),
    'checkpoint': (0,),
    'scan': (0,),
    'while_loop': (0, 1),
    'fori_loop': (2,),
    'cond': (1, 2),
    'switch': (1, 2, 3, 4, 5),
}
# Bare-name calls are ambiguous ('scan' could be anything); only these
# are unmistakable without a jax/lax prefix.
_BARE_WRAPPERS = {'jit', 'pjit', 'shard_map'}

_HOST_CALLS = {
    'print', 'input', 'breakpoint', 'open',
    'time.time', 'time.sleep', 'time.monotonic', 'time.perf_counter',
    'time.process_time',
    'os.getenv', 'os.system', 'os.environ.get',
    'urllib.request.urlopen', 'socket.create_connection',
    'socket.socket', 'subprocess.run', 'subprocess.Popen',
    'subprocess.check_output', 'subprocess.check_call',
}
_HOST_PREFIXES = ('requests.',)

_COERCION_METHODS = {'item', 'tolist'}
_COERCION_CALLS = {'float', 'int', 'bool', 'complex'}
_NUMPY_COERCIONS = {'np.asarray', 'np.array', 'numpy.asarray',
                    'numpy.array'}

# NOTE: 'update' is deliberately absent — it is the name of optax's
# PURE GradientTransformation.update (trainer step functions call it
# on a closed-over transform), and dict.update through a closure is
# caught by the assignment rule in practice.
_MUTATING_METHODS = {'append', 'extend', 'insert', 'remove', 'pop',
                     'clear', 'add', 'setdefault', 'popitem',
                     'discard'}


def _is_wrapper(func: ast.AST) -> Optional[Tuple[int, ...]]:
    """Positional indices of traced callables if `func` is a jax
    tracing wrapper, else None."""
    name = core.dotted_name(func)
    if name is None:
        return None
    parts = name.split('.')
    leaf = parts[-1]
    if leaf not in _WRAPPERS:
        return None
    if len(parts) == 1:
        return _WRAPPERS[leaf] if leaf in _BARE_WRAPPERS else None
    # Require a jax-ish qualifier: jax.jit, jax.lax.scan, lax.scan,
    # jax.experimental.shard_map.shard_map ... but not self.scan().
    if any(p in ('jax', 'lax', 'pjit', 'shard_map') for p in parts[:-1]):
        return _WRAPPERS[leaf]
    return None


def _partial_wrapped(call: ast.Call) -> Optional[ast.Call]:
    """functools.partial(jax.jit, ...) -> a synthetic view of the
    inner wrapper call (so static_argnames kwargs are readable)."""
    name = core.dotted_name(call.func)
    if name not in ('functools.partial', 'partial'):
        return None
    if not call.args:
        return None
    inner = call.args[0]
    if _is_wrapper(inner) is None:
        return None
    synthetic = ast.Call(func=inner, args=[], keywords=call.keywords)
    return synthetic


def _static_params(call: Optional[ast.Call]) -> Set[str]:
    """Literal static_argnames from a jit call, best-effort."""
    if call is None:
        return set()
    for kw in call.keywords:
        if kw.arg != 'static_argnames':
            continue
        value = kw.value
        if isinstance(value, ast.Constant) and isinstance(value.value,
                                                         str):
            return {value.value}
        if isinstance(value, (ast.Tuple, ast.List)):
            return {e.value for e in value.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str)}
    return set()


class _ScopeIndex:
    """Map function/lambda nodes to the trace scopes they define."""

    def __init__(self, tree: ast.AST) -> None:
        self.by_name: Dict[str, List[ast.AST]] = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.by_name.setdefault(node.name, []).append(node)
        # node -> static params for that trace entry
        self.traced: Dict[ast.AST, Set[str]] = {}

    def mark(self, target: ast.AST, static: Set[str]) -> None:
        if isinstance(target, ast.Lambda) or isinstance(
                target, (ast.FunctionDef, ast.AsyncFunctionDef)):
            prev = self.traced.get(target, set())
            self.traced[target] = prev | static
        elif isinstance(target, ast.Name):
            for fn in self.by_name.get(target.id, []):
                prev = self.traced.get(fn, set())
                self.traced[fn] = prev | static


def _collect_trace_scopes(tree: ast.AST) -> Dict[ast.AST, Set[str]]:
    index = _ScopeIndex(tree)
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for deco in node.decorator_list:
                if _is_wrapper(deco) is not None:
                    index.mark(node, set())
                elif isinstance(deco, ast.Call):
                    synthetic = _partial_wrapped(deco)
                    if synthetic is not None:
                        index.mark(node, _static_params(synthetic))
                    elif _is_wrapper(deco.func) is not None:
                        index.mark(node, _static_params(deco))
        if isinstance(node, ast.Call):
            indices = _is_wrapper(node.func)
            if indices is None:
                continue
            static = _static_params(node)
            for i in indices:
                if i < len(node.args):
                    index.mark(node.args[i], static)
    return index.traced


def _param_names(fn: ast.AST) -> Set[str]:
    args = fn.args
    names = {a.arg for a in args.args + args.kwonlyargs
             + getattr(args, 'posonlyargs', [])}
    if args.vararg:
        names.add(args.vararg.arg)
    if args.kwarg:
        names.add(args.kwarg.arg)
    return names


def _bound_names(fn: ast.AST) -> Set[str]:
    """Names bound anywhere inside `fn`: params, assignments, loop
    targets, withitems, comprehension targets, nested def names."""
    bound = _param_names(fn)

    def visit_target(t: ast.AST) -> None:
        # Only Store-context Names BIND: `cache[k] = v` mutates cache
        # (Load on the base) without binding it.
        for n in ast.walk(t):
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store):
                bound.add(n.id)

    for node in ast.walk(fn):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            bound.add(node.name)
            bound |= _param_names(node)
        elif isinstance(node, ast.Lambda):
            bound |= _param_names(node)
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                visit_target(t)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign,
                               ast.For, ast.AsyncFor)):
            visit_target(node.target)
        elif isinstance(node, (ast.withitem,)):
            if node.optional_vars is not None:
                visit_target(node.optional_vars)
        elif isinstance(node, ast.comprehension):
            visit_target(node.target)
        elif isinstance(node, ast.ExceptHandler) and node.name:
            bound.add(node.name)
    return bound


def _base_name(node: ast.AST) -> Optional[str]:
    """Leftmost Name of a Subscript/Attribute chain."""
    while isinstance(node, (ast.Subscript, ast.Attribute)):
        node = node.value
    if isinstance(node, ast.Name):
        return node.id
    return None


@register
class TraceSafetyChecker(Checker):
    name = 'trace-safety'
    description = ('host effects, tracer-to-host coercions, and '
                   'closure mutation inside jax-traced code')

    def check_file(self, pf: core.ParsedFile) -> Iterable[Finding]:
        traced = _collect_trace_scopes(pf.tree)
        findings: List[Finding] = []
        seen: Set[Tuple[int, int, str]] = set()

        def emit(node: ast.AST, rule: str, message: str) -> None:
            key = (node.lineno, node.col_offset, rule)
            if key in seen:
                return
            seen.add(key)
            findings.append(pf.finding(self.name, rule, node, message))

        for fn, static in traced.items():
            params = _param_names(fn) - static
            bound = _bound_names(fn)
            fn_name = getattr(fn, 'name', '<lambda>')
            for node in ast.walk(fn):
                self._check_node(node, fn_name, params, bound, static,
                                 emit)
        return findings

    def _check_node(self, node: ast.AST, fn_name: str,
                    tracer_params: Set[str], bound: Set[str],
                    static: Set[str], emit) -> None:
        if isinstance(node, ast.Call):
            name = core.dotted_name(node.func)
            if name in _HOST_CALLS or (
                    name and name.startswith(_HOST_PREFIXES)):
                emit(node, 'host-call',
                     f'{name}() inside traced `{fn_name}` runs at '
                     'trace time (once), not per step — hoist it out '
                     'of the traced function')
            elif name in _NUMPY_COERCIONS:
                args = node.args
                if args and isinstance(args[0], ast.Name) and \
                        args[0].id in tracer_params:
                    emit(node, 'tracer-coercion',
                         f'{name}({args[0].id}) forces the traced '
                         'value to host; use jnp, or mark the arg '
                         'static')
            elif name in _COERCION_CALLS:
                args = node.args
                if len(args) == 1 and isinstance(args[0], ast.Name) \
                        and args[0].id in tracer_params:
                    emit(node, 'tracer-coercion',
                         f'{name}({args[0].id}) on a traced value '
                         'raises ConcretizationTypeError (or silently '
                         'bakes in the trace-time value); mark the '
                         'parameter static or keep it a jnp array')
            elif isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _COERCION_METHODS:
                emit(node, 'tracer-coercion',
                     f'.{node.func.attr}() inside traced `{fn_name}` '
                     'forces a device->host sync per trace; return '
                     'the array instead')
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _MUTATING_METHODS:
                base = _base_name(node.func.value)
                if base is not None and base not in bound:
                    emit(node, 'closure-mutation',
                         f'.{node.func.attr}() mutates closed-over '
                         f'`{base}` inside traced `{fn_name}`; the '
                         'mutation happens once at trace time')
        elif isinstance(node, (ast.Global, ast.Nonlocal)):
            emit(node, 'closure-mutation',
                 f'{type(node).__name__.lower()} inside traced '
                 f'`{fn_name}`: rebinding outer state from traced '
                 'code happens at trace time, not per call')
        elif isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                if isinstance(t, (ast.Subscript, ast.Attribute)):
                    base = _base_name(t)
                    if base is not None and base not in bound:
                        emit(node, 'closure-mutation',
                             f'assignment through closed-over '
                             f'`{base}` inside traced `{fn_name}` '
                             'is a trace-time effect')
