"""CLI for skytpu-lint: `python -m skypilot_tpu.analysis`.

Exit codes: 0 clean (or baselined-only), 1 new findings, 2 usage
error. `--write-baseline` accepts the current findings as debt (and
prunes fixed entries); the gate then fails only on NEW findings.
"""
import argparse
import json
import sys
from typing import List, Optional, Sequence

from skypilot_tpu.analysis import baseline as baseline_lib
from skypilot_tpu.analysis import core


def _parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog='python -m skypilot_tpu.analysis',
        description='skytpu-lint: AST-based static analysis CI gate.')
    p.add_argument('paths', nargs='*',
                   help='files/dirs to scan (default: skypilot_tpu/)')
    p.add_argument('--checks',
                   help='comma-separated checker names '
                        '(default: all; see --list-checks)')
    p.add_argument('--format', choices=('text', 'json'),
                   default='text')
    p.add_argument('--baseline',
                   help='baseline file (default: '
                        f'<repo>/{baseline_lib.DEFAULT_BASENAME})')
    p.add_argument('--no-baseline', action='store_true',
                   help='report every finding, baselined or not')
    p.add_argument('--write-baseline', action='store_true',
                   help='accept current findings as the new baseline')
    p.add_argument('--list-checks', action='store_true')
    return p


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = _parser().parse_args(argv)
    root = core.repo_root()

    if args.list_checks:
        for name, cls in sorted(core.all_checkers().items()):
            print(f'{name:18s} {cls.description}')
        return 0

    checks = None
    if args.checks:
        checks = [c.strip() for c in args.checks.split(',')
                  if c.strip()]
    try:
        findings, suppressed = core.run(paths=args.paths or None,
                                        checks=checks, root=root)
    except ValueError as e:
        print(f'error: {e}', file=sys.stderr)
        return 2

    baseline_path = args.baseline or baseline_lib.default_path(root)
    if args.write_baseline:
        baseline_lib.write(baseline_path, findings)
        print(f'wrote {len(findings)} finding(s) to {baseline_path}')
        return 0

    try:
        entries = {} if args.no_baseline else baseline_lib.load(
            baseline_path)
    except ValueError as e:  # covers json.JSONDecodeError
        print(f'error: bad baseline {baseline_path}: {e}',
              file=sys.stderr)
        return 2
    new, baselined = baseline_lib.partition(findings, entries)

    if args.format == 'json':
        print(json.dumps({
            'new': [f.to_dict() for f in new],
            'baselined': [f.to_dict() for f in baselined],
            'suppressed_count': suppressed,
            'checks': sorted(checks or core.all_checkers()),
        }, indent=1))
    else:
        for f in new:
            print(f'{f.location()}: [{f.check}/{f.rule}] {f.message}')
            if f.snippet:
                print(f'    {f.snippet}')
        summary = (f'{len(new)} new finding(s), {len(baselined)} '
                   f'baselined, {suppressed} inline-suppressed')
        print(summary)
    return 1 if new else 0


if __name__ == '__main__':  # pragma: no cover
    sys.exit(main())
