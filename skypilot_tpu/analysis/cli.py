"""CLI for skytpu-lint: `python -m skypilot_tpu.analysis`.

Exit codes: 0 clean (or baselined-only), 1 new findings, 2 usage
error. `--write-baseline` accepts the current findings as debt (and
prunes fixed entries); the gate then fails only on NEW findings.
`--changed-only` narrows the scan to files touched since a git base
ref — the fast pre-gate pass in tests/run_full.sh; `--format github`
emits ::error workflow annotations.
"""
import argparse
import json
import os
import subprocess
import sys
from typing import List, Optional, Sequence

from skypilot_tpu.analysis import baseline as baseline_lib
from skypilot_tpu.analysis import core


def _parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog='python -m skypilot_tpu.analysis',
        description='skytpu-lint: flow-aware static analysis CI gate.')
    p.add_argument('paths', nargs='*',
                   help='files/dirs to scan (default: skypilot_tpu/)')
    p.add_argument('--checks',
                   help='comma-separated checker names '
                        '(default: all; see --list-checks)')
    p.add_argument('--format', choices=('text', 'json', 'github'),
                   default='text')
    p.add_argument('--baseline',
                   help='baseline file (default: '
                        f'<repo>/{baseline_lib.DEFAULT_BASENAME})')
    p.add_argument('--no-baseline', action='store_true',
                   help='report every finding, baselined or not')
    p.add_argument('--write-baseline', action='store_true',
                   help='accept current findings as the new baseline')
    p.add_argument('--migrate-baseline', action='store_true',
                   help='rewrite a v1 baseline in place as v2 '
                        '(statement-text fingerprints), keeping counts')
    p.add_argument('--changed-only', nargs='?', const='HEAD',
                   metavar='BASE_REF',
                   help='lint only .py files changed vs BASE_REF '
                        '(git diff --name-only; default HEAD). '
                        'Exits 0 when nothing relevant changed.')
    p.add_argument('--list-checks', action='store_true')
    return p


def changed_files(root: str, base_ref: str) -> Optional[List[str]]:
    """Repo files changed vs base_ref (staged, unstaged, and — for a
    non-HEAD ref — committed), or None when git itself fails (caller
    falls back to a full scan rather than silently passing).

    Filtered to the default scan surface (skypilot_tpu/) so the
    changed-only pass is a faster-but-equivalent subset of the full
    gate — it must never flag a file the full gate doesn't lint."""
    try:
        proc = subprocess.run(
            ['git', 'diff', '--name-only', base_ref, '--'],
            cwd=root, capture_output=True, text=True, timeout=60)
    except (OSError, subprocess.TimeoutExpired):
        return None
    if proc.returncode != 0:
        return None
    surface = os.path.join(root, 'skypilot_tpu') + os.sep
    out: List[str] = []
    for rel in proc.stdout.splitlines():
        rel = rel.strip()
        if not rel.endswith('.py'):
            continue
        path = os.path.join(root, rel)
        if not path.startswith(surface):
            continue
        if os.path.exists(path):  # deleted files need no lint
            out.append(path)
    return out


def _emit_github(findings: Sequence[core.Finding]) -> None:
    for f in findings:
        # %0A is the workflow-command newline escape.
        msg = f'[{f.check}/{f.rule}] {f.message}'.replace(
            '\n', '%0A')
        line = f',line={f.line}' if f.line else ''
        print(f'::error file={f.path}{line},'
              f'title=skytpu-lint {f.check}/{f.rule}::{msg}')


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = _parser().parse_args(argv)
    root = core.repo_root()

    if args.list_checks:
        for name, cls in sorted(core.all_checkers().items()):
            print(f'{name:18s} {cls.description}')
        return 0

    checks = None
    if args.checks:
        checks = [c.strip() for c in args.checks.split(',')
                  if c.strip()]

    paths = args.paths or None
    if args.changed_only:
        if paths:
            print('error: --changed-only and explicit paths are '
                  'mutually exclusive', file=sys.stderr)
            return 2
        changed = changed_files(root, args.changed_only)
        if changed is None:
            print('skytpu-lint: git diff failed; falling back to a '
                  'full scan', file=sys.stderr)
        elif not changed:
            print('skytpu-lint: no changed .py files vs '
                  f'{args.changed_only}; nothing to lint')
            return 0
        else:
            paths = changed

    try:
        findings, suppressed = core.run(paths=paths, checks=checks,
                                        root=root)
    except ValueError as e:
        print(f'error: {e}', file=sys.stderr)
        return 2

    baseline_path = args.baseline or baseline_lib.default_path(root)
    if args.migrate_baseline:
        try:
            carried = baseline_lib.migrate(baseline_path, findings)
        except ValueError as e:
            print(f'error: {e}', file=sys.stderr)
            return 2
        if carried < 0:
            print(f'{baseline_path}: already current; nothing to do')
        else:
            print(f'migrated {baseline_path} to v2 '
                  f'({carried} entr{"y" if carried == 1 else "ies"} '
                  'carried over)')
        return 0
    if args.write_baseline:
        baseline_lib.write(baseline_path, findings)
        print(f'wrote {len(findings)} finding(s) to {baseline_path}')
        return 0

    try:
        entries = {} if args.no_baseline else baseline_lib.load(
            baseline_path)
    except ValueError as e:  # covers json.JSONDecodeError
        print(f'error: bad baseline {baseline_path}: {e}',
              file=sys.stderr)
        return 2
    new, baselined = baseline_lib.partition(findings, entries)

    if args.format == 'json':
        print(json.dumps({
            'new': [f.to_dict() for f in new],
            'baselined': [f.to_dict() for f in baselined],
            'suppressed_count': suppressed,
            'checks': sorted(checks or core.all_checkers()),
        }, indent=1))
    elif args.format == 'github':
        _emit_github(new)
        print(f'{len(new)} new finding(s), {len(baselined)} '
              f'baselined, {suppressed} inline-suppressed')
    else:
        for f in new:
            print(f'{f.location()}: [{f.check}/{f.rule}] {f.message}')
            if f.snippet:
                print(f'    {f.snippet}')
        summary = (f'{len(new)} new finding(s), {len(baselined)} '
                   f'baselined, {suppressed} inline-suppressed')
        print(summary)
    return 1 if new else 0


if __name__ == '__main__':  # pragma: no cover
    sys.exit(main())
