"""Checker plugin API and file-walking runner for skytpu-lint.

v2 (flow-aware): the runner parses every file ONCE into a
`ParsedFile` (tree + source + lazily built, memoized per-function
CFGs) and hands the same object to every checker — ten checkers, one
parse, one CFG per function regardless of how many rules walk it.
Checkers see each file (`check_file(pf)`) and/or the whole project at
the end (`check_project(project)`, for contracts that live in runtime
registries rather than syntax — metrics catalog, fault points).

Findings are plain data; fingerprints are content-based (check + rule
+ path + normalized STATEMENT text, never line numbers) so the
committed baseline survives unrelated edits above a finding and
reformatting within one.
"""
import ast
import dataclasses
import hashlib
import os
from typing import Dict, Iterable, List, Optional, Sequence, Tuple, Type

from skypilot_tpu.analysis import cfg as cfg_mod

# Inline escape hatch: a finding whose source line carries
# `skytpu-lint: ignore[<rule-or-check>, ...]` is suppressed. Use it for
# the rare deliberate violation (e.g. fork handlers replacing a lock);
# use the baseline for bulk pre-existing debt.
SUPPRESS_MARKER = 'skytpu-lint: ignore['

# Total ast.parse calls made by run() since import — the lint bench
# asserts a full check_project pass parses each file exactly once
# (PR 3's trace_safety/lock_discipline each re-parsed on their own).
PARSE_CALLS = 0

# Filled in by run(): files scanned / parsed, CFG requests vs actual
# builds (requests > builds proves the per-file memoization works).
LAST_RUN_STATS: Dict[str, int] = {}


@dataclasses.dataclass(frozen=True)
class Finding:
    check: str      # checker name, e.g. 'trace-safety'
    rule: str       # sub-rule, e.g. 'host-call'
    path: str       # repo-relative, forward slashes
    line: int       # 1-based; 0 for project-level findings
    message: str
    snippet: str = ''    # stripped source line (display)
    statement: str = ''  # normalized enclosing statement (fingerprint)

    def fingerprint(self) -> str:
        basis = '|'.join((self.check, self.rule, self.path,
                          self.statement or self.snippet
                          or self.message))
        return hashlib.sha1(basis.encode()).hexdigest()[:16]

    def legacy_fingerprint(self) -> str:
        """The v1 (pre-statement) fingerprint — baseline migration
        matches old entries through this."""
        basis = '|'.join((self.check, self.rule, self.path,
                          self.snippet or self.message))
        return hashlib.sha1(basis.encode()).hexdigest()[:16]

    def location(self) -> str:
        return f'{self.path}:{self.line}' if self.line else self.path

    def to_dict(self) -> Dict[str, object]:
        d = dataclasses.asdict(self)
        d['fingerprint'] = self.fingerprint()
        return d


# Statement types whose source segment spans a whole block — for
# fingerprints only their header (through the line before the body)
# identifies them.
_COMPOUND = (ast.If, ast.While, ast.For, ast.AsyncFor, ast.With,
             ast.AsyncWith, ast.Try, ast.FunctionDef,
             ast.AsyncFunctionDef, ast.ClassDef)
_STATEMENT_TEXT_CAP = 300


class ParsedFile:
    """One parsed module, shared by every checker in a run: AST with
    parent links, source, and a per-function CFG cache (built on
    first request, reused across checkers)."""

    def __init__(self, path: str, rel: str, tree: ast.AST,
                 source: str) -> None:
        self.path = path
        self.rel = rel
        self.tree = tree
        self.source = source
        self.lines = source.splitlines()
        self._cfgs: Dict[int, cfg_mod.CFG] = {}
        self.cfg_requests = 0

    def cfg(self, fn: ast.AST) -> cfg_mod.CFG:
        """The function's CFG, built at most once per file per run —
        never once per checker."""
        self.cfg_requests += 1
        key = id(fn)
        got = self._cfgs.get(key)
        if got is None:
            got = cfg_mod.build(fn)
            self._cfgs[key] = got
        return got

    def cfg_builds(self) -> int:
        return len(self._cfgs)

    def statement_of(self, node: ast.AST) -> Optional[ast.stmt]:
        """The nearest enclosing statement (the node itself if it is
        one); needs annotate_parents."""
        cur: Optional[ast.AST] = node
        while cur is not None and not isinstance(cur, ast.stmt):
            cur = getattr(cur, 'skytpu_parent', None)
        return cur

    def statement_text(self, node: ast.AST) -> str:
        """Whitespace-normalized text of the enclosing statement —
        header only for compound statements — used as the fingerprint
        basis so findings survive pure line drift."""
        stmt = self.statement_of(node)
        if stmt is None:
            line = getattr(node, 'lineno', 0)
            return source_line(self.source, line)
        start = stmt.lineno
        if isinstance(stmt, _COMPOUND) and stmt.body:
            end = max(start, stmt.body[0].lineno - 1)
        else:
            end = getattr(stmt, 'end_lineno', start)
        text = ' '.join(
            part for raw in self.lines[start - 1:end]
            for part in raw.split())
        return text[:_STATEMENT_TEXT_CAP]

    def finding(self, check: str, rule: str, node: ast.AST,
                message: str) -> Finding:
        line = getattr(node, 'lineno', 0)
        return Finding(check=check, rule=rule, path=self.rel,
                       line=line, message=message,
                       snippet=source_line(self.source, line),
                       statement=self.statement_text(node))


@dataclasses.dataclass
class Project:
    """What check_project sees: the repo root, every ParsedFile from
    this run, and the raw path list (including unparseable files)."""
    root: str
    files: List[ParsedFile]
    paths: List[str] = dataclasses.field(default_factory=list)


class Checker:
    """Base class. Subclasses set `name`/`description` and override
    one or both hooks; `register` makes them CLI-selectable."""
    name: str = ''
    description: str = ''

    def check_file(self, pf: ParsedFile) -> Iterable[Finding]:
        return ()

    def check_project(self, project: Project) -> Iterable[Finding]:
        return ()


_CHECKERS: Dict[str, Type[Checker]] = {}


def register(cls: Type[Checker]) -> Type[Checker]:
    assert cls.name, cls
    assert cls.name not in _CHECKERS, f'duplicate checker {cls.name}'
    _CHECKERS[cls.name] = cls
    return cls


def all_checkers() -> Dict[str, Type[Checker]]:
    """name -> checker class, importing the built-in set."""
    from skypilot_tpu.analysis import checkers  # noqa: F401 — registers
    return dict(_CHECKERS)


def repo_root() -> str:
    """The checkout root (parent of the skypilot_tpu package)."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def _iter_py_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            if p.endswith('.py'):
                out.append(os.path.abspath(p))
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = [d for d in dirnames
                           if d not in ('__pycache__', '.git')]
            out.extend(os.path.join(os.path.abspath(dirpath), f)
                       for f in sorted(filenames) if f.endswith('.py'))
    return sorted(set(out))


def _suppressed(finding: Finding, lines: Sequence[str]) -> bool:
    if not (0 < finding.line <= len(lines)):
        return False
    line = lines[finding.line - 1]
    start = line.find(SUPPRESS_MARKER)
    if start < 0:
        return False
    start += len(SUPPRESS_MARKER)
    end = line.find(']', start)
    if end < 0:
        return False
    names = {n.strip() for n in line[start:end].split(',')}
    return finding.rule in names or finding.check in names


def annotate_parents(tree: ast.AST) -> None:
    """Stamp every node with `.skytpu_parent` (checkers walk up for
    with-lock / module-scope / enclosing-statement questions)."""
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child.skytpu_parent = node  # type: ignore[attr-defined]


def dotted_name(node: ast.AST) -> Optional[str]:
    """'a.b.c' for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return '.'.join(reversed(parts))
    return None


def parse_file(path: str, root: str) -> Optional[ParsedFile]:
    """Parse one file into a ParsedFile (None if unreadable or
    syntactically broken — some other gate's problem)."""
    global PARSE_CALLS
    try:
        with open(path, encoding='utf-8') as f:
            source = f.read()
        PARSE_CALLS += 1
        tree = ast.parse(source, filename=path)
    except (OSError, SyntaxError):
        return None
    annotate_parents(tree)
    rel = os.path.relpath(path, root).replace(os.sep, '/')
    return ParsedFile(path, rel, tree, source)


def run(paths: Optional[Sequence[str]] = None,
        checks: Optional[Sequence[str]] = None,
        root: Optional[str] = None,
        ) -> Tuple[List[Finding], int]:
    """Run checkers over paths (default: skypilot_tpu/ under the repo
    root). Returns (findings, suppressed_count); findings are sorted
    and inline-suppressed ones already removed."""
    root = root or repo_root()
    if not paths:
        paths = [os.path.join(root, 'skypilot_tpu')]
    available = all_checkers()
    if checks:
        unknown = sorted(set(checks) - set(available))
        if unknown:
            raise ValueError(
                f'unknown checks {unknown}; have {sorted(available)}')
        selected = [available[c]() for c in checks]
    else:
        selected = [cls() for cls in available.values()]

    files = _iter_py_files(paths)
    parsed: List[ParsedFile] = []
    for path in files:
        pf = parse_file(path, root)
        if pf is not None:
            parsed.append(pf)

    findings: List[Finding] = []
    suppressed = 0
    for pf in parsed:
        for checker in selected:
            for finding in checker.check_file(pf):
                if _suppressed(finding, pf.lines):
                    suppressed += 1
                else:
                    findings.append(finding)
    project = Project(root=root, files=parsed, paths=list(files))
    for checker in selected:
        findings.extend(checker.check_project(project))
    findings.sort(key=lambda f: (f.path, f.line, f.check, f.rule))

    LAST_RUN_STATS.clear()
    LAST_RUN_STATS.update(
        files=len(files), parsed=len(parsed),
        cfg_builds=sum(pf.cfg_builds() for pf in parsed),
        cfg_requests=sum(pf.cfg_requests for pf in parsed))
    return findings, suppressed


def source_line(source: str, lineno: int) -> str:
    lines = source.splitlines()
    if 0 < lineno <= len(lines):
        return lines[lineno - 1].strip()
    return ''
