"""Checker plugin API and file-walking runner for skytpu-lint.

A checker sees each file's parsed AST once (`check_file`) and/or the
whole project at the end (`check_project`, for contracts that live in
runtime registries rather than syntax — metrics catalog, fault
points). Findings are plain data; fingerprints are content-based
(path + rule + source line, NOT line numbers) so the committed
baseline survives unrelated edits above a finding.
"""
import ast
import dataclasses
import hashlib
import os
from typing import Dict, Iterable, List, Optional, Sequence, Tuple, Type

# Inline escape hatch: a finding whose source line carries
# `skytpu-lint: ignore[<rule-or-check>, ...]` is suppressed. Use it for
# the rare deliberate violation (e.g. fork handlers replacing a lock);
# use the baseline for bulk pre-existing debt.
SUPPRESS_MARKER = 'skytpu-lint: ignore['


@dataclasses.dataclass(frozen=True)
class Finding:
    check: str      # checker name, e.g. 'trace-safety'
    rule: str       # sub-rule, e.g. 'host-call'
    path: str       # repo-relative, forward slashes
    line: int       # 1-based; 0 for project-level findings
    message: str
    snippet: str = ''   # stripped source line (fingerprint basis)

    def fingerprint(self) -> str:
        basis = '|'.join((self.check, self.rule, self.path,
                          self.snippet or self.message))
        return hashlib.sha1(basis.encode()).hexdigest()[:16]

    def location(self) -> str:
        return f'{self.path}:{self.line}' if self.line else self.path

    def to_dict(self) -> Dict[str, object]:
        d = dataclasses.asdict(self)
        d['fingerprint'] = self.fingerprint()
        return d


class Checker:
    """Base class. Subclasses set `name`/`description` and override
    one or both hooks; `register` makes them CLI-selectable."""
    name: str = ''
    description: str = ''

    def check_file(self, path: str, rel: str, tree: ast.AST,
                   source: str) -> Iterable[Finding]:
        return ()

    def check_project(self, root: str,
                      files: Sequence[str]) -> Iterable[Finding]:
        return ()


_CHECKERS: Dict[str, Type[Checker]] = {}


def register(cls: Type[Checker]) -> Type[Checker]:
    assert cls.name, cls
    assert cls.name not in _CHECKERS, f'duplicate checker {cls.name}'
    _CHECKERS[cls.name] = cls
    return cls


def all_checkers() -> Dict[str, Type[Checker]]:
    """name -> checker class, importing the built-in set."""
    from skypilot_tpu.analysis import checkers  # noqa: F401 — registers
    return dict(_CHECKERS)


def repo_root() -> str:
    """The checkout root (parent of the skypilot_tpu package)."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def _iter_py_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            if p.endswith('.py'):
                out.append(os.path.abspath(p))
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = [d for d in dirnames
                           if d not in ('__pycache__', '.git')]
            out.extend(os.path.join(os.path.abspath(dirpath), f)
                       for f in sorted(filenames) if f.endswith('.py'))
    return sorted(set(out))


def _suppressed(finding: Finding, lines: Sequence[str]) -> bool:
    if not (0 < finding.line <= len(lines)):
        return False
    line = lines[finding.line - 1]
    start = line.find(SUPPRESS_MARKER)
    if start < 0:
        return False
    start += len(SUPPRESS_MARKER)
    end = line.find(']', start)
    if end < 0:
        return False
    names = {n.strip() for n in line[start:end].split(',')}
    return finding.rule in names or finding.check in names


def annotate_parents(tree: ast.AST) -> None:
    """Stamp every node with `.skytpu_parent` (checkers walk up for
    with-lock / module-scope questions)."""
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child.skytpu_parent = node  # type: ignore[attr-defined]


def dotted_name(node: ast.AST) -> Optional[str]:
    """'a.b.c' for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return '.'.join(reversed(parts))
    return None


def run(paths: Optional[Sequence[str]] = None,
        checks: Optional[Sequence[str]] = None,
        root: Optional[str] = None,
        ) -> Tuple[List[Finding], int]:
    """Run checkers over paths (default: skypilot_tpu/ under the repo
    root). Returns (findings, suppressed_count); findings are sorted
    and inline-suppressed ones already removed."""
    root = root or repo_root()
    if not paths:
        paths = [os.path.join(root, 'skypilot_tpu')]
    available = all_checkers()
    if checks:
        unknown = sorted(set(checks) - set(available))
        if unknown:
            raise ValueError(
                f'unknown checks {unknown}; have {sorted(available)}')
        selected = [available[c]() for c in checks]
    else:
        selected = [cls() for cls in available.values()]

    files = _iter_py_files(paths)
    findings: List[Finding] = []
    suppressed = 0
    for path in files:
        try:
            with open(path, encoding='utf-8') as f:
                source = f.read()
            tree = ast.parse(source, filename=path)
        except (OSError, SyntaxError):
            continue  # unparseable files are some other gate's problem
        annotate_parents(tree)
        rel = os.path.relpath(path, root).replace(os.sep, '/')
        lines = source.splitlines()
        for checker in selected:
            for finding in checker.check_file(path, rel, tree, source):
                if _suppressed(finding, lines):
                    suppressed += 1
                else:
                    findings.append(finding)
    for checker in selected:
        findings.extend(checker.check_project(root, files))
    findings.sort(key=lambda f: (f.path, f.line, f.check, f.rule))
    return findings, suppressed


def source_line(source: str, lineno: int) -> str:
    lines = source.splitlines()
    if 0 < lineno <= len(lines):
        return lines[lineno - 1].strip()
    return ''
