"""Dataflow queries over analysis.cfg graphs.

Everything here is deliberately small and worklist-based — the lint
bench holds the full ten-checker repo pass under a 30s wall bar, so
each query is linear-ish in graph size:

  max_weight_path      longest acyclic-path weight sum (host-sync
                       budgets: the worst single execution of a
                       function, loops collapsed via SCC condensation
                       so a sync in a loop contributes its SCC total)
  reach_avoiding       can `start` reach any `target` without passing
                       through a blocking node (resource-pairing: an
                       acquire that reaches exit avoiding every
                       release is a leak)
  forward_reach        plain forward reachability with per-node stop
                       predicate (donation-discipline: walk from the
                       dispatch site, stop at rebinds, flag reads)
  must_hold            forward must-analysis (meet = AND) of which
                       lock objects are held at each node
                       (lock-coverage beyond lexical `with` bodies)
  reaching_definitions classic may-analysis of name -> def sites
"""
import ast
from typing import Callable, Dict, FrozenSet, Iterable, List, \
    Optional, Sequence, Set, Tuple

from . import cfg as cfg_mod
from .cfg import CFG, Node


def _condense(graph: CFG) -> Tuple[Dict[int, int], List[Set[int]],
                                   Dict[int, Set[int]]]:
    """SCC condensation: (node index -> scc id, scc id -> member
    indices, scc id -> successor scc ids). Iterative Tarjan; scc ids
    are emitted in reverse topological order (successors first)."""
    index_of: Dict[int, int] = {}
    low: Dict[int, int] = {}
    on_stack: Set[int] = set()
    stack: List[int] = []
    comp_of: Dict[int, int] = {}
    comps: List[Set[int]] = []
    counter = [0]

    for root in graph.nodes:
        if root.index in index_of:
            continue
        work: List[Tuple[Node, int]] = [(root, 0)]
        while work:
            node, si = work[-1]
            if si == 0:
                index_of[node.index] = low[node.index] = counter[0]
                counter[0] += 1
                stack.append(node.index)
                on_stack.add(node.index)
            advanced = False
            succs = node.succs
            while si < len(succs):
                child = succs[si][0]
                si += 1
                if child.index not in index_of:
                    work[-1] = (node, si)
                    work.append((child, 0))
                    advanced = True
                    break
                if child.index in on_stack:
                    low[node.index] = min(low[node.index],
                                          index_of[child.index])
            if advanced:
                continue
            work[-1] = (node, si)
            if si >= len(succs):
                work.pop()
                if low[node.index] == index_of[node.index]:
                    members: Set[int] = set()
                    while True:
                        w = stack.pop()
                        on_stack.discard(w)
                        members.add(w)
                        if w == node.index:
                            break
                    cid = len(comps)
                    comps.append(members)
                    for m in members:
                        comp_of[m] = cid
                if work:
                    parent = work[-1][0]
                    low[parent.index] = min(low[parent.index],
                                            low[node.index])

    comp_succs: Dict[int, Set[int]] = {i: set() for i in
                                       range(len(comps))}
    for node in graph.nodes:
        cid = comp_of[node.index]
        for child, _ in node.succs:
            ccid = comp_of[child.index]
            if ccid != cid:
                comp_succs[cid].add(ccid)
    return comp_of, comps, comp_succs


def max_weight_path(graph: CFG, weight: Dict[int, int],
                    ) -> Tuple[int, List[Node]]:
    """Maximum sum of `weight[node.index]` over any execution path
    from entry. Cycles are condensed: every weighted node in an SCC
    counts once toward the SCC's weight (the budget checker reports
    loops separately via sync-in-loop). Returns (max weight, the
    weighted nodes on one witness path, program order)."""
    comp_of, comps, comp_succs = _condense(graph)
    n = len(comps)
    # comps is emitted successors-first, so ascending id IS a safe
    # evaluation order for the longest-path DP over the DAG.
    best: List[int] = [0] * n
    choice: List[Optional[int]] = [None] * n
    for cid in range(n):
        w = sum(weight.get(m, 0) for m in comps[cid])
        succ_best, succ_pick = 0, None
        for s in comp_succs[cid]:
            if best[s] > succ_best:
                succ_best, succ_pick = best[s], s
        best[cid] = w + succ_best
        choice[cid] = succ_pick
    start = comp_of[graph.entry.index]
    total = best[start]
    witness: List[Node] = []
    cid: Optional[int] = start
    by_index = {node.index: node for node in graph.nodes}
    while cid is not None:
        for m in sorted(comps[cid]):
            if weight.get(m, 0):
                witness.append(by_index[m])
        cid = choice[cid]
    witness.sort(key=lambda node: (node.lineno, node.index))
    return total, witness


def reach_avoiding(start: Node, targets: Set[int],
                   blocked: Callable[[Node], bool],
                   skip_start_exception: bool = False,
                   ) -> Optional[Node]:
    """BFS from `start`'s successors: can control reach a node whose
    index is in `targets` while never passing THROUGH a node for
    which blocked() is true? Blocked nodes are absorbing (the path is
    satisfied there, we do not continue past them). Returns the first
    reached target node, else None.

    `skip_start_exception` drops the START node's own exception edge
    from the seed frontier — an acquire() that itself raises never
    obtained the resource, so that edge is not a leak path."""
    seen: Set[int] = set()
    frontier: List[Node] = [
        t for t, kind in start.succs
        if not (skip_start_exception and kind == cfg_mod.EXCEPTION)]
    while frontier:
        node = frontier.pop()
        if node.index in seen:
            continue
        seen.add(node.index)
        if node.index in targets:
            return node
        if blocked(node):
            continue
        frontier.extend(t for t, _ in node.succs)
    return None


def forward_reach(start: Node, stop: Callable[[Node], bool],
                  include_start: bool = False) -> Iterable[Node]:
    """Yield every node reachable from `start` without passing
    through a node where stop() is true. Stop nodes themselves are
    yielded (a statement can both read and rebind — the caller
    inspects evaluation order) but not traversed past."""
    seen: Set[int] = set()
    frontier: List[Node] = [start] if include_start \
        else [t for t, _ in start.succs]
    while frontier:
        node = frontier.pop()
        if node.index in seen:
            continue
        seen.add(node.index)
        yield node
        if stop(node):
            continue
        frontier.extend(t for t, _ in node.succs)


def must_hold(graph: CFG,
              acquires: Callable[[Node], FrozenSet[str]],
              releases: Callable[[Node], FrozenSet[str]],
              universe: FrozenSet[str],
              ) -> Dict[int, FrozenSet[str]]:
    """Forward must-analysis: the set of lock names guaranteed held
    ON ENTRY to each node.  out(n) = (in(n) | acquires(n)) -
    releases(n); in(n) = intersection over preds.  Exception edges
    participate (a raise mid-critical-section still holds the lock
    until a handler releases it)."""
    preds: Dict[int, List[int]] = {node.index: []
                                   for node in graph.nodes}
    by_index: Dict[int, Node] = {}
    for node in graph.nodes:
        by_index[node.index] = node
        for child, _ in node.succs:
            preds[child.index].append(node.index)

    state_in: Dict[int, FrozenSet[str]] = {
        node.index: universe for node in graph.nodes}
    state_in[graph.entry.index] = frozenset()

    out_of: Dict[int, FrozenSet[str]] = {}

    def flow(idx: int) -> FrozenSet[str]:
        node = by_index[idx]
        return (state_in[idx] | acquires(node)) - releases(node)

    work = [node.index for node in graph.nodes]
    while work:
        idx = work.pop()
        if idx == graph.entry.index:
            new_in: FrozenSet[str] = frozenset()
        else:
            ps = preds[idx]
            if not ps:
                new_in = frozenset()
            else:
                acc: Optional[FrozenSet[str]] = None
                for p in ps:
                    o = out_of.get(p)
                    if o is None:
                        o = flow(p)
                    acc = o if acc is None else (acc & o)
                new_in = acc if acc is not None else frozenset()
        if new_in != state_in[idx] or idx not in out_of:
            state_in[idx] = new_in
            new_out = flow(idx)
            if out_of.get(idx) != new_out:
                out_of[idx] = new_out
                for child, _ in by_index[idx].succs:
                    work.append(child.index)
            else:
                out_of[idx] = new_out
    return state_in


def assigned_names(stmt: ast.stmt) -> Set[str]:
    """Plain names (re)bound by a statement — assignment targets,
    aug-assign, for targets, with ... as, except ... as, imports."""
    names: Set[str] = set()

    def targets(node: ast.AST) -> None:
        for t in ast.walk(node):
            if isinstance(t, ast.Name):
                names.add(t.id)

    if isinstance(stmt, ast.Assign):
        for t in stmt.targets:
            targets(t)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets(stmt.target)
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        targets(stmt.target)
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        for item in stmt.items:
            if item.optional_vars is not None:
                targets(item.optional_vars)
    elif isinstance(stmt, (ast.Import, ast.ImportFrom)):
        for alias in stmt.names:
            names.add((alias.asname or alias.name).split('.')[0])
    elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
        names.add(stmt.name)
    return names


def reaching_definitions(graph: CFG,
                         ) -> Dict[int, Dict[str, Set[int]]]:
    """May-analysis: for each node, name -> the node indices whose
    (re)binding of that name can reach it. Parameter bindings appear
    under the entry node's index."""
    gen: Dict[int, Set[str]] = {}
    for node in graph.nodes:
        if node.stmt is not None:
            gen[node.index] = assigned_names(node.stmt)
        elif node.kind == 'entry':
            params: Set[str] = set()
            fn = graph.fn
            args = getattr(fn, 'args', None)
            if args is not None:
                for a in (list(args.posonlyargs) + list(args.args)
                          + list(args.kwonlyargs)):
                    params.add(a.arg)
                if args.vararg:
                    params.add(args.vararg.arg)
                if args.kwarg:
                    params.add(args.kwarg.arg)
            gen[node.index] = params
        else:
            gen[node.index] = set()

    state: Dict[int, Dict[str, Set[int]]] = {
        node.index: {} for node in graph.nodes}
    work: List[Node] = [graph.entry]
    while work:
        node = work.pop()
        out: Dict[str, Set[int]] = dict(state[node.index])
        for name in gen[node.index]:
            out[name] = {node.index}
        for child, _ in node.succs:
            tgt = state[child.index]
            changed = False
            for name, defs in out.items():
                cur = tgt.get(name)
                if cur is None:
                    tgt[name] = set(defs)
                    changed = True
                elif not defs <= cur:
                    cur.update(defs)
                    changed = True
            if changed:
                work.append(child)
    return state
