"""Name → class registries for clouds and backends.

Same role as the reference registry (sky/utils/registry.py:16) but with a
plain-dict implementation and alias support.
"""
from typing import Callable, Dict, Generic, List, Optional, Type, TypeVar

T = TypeVar('T')


class Registry(Generic[T]):
    """Case-insensitive name → instance/class registry with aliases."""

    def __init__(self, registry_name: str):
        self._registry_name = registry_name
        self._entries: Dict[str, T] = {}
        self._aliases: Dict[str, str] = {}

    def register(self, name: Optional[str] = None,
                 aliases: Optional[List[str]] = None) -> Callable[[Type], Type]:
        def decorator(cls: Type) -> Type:
            key = (name or cls.__name__).lower()
            if key in self._entries:
                raise ValueError(
                    f'{self._registry_name} {key!r} already registered')
            self._entries[key] = cls
            for alias in aliases or []:
                self._aliases[alias.lower()] = key
            return cls
        return decorator

    def canonical_name(self, name: str) -> str:
        key = name.lower()
        return self._aliases.get(key, key)

    def get(self, name: str) -> T:
        key = self.canonical_name(name)
        if key not in self._entries:
            raise ValueError(
                f'Unknown {self._registry_name}: {name!r}. '
                f'Available: {sorted(self._entries)}')
        return self._entries[key]

    def try_get(self, name: str) -> Optional[T]:
        return self._entries.get(self.canonical_name(name))

    def names(self) -> List[str]:
        return sorted(self._entries)

    def __contains__(self, name: str) -> bool:
        return self.canonical_name(name) in self._entries


# Instantiated registries. Clouds register at import of skypilot_tpu.clouds;
# backends at import of skypilot_tpu.backends.
CLOUD_REGISTRY: Registry = Registry('cloud')
BACKEND_REGISTRY: Registry = Registry('backend')
