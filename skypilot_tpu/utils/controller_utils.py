"""Dedicated controller clusters for managed jobs and serve.

Reference analog: sky/utils/controller_utils.py:90 (`Controllers`
registry: per-controller cluster name + default resources + config
path) and :837 (`maybe_translate_local_file_mounts_and_sync_up`: the
2-hop translation — a controller VM cannot see client-local files, so
local file mounts/workdir are uploaded to a bucket and the task is
rewritten to mount from there).

Modes (config `jobs.controller.mode` / `serve.controller.mode`):
  consolidated  (default) controllers run as processes of the API
                server host — zero extra cost, single-host control
                plane (the reference's jobs-consolidation deployment).
  dedicated     controllers run as cluster jobs on a long-lived
                controller cluster launched through the normal stack
                (any cloud, incl. `local` for tests).
"""
import dataclasses
import hashlib
import os
import shlex
from typing import Any, Dict, Optional

from skypilot_tpu import envs
from skypilot_tpu import exceptions


@dataclasses.dataclass(frozen=True)
class ControllerSpec:
    kind: str                 # 'jobs' | 'serve'
    cluster_name: str
    default_resources: Dict[str, Any]


CONTROLLERS: Dict[str, ControllerSpec] = {
    'jobs': ControllerSpec(
        kind='jobs', cluster_name='tsky-jobs-controller',
        default_resources={'cpus': '4+', 'disk_size': 50}),
    'serve': ControllerSpec(
        kind='serve', cluster_name='tsky-serve-controller',
        default_resources={'cpus': '4+', 'disk_size': 50}),
}


def controller_mode(kind: str) -> str:
    from skypilot_tpu import config as config_lib
    mode = config_lib.get_nested((kind, 'controller', 'mode'),
                                 default='consolidated')
    if mode not in ('consolidated', 'dedicated'):
        raise exceptions.InvalidTaskError(
            f'{kind}.controller.mode must be consolidated|dedicated, '
            f'got {mode!r}')
    return mode


def controller_resources(kind: str):
    """Resources for the controller cluster: config overrides merged
    onto defaults (reference Controllers.controller_resources). With
    `{kind}.controller.ha: true` the resources carry the HA cluster
    overrides (Deployment-backed host + restart recovery command) for
    clouds with the HA_CONTROLLERS capability — kubernetes."""
    from skypilot_tpu import config as config_lib
    from skypilot_tpu import resources as resources_lib
    spec = CONTROLLERS[kind]
    cfg = dict(spec.default_resources)
    cfg.update(config_lib.get_nested((kind, 'controller', 'resources'),
                                     default=None) or {})
    res = resources_lib.Resources.from_yaml_config(cfg)
    if config_lib.get_nested((kind, 'controller', 'ha'),
                             default=False):
        res = res.copy(_cluster_config_overrides={
            **res.cluster_config_overrides,
            'ha': True,
            'recovery_command': ha_recovery_command(),
        })
    return res


def ha_recovery_command() -> str:
    """What a resurrected controller pod runs before steady state:
    restart the skylet, then crash-resume every controller that was
    mid-flight when the old pod died (reference ha_recovery script in
    sky/templates/kubernetes-ray.yml.j2; resume machinery:
    jobs/scheduler.recover_orphaned_controllers)."""
    from skypilot_tpu.provision import provisioner
    pkg = provisioner._PKG_REMOTE_DIR  # noqa: SLF001
    return (f'export PYTHONPATH={pkg}:$PYTHONPATH; '
            'nohup python3 -m skypilot_tpu.skylet.skylet '
            '>/tmp/skytpu-ha-skylet.log 2>&1 & '
            'python3 -c "from skypilot_tpu.jobs import scheduler; '
            'scheduler.recover_orphaned_controllers()" '
            '>/tmp/skytpu-ha-recover.log 2>&1 || true')


def ensure_controller_cluster(kind: str):
    """Launch (or reuse) the dedicated controller cluster; returns its
    handle. Idempotent: an UP cluster is reused by name."""
    from skypilot_tpu import execution
    from skypilot_tpu import state as state_lib
    from skypilot_tpu import task as task_lib
    spec = CONTROLLERS[kind]
    record = state_lib.get_cluster_from_name(spec.cluster_name)
    if record is not None and record['handle'] is not None and \
            record['status'] == state_lib.ClusterStatus.UP:
        return record['handle']
    bootstrap = task_lib.Task(name=f'{kind}-controller-up', run=None)
    bootstrap.set_resources(controller_resources(kind))
    _, handle = execution.launch(bootstrap,
                                 cluster_name=spec.cluster_name,
                                 stream_logs=False)
    return handle


def controller_run_command(handle, module: str, *args: str) -> str:
    """Shell command that runs `python -m <module> <args>` on the
    controller cluster with the shipped package importable."""
    from skypilot_tpu.provision import provisioner
    from skypilot_tpu.utils import command_runner as runner_lib
    from skypilot_tpu.backends import gang_backend
    backend = gang_backend.GangBackend()
    runners = backend._runners(handle)  # noqa: SLF001
    local = isinstance(runners[0], runner_lib.LocalProcessRunner)
    quoted = ' '.join(shlex.quote(a) for a in args)
    if local:
        import sys
        import skypilot_tpu
        pkg_parent = os.path.dirname(os.path.dirname(
            os.path.abspath(skypilot_tpu.__file__)))
        return (f'PYTHONPATH={shlex.quote(pkg_parent)}:$PYTHONPATH '
                f'{shlex.quote(sys.executable)} -m {module} {quoted}')
    return (f'PYTHONPATH={provisioner._PKG_REMOTE_DIR}'  # noqa: SLF001
            f':$PYTHONPATH python3 -m {module} {quoted}')


def translate_local_file_mounts(task, store_type: Optional[str] = None):
    """2-hop file-mount translation (reference controller_utils.py:837):
    a dedicated controller cannot read client-local paths, so every
    local file mount (and the workdir) is uploaded into a bucket and
    the task rewritten to COPY-mount from that bucket on the job
    cluster. Returns the task (mutated)."""
    from skypilot_tpu import config as config_lib
    from skypilot_tpu.data import storage as storage_lib
    store_type = store_type or config_lib.get_nested(
        ('jobs', 'bucket', 'store'), default='local')
    user = envs.SKYTPU_USER.get() or os.environ.get('USER', 'u')

    def _bucketize(local_path: str, remote_dst: str) -> None:
        digest = hashlib.sha1(
            f'{user}:{local_path}:{remote_dst}'.encode()).hexdigest()[:10]
        storage = storage_lib.Storage(
            name=f'skytpu-mounts-{user}-{digest}',
            source=local_path, store=store_type, mode='COPY',
            persistent=False)
        storage.sync()
        # The upload happened HERE (first hop). Clear the client-local
        # source so the controller host never tries to re-sync a path
        # that only exists on the client.
        storage.source = None
        task.storage_mounts[remote_dst] = storage

    if task.workdir and '://' not in task.workdir:
        _bucketize(task.workdir, '~/sky_workdir')
        task.workdir = None
    for dst, src in list((task.file_mounts or {}).items()):
        if '://' in src:
            continue
        if not os.path.exists(os.path.expanduser(src)):
            raise exceptions.InvalidTaskError(
                f'file_mount source {src!r} does not exist.')
        _bucketize(os.path.expanduser(src), dst)
        del task.file_mounts[dst]
    return task
