"""Parse/format infra strings: 'gcp', 'gcp/us-central2', 'gcp/us-central2/us-central2-b',
'k8s/my-context', 'local'.

Reference analog: sky/utils/infra_utils.py (195 LoC).
"""
import dataclasses
from typing import Optional

from skypilot_tpu import exceptions

_WILDCARD = '*'


@dataclasses.dataclass
class InfraInfo:
    cloud: Optional[str] = None
    region: Optional[str] = None
    zone: Optional[str] = None

    @classmethod
    def from_str(cls, infra: Optional[str]) -> 'InfraInfo':
        if infra is None or infra.strip() in ('', _WILDCARD):
            return cls()
        parts = [p.strip() for p in infra.strip().strip('/').split('/')]
        if any(not p for p in parts):
            raise exceptions.InvalidInfraError(
                f'Invalid infra string: {infra!r}')
        cloud = parts[0].lower()
        if cloud == _WILDCARD:
            cloud = None
        if cloud in ('k8s', 'kubernetes'):
            # k8s/<context-with-possible-slashes>
            context = '/'.join(parts[1:]) or None
            return cls(cloud='kubernetes', region=context)
        if len(parts) > 3:
            raise exceptions.InvalidInfraError(
                f'Invalid infra string (too many parts): {infra!r}')
        region = parts[1] if len(parts) > 1 and parts[1] != _WILDCARD else None
        zone = parts[2] if len(parts) > 2 and parts[2] != _WILDCARD else None
        return cls(cloud=cloud, region=region, zone=zone)

    def to_str(self) -> str:
        parts = [self.cloud or _WILDCARD]
        if self.region:
            parts.append(self.region)
        if self.zone:
            parts.append(self.zone)
        s = '/'.join(parts)
        return '' if s == _WILDCARD else s

    def __bool__(self) -> bool:
        return any([self.cloud, self.region, self.zone])
