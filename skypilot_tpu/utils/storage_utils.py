"""Storage helpers: .skyignore handling + upload size accounting.

Reference analog: sky/data/storage_utils.py (326 LoC).
"""
import os
from typing import List

SKYIGNORE_FILE = '.skyignore'
GITIGNORE_FILE = '.gitignore'


def skyignore_excludes(source: str) -> List[str]:
    """Exclusion patterns for an upload rooted at `source`.

    .skyignore wins when present; else .gitignore's simple patterns are
    honored (reference behavior: storage_utils.get_excluded_files).
    Comment lines and negations are skipped.
    """
    source = os.path.expanduser(source)
    if not os.path.isdir(source):
        return []
    for fname in (SKYIGNORE_FILE, GITIGNORE_FILE):
        path = os.path.join(source, fname)
        if not os.path.isfile(path):
            continue
        patterns = []
        with open(path, 'r', encoding='utf-8') as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith('#') or \
                        line.startswith('!'):
                    continue
                patterns.append(line.rstrip('/'))
        if fname == SKYIGNORE_FILE:
            return patterns
        if patterns:
            return patterns + ['.git']
    return []


def du_bytes(path: str) -> int:
    """Total size of a file/dir in bytes (pre-upload sanity checks)."""
    path = os.path.expanduser(path)
    if os.path.isfile(path):
        return os.path.getsize(path)
    total = 0
    for root, _, files in os.walk(path):
        for f in files:
            full = os.path.join(root, f)
            if not os.path.islink(full):
                try:
                    total += os.path.getsize(full)
                except OSError:
                    pass
    return total
