"""Storage helpers: .skyignore handling + upload size accounting.

Reference analog: sky/data/storage_utils.py (326 LoC).
"""
import os
from typing import List

SKYIGNORE_FILE = '.skyignore'
GITIGNORE_FILE = '.gitignore'


def skyignore_excludes(source: str) -> List[str]:
    """Exclusion patterns for an upload rooted at `source`.

    .skyignore wins when present; else .gitignore's simple patterns are
    honored (reference behavior: storage_utils.get_excluded_files).
    Comment lines and negations are skipped.
    """
    source = os.path.expanduser(source)
    if not os.path.isdir(source):
        return []
    for fname in (SKYIGNORE_FILE, GITIGNORE_FILE):
        path = os.path.join(source, fname)
        if not os.path.isfile(path):
            continue
        patterns = []
        with open(path, 'r', encoding='utf-8') as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith('#') or \
                        line.startswith('!'):
                    continue
                patterns.append(line.rstrip('/'))
        if fname == SKYIGNORE_FILE:
            return patterns
        if patterns:
            return patterns + ['.git']
    return []


def du_bytes(path: str) -> int:
    """Total size of a file/dir in bytes (pre-upload sanity checks)."""
    path = os.path.expanduser(path)
    if os.path.isfile(path):
        return os.path.getsize(path)
    total = 0
    for root, _, files in os.walk(path):
        for f in files:
            full = os.path.join(root, f)
            if not os.path.islink(full):
                try:
                    total += os.path.getsize(full)
                except OSError:
                    pass
    return total


def filtered_source(source: str) -> str:
    """`source` with .skyignore patterns applied: returns `source`
    unchanged when nothing is excluded, else a temp copy with the
    excluded entries removed (for uploaders without an exclude flag,
    e.g. `az storage blob upload-batch`)."""
    import shutil
    import tempfile
    source = os.path.expanduser(source)
    excludes = skyignore_excludes(source)
    if not excludes or not os.path.isdir(source):
        return source
    staged = tempfile.mkdtemp(prefix='skytpu-upload-')
    shutil.copytree(source, staged, dirs_exist_ok=True,
                    ignore=shutil.ignore_patterns(*excludes))
    return staged
