"""Declarative JSON-schema validation for every user-authored YAML.

Reference analog: sky/utils/schemas.py (1457 LoC of jsonschema dicts
validating task/config/resources YAML). Ours covers the same three
user surfaces — task YAML, resources section, layered config files —
plus the service and storage sub-sections, and reports EVERY problem
in one error with its YAML path (the reference shows one at a time).

These schemas validate *shape* (types, enums, unknown keys); semantic
checks that need context (catalog lookups, capability gates, path
existence) stay in the owning classes.
"""
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions

# --- building blocks -------------------------------------------------------

_STR = {'type': 'string'}
_BOOL = {'type': 'boolean'}
_INT = {'type': 'integer'}
_NUM = {'type': 'number'}
_NULL_OK_STR = {'type': ['string', 'null']}
# YAML authors write `cpus: 8`, `cpus: 8+`, `memory: 64`: accept both.
_NUM_OR_STR = {'type': ['number', 'string', 'null']}
_STR_DICT = {'type': 'object',
             'additionalProperties': {
                 'type': ['string', 'number', 'boolean', 'null']}}

_ACCELERATORS = {
    'oneOf': [
        {'type': 'string'},                       # 'tpu-v5p:8', 'A100:1'
        {'type': 'object',                        # {'tpu-v5p': 8}
         'additionalProperties': {'type': ['number', 'integer']}},
        {'type': 'null'},
    ]
}

_AUTOSTOP = {
    'oneOf': [
        {'type': 'boolean'},                      # autostop: true
        {'type': 'integer'},                      # autostop: 10 (minutes)
        # '10m' / '2h' — must stay in sync with AutostopConfig
        # .from_config's parser (resources.py).
        {'type': 'string', 'pattern': r'^[0-9]+[mh]?$'},
        {'type': 'object',
         'additionalProperties': False,
         'properties': {
             'enabled': _BOOL,   # emitted by AutostopConfig.to_config
             'idle_minutes': _INT,
             'down': _BOOL,
         }},
    ]
}

_PORTS = {
    'oneOf': [
        {'type': ['string', 'integer']},
        {'type': 'array', 'items': {'type': ['string', 'integer']}},
        {'type': 'null'},
    ]
}


def _resources_properties() -> Dict[str, Any]:
    return {
        'infra': _NULL_OK_STR,
        # Back-compat sugar, folded into infra by Resources:
        'cloud': _NULL_OK_STR,
        'region': _NULL_OK_STR,
        'zone': _NULL_OK_STR,
        'accelerators': _ACCELERATORS,
        'cpus': _NUM_OR_STR,
        'memory': _NUM_OR_STR,
        'instance_type': _NULL_OK_STR,
        'use_spot': _BOOL,
        'disk_size': _INT,
        'disk_tier': {'enum': ['low', 'medium', 'high', 'best', None]},
        'ports': _PORTS,
        'image_id': _NULL_OK_STR,
        'labels': _STR_DICT,
        'autostop': _AUTOSTOP,
        'job_recovery': {'type': ['string', 'object', 'null']},
    }


RESOURCES_SCHEMA: Dict[str, Any] = {
    '$schema': 'https://json-schema.org/draft/2020-12/schema',
    'type': 'object',
    'additionalProperties': False,
    'properties': {
        **_resources_properties(),
        'any_of': {
            'type': 'array',
            'items': {
                'type': 'object',
                'additionalProperties': False,
                'properties': _resources_properties(),
            },
        },
    },
}

SERVICE_SCHEMA: Dict[str, Any] = {
    'type': 'object',
    'additionalProperties': False,
    'required': ['readiness_probe'],
    'properties': {
        'readiness_probe': {
            'oneOf': [
                {'type': 'string'},               # path shorthand
                {'type': 'object',
                 'additionalProperties': False,
                 'properties': {
                     'path': _STR,
                     'initial_delay_seconds': _NUM,
                     'timeout_seconds': _NUM,
                     'post_data': {'type': ['object', 'string']},
                 }},
            ]
        },
        'replica_port': _INT,
        'replicas': _INT,
        'load_balancing_policy': {'enum': ['round_robin', 'least_load',
                                           'prefix_affinity']},
        # Disaggregated replica pools (prefill-heavy vs decode-heavy
        # hardware scaling independently); mutually exclusive with
        # replica_policy, enforced by ServiceSpec validation.
        'pools': {
            'type': 'object',
            'additionalProperties': {
                'type': 'object',
                'additionalProperties': False,
                'properties': {
                    'role': {'enum': ['prefill', 'decode', 'general']},
                    'min_replicas': _INT,
                    'max_replicas': _INT,
                    'target_qps_per_replica': _NUM,
                    'target_queue_per_replica': _NUM,
                    'kv_util_upscale_threshold': _NUM,
                    'ttft_p95_upscale_threshold': _NUM,
                    'decode_step_p95_upscale_threshold': _NUM,
                    'upscale_delay_seconds': _NUM,
                    'downscale_delay_seconds': _NUM,
                    'resources': {'type': 'object'},
                },
            },
        },
        'replica_policy': {
            'type': 'object',
            'additionalProperties': False,
            'properties': {
                'min_replicas': _INT,
                'max_replicas': _INT,
                'target_qps_per_replica': _NUM,
                'upscale_delay_seconds': _NUM,
                'downscale_delay_seconds': _NUM,
                'use_spot': _BOOL,
                'spot_zones': {'type': 'array', 'items': _STR},
                'base_ondemand_fallback_replicas': _INT,
                'dynamic_ondemand_fallback': _BOOL,
                'target_queue_per_replica': _NUM,
                'kv_util_upscale_threshold': _NUM,
            },
        },
    },
}

STORAGE_SCHEMA: Dict[str, Any] = {
    'type': 'object',
    'additionalProperties': False,
    'properties': {
        'name': _STR,
        'source': _NULL_OK_STR,
        'store': {'enum': ['gcs', 's3', 'azure', 'r2', 'cos', 'oci',
                           'local', None]},
        'mode': {'enum': ['MOUNT', 'COPY', 'mount', 'copy']},
        'persistent': _BOOL,
    },
}

TASK_SCHEMA: Dict[str, Any] = {
    '$schema': 'https://json-schema.org/draft/2020-12/schema',
    'type': 'object',
    'additionalProperties': False,
    'properties': {
        'name': _NULL_OK_STR,
        'workdir': _NULL_OK_STR,
        'setup': _NULL_OK_STR,
        'run': _NULL_OK_STR,
        'num_nodes': _INT,
        'envs': {'type': ['object', 'null'],
                 'additionalProperties': {
                     'type': ['string', 'number', 'boolean', 'null']}},
        'secrets': {'type': ['object', 'null'],
                    'additionalProperties': {
                        'type': ['string', 'number', 'boolean', 'null']}},
        'outputs': {
            'type': 'object',
            'additionalProperties': False,
            'properties': {'estimated_size_gigabytes': _NUM},
        },
        'file_mounts': {
            'type': ['object', 'null'],
            'additionalProperties': {
                'oneOf': [{'type': 'string'}, STORAGE_SCHEMA],
            },
        },
        'resources': {'oneOf': [RESOURCES_SCHEMA, {'type': 'null'}]},
        'service': SERVICE_SCHEMA,
    },
}

_CONTROLLER_SECTION = {
    'type': 'object',
    'additionalProperties': False,
    'properties': {
        'controller': {
            'type': 'object',
            'additionalProperties': False,
            'properties': {
                'mode': {'enum': ['consolidated', 'dedicated']},
                'resources': RESOURCES_SCHEMA,
                # Deployment-backed controller host (kubernetes).
                'ha': _BOOL,
            },
        },
        # 2-hop file-mount staging bucket (controller_utils).
        'bucket': {
            'type': 'object',
            'additionalProperties': False,
            'properties': {
                'store': {'enum': ['gcs', 's3', 'azure', 'r2', 'cos',
                                   'oci', 'local']},
                'name': _STR,
            },
        },
    },
}

CONFIG_SCHEMA: Dict[str, Any] = {
    '$schema': 'https://json-schema.org/draft/2020-12/schema',
    'type': 'object',
    'additionalProperties': False,
    'properties': {
        'allowed_clouds': {'type': 'array', 'items': _STR},
        'admin_policy': _STR,
        'api_server': {
            'type': 'object',
            'additionalProperties': False,
            'properties': {
                'endpoint': _STR,
                'token': _STR,
                'auth': _BOOL,
                'users': {'type': 'array', 'items': {
                    'type': 'object',
                    'additionalProperties': False,
                    'properties': {
                        'name': _STR, 'token': _STR,
                        'role': {'enum': ['admin', 'user', 'viewer']},
                        'workspace': _STR,
                    }}},
            },
        },
        'gcp': {
            'type': 'object',
            'additionalProperties': False,
            'properties': {
                'project_id': _STR,
                'network': _STR,
                'subnetwork': _STR,
                'use_internal_ips': _BOOL,
                # MIG/DWS queued capacity + persistent-disk volumes.
                'use_mig': _BOOL,
                'run_duration': _INT,
                'volumes': {'type': 'array', 'items': {
                    'type': 'object',
                    'additionalProperties': False,
                    'properties': {
                        'name': _STR,
                        'size_gb': _INT,
                        'type': _STR,
                        'mount_path': _STR,
                        'keep': _BOOL,
                    }}},
            },
        },
        'aws': {
            'type': 'object',
            'additionalProperties': False,
            'properties': {
                'vpc_id': _STR,
                'use_internal_ips': _BOOL,
            },
        },
        'azure': {
            'type': 'object',
            'additionalProperties': False,
            'properties': {
                'subscription_id': _STR,
                'use_internal_ips': _BOOL,
            },
        },
        'nebius': {
            'type': 'object',
            'additionalProperties': False,
            'properties': {
                'project_id': _STR,
                'subnet_id': _STR,
            },
        },
        'cudo': {
            'type': 'object',
            'additionalProperties': False,
            'properties': {'project_id': _STR},
        },
        'kubernetes': {
            'type': 'object',
            'additionalProperties': False,
            'properties': {'namespace': _STR},
        },
        'r2': {
            'type': 'object',
            'additionalProperties': False,
            'properties': {'endpoint_url': _STR},
        },
        'oci': {
            'type': 'object',
            'additionalProperties': False,
            'properties': {
                'compartment_id': _STR,
                'subnet_id': _STR,
                'image_id': _STR,
                's3_endpoint_url': _STR,
            },
        },
        'ibm': {
            'type': 'object',
            'additionalProperties': False,
            'properties': {
                'vpc_id': _STR,
                'subnet_id': _STR,
                'image_id': _STR,
                'cos_endpoint_url': _STR,
            },
        },
        'scp': {
            'type': 'object',
            'additionalProperties': False,
            'properties': {'image_id': _STR},
        },
        'vsphere': {
            'type': 'object',
            'additionalProperties': False,
            'properties': {
                'template': _STR,
                'resource_pool': _STR,
                'datastore': _STR,
                'customization_spec': _STR,
                'ssh_user': _STR,
            },
        },
        'ssh': {
            'type': 'object',
            'additionalProperties': False,
            'properties': {'node_pools': {'type': 'object'}},
        },
        'local': {
            'type': 'object',
            'additionalProperties': False,
            'properties': {
                # Abandoned local clusters leak skylet daemons on the
                # user's own machine; 0 disables the default reaper.
                'default_autostop_minutes': {'type': 'number'},
            },
        },
        'jobs': _CONTROLLER_SECTION,
        'serve': _CONTROLLER_SECTION,
        'logs': {
            'type': 'object',
            'additionalProperties': False,
            'properties': {
                'store': {'enum': ['gcp', None]},
                'gcp': {
                    'type': 'object',
                    'additionalProperties': False,
                    'properties': {'project_id': _STR},
                },
            },
        },
        'usage': {
            'type': 'object',
            'additionalProperties': False,
            'properties': {
                'enabled': _BOOL,
                'endpoint': _STR,
            },
        },
        # Cluster liveness heartbeats (skylet -> API server). `url`
        # overrides the server's advertised address when clusters
        # reach it through ingress (provision/provisioner.py
        # build_topology).
        'heartbeat': {
            'type': 'object',
            'additionalProperties': False,
            'properties': {
                'url': _STR,
            },
        },
    },
}


# --- validation driver ------------------------------------------------------

def _format_error(err) -> str:
    path = '.'.join(str(p) for p in err.absolute_path) or '<top level>'
    msg = err.message
    # 'additionalProperties' errors bury the offending key in prose;
    # surface valid keys so typos are one-glance fixable.
    if err.validator == 'additionalProperties':
        allowed = sorted((err.schema.get('properties') or {}).keys())
        if allowed:
            msg += f'. Valid keys: {allowed}'
    return f'{path}: {msg}'


def validate(instance: Any, schema: Dict[str, Any], what: str,
             exc_type: type = exceptions.InvalidTaskError) -> None:
    """Validate `instance`, raising `exc_type` listing EVERY violation
    (one pass fixes all typos, not one per run)."""
    import jsonschema
    validator = jsonschema.Draft202012Validator(schema)
    errors = sorted(validator.iter_errors(instance),
                    key=lambda e: list(e.absolute_path))
    if not errors:
        return
    # oneOf failures produce an unhelpful umbrella message plus precise
    # sub-errors; prefer the sub-errors.
    lines: List[str] = []
    for err in errors:
        best = jsonschema.exceptions.best_match([err])
        lines.append(_format_error(best if best is not None else err))
    detail = '\n  '.join(dict.fromkeys(lines))  # dedupe, keep order
    raise exc_type(f'Invalid {what}:\n  {detail}')


def validate_task(config: Dict[str, Any]) -> None:
    validate(config, TASK_SCHEMA, 'task YAML')


def validate_resources(config: Dict[str, Any]) -> None:
    validate(config, RESOURCES_SCHEMA, 'resources',
             exceptions.InvalidResourcesError)


def validate_service(config: Dict[str, Any]) -> None:
    validate(config, SERVICE_SCHEMA, 'service spec')


def validate_config(config: Dict[str, Any],
                    path: Optional[str] = None) -> None:
    what = f'config ({path})' if path else 'config'
    validate(config, CONFIG_SCHEMA, what, exceptions.ConfigError)
