"""Command runners — the control-plane communication backend.

Reference analog: sky/utils/command_runner.py:168 (`CommandRunner`,
`SSHCommandRunner` :439 with ControlMaster + proxy jump,
`KubernetesCommandRunner` :716). Ours adds `LocalProcessRunner` so the
local cloud exercises the identical interface with plain subprocesses.
"""
import os
import shlex
import subprocess
import tempfile
from typing import Dict, List, Optional, Tuple, Union

from skypilot_tpu import exceptions

_SSH_CONTROL_DIR = '~/.skytpu/ssh_control'


def _write_log(log_path: Optional[str], data: bytes) -> None:
    if not log_path:
        return
    os.makedirs(os.path.dirname(os.path.expanduser(log_path)) or '.',
                exist_ok=True)
    with open(os.path.expanduser(log_path), 'ab') as f:
        f.write(data)


class CommandRunner:
    """Run shell commands and rsync files against one host."""

    def __init__(self, node_id: str):
        self.node_id = node_id

    def run(self,
            cmd: Union[str, List[str]],
            *,
            env: Optional[Dict[str, str]] = None,
            stream_logs: bool = False,
            log_path: Optional[str] = None,
            cwd: Optional[str] = None,
            require_outputs: bool = False,
            timeout: Optional[float] = None
            ) -> Union[int, Tuple[int, str, str]]:
        raise NotImplementedError

    def rsync(self, source: str, target: str, *, up: bool,
              excludes: Optional[List[str]] = None,
              log_path: Optional[str] = None) -> None:
        raise NotImplementedError

    @staticmethod
    def _shell_prefix(env, cwd) -> str:
        prefix = ''
        if env:
            prefix += ' '.join(f'export {k}={shlex.quote(str(v))};'
                               for k, v in env.items())
        if cwd:
            prefix += f'cd {shlex.quote(cwd)} && '
        return prefix

    def check_connection(self) -> bool:
        try:
            rc = self.run('true', timeout=15)
            return rc == 0
        except Exception:  # noqa: BLE001
            return False

    # --- shared subprocess plumbing ----------------------------------------

    @staticmethod
    def _run_subprocess(argv: List[str], *, env=None, stream_logs=False,
                        log_path=None, cwd=None, require_outputs=False,
                        timeout=None, shell=False):
        stdout_chunks: List[bytes] = []
        stderr_chunks: List[bytes] = []
        proc = subprocess.Popen(
            argv, shell=shell, cwd=cwd,
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT if stream_logs and not require_outputs
            else subprocess.PIPE,
            start_new_session=True)
        try:
            if stream_logs and not require_outputs:
                assert proc.stdout is not None
                for line in iter(proc.stdout.readline, b''):
                    stdout_chunks.append(line)
                    print(line.decode(errors='replace'), end='', flush=True)
                    _write_log(log_path, line)
                proc.wait(timeout=timeout)
                out, err = b''.join(stdout_chunks), b''
            else:
                out, err = proc.communicate(timeout=timeout)
                out = out or b''
                err = err or b''
                _write_log(log_path, out + err)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()
            raise
        if require_outputs:
            return proc.returncode, out.decode(errors='replace'), \
                err.decode(errors='replace')
        return proc.returncode


class LocalProcessRunner(CommandRunner):
    """Run on this machine. Backs the `local` cloud."""

    def __init__(self, node_id: str = 'localhost'):
        super().__init__(node_id)

    def run(self, cmd, *, env=None, stream_logs=False, log_path=None,
            cwd=None, require_outputs=False, timeout=None):
        if isinstance(cmd, list):
            cmd = ' '.join(shlex.quote(c) for c in cmd)
        full_env = dict(os.environ)
        if env:
            full_env.update(env)
        return self._run_subprocess(
            ['bash', '-c', cmd], env=full_env, stream_logs=stream_logs,
            log_path=log_path, cwd=cwd, require_outputs=require_outputs,
            timeout=timeout)

    def rsync(self, source: str, target: str, *, up: bool,
              excludes=None, log_path=None):
        del up  # local: both directions identical
        import shutil
        source = os.path.expanduser(source)
        target = os.path.expanduser(target)
        os.makedirs(os.path.dirname(target.rstrip('/')) or '.',
                    exist_ok=True)
        if shutil.which('rsync'):
            argv = ['rsync', '-a']
            for e in excludes or []:
                argv += ['--exclude', e]
            argv += [source, target]
            rc, out, err = self._run_subprocess(argv, require_outputs=True,
                                                env=dict(os.environ))
            if rc != 0:
                raise exceptions.CommandError(rc, ' '.join(argv), err)
            return
        # Pure-python fallback (minimal images without rsync), keeping
        # rsync's trailing-slash semantics.
        ignore = (shutil.ignore_patterns(*excludes) if excludes else None)
        if os.path.isdir(source):
            if not source.endswith('/'):
                target = os.path.join(target,
                                      os.path.basename(source.rstrip('/')))
            shutil.copytree(source, target, dirs_exist_ok=True,
                            ignore=ignore)
        else:
            if target.endswith('/') or os.path.isdir(target):
                os.makedirs(target, exist_ok=True)
                target = os.path.join(target, os.path.basename(source))
            shutil.copy2(source, target)


class SSHCommandRunner(CommandRunner):
    """SSH + rsync against a remote host, with connection multiplexing."""

    def __init__(self, host: str, *, user: str,
                 private_key: Optional[str] = None, port: int = 22,
                 proxy_jump: Optional[str] = None):
        super().__init__(f'{user}@{host}:{port}')
        self.host = host
        self.user = user
        self.private_key = private_key
        self.port = port
        self.proxy_jump = proxy_jump

    def _ssh_base(self) -> List[str]:
        control_dir = os.path.expanduser(_SSH_CONTROL_DIR)
        os.makedirs(control_dir, exist_ok=True)
        opts = [
            '-o', 'StrictHostKeyChecking=no',
            '-o', 'UserKnownHostsFile=/dev/null',
            '-o', 'LogLevel=ERROR',
            '-o', 'IdentitiesOnly=yes',
            '-o', 'ConnectTimeout=30',
            '-o', 'ServerAliveInterval=5',
            '-o', 'ServerAliveCountMax=3',
            '-o', 'ControlMaster=auto',
            '-o', f'ControlPath={control_dir}/%C',
            '-o', 'ControlPersist=300s',
            '-p', str(self.port),
        ]
        if self.private_key:
            opts += ['-i', os.path.expanduser(self.private_key)]
        if self.proxy_jump:
            opts += ['-J', self.proxy_jump]
        return ['ssh'] + opts + [f'{self.user}@{self.host}']

    def interactive_argv(self) -> List[str]:
        """argv for an interactive login shell on the host (same
        option assembly as run/rsync — `tsky ssh` uses this). -t must
        precede the destination or ssh treats it as a remote command."""
        base = self._ssh_base()
        return base[:-1] + ['-t'] + base[-1:]

    def run(self, cmd, *, env=None, stream_logs=False, log_path=None,
            cwd=None, require_outputs=False, timeout=None):
        if isinstance(cmd, list):
            cmd = ' '.join(shlex.quote(c) for c in cmd)
        prefix = self._shell_prefix(env, cwd)
        wrapped = f'bash --login -c {shlex.quote(prefix + cmd)}'
        argv = self._ssh_base() + [wrapped]
        return self._run_subprocess(
            argv, env=dict(os.environ), stream_logs=stream_logs,
            log_path=log_path, require_outputs=require_outputs,
            timeout=timeout)

    def rsync(self, source: str, target: str, *, up: bool,
              excludes=None, log_path=None):
        """up: local `source` → remote `target`; down: remote `source` →
        local `target` (reference convention, command_runner.py:168)."""
        ssh_cmd = ' '.join(self._ssh_base()[:-1])  # drop user@host
        argv = ['rsync', '-az', '-e', ssh_cmd]
        for e in excludes or []:
            argv += ['--exclude', e]
        if up:
            argv += [os.path.expanduser(source),
                     f'{self.user}@{self.host}:{target}']
        else:
            local_target = os.path.expanduser(target)
            os.makedirs(os.path.dirname(local_target.rstrip('/')) or '.',
                        exist_ok=True)
            argv += [f'{self.user}@{self.host}:{source}', local_target]
        rc, out, err = self._run_subprocess(argv, require_outputs=True,
                                            env=dict(os.environ))
        if rc != 0:
            raise exceptions.CommandError(rc, 'rsync', err)


class KubernetesCommandRunner(CommandRunner):
    """kubectl exec / cp against one pod (reference
    utils/command_runner.py:716)."""

    def __init__(self, pod_name: str, *, namespace: str = 'default',
                 container: str = 'main'):
        super().__init__(f'{namespace}/{pod_name}')
        self.pod_name = pod_name
        self.namespace = namespace
        self.container = container
        self._pod_home = None

    def _base(self) -> List[str]:
        return ['kubectl', '-n', self.namespace]

    def interactive_argv(self) -> List[str]:
        """argv for an interactive shell in the pod (`tsky ssh`)."""
        return self._base() + ['exec', '-it', self.pod_name,
                               '-c', self.container, '--', 'bash']

    def run(self, cmd, *, env=None, stream_logs=False, log_path=None,
            cwd=None, require_outputs=False, timeout=None):
        if isinstance(cmd, list):
            cmd = ' '.join(shlex.quote(c) for c in cmd)
        prefix = self._shell_prefix(env, cwd)
        argv = self._base() + [
            'exec', self.pod_name, '-c', self.container, '--',
            'bash', '-c', prefix + cmd]
        return self._run_subprocess(
            argv, env=dict(os.environ), stream_logs=stream_logs,
            log_path=log_path, require_outputs=require_outputs,
            timeout=timeout)

    def _resolve_home(self, path: str) -> str:
        """'~/x' -> '$HOME/x' in the POD (kubectl cp and quoted shell
        substitutions never tilde-expand)."""
        if not path.startswith('~'):
            return path
        if self._pod_home is None:
            rc, out, err = self.run('echo $HOME', require_outputs=True)
            if rc != 0 or not out.strip():
                raise exceptions.CommandError(rc, 'echo $HOME',
                                              err or out)
            self._pod_home = out.strip().splitlines()[-1]
        rest = path[1:].lstrip('/')
        return f'{self._pod_home}/{rest}' if rest else self._pod_home

    def rsync(self, source: str, target: str, *, up: bool,
              excludes=None, log_path=None):
        """Directory sync via tar over kubectl exec (honors excludes);
        single files via kubectl cp. up: local `source` → pod `target`;
        down: pod `source` → local `target` (reference convention)."""
        if up:
            source = os.path.expanduser(source)
            target = self._resolve_home(target)
            if os.path.isdir(source):
                tar_args = ''.join(
                    f'--exclude={shlex.quote(e)} ' for e in excludes or [])
                dest = target.rstrip('/')
                local = (f'tar -cz {tar_args}-C {shlex.quote(source)} .')
                remote = (f'mkdir -p {shlex.quote(dest)} && '
                          f'tar -xz -C {shlex.quote(dest)}')
                argv = self._base() + [
                    'exec', '-i', self.pod_name, '-c', self.container,
                    '--', 'bash', '-c', remote]
                import subprocess as sp
                tar_proc = sp.Popen(['bash', '-c', local],
                                    stdout=sp.PIPE)
                rc = sp.run(argv, stdin=tar_proc.stdout,
                            capture_output=True, check=False).returncode
                tar_proc.wait()
                if rc != 0 or tar_proc.returncode != 0:
                    raise exceptions.CommandError(
                        rc or tar_proc.returncode, 'tar|kubectl exec', '')
                return
            self.run(f'mkdir -p $(dirname {shlex.quote(target)})')
            argv = self._base() + [
                'cp', source,
                f'{self.namespace}/{self.pod_name}:{target}',
                '-c', self.container]
        else:
            local_target = os.path.expanduser(target)
            os.makedirs(os.path.dirname(local_target.rstrip('/')) or '.',
                        exist_ok=True)
            argv = self._base() + [
                'cp',
                f'{self.namespace}/{self.pod_name}:'
                f'{self._resolve_home(source)}',
                local_target, '-c', self.container]
        rc, out, err = self._run_subprocess(argv, require_outputs=True,
                                            env=dict(os.environ))
        if rc != 0:
            raise exceptions.CommandError(rc, 'kubectl cp', err or out)
