"""Timeline profiling: spans -> Chrome trace JSON.

Reference analog: sky/utils/timeline.py:22 (`Event`, `@timeline.event`
:75; enabled via env var, viewable in chrome://tracing / Perfetto).
Enable with SKYTPU_TIMELINE=/path/to/trace.json.
"""
import atexit
import functools
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import envs
from skypilot_tpu.observability import tracing

_events: List[Dict[str, Any]] = []
_lock = threading.Lock()
_registered = False


def enabled() -> bool:
    return envs.SKYTPU_TIMELINE.is_set()


def _ensure_flush_registered() -> None:
    global _registered
    with _lock:
        if not _registered:
            atexit.register(save)
            _registered = True


class Event:
    """Context manager emitting one complete ('X') trace event."""

    def __init__(self, name: str, message: Optional[str] = None):
        self._name = name
        self._message = message
        self._begin = 0.0

    def __enter__(self) -> 'Event':
        self._begin = time.time()
        return self

    def __exit__(self, *args) -> None:
        if not enabled():
            return
        _ensure_flush_registered()
        event = {
            'name': self._name,
            'ph': 'X',
            'ts': self._begin * 1e6,
            'dur': (time.time() - self._begin) * 1e6,
            'pid': os.getpid(),
            'tid': threading.get_ident() % (1 << 31),
        }
        args: Dict[str, Any] = {}
        if self._message:
            args['message'] = self._message
        # Correlation: the contextvar request ID (observability.tracing)
        # lands in the span args, so a slow span in the Chrome trace
        # resolves to the exact `rid=` log lines of the same request.
        request_id = tracing.get_request_id()
        if request_id is not None:
            args['request_id'] = request_id
        if args:
            event['args'] = args
        with _lock:
            _events.append(event)


def event(fn=None, *, name: Optional[str] = None):
    """Decorator form: @timeline.event or @timeline.event(name=...)."""
    def wrap(f):
        label = name or f'{f.__module__}.{f.__qualname__}'

        @functools.wraps(f)
        def inner(*args, **kwargs):
            with Event(label):
                return f(*args, **kwargs)
        return inner
    if fn is not None:
        return wrap(fn)
    return wrap


def save(path: Optional[str] = None) -> Optional[str]:
    """Write accumulated events as a Chrome trace; returns the path."""
    path = path or envs.SKYTPU_TIMELINE.get()
    if not path:
        return None
    # Take-and-clear: an explicit save() followed by the atexit flush
    # (or two explicit saves) must not write a second per-PID file
    # duplicating every span already on disk.
    with _lock:
        events = list(_events)
        _events.clear()
    if not events:
        return None
    try:
        path = os.path.expanduser(path)
        os.makedirs(os.path.dirname(path) or '.', exist_ok=True)
        # One file per process: the server's forked workers each trace.
        if os.path.exists(path):
            root, ext = os.path.splitext(path)
            path = f'{root}.{os.getpid()}{ext}'
        with open(path, 'w', encoding='utf-8') as f:
            json.dump({'traceEvents': events}, f)
    except OSError:
        # Failed write (full/unwritable disk): put the spans back so a
        # later save() — e.g. the atexit flush — can retry instead of
        # silently losing the whole trace.
        with _lock:
            _events[:0] = events
        raise
    return path
