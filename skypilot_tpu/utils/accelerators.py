"""Accelerator canonicalization — TPUs are first-class.

The reference special-cases TPUs throughout (sky/resources.py:737 accelerator
canonicalization, sky/clouds/utils/gcp_utils.py:29 `is_tpu` predicates,
sky/catalog/gcp_catalog.py TPU branches). Here there is ONE accelerator
grammar and TPUs flow through the same path as GPUs:

    A100:8            -> 8x A100 GPUs on one node
    tpu-v5p:8         -> an 8-chip v5p slice (topology auto-selected)
    tpu-v5p-16        -> GCP slice-type spelling: 16 TensorCores == 8 chips
    tpu-v6e:256       -> a 256-chip v6e pod slice (multi-host)

For TPUs the framework, not the user, derives: the GCP acceleratorType
string, the chip<->core conversion, hosts per slice, and the default
ICI topology. All of that lives in `TpuGen` below.
"""
import dataclasses
import re
from typing import Dict, List, Optional, Tuple

from skypilot_tpu import exceptions


@dataclasses.dataclass(frozen=True)
class TpuGen:
    """Static description of one TPU generation."""
    name: str                 # canonical: 'tpu-v5p'
    gcp_prefix: str           # GCP acceleratorType prefix: 'v5p'
    size_unit: str            # 'cores' (v2-v4, v5p) or 'chips' (v5e, v6e)
    cores_per_chip: int       # for core-named gens: 2
    chips_per_host: int       # host VMs per slice = chips / chips_per_host
    hbm_gb_per_chip: float
    bf16_tflops_per_chip: float
    max_chips: int            # largest single-slice size
    default_runtime_version: str

    def slice_type(self, num_chips: int) -> str:
        """GCP acceleratorType, e.g. 8 chips of v5p -> 'v5p-16'."""
        if self.size_unit == 'cores':
            return f'{self.gcp_prefix}-{num_chips * self.cores_per_chip}'
        return f'{self.gcp_prefix}-{num_chips}'

    def chips_from_slice_size(self, size: int) -> int:
        if self.size_unit == 'cores':
            if size % self.cores_per_chip != 0:
                raise exceptions.InvalidResourcesError(
                    f'{self.gcp_prefix}-{size}: size must be a multiple of '
                    f'{self.cores_per_chip} (cores per chip)')
            return size // self.cores_per_chip
        return size

    def num_hosts(self, num_chips: int) -> int:
        return max(1, -(-num_chips // self.chips_per_host))

    def valid_chip_count(self, num_chips: int) -> bool:
        """Whether a slice of this many chips exists on GCP.

        Chip-unit gens (v5e/v6e) offer 1/4/8 then powers of two; core-unit
        gens (v2-v4, v5p) start at 4 chips and grow as 3D-torus multiples
        of 4.
        """
        if num_chips < 1 or num_chips > self.max_chips:
            return False
        if self.size_unit == 'chips':
            return num_chips in (1, 4) or (
                num_chips % 8 == 0 and (num_chips & (num_chips - 1)) == 0)
        return num_chips == 4 or (num_chips >= 8 and num_chips % 4 == 0)


# Public TPU generation data (cloud.google.com/tpu/docs). v5p/v6e are the
# flagship targets; older gens kept for catalog completeness.
TPU_GENERATIONS: Dict[str, TpuGen] = {
    g.name: g for g in [
        TpuGen('tpu-v2', 'v2', 'cores', 2, 4, 8.0, 23.0, 256, 'tpu-vm-base'),
        TpuGen('tpu-v3', 'v3', 'cores', 2, 4, 16.0, 61.0, 1024,
               'tpu-vm-base'),
        TpuGen('tpu-v4', 'v4', 'cores', 2, 4, 32.0, 137.5, 4096,
               'tpu-vm-v4-base'),
        TpuGen('tpu-v5e', 'v5litepod', 'chips', 1, 8, 16.0, 197.0, 256,
               'v2-alpha-tpuv5-lite'),
        TpuGen('tpu-v5p', 'v5p', 'cores', 2, 4, 95.0, 459.0, 8960,
               'v2-alpha-tpuv5'),
        TpuGen('tpu-v6e', 'v6e', 'chips', 1, 8, 32.0, 918.0, 256,
               'v2-alpha-tpuv6e'),
    ]
}

_TPU_ALIASES = {
    'tpu-v5litepod': 'tpu-v5e',
    'tpu-v5lite': 'tpu-v5e',
    'tpu-trillium': 'tpu-v6e',
}

# Canonical GPU names (subset; catalog carries the full per-cloud list).
_GPU_CANONICAL = [
    'A100', 'A100-80GB', 'H100', 'H200', 'B200', 'L4', 'T4', 'V100', 'P100',
    'A10G', 'L40S',
]
_GPU_LOWER = {g.lower(): g for g in _GPU_CANONICAL}

_TPU_SLICE_RE = re.compile(r'^(tpu-)?(v\d+[a-z]*|v5litepod)-(\d+)$',
                           re.IGNORECASE)
_TPU_GEN_RE = re.compile(r'^(tpu-)?(v\d+[a-z]*|v5litepod|trillium)$',
                         re.IGNORECASE)


def _lookup_gen(gen_token: str) -> Optional[TpuGen]:
    name = f'tpu-{gen_token.lower()}'
    name = _TPU_ALIASES.get(name, name)
    if name == 'tpu-v5litepod':
        name = 'tpu-v5e'
    return TPU_GENERATIONS.get(name)


def is_tpu(acc_name: Optional[str]) -> bool:
    return acc_name is not None and acc_name.lower().startswith('tpu-')


def canonicalize(name: str, count: float) -> Tuple[str, float]:
    """Canonicalize an accelerator (name, count) pair.

    TPU slice-type spellings ('tpu-v5p-16', 'v5litepod-8') fold into
    (generation, chip-count). GPU names are case-corrected. Unknown names
    pass through unchanged (catalog decides launchability later).
    """
    m = _TPU_SLICE_RE.match(name)
    if m:
        gen = _lookup_gen(m.group(2))
        if gen is not None:
            if count != 1:
                raise exceptions.InvalidResourcesError(
                    f'{name}:{count}: slice-type TPU names already encode '
                    f'size; use {gen.name}:<chips> to request chips.')
            return gen.name, float(gen.chips_from_slice_size(int(m.group(3))))
    m = _TPU_GEN_RE.match(name)
    if m:
        gen = _lookup_gen(m.group(2))
        if gen is not None:
            return gen.name, count
    return _GPU_LOWER.get(name.lower(), name), count


def tpu_gen(acc_name: str) -> TpuGen:
    gen = TPU_GENERATIONS.get(_TPU_ALIASES.get(acc_name.lower(),
                                               acc_name.lower()))
    if gen is None:
        raise exceptions.AcceleratorNotFoundError(
            f'Unknown TPU generation: {acc_name!r}. '
            f'Known: {sorted(TPU_GENERATIONS)}')
    return gen


def parse_accelerator_spec(spec) -> Optional[Dict[str, float]]:
    """Parse the user-facing `accelerators:` field.

    Accepts 'A100', 'A100:4', 'tpu-v5p:8', 'tpu-v5p-16', {'A100': 4},
    ['A100:8', 'tpu-v5e:8'] (ordered preference list -> dict).
    Returns canonicalized {name: count} or None.
    """
    if spec is None:
        return None
    if isinstance(spec, dict):
        out: Dict[str, float] = {}
        for k, v in spec.items():
            name, count = canonicalize(str(k), float(v))
            out[name] = count
        return out
    if isinstance(spec, str):
        specs = [spec]
    elif isinstance(spec, (list, tuple)):
        specs = [str(s) for s in spec]
    else:
        raise exceptions.InvalidResourcesError(
            f'Invalid accelerators spec: {spec!r}')
    out = {}
    for s in specs:
        s = s.strip()
        if ':' in s:
            name, _, count_str = s.partition(':')
            try:
                count = float(count_str)
            except ValueError as e:
                raise exceptions.InvalidResourcesError(
                    f'Invalid accelerator count in {s!r}') from e
        else:
            name, count = s, 1.0
        cname, ccount = canonicalize(name.strip(), count)
        out[cname] = ccount
    return out
