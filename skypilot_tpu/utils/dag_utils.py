"""DAG YAML load/dump: multi-document YAML = a task chain.

Reference analog: sky/utils/dag_utils.py (235 LoC). A pipeline file is
several `---`-separated task documents; an optional leading document
with only `name:` names the dag.
"""
from typing import Optional

from skypilot_tpu import dag as dag_lib
from skypilot_tpu import task as task_lib
from skypilot_tpu.utils import common_utils


def load_chain_dag_from_yaml(path: str,
                             env_overrides: Optional[dict] = None
                             ) -> dag_lib.Dag:
    configs = [c for c in common_utils.read_yaml_all(
        common_utils.expand_path(path)) if c]
    dag = dag_lib.Dag()
    if configs and set(configs[0].keys()) == {'name'}:
        dag.name = configs[0]['name']
        configs = configs[1:]
    prev = None
    for cfg in configs:
        task = task_lib.Task.from_yaml_config(cfg, env_overrides)
        dag.add(task)
        if prev is not None:
            dag.add_edge(prev, task)
        prev = task
    return dag


def dump_chain_dag_to_yaml(dag: dag_lib.Dag, path: str) -> None:
    import yaml
    docs = []
    if dag.name:
        docs.append({'name': dag.name})
    docs.extend(t.to_yaml_config() for t in dag.topological_order())
    with open(path, 'w', encoding='utf-8') as f:
        yaml.safe_dump_all(docs, f, sort_keys=False)
