"""Terminal status UX: spinner on TTYs, plain lines everywhere else.

Reference analog: sky/utils/rich_utils.py (395 LoC around the rich
library). rich isn't a dependency here; a thread-drawn spinner covers
the interactive case and logs degrade to one line per update, which is
what CI/pipes want anyway.
"""
import itertools
import sys
import threading
import time
from typing import Optional

_SPINNER_FRAMES = '⠋⠙⠹⠸⠼⠴⠦⠧⠇⠏'


class Status:
    """`with rich_utils.status('Provisioning'):` — spinner + message.

    update() swaps the message mid-flight; on non-TTY output each
    message prints once, so logs stay readable.
    """

    def __init__(self, message: str, out=None) -> None:
        self._message = message
        self._out = out or sys.stderr
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    def _is_tty(self) -> bool:
        return bool(getattr(self._out, 'isatty', lambda: False)())

    def update(self, message: str) -> None:
        with self._lock:
            self._message = message
        if not self._is_tty():
            self._out.write(f'{message}\n')
            self._out.flush()

    def _spin(self) -> None:
        for frame in itertools.cycle(_SPINNER_FRAMES):
            if self._stop.is_set():
                break
            with self._lock:
                message = self._message
            self._out.write(f'\r\x1b[2K{frame} {message}')
            self._out.flush()
            time.sleep(0.1)
        self._out.write('\r\x1b[2K')
        self._out.flush()

    def __enter__(self) -> 'Status':
        if self._is_tty():
            self._thread = threading.Thread(target=self._spin,
                                            daemon=True)
            self._thread.start()
        else:
            self._out.write(f'{self._message}\n')
            self._out.flush()
        return self

    def __exit__(self, *exc) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)


def status(message: str, out=None) -> Status:
    return Status(message, out=out)


# --- nesting / quiet handling (reference rich_utils client_status) ---------

_ACTIVE: list = []


class _NestedStatus:
    """Re-enter the live spinner instead of stacking a second one:
    inner scopes update the outer message and restore it on exit."""

    def __init__(self, outer: Status, message: str) -> None:
        self._outer = outer
        self._message = message
        self._saved: Optional[str] = None

    def __enter__(self):
        self._saved = self._outer._message  # noqa: SLF001
        self._outer.update(self._message)
        return self._outer

    def __exit__(self, *exc) -> None:
        if self._saved is not None:
            self._outer.update(self._saved)


class _NullStatus:
    def update(self, message: str) -> None:
        del message

    def __enter__(self):
        return self

    def __exit__(self, *exc) -> None:
        pass


def safe_status(message: str, out=None):
    """The status everyone should use: quiet under SKYTPU_QUIET, joins
    a live spinner instead of fighting it, plain Status otherwise
    (reference safe_status/client_status)."""
    from skypilot_tpu import envs
    if envs.SKYTPU_QUIET.get():
        return _NullStatus()
    if _ACTIVE:
        return _NestedStatus(_ACTIVE[-1], message)
    outer = Status(message, out=out)
    orig_enter, orig_exit = outer.__enter__, outer.__exit__

    class _Tracked:
        def update(self, m):
            outer.update(m)

        def __enter__(self):
            orig_enter()
            _ACTIVE.append(outer)
            return outer

        def __exit__(self, *exc):
            if _ACTIVE and _ACTIVE[-1] is outer:
                _ACTIVE.pop()
            orig_exit(*exc)

    return _Tracked()
