"""Table/duration/status formatting + streaming line processors.

Reference analog: sky/utils/log_utils.py (623 LoC: colored statuses,
RayUpLineProcessor-style streaming log parsers, table helpers)."""
import sys
import time
from typing import List, Optional


def print_table(headers: List[str], rows: List[List[str]],
                title: Optional[str] = None) -> None:
    try:
        import rich.console
        import rich.table
        table = rich.table.Table(title=title, box=None,
                                 header_style='bold')
        for h in headers:
            table.add_column(h)
        for row in rows:
            table.add_row(*[str(c) for c in row])
        rich.console.Console().print(table)
    except ImportError:  # pragma: no cover
        widths = [max(len(str(h)), *(len(str(r[i])) for r in rows))
                  if rows else len(str(h)) for i, h in enumerate(headers)]
        if title:
            print(title)
        print('  '.join(h.ljust(w) for h, w in zip(headers, widths)))
        for row in rows:
            print('  '.join(str(c).ljust(w) for c, w in zip(row, widths)))


def format_table(headers: List[str], rows: List[List[str]]) -> str:
    if not rows:
        widths = [len(h) for h in headers]
    else:
        widths = [max(len(str(h)), *(len(str(r[i])) for r in rows))
                  for i, h in enumerate(headers)]
    lines = ['  '.join(h.ljust(w) for h, w in zip(headers, widths))]
    for row in rows:
        lines.append('  '.join(str(c).ljust(w) for c, w in zip(row, widths)))
    return '\n'.join(lines)


# Status word -> ANSI color class (green/red/yellow/dim), mirroring the
# dashboard's chip classes so terminal and browser read the same.
_GREEN = ('UP', 'READY', 'RUNNING', 'SUCCEEDED', 'HEALTHY', 'enabled')
_RED = ('FAILED', 'FAILED_NO_RESOURCE', 'FAILED_CONTROLLER',
        'NOT_READY', 'UNHEALTHY', 'CANCELLED')
_YELLOW = ('PENDING', 'PROVISIONING', 'RECOVERING', 'STARTING', 'INIT',
           'STOPPED', 'STOPPING', 'SHUTTING_DOWN', 'SUBMITTED')


def colorize_status(status: str, out=None) -> str:
    """ANSI-colored status word on TTYs; plain text through pipes (CI
    logs must stay grep-able)."""
    out = out or sys.stdout
    if not getattr(out, 'isatty', lambda: False)():
        return status
    word = status.strip()  # callers pre-pad for table columns
    if word in _GREEN:
        code = '32'
    elif word in _RED:
        code = '31'
    elif word in _YELLOW:
        code = '33'
    else:
        code = '2'
    return f'\x1b[{code}m{status}\x1b[0m'


class LineProcessor:
    """Streaming log parser: feed lines as they arrive, derive UX
    state (reference RayUpLineProcessor / SkyLocalUpLineProcessor).
    Subclasses override process_line."""

    def __enter__(self) -> 'LineProcessor':
        return self

    def __exit__(self, *exc) -> None:
        pass

    def process_line(self, line: str) -> None:
        del line


class ProvisionLogProcessor(LineProcessor):
    """Drives a rich_utils.Status from provision-stream lines: phase
    markers update the spinner message; failures are collected for the
    post-mortem instead of scrolling away."""

    _PHASES = (
        ('waiting for', 'Waiting for instances'),
        ('starting skylet', 'Starting skylet'),
        ('setup:', 'Running setup'),
        ('[gang] run:', 'Running'),
    )

    def __init__(self, status=None) -> None:
        self.status = status
        self.phase = 'Provisioning'
        self.errors: List[str] = []

    def process_line(self, line: str) -> None:
        lowered = line.lower()
        for marker, phase in self._PHASES:
            if marker in lowered:
                self.phase = phase
                if self.status is not None:
                    self.status.update(phase)
                break
        if 'error' in lowered or 'failed' in lowered:
            self.errors.append(line.strip())


# A cluster whose skylet has gone quiet for this long is flagged stale
# (HeartbeatEvent ticks every 60s; 3 missed beats + slack).
HEARTBEAT_STALE_SECONDS = 240.0


def heartbeat_str(age_s: Optional[float], status: Optional[str] = None
                  ) -> str:
    """Render a liveness-heartbeat age for status tables: '32s ago',
    '5m ago (stale)', or '-' when the cluster has never reported (a
    STOPPED cluster's silence is expected, not stale)."""
    if age_s is None:
        return '-'
    now = time.time()
    rendered = readable_time_duration(now - age_s, now) + ' ago'
    if age_s > HEARTBEAT_STALE_SECONDS and status not in ('STOPPED', None):
        rendered += ' (stale)'
    return rendered


def readable_time_duration(start: Optional[float],
                           end: Optional[float] = None,
                           absolute: bool = False) -> str:
    if start is None:
        return '-'
    if end is None:
        end = time.time()
    secs = max(0, int(end - start))
    if secs < 60:
        return f'{secs}s'
    mins, secs = divmod(secs, 60)
    if mins < 60:
        return f'{mins}m {secs}s' if absolute else f'{mins}m'
    hours, mins = divmod(mins, 60)
    if hours < 24:
        return f'{hours}h {mins}m'
    days, hours = divmod(hours, 24)
    return f'{days}d {hours}h'
