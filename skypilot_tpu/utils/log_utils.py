"""Table/duration formatting helpers for CLI output."""
import time
from typing import List, Optional


def print_table(headers: List[str], rows: List[List[str]],
                title: Optional[str] = None) -> None:
    try:
        import rich.console
        import rich.table
        table = rich.table.Table(title=title, box=None,
                                 header_style='bold')
        for h in headers:
            table.add_column(h)
        for row in rows:
            table.add_row(*[str(c) for c in row])
        rich.console.Console().print(table)
    except ImportError:  # pragma: no cover
        widths = [max(len(str(h)), *(len(str(r[i])) for r in rows))
                  if rows else len(str(h)) for i, h in enumerate(headers)]
        if title:
            print(title)
        print('  '.join(h.ljust(w) for h, w in zip(headers, widths)))
        for row in rows:
            print('  '.join(str(c).ljust(w) for c, w in zip(row, widths)))


def format_table(headers: List[str], rows: List[List[str]]) -> str:
    if not rows:
        widths = [len(h) for h in headers]
    else:
        widths = [max(len(str(h)), *(len(str(r[i])) for r in rows))
                  for i, h in enumerate(headers)]
    lines = ['  '.join(h.ljust(w) for h, w in zip(headers, widths))]
    for row in rows:
        lines.append('  '.join(str(c).ljust(w) for c, w in zip(row, widths)))
    return '\n'.join(lines)


def readable_time_duration(start: Optional[float],
                           end: Optional[float] = None,
                           absolute: bool = False) -> str:
    if start is None:
        return '-'
    if end is None:
        end = time.time()
    secs = max(0, int(end - start))
    if secs < 60:
        return f'{secs}s'
    mins, secs = divmod(secs, 60)
    if mins < 60:
        return f'{mins}m {secs}s' if absolute else f'{mins}m'
    hours, mins = divmod(mins, 60)
    if hours < 24:
        return f'{hours}h {mins}m'
    days, hours = divmod(hours, 24)
    return f'{days}d {hours}h'
