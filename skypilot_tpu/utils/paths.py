"""Canonical on-disk locations (client side and on-cluster runtime)."""
import os
from skypilot_tpu import envs


def state_dir() -> str:
    """Client-side state root (~/.skytpu or $SKYTPU_STATE_DIR)."""
    d = envs.SKYTPU_STATE_DIR.get() or os.path.expanduser('~/.skytpu')
    os.makedirs(d, exist_ok=True)
    return d


def state_db_path() -> str:
    return os.path.join(state_dir(), 'state.db')


def cluster_yaml_dir() -> str:
    d = os.path.join(state_dir(), 'generated')
    os.makedirs(d, exist_ok=True)
    return d


def local_clusters_dir() -> str:
    d = os.path.join(state_dir(), 'local_clusters')
    os.makedirs(d, exist_ok=True)
    return d


def client_logs_dir() -> str:
    d = os.path.join(state_dir(), 'logs')
    os.makedirs(d, exist_ok=True)
    return d
