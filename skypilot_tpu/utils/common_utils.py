"""Small shared helpers: ids, name validation, size parsing, yaml io."""
import hashlib
import os
import re
import socket
import uuid
from typing import Any, Dict, List, Optional, Union

import yaml

from skypilot_tpu import exceptions

_CLUSTER_NAME_RE = re.compile(r'^[a-zA-Z]([-_a-zA-Z0-9]*[a-zA-Z0-9])?$')

_SIZE_UNITS = {
    '': 1, 'b': 1,
    'k': 2**10, 'kb': 2**10,
    'm': 2**20, 'mb': 2**20,
    'g': 2**30, 'gb': 2**30,
    't': 2**40, 'tb': 2**40,
}


def get_user_hash() -> str:
    """Stable per-user hash used in default cluster names and telemetry."""
    user = os.environ.get('USER', 'unknown')
    host = socket.gethostname()
    return hashlib.md5(f'{user}@{host}'.encode()).hexdigest()[:8]


def get_usage_run_id() -> str:
    return str(uuid.uuid4())


def check_cluster_name_is_valid(name: str) -> str:
    if not name or not _CLUSTER_NAME_RE.match(name):
        raise exceptions.InvalidTaskError(
            f'Cluster name {name!r} is invalid: must start with a letter and '
            'contain only letters, digits, "-" and "_".')
    return name


def make_cluster_name_on_cloud(name: str, max_len: int = 35) -> str:
    """Cloud-safe resource name: lowercase, deduped by hash when truncated."""
    safe = re.sub(r'[^a-z0-9-]', '-', name.lower())
    if len(safe) <= max_len:
        return safe
    digest = hashlib.md5(name.encode()).hexdigest()[:6]
    return f'{safe[:max_len - 7]}-{digest}'


def parse_memory_size(mem: Union[str, int, float],
                      field: str = 'memory') -> float:
    """'16', '16GB', '0.5tb', 16 -> GiB as float. A trailing '+' means
    at-least and is stripped (caller tracks the plus separately)."""
    if isinstance(mem, (int, float)):
        return float(mem)
    s = str(mem).strip().lower().rstrip('+')
    m = re.match(r'^([0-9.]+)\s*([a-z]*)$', s)
    if not m or m.group(2) not in _SIZE_UNITS:
        raise exceptions.InvalidResourcesError(
            f'Invalid {field} spec: {mem!r}')
    bytes_val = float(m.group(1)) * _SIZE_UNITS[m.group(2)]
    if m.group(2) in ('', 'b') and bytes_val < 2**20:
        # Bare numbers are GiB by convention ('16' == 16 GiB).
        return float(m.group(1))
    return bytes_val / 2**30


def parse_count_with_plus(value: Union[str, int, float]) -> tuple:
    """'8+' -> (8.0, True); 8 -> (8.0, False)."""
    if isinstance(value, (int, float)):
        return float(value), False
    s = str(value).strip()
    plus = s.endswith('+')
    return float(s.rstrip('+')), plus


def read_yaml(path: str) -> Any:
    with open(path, 'r', encoding='utf-8') as f:
        return yaml.safe_load(f)


def read_yaml_all(path: str) -> List[Any]:
    with open(path, 'r', encoding='utf-8') as f:
        return list(yaml.safe_load_all(f))


def dump_yaml(path: str, config: Any) -> None:
    with open(path, 'w', encoding='utf-8') as f:
        yaml.safe_dump(config, f, default_flow_style=False, sort_keys=False)


def dump_yaml_str(config: Any) -> str:
    return yaml.safe_dump(config, default_flow_style=False, sort_keys=False)


def deterministic_hash(obj: Any) -> str:
    """Stable hash of a JSON-able structure (cluster-config idempotency)."""
    canonical = yaml.safe_dump(obj, sort_keys=True)
    return hashlib.sha256(canonical.encode()).hexdigest()[:16]


def format_float(x: Optional[float], precision: int = 2) -> str:
    if x is None:
        return '-'
    if abs(x - round(x)) < 1e-9:
        return str(int(round(x)))
    return f'{x:.{precision}f}'


def expand_path(path: str) -> str:
    return os.path.abspath(os.path.expanduser(path))


def fill_template(template: str, variables: Dict[str, Any]) -> str:
    import jinja2  # lazy: keep base import light
    return jinja2.Template(template,
                           undefined=jinja2.StrictUndefined).render(**variables)


def generate_cluster_name() -> str:
    """tsky-<user>-<4 hex> (reference generate_cluster_name pattern)."""
    user = re.sub(r'[^a-z0-9-]', '', os.environ.get('USER', 'user').lower())
    return f'tsky-{user or "user"}-{uuid.uuid4().hex[:4]}'
