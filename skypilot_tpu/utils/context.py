"""Cooperative cancellation for request workers.

Reference analog: sky/utils/context.py (contextvar-scoped cancellation
the server checks inside long operations). Ours: each forked request
worker installs a SIGTERM handler that flips the current token, giving
in-flight code one grace window to stop at a safe point (flush state,
release a lock) before the process-group kill lands.

    from skypilot_tpu.utils import context
    ...
    while tailing_logs:
        context.raise_if_cancelled()   # or: if context.is_cancelled()
"""
import contextvars
import signal
import threading
from typing import Optional

from skypilot_tpu import exceptions


class CancellationToken:
    def __init__(self) -> None:
        self._event = threading.Event()

    def cancel(self) -> None:
        self._event.set()

    @property
    def cancelled(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._event.wait(timeout)


_current: contextvars.ContextVar[Optional[CancellationToken]] = \
    contextvars.ContextVar('skytpu_cancellation', default=None)
# Worker processes are one-request-per-fork: install_sigterm_handler
# also records the token process-globally so helper THREADS (bare
# threading.Thread starts with a fresh context) observe cancellation
# too. The contextvar layer keeps in-process tests isolated.
_process_token: Optional[CancellationToken] = None


def new_token() -> CancellationToken:
    """Create + activate a token for the current context."""
    token = CancellationToken()
    _current.set(token)
    return token


def current() -> Optional[CancellationToken]:
    return _current.get() or _process_token


def is_cancelled() -> bool:
    token = current()
    return token is not None and token.cancelled


def raise_if_cancelled() -> None:
    if is_cancelled():
        raise exceptions.RequestCancelled(
            'Operation cancelled by the server.')


def install_sigterm_handler() -> CancellationToken:
    """Worker-process setup: SIGTERM flips the token FIRST (cooperative
    window); a second SIGTERM — or the executor's follow-up SIGKILL —
    still terminates hard."""
    global _process_token
    token = new_token()
    _process_token = token

    def _handler(signum, frame):
        del frame
        if token.cancelled:
            signal.signal(signum, signal.SIG_DFL)
            signal.raise_signal(signum)
            return
        token.cancel()

    signal.signal(signal.SIGTERM, _handler)
    return token
