"""Dag: a DAG of Tasks with a context-manager builder.

Reference analog: sky/dag.py:11 (113 LoC). Chains are the common case
(managed-job pipelines); general DAGs validate acyclicity via networkx.
"""
import threading
from typing import List, Optional

from skypilot_tpu import exceptions

_dag_context = threading.local()


def _dag_stack() -> List['Dag']:
    stack = getattr(_dag_context, 'stack', None)
    if stack is None:
        stack = []
        _dag_context.stack = stack
    return stack


def get_current_dag() -> Optional['Dag']:
    stack = _dag_stack()
    return stack[-1] if stack else None


class Dag:
    def __init__(self, name: Optional[str] = None):
        self.name = name
        self.tasks: List = []
        self._edges: List = []  # (from_task, to_task)

    # --- building -----------------------------------------------------------

    def add(self, task) -> None:
        if task not in self.tasks:
            task.dag = self
            self.tasks.append(task)

    def remove(self, task) -> None:
        self.tasks.remove(task)
        self._edges = [(a, b) for a, b in self._edges
                       if a is not task and b is not task]

    def add_edge(self, a, b) -> None:
        self.add(a)
        self.add(b)
        self._edges.append((a, b))

    def __enter__(self) -> 'Dag':
        _dag_stack().append(self)
        return self

    def __exit__(self, *args) -> None:
        stack = _dag_stack()
        if stack and stack[-1] is self:
            stack.pop()

    def __len__(self) -> int:
        return len(self.tasks)

    # --- queries ------------------------------------------------------------

    @property
    def edges(self) -> List:
        return list(self._edges)

    def is_chain(self) -> bool:
        if len(self.tasks) <= 1:
            return True
        if len(self._edges) != len(self.tasks) - 1:
            return False
        order = self.topological_order()
        return all((order[i], order[i + 1]) in
                   {(a, b) for a, b in self._edges}
                   for i in range(len(order) - 1))

    def topological_order(self) -> List:
        import networkx as nx  # lazy
        g = nx.DiGraph()
        for t in self.tasks:
            g.add_node(id(t))
        for a, b in self._edges:
            g.add_edge(id(a), id(b))
        if not nx.is_directed_acyclic_graph(g):
            raise exceptions.InvalidDagError(f'Dag {self.name!r} has a cycle')
        by_id = {id(t): t for t in self.tasks}
        # Stable: prefer insertion order among ready nodes.
        order_ids = list(nx.lexicographical_topological_sort(
            g, key=lambda n: self.tasks.index(by_id[n])))
        return [by_id[i] for i in order_ids]

    def validate(self) -> None:
        self.topological_order()

    def __repr__(self) -> str:
        return (f'Dag({self.name!r}, tasks={len(self.tasks)}, '
                f'edges={len(self._edges)})')
