"""Framework logging setup: per-module loggers with env-gated debug.

Reference analog: sky/sky_logging.py (223 LoC). Usage:

    from skypilot_tpu import sky_logging
    logger = sky_logging.init_logger(__name__)

Env vars:
    SKYTPU_DEBUG=1                  everything at DEBUG
    SKYTPU_DEBUG_MODULES=a,b        only modules whose dotted name
                                    contains one of the fragments
    SKYTPU_MINIMIZE_LOGGING=1       WARNING+ only (scripting/CI)
"""
import logging
import sys
import threading
from typing import Optional

from skypilot_tpu import envs
from skypilot_tpu.observability import tracing

_FORMAT = ('%(levelname).1s %(asctime)s %(name)s:%(lineno)d]'
           '%(skytpu_rid)s %(message)s')
_DATE_FORMAT = '%m-%d %H:%M:%S'


class RequestIdFilter(logging.Filter):
    """Stamps records with the contextvar request ID (as ` rid=<id>`,
    or '' outside any request scope) so log lines correlate with
    timeline spans carrying the same ID. A filter rather than a
    formatter: it composes with any formatter and runs exactly once
    per record."""

    def filter(self, record: logging.LogRecord) -> bool:
        rid = tracing.get_request_id()
        record.skytpu_rid = f' rid={rid}' if rid else ''
        return True

_lock = threading.Lock()
_root_initialized = False


def _debug_all() -> bool:
    return envs.SKYTPU_DEBUG.get()


def _debug_fragments():
    return envs.SKYTPU_DEBUG_MODULES.get()


def _minimized() -> bool:
    return envs.SKYTPU_MINIMIZE_LOGGING.get()


def _level_for(name: str) -> int:
    if _debug_all():
        return logging.DEBUG
    for fragment in _debug_fragments():
        if fragment in name:
            return logging.DEBUG
    if _minimized():
        return logging.WARNING
    return logging.INFO


def _ensure_root_handler() -> None:
    global _root_initialized
    with _lock:
        if _root_initialized:
            return
        root = logging.getLogger('skypilot_tpu')
        if not root.handlers:
            handler = logging.StreamHandler(sys.stderr)
            handler.setFormatter(
                logging.Formatter(_FORMAT, datefmt=_DATE_FORMAT))
            handler.addFilter(RequestIdFilter())
            root.addHandler(handler)
        root.propagate = False
        _root_initialized = True


def init_logger(name: str) -> logging.Logger:
    """Module logger with the env-derived level applied."""
    _ensure_root_handler()
    logger = logging.getLogger(name)
    logger.setLevel(_level_for(name))
    return logger


def reload_levels() -> None:
    """Re-apply env-derived levels to every existing framework logger
    (tests / long-lived servers after env changes)."""
    for name, logger in logging.Logger.manager.loggerDict.items():
        if isinstance(logger, logging.Logger) and \
                name.startswith('skypilot_tpu'):
            logger.setLevel(_level_for(name))


class SuppressOutput:
    """Context manager silencing a logger temporarily (reference
    sky_logging.silent())."""

    def __init__(self, name: str = 'skypilot_tpu',
                 level: int = logging.ERROR) -> None:
        self._name = name
        self._level = level
        self._previous: Optional[int] = None

    def __enter__(self):
        logger = logging.getLogger(self._name)
        self._previous = logger.level
        logger.setLevel(self._level)
        return self

    def __exit__(self, *exc):
        logging.getLogger(self._name).setLevel(self._previous)
