"""Central registry of every SKYTPU_* environment variable.

One declaration per knob — name, type, default, and a docstring — so
the surface area of env-driven behavior is enumerable (docs, `tsky
env` tooling, the static-analysis gate) instead of scattered across
`os.environ.get` call sites with drifting defaults.

Contract (enforced by `skypilot_tpu.analysis`'s env-registry checker):

  * every `'SKYTPU_*'` string literal in the codebase must name a
    variable declared here;
  * values are read at CALL time, never at import time — controllers
    are spawned and tests set env vars after modules load, so an
    import-time read silently freezes the default (the trap that bit
    SKYTPU_JOBS_RETRY_GAP before PR 2);
  * reads go through `EnvVar.get()`, which parses by declared type and
    falls back to the default on malformed values — a typo'd tuning
    knob degrades to the default instead of crashing every import or
    500ing every request.

This module must stay dependency-free (stdlib only): it is imported by
logging, paths, and config bootstrap code.
"""
import dataclasses
import os
from typing import Any, Dict, FrozenSet, Optional

_FALSEY = ('0', 'false', 'no', 'off')
_UNSET = object()

_REGISTRY: Dict[str, 'EnvVar'] = {}


@dataclasses.dataclass(frozen=True)
class EnvVar:
    """One declared environment variable. `type` is one of str, int,
    float, bool, or list (comma-separated values)."""
    name: str
    type: type
    default: Any
    doc: str

    def raw(self) -> Optional[str]:
        """The exact string in the environment (None when unset).
        For save/restore dances; normal reads use get()."""
        return os.environ.get(self.name)

    def is_set(self) -> bool:
        return bool(os.environ.get(self.name))

    def get(self, default: Any = _UNSET, strict: bool = False) -> Any:
        """Parse the variable by its declared type, at call time.

        Unset or empty reads return the default (the declared one, or
        the per-call override for knobs whose default differs by
        plane). Malformed values also return the default: a typo'd
        TUNING knob must never take down an import or a request path.
        `strict=True` raises on malformed values instead — for
        identity-contract vars (gang coordinates) where silently
        falling back to a default (e.g. process_id=0 on two hosts)
        corrupts the job rather than degrading it.
        """
        fallback = self.default if default is _UNSET else default
        value = os.environ.get(self.name)
        if value is None:
            return fallback
        if value == '':
            # Set-but-empty is a distinct failure in strict mode: a
            # templating bug (VAR=$rank with rank unset) must not
            # silently collapse every host onto the default identity.
            if strict:
                raise ValueError(
                    f'{self.name} is set but empty; expected a '
                    f'{self.type.__name__}')
            return fallback
        if self.type is bool:
            return value.strip().lower() not in _FALSEY
        if self.type is list:
            return [p.strip() for p in value.split(',') if p.strip()]
        try:
            return self.type(value)
        except (TypeError, ValueError):
            if strict:
                raise ValueError(
                    f'{self.name}={value!r} is not a valid '
                    f'{self.type.__name__}') from None
            return fallback


def declare(name: str, type_: type, default: Any, doc: str) -> EnvVar:
    """Register one variable. Names are unique and SKYTPU_-prefixed."""
    if not name.startswith('SKYTPU_') or not name.isupper():
        raise ValueError(f'env var {name!r} must be SKYTPU_UPPER_CASE')
    if name in _REGISTRY:
        raise ValueError(f'env var {name!r} declared twice')
    if type_ not in (str, int, float, bool, list):
        raise ValueError(f'{name}: unsupported type {type_!r}')
    if not doc or len(doc.strip()) < 10:
        raise ValueError(f'{name}: declare a real docstring')
    var = EnvVar(name=name, type=type_, default=default, doc=doc)
    _REGISTRY[name] = var
    return var


def declared() -> Dict[str, EnvVar]:
    """Name -> EnvVar for every declared variable (a copy)."""
    return dict(_REGISTRY)


def declared_names() -> FrozenSet[str]:
    return frozenset(_REGISTRY)


# --- client / CLI -----------------------------------------------------------

SKYTPU_API_SERVER_URL = declare(
    'SKYTPU_API_SERVER_URL', str, None,
    'Remote API server endpoint; unset means auto-start/use the local '
    'server. Also inherited by executor workers so provisioned '
    'clusters learn where to send heartbeats.')
SKYTPU_API_TOKEN = declare(
    'SKYTPU_API_TOKEN', str, None,
    'Bearer token for the API server; wins over api_server.token in '
    'config.')
SKYTPU_CONFIG = declare(
    'SKYTPU_CONFIG', str, None,
    'Path of an extra config layer merged over user/project config.')
SKYTPU_STATE_DIR = declare(
    'SKYTPU_STATE_DIR', str, None,
    'Client-side state root; defaults to ~/.skytpu.')
SKYTPU_WORKSPACE = declare(
    'SKYTPU_WORKSPACE', str, 'default',
    'Workspace this request acts in (set by the API server from the '
    'authenticated user).')
SKYTPU_USER = declare(
    'SKYTPU_USER', str, None,
    'Acting username override; falls back to $USER.')
SKYTPU_QUIET = declare(
    'SKYTPU_QUIET', bool, False,
    'Suppress interactive spinners/status output (scripting, CI).')

# --- logging / diagnostics --------------------------------------------------

SKYTPU_DEBUG = declare(
    'SKYTPU_DEBUG', bool, False,
    'Log everything at DEBUG.')
SKYTPU_DEBUG_MODULES = declare(
    'SKYTPU_DEBUG_MODULES', list, (),
    'Comma-separated dotted-name fragments; matching modules log at '
    'DEBUG.')
SKYTPU_MINIMIZE_LOGGING = declare(
    'SKYTPU_MINIMIZE_LOGGING', bool, False,
    'WARNING+ only (scripting/CI).')
SKYTPU_TIMELINE = declare(
    'SKYTPU_TIMELINE', str, None,
    'Path to write the chrome://tracing timeline to; unset disables '
    'timeline recording.')

# --- API server -------------------------------------------------------------

SKYTPU_HEARTBEAT_URL = declare(
    'SKYTPU_HEARTBEAT_URL', str, None,
    'URL clusters should send liveness heartbeats to, when the bound '
    'address is not reachable from them (e.g. behind ingress).')
SKYTPU_WATCHDOG_INTERVAL = declare(
    'SKYTPU_WATCHDOG_INTERVAL', float, 30.0,
    'Seconds between watchdog checks (server state-dir watchdog; the '
    'inference server parent-death watchdog overrides the default to '
    '5s).')
SKYTPU_CANCEL_GRACE_SECONDS = declare(
    'SKYTPU_CANCEL_GRACE_SECONDS', float, 5.0,
    'Cooperative-cancellation grace before a request worker is '
    'SIGKILLed.')
SKYTPU_BOOTSTRAP_ADMIN_TOKEN = declare(
    'SKYTPU_BOOTSTRAP_ADMIN_TOKEN', str, None,
    'Deployment bootstrap credential: a fresh install has exactly one '
    'admin, who then creates real users over the API.')

# --- inference --------------------------------------------------------------

SKYTPU_MAX_QUEUE_DEPTH = declare(
    'SKYTPU_MAX_QUEUE_DEPTH', int, 0,
    'Inference-server load shedding: queue depth beyond which requests '
    'get a fast 503 + Retry-After. 0/unset disables.')
SKYTPU_DECODE_FUSE_STEPS = declare(
    'SKYTPU_DECODE_FUSE_STEPS', int, 8,
    'Decode steps fused into ONE device dispatch per engine host step '
    '(lax.fori_loop with donated KV buffers). 1 falls back to '
    'host-stepped decode (one dispatch per token).')
SKYTPU_KV_QUANT = declare(
    'SKYTPU_KV_QUANT', str, 'auto',
    'Default KV-cache quantization for engines constructed without an '
    'explicit kv_quant: none | int8 | auto (int8 on TPU, none '
    'elsewhere — int8 halves HBM traffic; CPU runs keep bf16 '
    'exactness).')
SKYTPU_KV_PAGE_SIZE = declare(
    'SKYTPU_KV_PAGE_SIZE', int, 64,
    'Positions per KV-cache page for the paged (block) allocator; '
    'engines built without an explicit kv_page_size use this. '
    '0 disables paging (dense per-slot cache). Applies to unsharded '
    'AND tensor-sharded engines (see SKYTPU_KV_PAGES_SHARDED); '
    'context-sharded meshes keep the dense layout.')
SKYTPU_KV_PAGES_SHARDED = declare(
    'SKYTPU_KV_PAGES_SHARDED', bool, True,
    'Whether engines on a tensor-sharded mesh default to the PAGED '
    'KV layout (the page pool shards its KV-heads axis over the '
    'tensor axis; block tables stay replicated). 0 keeps sharded '
    'engines dense by default; an explicit kv_page_size always wins. '
    'Context-sharded meshes ignore this and stay dense (pages '
    'indirect the sequence dim the context axis partitions).')
SKYTPU_KV_PAGES = declare(
    'SKYTPU_KV_PAGES', int, 0,
    'Paged KV pool size in pages (plus one reserved scratch page). '
    '0 sizes the pool to the dense equivalent '
    '(batch_size * pages-per-slot); smaller values oversubscribe and '
    'queue requests until pages free.')
SKYTPU_PREFIX_CACHE = declare(
    'SKYTPU_PREFIX_CACHE', bool, True,
    'Cross-request prefix KV reuse: index finished requests\' paged '
    'KV in a radix tree so a new prompt sharing a cached prefix maps '
    'those pages copy-on-write into its block table and prefills only '
    'from the first unmatched token. Applies to paged, draft-free '
    'engines — tensor-sharded meshes included (the index is host-side '
    'bookkeeping over page ids); false disables.')
SKYTPU_PREFIX_CACHE_MAX_PAGES = declare(
    'SKYTPU_PREFIX_CACHE_MAX_PAGES', int, 0,
    'Cap on KV pages the prefix cache may retain after publishing a '
    'finished request (LRU-evicted down to the cap). 0 bounds the '
    'cache only by the page pool itself — live requests always '
    'reclaim cold refcount-0 cache pages on demand.')
SKYTPU_PREFILL_INTERLEAVE = declare(
    'SKYTPU_PREFILL_INTERLEAVE', int, -1,
    'Default interleaved-prefill threshold (tokens) for engines built '
    'without an explicit prefill_interleave: prompts longer than this '
    'prefill one chunk per engine step. -1 keeps the built-in default '
    '(4x prefill_chunk); 0 disables interleaving.')
SKYTPU_SPEC_K = declare(
    'SKYTPU_SPEC_K', int, 4,
    'Speculative-decoding draft length: tokens the draft model '
    'proposes per big-model verify pass when a draft is attached.')
SKYTPU_SPEC_FUSE_ROUNDS = declare(
    'SKYTPU_SPEC_FUSE_ROUNDS', int, 8,
    'Speculative draft/verify rounds fused into ONE device dispatch '
    'per engine host step (donated-buffer lax.while_loop; up to '
    'rounds * SKYTPU_SPEC_K tokens per round-trip), aligned with '
    'SKYTPU_DECODE_FUSE_STEPS by default. 1 falls back to one host '
    'dispatch per speculative round.')

# --- checkpoints (HF safetensors import/export) -----------------------------

SKYTPU_HF_IMPORT_STRICT = declare(
    'SKYTPU_HF_IMPORT_STRICT', bool, True,
    'HF checkpoint import: fail on tensors that do not map onto the '
    'engine pytree (usually a wrong config.json or mis-detected '
    'family). 0 downgrades unexpected-tensor errors to warnings; '
    'missing tensors are always fatal.')
SKYTPU_HF_IMPORT_CONCURRENCY = declare(
    'SKYTPU_HF_IMPORT_CONCURRENCY', int, 1,
    'Shard read/transform threads running ahead of device placement '
    'during HF checkpoint import. 1 is fully synchronous; N>1 keeps '
    'up to N transformed layer tensors on the host at once (memory/'
    'speed trade on top of the O(largest tensor) floor).')

# --- serve plane ------------------------------------------------------------

SKYTPU_SERVE_LOOP_INTERVAL = declare(
    'SKYTPU_SERVE_LOOP_INTERVAL', float, 10.0,
    'Seconds between serve-controller probe/autoscale/sync iterations.')
SKYTPU_SERVE_LAUNCH_RETRY_GAP = declare(
    'SKYTPU_SERVE_LAUNCH_RETRY_GAP', float, 10.0,
    'Base backoff between replica launch retries.')
SKYTPU_PROBE_BREAKER_RECOVERY = declare(
    'SKYTPU_PROBE_BREAKER_RECOVERY', float, 30.0,
    'Seconds an open probe circuit waits before a half-open retry.')

# --- managed jobs -----------------------------------------------------------

SKYTPU_JOBS_POLL_INTERVAL = declare(
    'SKYTPU_JOBS_POLL_INTERVAL', float, 15.0,
    'Seconds between managed-job controller poll iterations.')
SKYTPU_JOBS_RETRY_GAP = declare(
    'SKYTPU_JOBS_RETRY_GAP', float, 10.0,
    'Base backoff between managed-job recovery launch attempts.')
SKYTPU_JOBS_RECOVERY_DEADLINE = declare(
    'SKYTPU_JOBS_RECOVERY_DEADLINE', float, None,
    'Total seconds a managed-job recovery may keep retrying; unset '
    'means no deadline.')
SKYTPU_JOBS_MAX_CONCURRENT_LAUNCHES = declare(
    'SKYTPU_JOBS_MAX_CONCURRENT_LAUNCHES', int, 8,
    'Cap on managed-job controller processes in the launching phase.')

# --- provisioning / execution ----------------------------------------------

SKYTPU_RETRY_UNTIL_UP_GAP = declare(
    'SKYTPU_RETRY_UNTIL_UP_GAP', float, 300.0,
    'Seconds between full provision-failover rounds under '
    '--retry-until-up.')

# --- training ---------------------------------------------------------------

SKYTPU_CKPT_RETRY_GAP = declare(
    'SKYTPU_CKPT_RETRY_GAP', float, 2.0,
    'Base backoff between checkpoint-save retries.')

# --- usage telemetry --------------------------------------------------------

SKYTPU_DISABLE_USAGE_COLLECTION = declare(
    'SKYTPU_DISABLE_USAGE_COLLECTION', bool, False,
    'Disable usage-event recording and shipping entirely.')
SKYTPU_USAGE_ENDPOINT = declare(
    'SKYTPU_USAGE_ENDPOINT', str, None,
    'HTTP endpoint usage events POST to, best-effort; unset means '
    'spool-only.')
SKYTPU_USAGE_SPOOL_MAX_BYTES = declare(
    'SKYTPU_USAGE_SPOOL_MAX_BYTES', int, 8 * 1024 * 1024,
    'Spool size at which usage_events.jsonl rotates to one .1 '
    'generation.')

# --- resilience / chaos -----------------------------------------------------

SKYTPU_FAULTS = declare(
    'SKYTPU_FAULTS', str, '',
    'Comma-separated fault-injection specs '
    '(point[:times|forever[:latency]]), re-read at inject time.')

# --- preemption-safe serving (drain + mid-stream migration) ------------------

SKYTPU_MIGRATION_ENABLE = declare(
    'SKYTPU_MIGRATION_ENABLE', bool, True,
    'Mid-stream request migration: on replica drain or upstream '
    'death the LB fetches the request\'s KV snapshot and resumes it '
    'on another replica. Off, every interrupted stream takes the '
    'honest-termination path.')
SKYTPU_DRAIN_DEADLINE_SECONDS = declare(
    'SKYTPU_DRAIN_DEADLINE_SECONDS', float, 10.0,
    'Seconds /internal/drain waits for in-flight requests to finish '
    'naturally before snapshotting the stragglers for migration '
    '(spot preemption notice is ~30s; leave headroom for restore).')
SKYTPU_MIGRATION_DEADLINE_SECONDS = declare(
    'SKYTPU_MIGRATION_DEADLINE_SECONDS', float, 15.0,
    'Total wall-clock budget for one stream migration on the LB '
    '(snapshot fetch + restore attempts across replicas); past it '
    'the stream falls back to honest termination.')
SKYTPU_MIGRATION_MAX_BYTES = declare(
    'SKYTPU_MIGRATION_MAX_BYTES', int, 256 * 1024 * 1024,
    'Cap on one request\'s serialized KV snapshot; snapshot_request '
    'refuses larger blobs (the request honest-terminates instead of '
    'shipping an unbounded payload through the LB).')

# --- disaggregated prefill/decode (planned KV handoff) -----------------------

SKYTPU_HANDOFF_LEASE_SECONDS = declare(
    'SKYTPU_HANDOFF_LEASE_SECONDS', float, 5.0,
    'Seconds a prefill replica holds a handoff-paused request\'s '
    'slot live waiting for the LB to confirm the decode-leg restore '
    'or call /internal/resume; past it the engine resumes decoding '
    'locally (co-located fallback, never a lost token).')
SKYTPU_HANDOFF_DEADLINE_SECONDS = declare(
    'SKYTPU_HANDOFF_DEADLINE_SECONDS', float, 3.0,
    'Total wall-clock budget for the LB\'s planned prefill->decode '
    'handoff (restore attempts across the decode pool); past it the '
    'LB resumes the request co-located on the prefill replica — a '
    'counted fallback, never an error. Keep it under '
    'SKYTPU_HANDOFF_LEASE_SECONDS or the lease resumes first.')
SKYTPU_HANDOFF_MAX_BYTES = declare(
    'SKYTPU_HANDOFF_MAX_BYTES', int, 256 * 1024 * 1024,
    'Cap on a planned-handoff KV blob the LB will ship to the '
    'decode pool; larger blobs skip the transfer and resume '
    'co-located on the prefill replica (counted as a fallback).')

# --- serve LB streaming -----------------------------------------------------

SKYTPU_LB_STREAM_READ_TIMEOUT = declare(
    'SKYTPU_LB_STREAM_READ_TIMEOUT', float, 120.0,
    'Seconds the LB waits for the NEXT chunk from an upstream that '
    'already sent response bytes; a wedged upstream terminates the '
    'client stream instead of hanging it. 0 disables.')

# --- serve LB routing (prefix affinity + replica pools) ---------------------

SKYTPU_LB_POLICY = declare(
    'SKYTPU_LB_POLICY', str, None,
    'Override the load-balancing policy the service spec picked '
    '(round_robin / least_load / prefix_affinity) without editing the '
    'task YAML — an operator escape hatch for live A/B routing runs.')
SKYTPU_LB_AFFINITY_BOUND = declare(
    'SKYTPU_LB_AFFINITY_BOUND', float, 2.0,
    'Bounded-load constant c for prefix-affinity routing: the affine '
    'replica is skipped (least-load fallback) once its load would '
    'exceed ceil(c * (total_load + 1) / replicas) — affinity must '
    'never create a hotspot.')
SKYTPU_LB_AFFINITY_PAGE_TOKENS = declare(
    'SKYTPU_LB_AFFINITY_PAGE_TOKENS', int, 64,
    'Token-page granularity of the LB\'s prompt-prefix fingerprint '
    'index. Match the engine\'s SKYTPU_KV_PAGE_SIZE so LB affinity '
    'decisions align with what the replica radix cache can actually '
    'reuse.')
SKYTPU_LB_AFFINITY_MAX_ENTRIES = declare(
    'SKYTPU_LB_AFFINITY_MAX_ENTRIES', int, 65536,
    'LRU cap on prompt-prefix fingerprints the LB affinity index '
    'holds (each entry maps one page-aligned prefix to the replicas '
    'that served it).')
SKYTPU_LB_AFFINITY_LOAD_WINDOW = declare(
    'SKYTPU_LB_AFFINITY_LOAD_WINDOW', float, 1.0,
    'Seconds of recent request starts counted (on top of in-flight '
    'requests) as a replica\'s load in the bounded-load check — '
    'protects against a burst of simultaneous dispatches to one warm '
    'replica. 0 uses pure in-flight load.')
SKYTPU_LB_POOL_PROMPT_THRESHOLD = declare(
    'SKYTPU_LB_POOL_PROMPT_THRESHOLD', int, 1024,
    'Prompt-token count at or above which a request counts as '
    'long-prompt for replica-pool routing (long-prompt + short-gen '
    'requests prefer the prefill-role pool).')
SKYTPU_LB_POOL_MAX_NEW_THRESHOLD = declare(
    'SKYTPU_LB_POOL_MAX_NEW_THRESHOLD', int, 32,
    'max_new_tokens at or below which a request counts as short-gen '
    'for replica-pool routing; paired with '
    'SKYTPU_LB_POOL_PROMPT_THRESHOLD.')

# --- distributed request tracing --------------------------------------------

SKYTPU_TRACE_SAMPLE = declare(
    'SKYTPU_TRACE_SAMPLE', float, 0.01,
    'Head-sampling rate for request span trees (0..1). Errored and '
    'slow requests are kept regardless of the coin; 1.0 keeps every '
    'trace (debug / smoke runs).')
SKYTPU_TRACE_MAX_SPANS = declare(
    'SKYTPU_TRACE_MAX_SPANS', int, 20000,
    'Process-wide cap on buffered spans (active + completed). Over '
    'the cap the collector evicts the oldest completed trees, then '
    'drops new spans (counted, never raised).')
SKYTPU_TRACE_RECORDER_CAPACITY = declare(
    'SKYTPU_TRACE_RECORDER_CAPACITY', int, 32,
    'Completed span trees kept in the per-process flight-recorder '
    'ring (dumped on SLO breach / breaker open).')
SKYTPU_TRACE_SLOW_SECONDS = declare(
    'SKYTPU_TRACE_SLOW_SECONDS', float, 5.0,
    'Trace trees whose wall duration meets this threshold are kept '
    'even when the head-sampling coin said drop.')
SKYTPU_TRACE_DUMP_DIR = declare(
    'SKYTPU_TRACE_DUMP_DIR', str, None,
    'When set, the LB dumps the flight-recorder ring here as '
    'TRACE_<reason>_<pid>.json whenever a circuit breaker opens, and '
    'the telemetry watchdog dumps the ring plus the offending metric '
    'window as WATCHDOG_<rule>_<pid>.json whenever a rule fires.')

# --- live telemetry plane (time-series ring + watchdog) ----------------------

SKYTPU_TS_SAMPLE_SECONDS = declare(
    'SKYTPU_TS_SAMPLE_SECONDS', float, 5.0,
    'Seconds between background samples of the whole skytpu_* '
    'registry into the in-process time-series ring (the store behind '
    '/internal/timeseries). 0 disables the sampler thread.')
SKYTPU_TS_CAPACITY = declare(
    'SKYTPU_TS_CAPACITY', int, 240,
    'Samples retained per series in the time-series ring (240 x the '
    '5s default cadence = 20 minutes of history). Older samples fall '
    'off the ring; memory stays hard-bounded.')
SKYTPU_TS_MAX_SERIES = declare(
    'SKYTPU_TS_MAX_SERIES', int, 4096,
    'Hard cap on distinct series the time-series store retains. Past '
    'the cap, new series only displace series that went stale '
    '(stopped appearing in samples); fresh series are dropped and '
    'counted, so label churn can never grow memory without bound.')
SKYTPU_WATCHDOG_TICK_SECONDS = declare(
    'SKYTPU_WATCHDOG_TICK_SECONDS', float, 15.0,
    'Seconds between live watchdog rule evaluations over the '
    'time-series store. 0 disables the watchdog thread. (Distinct '
    'from SKYTPU_WATCHDOG_INTERVAL, the server state-dir watchdog.)')
SKYTPU_WATCHDOG_RULES = declare(
    'SKYTPU_WATCHDOG_RULES', str, None,
    'Semicolon-separated live SLO rules, e.g. '
    '"p95(skytpu_prefill_seconds)<0.5@60; '
    'ratio(skytpu_spec_accepted_tokens_total/'
    'skytpu_spec_proposed_tokens_total)>=0.5@120; '
    'within(skytpu_kv_pages_free,1,inf); '
    'anomaly(skytpu_decode_step_seconds)". See '
    'docs/guides/observability.md for the grammar. Unset means the '
    'built-in anomaly detectors only.')
SKYTPU_WATCHDOG_WINDOW_SECONDS = declare(
    'SKYTPU_WATCHDOG_WINDOW_SECONDS', float, 60.0,
    'Default query window (seconds) for watchdog rules that do not '
    'spell their own @window suffix.')
SKYTPU_WATCHDOG_BREACH_TICKS = declare(
    'SKYTPU_WATCHDOG_BREACH_TICKS', int, 2,
    'Consecutive breached watchdog evaluations before a rule FIRES '
    '(hysteresis against one-tick blips).')
SKYTPU_WATCHDOG_CLEAR_TICKS = declare(
    'SKYTPU_WATCHDOG_CLEAR_TICKS', int, 3,
    'Consecutive healthy watchdog evaluations before a firing rule '
    'CLEARS (hysteresis against boundary-hugging flapping).')
SKYTPU_WATCHDOG_ANOMALY_Z = declare(
    'SKYTPU_WATCHDOG_ANOMALY_Z', float, 8.0,
    'Robust-z threshold for the EWMA anomaly detector over step-time '
    'and TTFT series (deviation vs EWMA mean, scaled by an EWMA of '
    'absolute deviation). 0 disables the built-in anomaly rules.')

# --- fleet simulation / soak harness ----------------------------------------

SKYTPU_FLEETSIM_SEED = declare(
    'SKYTPU_FLEETSIM_SEED', int, 0,
    'Deterministic RNG seed for fleetsim traffic and replica latency '
    'distributions; one seed reproduces one soak run exactly.')
SKYTPU_FLEETSIM_TICK_SECONDS = declare(
    'SKYTPU_FLEETSIM_TICK_SECONDS', float, 0.0,
    'Override the scenario-declared virtual-clock tick (simulated '
    'seconds per controller step). 0/unset keeps the scenario value.')
SKYTPU_FLEETSIM_SCALE = declare(
    'SKYTPU_FLEETSIM_SCALE', float, 1.0,
    'Multiplier on scenario replica counts and traffic rates, so CI '
    'tiers can shrink a 1000-replica soak without editing scenarios.')
SKYTPU_FLEETSIM_OUT_DIR = declare(
    'SKYTPU_FLEETSIM_OUT_DIR', str, None,
    'Directory SLO_<scenario>.json reports are written to; unset '
    'means the current working directory.')
SKYTPU_FLEETSIM_MAX_WALL_SECONDS = declare(
    'SKYTPU_FLEETSIM_MAX_WALL_SECONDS', float, 300.0,
    'Wall-clock abort budget for one scenario run: a wedged sim '
    'fails its SLO report (rc=1) instead of hanging CI.')

# --- on-cluster runtime (the gang contract; injected per job process) -------

SKYTPU_RUNTIME_DIR = declare(
    'SKYTPU_RUNTIME_DIR', str, None,
    'On-cluster runtime root; defaults to ~/.skytpu_runtime. The local '
    'cloud gives every cluster its own runtime on one machine.')
SKYTPU_NUM_NODES = declare(
    'SKYTPU_NUM_NODES', int, 1,
    'Injected into job processes: logical nodes (slices) in the gang.')
SKYTPU_NODE_RANK = declare(
    'SKYTPU_NODE_RANK', int, 0,
    'Injected into job processes: this host\'s slice index.')
SKYTPU_NODE_IPS = declare(
    'SKYTPU_NODE_IPS', str, '',
    'Injected into job processes: newline-separated head-host IPs.')
SKYTPU_NUM_PROCESSES = declare(
    'SKYTPU_NUM_PROCESSES', int, 1,
    'Injected into job processes: total host processes in the gang.')
SKYTPU_PROCESS_ID = declare(
    'SKYTPU_PROCESS_ID', int, 0,
    'Injected into job processes: global host index of this process.')
SKYTPU_COORDINATOR_ADDR = declare(
    'SKYTPU_COORDINATOR_ADDR', str, None,
    'Injected into job processes: ip:port of process 0 for '
    'jax.distributed.initialize.')
SKYTPU_JOB_ID = declare(
    'SKYTPU_JOB_ID', str, None,
    'Injected into job processes: the cluster-local job id.')
SKYTPU_CLUSTER_NAME = declare(
    'SKYTPU_CLUSTER_NAME', str, None,
    'Injected into job processes: name of the cluster running the job.')
SKYTPU_ACCELERATORS_PER_NODE = declare(
    'SKYTPU_ACCELERATORS_PER_NODE', int, 0,
    'Injected into job processes: accelerator chips per logical node.')

# --- test / dev -------------------------------------------------------------

SKYTPU_SMOKE_REAL_GCP = declare(
    'SKYTPU_SMOKE_REAL_GCP', bool, False,
    'Opt smoke tests into touching real GCP with real credentials.')
