"""Browser login flow for `tsky api login --browser`.

Reference analog: sky/client/oauth.py (OAuth-proxy callback listener).
The shape is the same localhost-callback dance: the CLI opens the
server's `/dashboard/cli-auth?port=N` page in a browser, the user
authenticates there (cookie login if not already signed in), and the
server redirects to `http://127.0.0.1:N/callback?token=...` where the
CLI's one-shot listener catches the credential. No retyping tokens
into terminals, and the token never transits anything but the user's
own browser and loopback.
"""
import http.server
import threading
import urllib.parse
import webbrowser
from typing import Optional

from skypilot_tpu import exceptions

_SUCCESS_PAGE = (b'<!doctype html><html><body style="font-family:'
                 b'sans-serif;background:#0d1117;color:#c9d1d9;'
                 b'display:grid;place-items:center;height:100vh">'
                 b'<div>Logged in &mdash; you can close this tab and '
                 b'return to the terminal.</div></body></html>')


class _Callback(http.server.BaseHTTPRequestHandler):
    token: Optional[str] = None
    event: threading.Event

    def do_GET(self):  # noqa: N802 — http.server API
        parsed = urllib.parse.urlsplit(self.path)
        if parsed.path != '/callback':
            self.send_error(404)
            return
        params = urllib.parse.parse_qs(parsed.query)
        type(self).token = params.get('token', [''])[0]
        self.send_response(200)
        self.send_header('Content-Type', 'text/html')
        self.end_headers()
        self.wfile.write(_SUCCESS_PAGE)
        type(self).event.set()

    def log_message(self, *args):  # quiet
        del args


def browser_login(endpoint: str, timeout: float = 180.0,
                  open_browser=webbrowser.open) -> str:
    """Run the callback listener, open the auth page, return the
    token the server hands back (empty string = open local mode)."""
    handler = type('Handler', (_Callback,), {
        'token': None, 'event': threading.Event()})
    server = http.server.HTTPServer(('127.0.0.1', 0), handler)
    port = server.server_address[1]
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    url = f'{endpoint.rstrip("/")}/dashboard/cli-auth?port={port}'
    try:
        open_browser(url)
        print(f'Opening {url}\n(waiting for browser sign-in...)')
        if not handler.event.wait(timeout):
            raise exceptions.SkyTpuError(
                f'Browser login timed out after {timeout:.0f}s; '
                'use `tsky api login --token ...` instead.')
        return handler.token or ''
    finally:
        server.shutdown()
        thread.join(timeout=5)
