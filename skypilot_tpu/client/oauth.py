"""Browser login flow for `tsky api login --browser`.

Reference analog: sky/client/oauth.py (OAuth-proxy callback listener).
The shape is the same localhost-callback dance: the CLI opens the
server's `/dashboard/cli-auth?port=N&state=S` page in a browser, the
user authenticates there (cookie login if not already signed in), and
the page POSTs the token to `http://127.0.0.1:N/callback` — in the
request body, so the credential never appears in a URL (browser
history, proxy logs); a `?token=` GET redirect remains as a degraded
fallback for browsers that block page->loopback fetches (Chrome
Private Network Access on insecure public origins). Either way the
delivery must echo the CLI's single-use random `state`: the listener
sits on an open loopback port any web page can POST to, and without
the nonce an attacker could fix the session by racing their own token
into the CLI (classic OAuth login-CSRF — the state parameter exists
for exactly this).
"""
import hmac
import http.server
import secrets
import threading
import urllib.parse
import webbrowser
from typing import Optional

from skypilot_tpu import exceptions

_SUCCESS_PAGE = (b'<!doctype html><html><body style="font-family:'
                 b'sans-serif;background:#0d1117;color:#c9d1d9;'
                 b'display:grid;place-items:center;height:100vh">'
                 b'<div>Logged in &mdash; you can close this tab and '
                 b'return to the terminal.</div></body></html>')


class _Callback(http.server.BaseHTTPRequestHandler):
    token: Optional[str] = None
    state: str = ''
    error: Optional[str] = None
    event: threading.Event

    def _deny(self, code: int, msg: str) -> None:
        """Refusals carry the CORS header too: without it the consent
        page's fetch sees a 403 as a TypeError — indistinguishable
        from a network block — and its PNA fallback would redirect
        the token into a URL, the exact leak the POST path exists to
        avoid."""
        body = msg.encode()
        self.send_response(code)
        self.send_header('Access-Control-Allow-Origin', '*')
        self.send_header('Content-Type', 'text/plain')
        self.send_header('Content-Length', str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _accept(self, params, via_redirect: bool = False) -> bool:
        """Shared delivery rule for both verbs: a token field must be
        present (a field-less probe from a port scanner must not
        complete the flow — an empty result means 'open local mode'
        to the caller, which would silently drop auth; `token=`
        present-but-empty IS a real grant: open-mode servers have no
        token to hand out), and the state nonce must echo ours (an
        arbitrary web page can reach this listener; without the nonce
        it could fix the session with an attacker token)."""
        if 'token' not in params:
            self._deny(400, 'missing token field')
            return False
        if 'state' not in params:
            if via_redirect:
                # A token WITHOUT a state on the GET path is an old
                # server's redirect delivery — fail fast IN THE
                # TERMINAL (set error + wake browser_login) instead of
                # 403-looping a message into a browser tab until the
                # CLI's 180s timeout. Deliberate trade-off: a drive-by
                # page CAN fire this GET and abort the flow (it cannot
                # steal anything, only deny) — the message below names
                # both causes so interference isn't misdiagnosed as
                # version skew.
                type(self).error = (
                    'Received a token without the state nonce. Either '
                    'this API server is too old for --browser login, '
                    'or a local web page interfered with the flow; '
                    'retry, or use `tsky api login --token ...`.')
                self._deny(403, 'no state (old server)')
                type(self).event.set()
                return False
            # A state-less POST is never an old server (old servers
            # redirect; they don't POST) — it's a drive-by cross-origin
            # POST from some web page (the request executes even though
            # the response is CORS-opaque). Refuse WITHOUT waking the
            # login flow: aborting here would let any page kill an
            # in-flight `tsky api login --browser` and misdiagnose it
            # as version skew.
            self._deny(403, 'missing state')
            return False
        got = params['state'][0]
        # bytes comparison: compare_digest raises on non-ASCII str.
        if not hmac.compare_digest(got.encode(),
                                   type(self).state.encode()):
            self._deny(403, 'state mismatch')
            return False
        type(self).token = params['token'][0]
        return True

    def do_POST(self):  # noqa: N802 — http.server API
        """Primary path: the consent page POSTs token/state
        (urlencoded body). The CORS header lets the page's
        cross-origin fetch read the 200 and render its own success
        state."""
        if urllib.parse.urlsplit(self.path).path != '/callback':
            self.send_error(404)
            return
        length = int(self.headers.get('Content-Length') or 0)
        body = self.rfile.read(length).decode('utf-8', errors='replace')
        params = urllib.parse.parse_qs(body, keep_blank_values=True)
        if not self._accept(params):
            return
        self.send_response(200)
        self.send_header('Access-Control-Allow-Origin', '*')
        self.send_header('Content-Type', 'text/plain')
        self.end_headers()
        self.wfile.write(b'ok')
        type(self).event.set()

    def do_OPTIONS(self):  # noqa: N802 — http.server API
        """CORS preflight: browsers enforcing Private/Local Network
        Access preflight public-origin -> 127.0.0.1 fetches; without
        this the POST handoff dies with a 501."""
        self.send_response(204)
        self.send_header('Access-Control-Allow-Origin', '*')
        self.send_header('Access-Control-Allow-Methods', 'POST')
        self.send_header('Access-Control-Allow-Headers',
                         'Content-Type')
        self.send_header('Access-Control-Allow-Private-Network', 'true')
        self.end_headers()

    def do_GET(self):  # noqa: N802 — http.server API
        """Fallback for browsers whose page->loopback fetch is blocked
        (the consent page redirects here with token+state in the
        query). Same delivery rule as do_POST."""
        parsed = urllib.parse.urlsplit(self.path)
        if parsed.path != '/callback':
            self.send_error(404)
            return
        params = urllib.parse.parse_qs(parsed.query,
                                       keep_blank_values=True)
        if not self._accept(params, via_redirect=True):
            return
        self.send_response(200)
        self.send_header('Content-Type', 'text/html')
        self.end_headers()
        self.wfile.write(_SUCCESS_PAGE)
        type(self).event.set()

    def log_message(self, *args):  # quiet
        del args


def browser_login(endpoint: str, timeout: float = 180.0,
                  open_browser=webbrowser.open) -> str:
    """Run the callback listener, open the auth page, return the
    token the server hands back (empty string = open local mode)."""
    state = secrets.token_urlsafe(16)
    handler = type('Handler', (_Callback,), {
        'token': None, 'state': state, 'error': None,
        'event': threading.Event()})
    server = http.server.HTTPServer(('127.0.0.1', 0), handler)
    port = server.server_address[1]
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    url = (f'{endpoint.rstrip("/")}/dashboard/cli-auth?port={port}'
           f'&state={state}')
    try:
        open_browser(url)
        print(f'Opening {url}\n(waiting for browser sign-in...)')
        if not handler.event.wait(timeout):
            raise exceptions.SkyTpuError(
                f'Browser login timed out after {timeout:.0f}s; '
                'use `tsky api login --token ...` instead.')
        if handler.token is None:
            raise exceptions.SkyTpuError(
                handler.error or 'Browser login failed.')
        return handler.token
    finally:
        server.shutdown()
        thread.join(timeout=5)
