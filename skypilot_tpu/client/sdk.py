"""Client SDK: async HTTP calls to the API server, with auto-start.

Reference analog: sky/client/sdk.py (launch :361, exec :633, tail_logs
:717, stream_response :74; @check_server_healthy_or_start). Every call
returns a `request_id`; `get()` blocks for the result, `stream_and_get()`
also relays the server-side log stream to stdout.
"""
import json
import os
import subprocess
import sys
import time
import urllib.parse
import urllib.request
from typing import Any, Dict, List, Optional

from skypilot_tpu import envs
from skypilot_tpu import exceptions
from skypilot_tpu.server import app as server_app
from skypilot_tpu.utils import paths

_API_PREFIX = server_app.API_PREFIX


def api_server_url() -> str:
    url = envs.SKYTPU_API_SERVER_URL.get()
    if url:
        return url.rstrip('/')
    from skypilot_tpu import config as config_lib
    url = config_lib.get_nested(('api_server', 'endpoint'), default=None)
    if url:
        return str(url).rstrip('/')
    return f'http://127.0.0.1:{server_app.DEFAULT_PORT}'


def api_token() -> Optional[str]:
    """Bearer token for the API server (env wins over config)."""
    token = envs.SKYTPU_API_TOKEN.get()
    if token:
        return token
    from skypilot_tpu import config as config_lib
    return config_lib.get_nested(('api_server', 'token'), default=None)


def _request_raw(method: str, path: str,
                 payload: Optional[Dict[str, Any]] = None,
                 stream: bool = False, timeout: float = 300.0):
    from skypilot_tpu.server import auth as server_auth
    url = f'{api_server_url()}{_API_PREFIX}{path}'
    data = None
    headers = {server_auth.VERSION_HEADER: str(server_auth.API_VERSION)}
    token = api_token()
    if token:
        headers['Authorization'] = f'Bearer {token}'
    if payload is not None:
        data = json.dumps(payload).encode()
        headers['Content-Type'] = 'application/json'
    req = urllib.request.Request(url, data=data, headers=headers,
                                 method=method)
    try:
        resp = urllib.request.urlopen(req, timeout=timeout)
    except urllib.error.HTTPError as e:
        body = e.read().decode(errors='replace')
        if e.code == 426:
            raise exceptions.ApiVersionMismatchError(body) from e
        if e.code in (401, 403):
            raise exceptions.PermissionDeniedError(
                f'{method} {path}: HTTP {e.code}: {body}') from e
        raise exceptions.ApiServerError(
            f'{method} {path}: HTTP {e.code}: {body}') from e
    except urllib.error.URLError as e:
        raise exceptions.ApiServerError(
            f'API server unreachable at {api_server_url()}: '
            f'{e.reason}') from e
    if stream:
        return resp
    with resp:
        body = resp.read()
    return json.loads(body) if body else None


def server_healthy() -> bool:
    try:
        info = _request_raw('GET', '/health', timeout=2.0)
    except exceptions.ApiServerError:
        return False
    if info is None or info.get('status') != 'healthy':
        return False
    from skypilot_tpu.server import auth as server_auth
    server_api = info.get('api_version')
    if server_api is not None and server_api != server_auth.API_VERSION:
        raise exceptions.ApiVersionMismatchError(
            f'API server at {api_server_url()} speaks api_version '
            f'{server_api}; this client speaks '
            f'{server_auth.API_VERSION}. Upgrade the '
            f'{"client" if server_api > server_auth.API_VERSION else "server"}.')
    return True


def ensure_server_running(start_timeout: float = 30.0) -> None:
    """Auto-start a local API server when none is reachable (reference
    @check_server_healthy_or_start, sky/server/common.py)."""
    if server_healthy():
        return
    if envs.SKYTPU_API_SERVER_URL.is_set():
        raise exceptions.ApiServerError(
            f'Configured API server {api_server_url()} is unreachable.')
    log_path = os.path.join(paths.client_logs_dir(), 'api_server.log')
    with open(log_path, 'ab') as log_f:
        subprocess.Popen(
            [sys.executable, '-m', 'skypilot_tpu.server.app',
             '--port', str(server_app.DEFAULT_PORT)],
            stdout=log_f, stderr=log_f,
            start_new_session=True,
            env=dict(os.environ, JAX_PLATFORMS='cpu'))
    deadline = time.time() + start_timeout
    while time.time() < deadline:
        if server_healthy():
            return
        time.sleep(0.5)
    raise exceptions.ApiServerError(
        f'API server failed to start within {start_timeout:.0f}s; see '
        f'{log_path}')


def _submit(name: str, payload: Dict[str, Any]) -> str:
    ensure_server_running()
    resp = _request_raw('POST', f'/{name}', payload)
    return resp['request_id']


# --- request lifecycle ------------------------------------------------------

def get(request_id: str, timeout: Optional[float] = None) -> Any:
    """Block until the request finishes; return its result or raise."""
    deadline = None if timeout is None else time.time() + timeout
    while True:
        record = _request_raw('GET', f'/requests/{request_id}')
        status = record['status']
        if status == 'SUCCEEDED':
            return record['result']
        if status == 'CANCELLED':
            raise exceptions.RequestCancelled(
                f'Request {request_id} was cancelled.')
        if status == 'FAILED':
            raise exceptions.ApiServerError(
                f'Request {record["name"]} ({request_id}) failed: '
                f'{record["error"]}')
        if deadline is not None and time.time() > deadline:
            raise TimeoutError(
                f'Request {request_id} still {status} after {timeout}s')
        time.sleep(0.5)


def stream(request_id: str, output=None, follow: bool = True) -> None:
    """Relay the request's server-side log to `output` (default stdout)."""
    output = output or sys.stdout
    params = urllib.parse.urlencode({'follow': str(follow).lower()})
    resp = _request_raw('GET', f'/requests/{request_id}/stream?{params}',
                        stream=True, timeout=86400.0)
    with resp:
        while True:
            chunk = resp.read(4096)
            if not chunk:
                break
            output.write(chunk.decode(errors='replace'))
            output.flush()


def stream_and_get(request_id: str) -> Any:
    stream(request_id)
    return get(request_id)


def cancel_request(request_id: str) -> bool:
    resp = _request_raw('POST', f'/requests/{request_id}/cancel')
    return resp['cancelled']


def api_status(limit: int = 100) -> List[Dict[str, Any]]:
    ensure_server_running()
    return _request_raw('GET', f'/requests?limit={limit}')


# --- commands (each returns a request_id) -----------------------------------

def launch(task, cluster_name: str, *, dryrun: bool = False,
           detach_run: bool = False, no_setup: bool = False,
           retry_until_up: bool = False,
           minimize: str = 'COST') -> str:
    return _submit('launch', {
        'task': task.to_yaml_config(),
        'cluster_name': cluster_name,
        'dryrun': dryrun,
        'detach_run': detach_run,
        'no_setup': no_setup,
        'retry_until_up': retry_until_up,
        'minimize': minimize,
    })


def exec_cmd(task, cluster_name: str, *, detach_run: bool = False) -> str:
    return _submit('exec', {
        'task': task.to_yaml_config(),
        'cluster_name': cluster_name,
        'detach_run': detach_run,
    })


def status(cluster_names: Optional[List[str]] = None,
           refresh: bool = False) -> str:
    return _submit('status', {'cluster_names': cluster_names,
                              'refresh': refresh})


def start(cluster_name: str, idle_minutes: Optional[int] = None,
          down: bool = False) -> str:
    return _submit('start', {'cluster_name': cluster_name,
                             'idle_minutes': idle_minutes, 'down': down})


def stop(cluster_name: str) -> str:
    return _submit('stop', {'cluster_name': cluster_name})


def down(cluster_name: str, purge: bool = False) -> str:
    return _submit('down', {'cluster_name': cluster_name, 'purge': purge})


def autostop(cluster_name: str, idle_minutes: Optional[int],
             down: bool = False) -> str:
    return _submit('autostop', {'cluster_name': cluster_name,
                                'idle_minutes': idle_minutes,
                                'down': down})


def queue(cluster_name: str) -> str:
    return _submit('queue', {'cluster_name': cluster_name})


def cancel(cluster_name: str, job_ids: Optional[List[int]] = None,
           all_jobs: bool = False) -> str:
    return _submit('cancel', {'cluster_name': cluster_name,
                              'job_ids': job_ids, 'all_jobs': all_jobs})


def tail_logs(cluster_name: str, job_id: Optional[int] = None,
              follow: bool = True, tail: int = 0) -> str:
    return _submit('logs', {'cluster_name': cluster_name, 'job_id': job_id,
                            'follow': follow, 'tail': tail})


def cost_report() -> str:
    return _submit('cost_report', {})


def check(probe: bool = False, verbose: bool = False) -> str:
    return _submit('check', {'probe': probe, 'verbose': verbose})


def optimize(task, minimize: str = 'COST') -> str:
    return _submit('optimize', {'task': task.to_yaml_config(),
                                'minimize': minimize})


# --- managed jobs -----------------------------------------------------------

def jobs_launch(task_or_dag, name: Optional[str] = None,
                max_recoveries: int = 3,
                strategy: str = 'EAGER_NEXT_REGION') -> str:
    from skypilot_tpu import dag as dag_lib
    payload: Dict[str, Any] = {
        'name': name,
        'max_recoveries': max_recoveries,
        'strategy': strategy,
    }
    if isinstance(task_or_dag, dag_lib.Dag) and \
            len(task_or_dag.tasks) > 1:
        payload['pipeline'] = [t.to_yaml_config()
                               for t in task_or_dag.topological_order()]
        payload['name'] = name or task_or_dag.name
    else:
        task = (task_or_dag.tasks[0]
                if isinstance(task_or_dag, dag_lib.Dag) else task_or_dag)
        payload['task'] = task.to_yaml_config()
    return _submit('jobs_launch', payload)


def jobs_queue() -> str:
    return _submit('jobs_queue', {})


def jobs_cancel(job_ids: Optional[List[int]] = None,
                all_jobs: bool = False) -> str:
    return _submit('jobs_cancel', {'job_ids': job_ids,
                                   'all_jobs': all_jobs})


def jobs_logs(job_id: int, follow: bool = True) -> str:
    return _submit('jobs_logs', {'job_id': job_id, 'follow': follow})


# --- serve ------------------------------------------------------------------

def serve_up(task, service_name: str, wait_seconds: float = 0.0) -> str:
    return _submit('serve_up', {
        'task': task.to_yaml_config(),
        'service_name': service_name,
        'wait_seconds': wait_seconds,
    })


def serve_down(service_name: str, purge: bool = False) -> str:
    return _submit('serve_down', {'service_name': service_name,
                                  'purge': purge})


def serve_status(service_names: Optional[List[str]] = None) -> str:
    return _submit('serve_status', {'service_names': service_names})


def serve_logs(service_name: str, follow: bool = True) -> str:
    return _submit('serve_logs', {'service_name': service_name,
                                  'follow': follow})


def serve_update(task, service_name: str) -> str:
    return _submit('serve_update', {'task': task.to_yaml_config(),
                                    'service_name': service_name})


def storage_ls() -> str:
    return _submit('storage_ls', {})


def storage_delete(names: Optional[List[str]] = None,
                   all_storage: bool = False) -> str:
    return _submit('storage_delete', {'names': names,
                                      'all': all_storage})


def accelerators(name_filter: Optional[str] = None) -> str:
    return _submit('accelerators', {'name_filter': name_filter})


# --- admin: workspaces + users (synchronous CRUD, not queued) --------------

def workspaces_list() -> List[Dict[str, Any]]:
    ensure_server_running()
    return _request_raw('GET', '/workspaces')


def workspace_create(name: str, spec: Dict[str, Any]) -> Dict[str, Any]:
    ensure_server_running()
    return _request_raw('POST', '/workspaces',
                        {'name': name, **spec})


def workspace_update(name: str, spec: Dict[str, Any]) -> Dict[str, Any]:
    ensure_server_running()
    return _request_raw('PUT', f'/workspaces/{name}', spec)


def workspace_delete(name: str) -> Dict[str, Any]:
    ensure_server_running()
    return _request_raw('DELETE', f'/workspaces/{name}')


def users_list() -> List[Dict[str, Any]]:
    ensure_server_running()
    return _request_raw('GET', '/users')


def user_create(name: str, role: str = 'user',
                workspace: str = 'default') -> Dict[str, Any]:
    """Returns the doc with the generated token (echoed exactly once)."""
    ensure_server_running()
    return _request_raw('POST', '/users',
                        {'name': name, 'role': role,
                         'workspace': workspace})


def user_rotate(name: str) -> Dict[str, Any]:
    ensure_server_running()
    return _request_raw('POST', f'/users/{name}/rotate', {})


def user_update(name: str, role: Optional[str] = None,
                workspace: Optional[str] = None,
                disabled: Optional[bool] = None) -> Dict[str, Any]:
    ensure_server_running()
    payload: Dict[str, Any] = {}
    if role is not None:
        payload['role'] = role
    if workspace is not None:
        payload['workspace'] = workspace
    if disabled is not None:
        payload['disabled'] = disabled
    return _request_raw('PUT', f'/users/{name}', payload)


def user_delete(name: str) -> Dict[str, Any]:
    ensure_server_running()
    return _request_raw('DELETE', f'/users/{name}')


def api_server_pid() -> Optional[int]:
    """Pid of the (local) API server from its health endpoint."""
    try:
        info = _request_raw('GET', '/health', timeout=2.0)
    except exceptions.ApiServerError:
        return None
    return info.get('pid') if info else None
