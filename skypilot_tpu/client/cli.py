"""`tsky` — the CLI. Thin wrappers over the client SDK.

Reference analog: sky/client/cli/command.py (cli group :748, launch :901,
exec :1076); every command submits an async request and streams/polls.
"""
import json
import os
import sys
from typing import List, Optional

import click

from skypilot_tpu import exceptions


def _task_from_args(entrypoint, cluster_name: Optional[str], num_nodes,
                    accelerators, cloud, workdir, env, name):
    """YAML path -> Task; bare command -> inline Task (reference
    _make_task_or_dag_from_entrypoint)."""
    from skypilot_tpu import task as task_lib
    entry = ' '.join(entrypoint) if entrypoint else None
    is_yaml = bool(entry) and (entry.endswith(('.yaml', '.yml'))
                               and os.path.isfile(os.path.expanduser(entry)))
    if is_yaml:
        task = task_lib.Task.from_yaml(os.path.expanduser(entry))
    else:
        task = task_lib.Task(run=entry, name=name)
    if name:
        task.name = name
    if workdir:
        task.workdir = workdir
    if num_nodes:
        task.num_nodes = num_nodes
    envs = dict(e.split('=', 1) for e in env or [])
    if envs:
        task.update_envs(envs)
    if accelerators or cloud:
        from skypilot_tpu import resources as resources_lib
        base = next(iter(task.resources)) if task.resources else \
            resources_lib.Resources()
        overrides = {}
        if accelerators:
            overrides['accelerators'] = accelerators
        if cloud:
            overrides['infra'] = cloud
        task.set_resources({base.copy(**overrides)})
    return task


def _run_and_stream(request_id: str) -> None:
    from skypilot_tpu.client import sdk
    try:
        sdk.stream(request_id)
        sdk.get(request_id)
    except KeyboardInterrupt:
        click.echo(f'\nInterrupted. Request {request_id} keeps running; '
                   f'cancel with: tsky api cancel {request_id}')
        raise


@click.group()
@click.version_option(message='%(version)s',
                      package_name='skypilot_tpu',
                      version=__import__('skypilot_tpu').__version__)
def cli():
    """tsky: run AI workloads on TPU infrastructure."""


@cli.command()
@click.argument('entrypoint', nargs=-1)
@click.option('--cluster', '-c', default=None, help='Cluster name.')
@click.option('--name', '-n', default=None, help='Task name.')
@click.option('--num-nodes', type=int, default=None)
@click.option('--gpus', '--accelerators', 'accelerators', default=None,
              help='Accelerator spec, e.g. tpu-v5p:8 or A100:1.')
@click.option('--infra', '--cloud', 'cloud', default=None,
              help='Infra to use, e.g. gcp, gcp/us-central2, local.')
@click.option('--workdir', default=None)
@click.option('--env', multiple=True, help='KEY=VALUE env overrides.')
@click.option('--detach-run', '-d', is_flag=True)
@click.option('--dryrun', is_flag=True)
@click.option('--no-setup', is_flag=True)
@click.option('--down', is_flag=True,
              help='Autodown the cluster when the job finishes.')
@click.option('--idle-minutes-to-autostop', '-i', type=int, default=None)
@click.option('--retry-until-up', '-r', is_flag=True,
              help='Keep retrying provisioning until capacity is found.')
@click.option('--optimize-target', '-t',
              type=click.Choice(['cost', 'time']), default='cost',
              help='Minimize hourly cost or estimated completion time.')
def launch(entrypoint, cluster, name, num_nodes, accelerators, cloud,
           workdir, env, detach_run, dryrun, no_setup, down,
           idle_minutes_to_autostop, retry_until_up, optimize_target):
    """Launch a task (provision + setup + run)."""
    from skypilot_tpu.client import sdk
    from skypilot_tpu.utils import common_utils
    task = _task_from_args(entrypoint, cluster, num_nodes, accelerators,
                           cloud, workdir, env, name)
    if idle_minutes_to_autostop is not None or down:
        autostop_cfg = {'idle_minutes': idle_minutes_to_autostop
                        if idle_minutes_to_autostop is not None else 5,
                        'down': down}
        task.set_resources({r.copy(autostop=autostop_cfg)
                            for r in task.resources} or
                           None)
    cluster = cluster or common_utils.generate_cluster_name()
    click.echo(f'Launching on cluster {cluster!r}...')
    request_id = sdk.launch(task, cluster, dryrun=dryrun,
                            detach_run=detach_run, no_setup=no_setup,
                            retry_until_up=retry_until_up,
                            minimize=optimize_target.upper())
    _run_and_stream(request_id)


@cli.command('exec')
@click.argument('cluster')
@click.argument('entrypoint', nargs=-1, required=True)
@click.option('--name', '-n', default=None)
@click.option('--num-nodes', type=int, default=None)
@click.option('--workdir', default=None)
@click.option('--env', multiple=True)
@click.option('--detach-run', '-d', is_flag=True)
def exec_command(cluster, entrypoint, name, num_nodes, workdir, env,
                 detach_run):
    """Run a command/task on an existing cluster (skips provision/setup)."""
    from skypilot_tpu.client import sdk
    task = _task_from_args(entrypoint, cluster, num_nodes, None, None,
                           workdir, env, name)
    request_id = sdk.exec_cmd(task, cluster, detach_run=detach_run)
    _run_and_stream(request_id)


@cli.command()
@click.argument('clusters', nargs=-1)
@click.option('--refresh', '-r', is_flag=True,
              help='Reconcile against the cloud.')
def status(clusters, refresh):
    """Show clusters."""
    from skypilot_tpu.client import sdk
    records = sdk.get(sdk.status(list(clusters) or None, refresh=refresh))
    if not records:
        click.echo('No existing clusters.')
        return
    fmt = '{:<20} {:<28} {:<10} {:<8} {:<10} {}'
    click.echo(fmt.format('NAME', 'RESOURCES', 'STATUS', 'NODES',
                          'AUTOSTOP', 'HEARTBEAT'))
    from skypilot_tpu.utils import log_utils
    for r in records:
        autostop = r.get('autostop') or {}
        autostop_str = (f'{autostop.get("idle_minutes")}m'
                        f'{" (down)" if autostop.get("down") else ""}'
                        if autostop else '-')
        # Pad the PLAIN word first: ANSI codes must not count toward
        # the column width or colored rows shift the table.
        status_cell = log_utils.colorize_status(f'{r["status"]:<10}')
        click.echo(fmt.format(r['name'], r.get('resources_str') or '-',
                              status_cell, r.get('num_nodes') or 1,
                              autostop_str,
                              log_utils.heartbeat_str(
                                  r.get('heartbeat_age_s'),
                                  r.get('status'))))


@cli.command()
@click.argument('cluster')
@click.option('--idle-minutes-to-autostop', '-i', type=int, default=None)
@click.option('--down', is_flag=True)
def start(cluster, idle_minutes_to_autostop, down):
    """Restart a stopped cluster."""
    from skypilot_tpu.client import sdk
    sdk.stream_and_get(sdk.start(cluster, idle_minutes_to_autostop, down))
    click.echo(f'Cluster {cluster!r} started.')


@cli.command()
@click.argument('clusters', nargs=-1, required=True)
@click.option('--yes', '-y', is_flag=True)
def stop(clusters, yes):
    """Stop cluster(s) (kept on disk; restart with tsky start)."""
    from skypilot_tpu.client import sdk
    if not yes:
        click.confirm(f'Stop {", ".join(clusters)}?', abort=True)
    for c in clusters:
        sdk.stream_and_get(sdk.stop(c))
        click.echo(f'Cluster {c!r} stopped.')


@cli.command()
@click.argument('clusters', nargs=-1, required=True)
@click.option('--yes', '-y', is_flag=True)
@click.option('--purge', is_flag=True,
              help='Drop the record even if cloud teardown fails.')
def down(clusters, yes, purge):
    """Terminate cluster(s)."""
    from skypilot_tpu.client import sdk
    if not yes:
        click.confirm(f'Terminate {", ".join(clusters)}?', abort=True)
    for c in clusters:
        sdk.stream_and_get(sdk.down(c, purge=purge))
        click.echo(f'Cluster {c!r} terminated.')


@cli.command()
@click.argument('cluster')
@click.option('--idle-minutes', '-i', type=int, default=None,
              help='Idle minutes before autostop; -1 cancels.')
@click.option('--cancel', 'cancel_flag', is_flag=True)
@click.option('--down', is_flag=True)
def autostop(cluster, idle_minutes, cancel_flag, down):
    """Configure autostop/autodown on a cluster."""
    from skypilot_tpu.client import sdk
    if cancel_flag:
        idle_minutes = None
    elif idle_minutes is None:
        idle_minutes = 5
    sdk.get(sdk.autostop(cluster, idle_minutes, down))
    click.echo('Autostop updated.')


@cli.command()
@click.argument('cluster')
def queue(cluster):
    """Show a cluster's job queue."""
    from skypilot_tpu.client import sdk
    jobs = sdk.get(sdk.queue(cluster))
    fmt = '{:<6} {:<20} {:<12} {}'
    click.echo(fmt.format('ID', 'NAME', 'STATUS', 'RESOURCES'))
    for j in jobs:
        click.echo(fmt.format(j['job_id'], j.get('name') or '-',
                              j['status'], j.get('resources_str') or '-'))


@cli.command()
@click.argument('cluster')
@click.argument('job_ids', nargs=-1, type=int)
@click.option('--all', 'all_jobs', is_flag=True)
def cancel(cluster, job_ids, all_jobs):
    """Cancel job(s) on a cluster."""
    from skypilot_tpu.client import sdk
    result = sdk.get(sdk.cancel(cluster, list(job_ids) or None, all_jobs))
    click.echo(f'Cancelled jobs: {result["cancelled"]}')


@cli.command()
@click.argument('cluster')
@click.argument('job_id', required=False, type=int)
@click.option('--no-follow', is_flag=True)
@click.option('--tail', type=int, default=0)
def logs(cluster, job_id, no_follow, tail):
    """Tail a job's logs."""
    from skypilot_tpu.client import sdk
    request_id = sdk.tail_logs(cluster, job_id, follow=not no_follow,
                               tail=tail)
    _run_and_stream(request_id)


@cli.command()
@click.option('--no-probe', is_flag=True,
              help='Skip the per-cloud authenticated API probes '
                   '(presence checks only; offline).')
def check(no_probe):
    """Probe cloud credentials and cache enabled clouds.

    By default each present credential is VERIFIED with one cheap
    authenticated API call, so a revoked key fails here with the
    cloud named — not as a mid-provision failover."""
    from skypilot_tpu.client import sdk
    result = sdk.get(sdk.check(probe=not no_probe, verbose=True),
                     timeout=180)
    details = result.get('details', {})
    enabled = result.get('enabled', [])
    for name in sorted(details):
        d = details[name]
        reason = str(d.get('reason') or '')
        if d.get('ok'):
            if 'inconclusive' in reason:
                click.echo(f'  {name}: enabled ({reason})')
            else:
                kind = ('verified' if d.get('probed')
                        else 'credentials found')
                click.echo(f'  {name}: enabled ({kind})')
        elif ('reject' in reason.lower() or 'probe' in reason.lower()
              or 'error' in reason.lower()):
            # Rejected/broken credentials are loud (these phrasings
            # come from cloud.py's probe taxonomy and check.py's
            # exception wrapper, not free text); absent ones are the
            # normal case and stay quiet.
            click.echo(f'  {name}: DISABLED: {reason}')
    if enabled:
        click.echo('Enabled infra: ' + ', '.join(enabled))
    else:
        click.echo('No cloud credentials found. The `local` cloud is '
                   'always available for dev runs.')


@cli.command('cost-report')
def cost_report():
    """Estimated costs for live + historical clusters."""
    from skypilot_tpu.client import sdk
    rows = sdk.get(sdk.cost_report())
    fmt = '{:<24} {:<10} {:<12} {}'
    click.echo(fmt.format('NAME', 'STATUS', 'DURATION', 'COST ($)'))
    for r in rows:
        dur_h = (r.get('duration_s') or 0) / 3600.0
        cost = r.get('total_cost')
        click.echo(fmt.format(
            r['name'], r.get('status') or '-', f'{dur_h:.1f}h',
            f'{cost:.2f}' if cost is not None else '-'))


@cli.group()
def jobs():
    """Managed jobs: auto-recovering from TPU preemption."""


@jobs.command('launch')
@click.argument('entrypoint', nargs=-1)
@click.option('--name', '-n', default=None)
@click.option('--num-nodes', type=int, default=None)
@click.option('--gpus', '--accelerators', 'accelerators', default=None)
@click.option('--infra', '--cloud', 'cloud', default=None)
@click.option('--workdir', default=None)
@click.option('--env', multiple=True)
@click.option('--max-recoveries', type=int, default=3)
@click.option('--strategy', default='EAGER_NEXT_REGION',
              type=click.Choice(['FAILOVER', 'EAGER_NEXT_REGION'],
                                case_sensitive=False))
@click.option('--detach-run', '-d', is_flag=True)
def jobs_launch(entrypoint, name, num_nodes, accelerators, cloud, workdir,
                env, max_recoveries, strategy, detach_run):
    """Launch a managed job (controller relaunches it on preemption)."""
    from skypilot_tpu.client import sdk
    entry = ' '.join(entrypoint) if entrypoint else None
    target = None
    if entry and entry.endswith(('.yaml', '.yml')) and \
            os.path.isfile(os.path.expanduser(entry)):
        from skypilot_tpu.utils import common_utils
        docs = [c for c in common_utils.read_yaml_all(
            os.path.expanduser(entry)) if c]
        if len(docs) > 1:  # multi-document YAML = pipeline
            from skypilot_tpu.utils import dag_utils
            target = dag_utils.load_chain_dag_from_yaml(
                os.path.expanduser(entry),
                dict(e.split('=', 1) for e in env or []) or None)
    if target is None:
        target = _task_from_args(entrypoint, None, num_nodes,
                                 accelerators, cloud, workdir, env, name)
    result = sdk.get(sdk.jobs_launch(target, name=name,
                                     max_recoveries=max_recoveries,
                                     strategy=strategy.upper()))
    job_id = result['job_id']
    click.echo(f'Managed job {job_id} submitted.')
    if not detach_run:
        request_id = sdk.jobs_logs(job_id, follow=True)
        _run_and_stream(request_id)


@jobs.command('queue')
def jobs_queue_cmd():
    """List managed jobs."""
    from skypilot_tpu.client import sdk
    rows = sdk.get(sdk.jobs_queue())
    fmt = '{:<6} {:<20} {:<18} {:<10} {}'
    click.echo(fmt.format('ID', 'NAME', 'STATUS', 'RECOVERIES',
                          'CLUSTER'))
    for r in rows:
        click.echo(fmt.format(r['job_id'], r.get('name') or '-',
                              r['status'], r['recovery_count'],
                              r.get('cluster_name') or '-'))


@jobs.command('cancel')
@click.argument('job_ids', nargs=-1, type=int)
@click.option('--all', 'all_jobs', is_flag=True)
@click.option('--yes', '-y', is_flag=True)
def jobs_cancel_cmd(job_ids, all_jobs, yes):
    """Cancel managed job(s)."""
    from skypilot_tpu.client import sdk
    if not yes:
        target = 'ALL managed jobs' if all_jobs else f'jobs {job_ids}'
        click.confirm(f'Cancel {target}?', abort=True)
    result = sdk.get(sdk.jobs_cancel(list(job_ids) or None, all_jobs))
    click.echo(f'Cancelled: {result["cancelled"]}')


@jobs.command('logs')
@click.argument('job_id', type=int)
@click.option('--no-follow', is_flag=True)
def jobs_logs_cmd(job_id, no_follow):
    """Tail a managed job's controller+job logs."""
    from skypilot_tpu.client import sdk
    request_id = sdk.jobs_logs(job_id, follow=not no_follow)
    _run_and_stream(request_id)


@cli.group()
def serve():
    """Serve models behind an autoscaled load balancer."""


@serve.command('up')
@click.argument('entrypoint', nargs=-1, required=True)
@click.option('--service-name', '-n', default=None)
def serve_up_cmd(entrypoint, service_name):
    """Bring up a service from a task YAML with a service: section."""
    from skypilot_tpu.client import sdk
    from skypilot_tpu.utils import common_utils
    task = _task_from_args(entrypoint, None, None, None, None, None, None,
                           None)
    service_name = service_name or common_utils.generate_cluster_name(
    ).replace('tsky-', 'svc-')
    result = sdk.get(sdk.serve_up(task, service_name))
    click.echo(f'Service {service_name!r} starting; endpoint: '
               f'{result["endpoint"]}')


@serve.command('status')
@click.argument('service_names', nargs=-1)
def serve_status_cmd(service_names):
    """Show services and their replicas."""
    from skypilot_tpu.client import sdk
    rows = sdk.get(sdk.serve_status(list(service_names) or None))
    if not rows:
        click.echo('No services.')
        return
    for s in rows:
        click.echo(f'{s["name"]}  {s["status"]}  {s["endpoint"]}  '
                   f'v{s["version"]}')
        for r in s['replicas']:
            click.echo(f'  replica {r["replica_id"]}: {r["status"]} '
                       f'({r["cluster_name"]})')


@serve.command('update')
@click.argument('service_name')
@click.argument('entrypoint', nargs=-1, required=True)
def serve_update_cmd(service_name, entrypoint):
    """Rolling-update a service to a new task YAML."""
    from skypilot_tpu.client import sdk
    task = _task_from_args(entrypoint, None, None, None, None, None, None,
                           None)
    result = sdk.get(sdk.serve_update(task, service_name))
    click.echo(f'Service {service_name!r} updating to '
               f'v{result["version"]} (rolling).')


@serve.command('down')
@click.argument('service_names', nargs=-1, required=True)
@click.option('--yes', '-y', is_flag=True)
@click.option('--purge', is_flag=True)
def serve_down_cmd(service_names, yes, purge):
    """Tear down service(s) and their replicas."""
    from skypilot_tpu.client import sdk
    if not yes:
        click.confirm(f'Tear down {", ".join(service_names)}?', abort=True)
    for name in service_names:
        sdk.get(sdk.serve_down(name, purge=purge))
        click.echo(f'Service {name!r} terminated.')


@serve.command('logs')
@click.argument('service_name')
@click.option('--no-follow', is_flag=True)
def serve_logs_cmd(service_name, no_follow):
    """Tail a service's controller log."""
    from skypilot_tpu.client import sdk
    _run_and_stream(sdk.serve_logs(service_name, follow=not no_follow))


@cli.command('ssh')
@click.argument('cluster')
@click.option('--host-rank', type=int, default=0,
              help='Host index within the (slice) cluster, 0 = head.')
@click.option('--print-command', is_flag=True,
              help='Print the command instead of executing it.')
def ssh_cmd(cluster, host_rank, print_command):
    """Interactive shell on a cluster host (reference `ssh <cluster>`
    via generated ssh-config; ours builds the command from the stored
    handle — kubernetes clusters get `kubectl exec`).

    Needs the cluster state on THIS machine (consolidated API server);
    with a remote SKYTPU_API_SERVER_URL, run it on the server host.
    """
    import os as _os
    import shlex as _shlex
    from skypilot_tpu.client import sdk
    from skypilot_tpu.server import app as _app
    local_default = f'http://127.0.0.1:{_app.DEFAULT_PORT}'
    if sdk.api_server_url() != local_default:
        # Remote API server (env var OR api login-stored endpoint):
        # bridge this terminal over the server's websocket shell proxy
        # (reference ws SSH proxy, sky/server/server.py:1338).
        from skypilot_tpu.server import ws_proxy
        if print_command:
            click.echo(f'[ws-proxy] {sdk.api_server_url()}'
                       f'/api/v1/clusters/{cluster}/shell'
                       f'?host_rank={host_rank}')
            return
        try:
            sys.exit(ws_proxy.connect_ws_shell(
                sdk.api_server_url(), cluster, host_rank,
                token=sdk.api_token()))
        except exceptions.SkyTpuError as e:
            raise click.ClickException(str(e))
    from skypilot_tpu import exceptions as exceptions_lib
    from skypilot_tpu.server import ws_proxy
    try:
        # Single source of truth shared with the ws shell proxy.
        argv = ws_proxy.interactive_argv_for(cluster, host_rank)
    except exceptions_lib.SkyTpuError as e:
        raise click.ClickException(str(e))
    if print_command:
        click.echo(_shlex.join(argv))
        return
    _os.execvp(argv[0], argv)


@cli.command('show-gpus')
@click.argument('name_filter', required=False)
def show_gpus(name_filter):
    """List accelerators (GPUs and TPUs) with pricing per zone."""
    from skypilot_tpu.client import sdk
    accs = sdk.get(sdk.accelerators(name_filter), timeout=60)
    fmt = '{:<12} {:<8} {:<20} {:>6} {:>10} {:>10}  {}'
    click.echo(fmt.format('ACCELERATOR', 'CLOUD', 'INSTANCE', 'COUNT',
                          '$/hr', 'SPOT$/hr', 'REGION'))
    for name in sorted(accs):
        for r in accs[name]:
            spot = r['spot_price']
            click.echo(fmt.format(
                name, r['cloud'], r['instance_type'],
                int(r['count']) if r['count'] == int(r['count'])
                else r['count'],
                f"{r['price']:.2f}",
                f"{spot:.2f}" if spot is not None else '-',
                r['region']))


@cli.command('config')
def show_config():
    """Print the merged layered configuration."""
    import yaml as _yaml
    from skypilot_tpu import config as config_lib
    config_lib.reload()
    merged = config_lib.to_dict()
    click.echo(_yaml.safe_dump(merged or {}, default_flow_style=False)
               .rstrip() or '(empty)')


@cli.command('dashboard')
def dashboard_cmd():
    """Print the dashboard URL (auto-starting the server)."""
    from skypilot_tpu.client import sdk
    sdk.ensure_server_running()
    click.echo(f'{sdk.api_server_url()}/dashboard')


@cli.group()
def storage():
    """Manage storage objects (buckets)."""


@storage.command('ls')
def storage_ls_cmd():
    """List registered storage objects."""
    from skypilot_tpu.client import sdk
    rows = sdk.get(sdk.storage_ls(), timeout=60)
    fmt = '{:<32} {:<8} {:<12} {}'
    click.echo(fmt.format('NAME', 'STORE', 'WORKSPACE', 'SOURCE'))
    for r in rows:
        click.echo(fmt.format(r['name'], r['store'],
                              r.get('workspace') or '-',
                              r.get('source') or '-'))


@storage.command('delete')
@click.argument('names', nargs=-1)
@click.option('--all', 'all_storage', is_flag=True)
@click.option('--yes', '-y', is_flag=True)
def storage_delete_cmd(names, all_storage, yes):
    """Delete storage objects (bucket + record)."""
    if not names and not all_storage:
        raise click.UsageError('Pass storage names or --all.')
    if not yes:
        click.confirm(
            f'Delete {"ALL storage" if all_storage else list(names)}?',
            abort=True)
    from skypilot_tpu.client import sdk
    result = sdk.get(sdk.storage_delete(list(names) or None,
                                        all_storage), timeout=300)
    click.echo(f'Deleted: {result["deleted"]}')


@cli.group()
def catalog():
    """Inspect and QA the instance/price catalogs."""


@catalog.command('qa')
@click.option('--strict', is_flag=True,
              help='Exit non-zero on warnings too.')
@click.option('--json', 'as_json', is_flag=True)
def catalog_qa_cmd(strict, as_json):
    """Health-check the shipped catalog CSVs (duplicate offers, bad or
    inverted prices, accelerator vocabulary drift, cross-cloud price
    outliers). The same gate runs in CI."""
    from skypilot_tpu.catalog import analyze
    args = ['qa'] + (['--strict'] if strict else []) + \
        (['--json'] if as_json else [])
    raise SystemExit(analyze.main(args))


@catalog.command('diff')
@click.argument('new_dir')
@click.option('--json', 'as_json', is_flag=True)
def catalog_diff_cmd(new_dir, as_json):
    """Compare a fresh fetcher run (--out-dir) against the shipped
    catalogs: offers added/removed and price moves per cloud."""
    from skypilot_tpu.catalog import analyze
    args = ['diff', new_dir] + (['--json'] if as_json else [])
    raise SystemExit(analyze.main(args))


@cli.group()
def workspace():
    """Manage workspaces (reference sky/workspaces/core.py CRUD)."""


def _spec_from_flags(description, allowed_clouds, private,
                     allowed_users):
    """Only the flags given reach the server (update MERGES; omitted
    fields keep their value). The literal `none` clears a list."""
    def _listy(value):
        if value.lower() == 'none':
            return None
        return [v.strip() for v in value.split(',')]
    spec = {}
    if description is not None:
        spec['description'] = description
    if allowed_clouds:
        spec['allowed_clouds'] = _listy(allowed_clouds)
    if private is not None:
        spec['private'] = private
    if allowed_users:
        spec['allowed_users'] = _listy(allowed_users)
    return spec


_WS_FLAGS = [
    click.option('--description', default=None),
    click.option('--allowed-clouds', default=None,
                 help='Comma-separated cloud allowlist for launches '
                      'in this workspace (`none` clears it).'),
    click.option('--private/--no-private', default=None,
                 help='Restrict commands to --allowed-users.'),
    click.option('--allowed-users', default=None,
                 help='Comma-separated user names (with --private; '
                      '`none` clears the list).'),
]


def _with_ws_flags(fn):
    for flag in reversed(_WS_FLAGS):
        fn = flag(fn)
    return fn


@workspace.command('list')
def workspace_list():
    """Workspaces with their policy and live-resource counts."""
    from skypilot_tpu.client import sdk
    fmt = '{:<16} {:<9} {:<9} {:<20} {}'
    click.echo(fmt.format('NAME', 'CLUSTERS', 'STORAGE', 'CLOUDS',
                          'DESCRIPTION'))
    for ws in sdk.workspaces_list():
        clouds = ','.join(ws.get('allowed_clouds') or []) or '(all)'
        if ws.get('private'):
            clouds += ' [private]'
        click.echo(fmt.format(
            ws['name'], ws['active']['clusters'],
            ws['active']['storage'], clouds,
            ws.get('description') or ''))


@workspace.command('create')
@click.argument('name')
@_with_ws_flags
def workspace_create(name, description, allowed_clouds, private,
                     allowed_users):
    """Create a workspace."""
    from skypilot_tpu.client import sdk
    ws = sdk.workspace_create(name, _spec_from_flags(
        description, allowed_clouds, private, allowed_users))
    click.echo(f'Created workspace {ws["name"]!r}.')


@workspace.command('update')
@click.argument('name')
@_with_ws_flags
def workspace_update(name, description, allowed_clouds, private,
                     allowed_users):
    """Replace a workspace's policy (refused while narrowing under
    live resources)."""
    from skypilot_tpu.client import sdk
    ws = sdk.workspace_update(name, _spec_from_flags(
        description, allowed_clouds, private, allowed_users))
    click.echo(f'Updated workspace {ws["name"]!r}.')


@workspace.command('delete')
@click.argument('name')
@click.option('--yes', '-y', is_flag=True)
def workspace_delete(name, yes):
    """Delete a workspace (refused while it has live resources)."""
    from skypilot_tpu.client import sdk
    if not yes:
        click.confirm(f'Delete workspace {name!r}?', abort=True)
    sdk.workspace_delete(name)
    click.echo(f'Deleted workspace {name!r}.')


@cli.group()
def user():
    """Manage API users (reference sky/users/server.py CRUD)."""


@user.command('list')
def user_list():
    """All users: config-declared and API-created."""
    from skypilot_tpu.client import sdk
    fmt = '{:<16} {:<8} {:<14} {:<8} {}'
    click.echo(fmt.format('NAME', 'ROLE', 'WORKSPACE', 'SOURCE',
                          'STATE'))
    for u in sdk.users_list():
        click.echo(fmt.format(
            u['name'], u['role'], u['workspace'], u['source'],
            'disabled' if u.get('disabled') else 'active'))


@user.command('add')
@click.argument('name')
@click.option('--role', default='user',
              type=click.Choice(['admin', 'user', 'viewer']))
@click.option('--workspace', default='default')
def user_add(name, role, workspace):
    """Create a user; prints the generated token ONCE."""
    from skypilot_tpu.client import sdk
    doc = sdk.user_create(name, role=role, workspace=workspace)
    click.echo(f'Created user {doc["name"]!r} (role {doc["role"]}, '
               f'workspace {doc["workspace"]}).')
    click.echo(f'Token (shown once): {doc["token"]}')


@user.command('rotate')
@click.argument('name')
def user_rotate(name):
    """Invalidate the user's token and print the new one ONCE."""
    from skypilot_tpu.client import sdk
    doc = sdk.user_rotate(name)
    click.echo(f'New token for {name!r} (shown once): {doc["token"]}')


@user.command('set-role')
@click.argument('name')
@click.argument('role', type=click.Choice(['admin', 'user', 'viewer']))
def user_set_role(name, role):
    from skypilot_tpu.client import sdk
    sdk.user_update(name, role=role)
    click.echo(f'User {name!r} is now role {role}.')


@user.command('set-workspace')
@click.argument('name')
@click.argument('workspace')
def user_set_workspace(name, workspace):
    from skypilot_tpu.client import sdk
    sdk.user_update(name, workspace=workspace)
    click.echo(f'User {name!r} now works in {workspace!r}.')


@user.command('disable')
@click.argument('name')
def user_disable(name):
    """Reject the user's token without deleting the account."""
    from skypilot_tpu.client import sdk
    sdk.user_update(name, disabled=True)
    click.echo(f'User {name!r} disabled.')


@user.command('enable')
@click.argument('name')
def user_enable(name):
    from skypilot_tpu.client import sdk
    sdk.user_update(name, disabled=False)
    click.echo(f'User {name!r} enabled.')


@user.command('rm')
@click.argument('name')
@click.option('--yes', '-y', is_flag=True)
def user_rm(name, yes):
    from skypilot_tpu.client import sdk
    if not yes:
        click.confirm(f'Delete user {name!r}?', abort=True)
    sdk.user_delete(name)
    click.echo(f'Deleted user {name!r}.')


@cli.group()
def api():
    """Manage the API server."""


@api.command('status')
def api_status_cmd():
    """List recent requests."""
    from skypilot_tpu.client import sdk
    rows = sdk.api_status()
    fmt = '{:<18} {:<12} {:<10} {}'
    click.echo(fmt.format('REQUEST', 'NAME', 'STATUS', 'CREATED'))
    for r in rows:
        click.echo(fmt.format(r['request_id'], r['name'], r['status'],
                              r.get('created_at') or '-'))


@api.command('cancel')
@click.argument('request_id')
def api_cancel(request_id):
    from skypilot_tpu.client import sdk
    ok = sdk.cancel_request(request_id)
    click.echo('Cancelled.' if ok else 'Request already finished.')


@api.command('start')
def api_start():
    from skypilot_tpu.client import sdk
    sdk.ensure_server_running()
    click.echo(f'API server running at {sdk.api_server_url()}')


@api.command('logs')
@click.argument('request_id')
def api_logs(request_id):
    from skypilot_tpu.client import sdk
    sdk.stream(request_id, follow=False)


@api.command('info')
def api_info():
    """Server URL, version, and API version."""
    import json as _json
    from skypilot_tpu.client import sdk
    from skypilot_tpu.client.sdk import _request_raw
    info = _request_raw('GET', '/health', timeout=5.0)
    click.echo(f'URL: {sdk.api_server_url()}')
    click.echo(_json.dumps(info, indent=1))


@api.command('login')
@click.option('--endpoint', default=None,
              help='API server URL (e.g. http://host:46590).')
@click.option('--token', default=None,
              help='Bearer token; prompted for when omitted.')
@click.option('--browser', is_flag=True,
              help='Sign in through the server dashboard in a browser '
                   'instead of pasting a token (reference '
                   'sky/client/oauth.py flow).')
def api_login(endpoint, token, browser):
    """Store API server endpoint + token in the user config
    (reference sky api login / client/oauth.py)."""
    import os as _os
    import yaml as _yaml
    from skypilot_tpu import config as config_lib
    if browser and token is None:
        from skypilot_tpu import exceptions as _exc
        from skypilot_tpu.client import oauth
        from skypilot_tpu.client import sdk as _sdk
        target = (endpoint or _sdk.api_server_url()).rstrip('/')
        try:
            token = oauth.browser_login(target)
        except _exc.SkyTpuError as e:
            raise click.ClickException(str(e))
        if token == '':
            # Open local mode: the handoff SUCCEEDED and there is no
            # token to store — don't fall into the paste prompt.
            click.echo('Server is in open local mode; no token '
                       'needed.')
            token = None
    elif token is None:
        token = click.prompt('API token', hide_input=True, default='',
                             show_default=False) or None
    cfg_path = _os.path.expanduser(config_lib.USER_CONFIG_PATH)
    _os.makedirs(_os.path.dirname(cfg_path), exist_ok=True)
    try:
        with open(cfg_path, 'r', encoding='utf-8') as f:
            cfg = _yaml.safe_load(f) or {}
    except FileNotFoundError:
        cfg = {}
    section = cfg.setdefault('api_server', {})
    if token:
        section['token'] = token
    if endpoint:
        section['endpoint'] = endpoint.rstrip('/')
    # 0o600 from CREATION: chmod-after-write leaves a window where a
    # default-umask file briefly exposes the token on shared hosts.
    fd = _os.open(cfg_path, _os.O_WRONLY | _os.O_CREAT | _os.O_TRUNC,
                  0o600)
    with _os.fdopen(fd, 'w', encoding='utf-8') as f:
        _yaml.safe_dump(cfg, f, default_flow_style=False)
    _os.chmod(cfg_path, 0o600)  # pre-existing files keep tight perms
    config_lib.reload()
    stored = [k for k in ('token', 'endpoint') if section.get(k)]
    click.echo(f'Stored {" + ".join(stored) or "nothing"} in '
               f'{cfg_path}.')


@api.command('stop')
def api_stop():
    """Stop the local API server (reference `sky api stop`)."""
    import signal as _signal
    from skypilot_tpu import envs
    from skypilot_tpu.client import sdk
    if envs.SKYTPU_API_SERVER_URL.is_set():
        raise click.ClickException(
            'Refusing to stop a remote API server '
            '(SKYTPU_API_SERVER_URL is set); unset it to manage the '
            'local one.')
    pid = sdk.api_server_pid()
    if pid is None:
        click.echo('API server is not running.')
        return
    try:
        _os.kill(pid, _signal.SIGTERM)
    except ProcessLookupError:
        pass
    click.echo(f'Stopped API server (pid {pid}).')


def main():
    try:
        cli(standalone_mode=True)
    except exceptions.SkyTpuError as e:
        click.echo(f'Error: {e}', err=True)
        sys.exit(1)


if __name__ == '__main__':
    main()
