"""GCP cloud policy — the flagship cloud: TPU-VMs and TPU pod slices.

Reference analog: sky/clouds/gcp.py (1505 LoC; TPU template vars :495-530,
TPU-VM host sizing :688-740). Ours collapses the reference's
TPU-node/TPU-VM split: only TPU-VM (the modern architecture) exists, and a
pod slice is one logical node with `num_hosts` workers.
"""
import os
import subprocess
from typing import Dict, Optional, Tuple

from skypilot_tpu.clouds import cloud
from skypilot_tpu.utils import registry


@registry.CLOUD_REGISTRY.register(name='gcp')
class GCP(cloud.Cloud):
    NAME = 'gcp'
    CAPABILITIES = frozenset({
        cloud.CloudCapability.MULTI_NODE,
        cloud.CloudCapability.SPOT_INSTANCE,
        cloud.CloudCapability.STOP,
        cloud.CloudCapability.AUTOSTOP,
        cloud.CloudCapability.OPEN_PORTS,
        cloud.CloudCapability.STORAGE_MOUNT,
        cloud.CloudCapability.TPU,
        cloud.CloudCapability.CUSTOM_IMAGE,
        cloud.CloudCapability.HOST_CONTROLLERS,
    })
    MAX_CLUSTER_NAME_LENGTH = 35

    def supports(self, cap: cloud.CloudCapability) -> bool:
        return cap in self.CAPABILITIES

    def supports_for(self, cap: cloud.CloudCapability, resources) -> bool:
        """Per-resource capability: TPU slices cannot STOP, only terminate
        (reference clouds/gcp.py:216-226) — autostop must tear down."""
        if cap == cloud.CloudCapability.STOP and resources.is_tpu:
            return False
        return self.supports(cap)

    def provision_module(self) -> str:
        return 'skypilot_tpu.provision.gcp'

    def make_deploy_variables(self, resources, cluster_name_on_cloud: str,
                              region: str, zone: Optional[str]
                              ) -> Dict[str, object]:
        resources.assert_launchable()
        from skypilot_tpu import config as config_lib
        auth = self.authentication_config()
        variables: Dict[str, object] = {
            'cluster_name_on_cloud': cluster_name_on_cloud,
            'project_id': config_lib.get_nested(('gcp', 'project_id')),
            'network': config_lib.get_nested(('gcp', 'network')),
            'subnetwork': config_lib.get_nested(('gcp', 'subnetwork')),
            'use_internal_ips': bool(
                config_lib.get_nested(('gcp', 'use_internal_ips'),
                                      default=False)),
            'ssh_user': auth.get('ssh_user'),
            'ssh_private_key': auth.get('ssh_private_key'),
            'region': region,
            'zone': zone,
            'instance_type': resources.instance_type,
            'use_spot': resources.use_spot,
            'disk_size': resources.disk_size,
            'labels': dict(resources.labels),
            'ports': list(resources.ports or []),
            'num_nodes': None,  # filled by provisioner from cluster config
        }
        gen = resources.tpu_gen
        if gen is not None:
            variables.update({
                'tpu_vm': True,
                'tpu_generation': gen.name,
                'accelerator_type': resources.tpu_slice_type,
                'runtime_version': resources.cluster_config_overrides.get(
                    'runtime_version', gen.default_runtime_version),
                'num_hosts': resources.num_hosts_per_node,
            })
        else:
            variables['tpu_vm'] = False
            if resources.image_id:
                variables['image_id'] = resources.image_id
            # MIG/DWS queued capacity + persistent-disk volumes
            # (reference mig_utils.py / volume_utils.py).
            if config_lib.get_nested(('gcp', 'use_mig'), default=False):
                variables['use_mig'] = True
                variables['run_duration'] = config_lib.get_nested(
                    ('gcp', 'run_duration'), default=0)
        volumes = config_lib.get_nested(('gcp', 'volumes'), default=None)
        if volumes:
            variables['volumes'] = volumes
        return variables

    def authentication_config(self) -> Dict[str, object]:
        from skypilot_tpu import authentication
        return authentication.authentication_config()

    def check_credentials(self) -> Tuple[bool, Optional[str]]:
        # Application-default credentials or an active gcloud account.
        adc = os.path.expanduser(
            '~/.config/gcloud/application_default_credentials.json')
        if os.path.isfile(adc) or os.environ.get(
                'GOOGLE_APPLICATION_CREDENTIALS'):
            return True, None
        try:
            proc = subprocess.run(
                ['gcloud', 'auth', 'list',
                 '--filter=status:ACTIVE', '--format=value(account)'],
                capture_output=True, timeout=10, check=False)
            if proc.returncode == 0 and proc.stdout.strip():
                return True, None
        except (FileNotFoundError, subprocess.TimeoutExpired):
            pass
        return False, ('GCP credentials not found. Run `gcloud auth '
                       'application-default login`.')

    def probe_credentials(self):
        """Authenticated probe: one zones.list page against the
        default project (reference sky/check.py:53)."""
        ok, reason = self.check_credentials()
        if not ok:
            return ok, reason
        from skypilot_tpu.adaptors import gcp as adaptor
        try:
            project = adaptor.default_project()
            adaptor.transport().request(
                'GET',
                f'{adaptor.COMPUTE_API}/projects/{project}/zones',
                params={'maxResults': '1'})
        except Exception as e:  # noqa: BLE001
            return self._classify_probe_error(e)
        return True, None
