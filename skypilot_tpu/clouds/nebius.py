"""Nebius AI Cloud policy — H100/H200 GPU cloud with real stop/start.

Reference analog: sky/clouds/nebius.py. Catalog instance types are
`<platform>_<preset>` pairs (the API's native naming); region is the
single API region the account points at.
"""
from typing import Dict, Optional, Tuple

from skypilot_tpu.clouds import cloud
from skypilot_tpu.utils import registry


@registry.CLOUD_REGISTRY.register(name='nebius')
class Nebius(cloud.Cloud):
    NAME = 'nebius'
    CAPABILITIES = frozenset({
        cloud.CloudCapability.MULTI_NODE,
        cloud.CloudCapability.STOP,
        cloud.CloudCapability.AUTOSTOP,
        cloud.CloudCapability.STORAGE_MOUNT,
        cloud.CloudCapability.CUSTOM_IMAGE,
        cloud.CloudCapability.HOST_CONTROLLERS,
    })
    MAX_CLUSTER_NAME_LENGTH = 56

    def provision_module(self) -> str:
        return 'skypilot_tpu.provision.nebius'

    def make_deploy_variables(self, resources, cluster_name_on_cloud: str,
                              region: str, zone: Optional[str]
                              ) -> Dict[str, object]:
        resources.assert_launchable()
        from skypilot_tpu import config as config_lib
        auth = self.authentication_config()
        variables: Dict[str, object] = {
            'cluster_name_on_cloud': cluster_name_on_cloud,
            'region': region,
            'zone': None,
            'instance_type': resources.instance_type,
            'use_spot': False,  # no spot market
            'disk_size': resources.disk_size,
            'project_id': config_lib.get_nested(
                ('nebius', 'project_id')),
            'subnet_id': config_lib.get_nested(
                ('nebius', 'subnet_id'), default='') or '',
            'ssh_user': auth.get('ssh_user'),
            'ssh_private_key': auth.get('ssh_private_key'),
            'num_nodes': None,  # filled by the provisioner
        }
        if resources.image_id:
            variables['image_id'] = resources.image_id
        return variables

    def authentication_config(self) -> Dict[str, object]:
        from skypilot_tpu import authentication
        return authentication.authentication_config()

    # Cheap authenticated probe for `tsky check` (clouds/cloud.py).
    PROBE = ('nebius', '/compute/v1/instances', {'pageSize': '1'})

    def check_credentials(self) -> Tuple[bool, Optional[str]]:
        from skypilot_tpu.adaptors import nebius as adaptor
        if adaptor.get_iam_token():
            return True, None
        return False, ('Nebius IAM token not found. Set '
                       'NEBIUS_IAM_TOKEN or create '
                       f'{adaptor.CREDENTIALS_PATH}.')
