"""RunPod cloud policy — container GPU cloud.

Reference analog: sky/clouds/runpod.py. Pods stop (volume kept) and
resume; "COMMUNITY" interruptible pods are the spot analog. The
catalog models one synthetic instance type per (gpu, count):
`<count>x_<GPU>` (e.g. `1x_A100-80GB`), which the provisioner splits
back into gpuTypeId + gpuCount.
"""
from typing import Dict, Optional, Tuple

from skypilot_tpu.clouds import cloud
from skypilot_tpu.utils import registry


def split_instance_type(instance_type: str) -> Tuple[str, int]:
    """'2x_A100-80GB' -> ('A100-80GB', 2)."""
    count_s, _, gpu = instance_type.partition('x_')
    try:
        return gpu, int(count_s)
    except ValueError:
        return instance_type, 1


@registry.CLOUD_REGISTRY.register(name='runpod')
class RunPod(cloud.Cloud):
    NAME = 'runpod'
    CAPABILITIES = frozenset({
        cloud.CloudCapability.MULTI_NODE,
        cloud.CloudCapability.SPOT_INSTANCE,
        cloud.CloudCapability.STOP,
        cloud.CloudCapability.AUTOSTOP,
        cloud.CloudCapability.CUSTOM_IMAGE,
    })
    MAX_CLUSTER_NAME_LENGTH = 56

    def provision_module(self) -> str:
        return 'skypilot_tpu.provision.runpod'

    def make_deploy_variables(self, resources, cluster_name_on_cloud: str,
                              region: str, zone: Optional[str]
                              ) -> Dict[str, object]:
        resources.assert_launchable()
        auth = self.authentication_config()
        gpu_type, gpu_count = split_instance_type(resources.instance_type)
        variables: Dict[str, object] = {
            'cluster_name_on_cloud': cluster_name_on_cloud,
            'region': region,
            'zone': None,
            'instance_type': resources.instance_type,
            'gpu_type': gpu_type,
            'gpu_count': gpu_count,
            'use_spot': resources.use_spot,
            'disk_size': resources.disk_size,
            'ssh_user': 'root',
            'ssh_private_key': auth.get('ssh_private_key'),
            'num_nodes': None,  # filled by the provisioner
        }
        if resources.image_id:
            variables['image_id'] = resources.image_id
        return variables

    def authentication_config(self) -> Dict[str, object]:
        from skypilot_tpu import authentication
        return authentication.authentication_config()

    # Cheap authenticated probe for `tsky check` (clouds/cloud.py).
    PROBE = ('runpod', '/pods', None)

    def check_credentials(self) -> Tuple[bool, Optional[str]]:
        from skypilot_tpu.adaptors import runpod as adaptor
        if adaptor.get_api_key():
            return True, None
        return False, ('RunPod API key not found. Set RUNPOD_API_KEY '
                       f'or create {adaptor.CREDENTIALS_PATH}.')
