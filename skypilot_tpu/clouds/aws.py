"""AWS cloud policy — the second VM cloud.

Reference analog: sky/clouds/aws.py (1203 LoC). No TPUs here: AWS
carries controllers, CPU workers, and GPU recipes, and proves the
multi-cloud abstraction (optimizer failover GCP↔AWS through the same
blocked-resources loop).
"""
from typing import Dict, Optional, Tuple

from skypilot_tpu.clouds import cloud
from skypilot_tpu.utils import registry


@registry.CLOUD_REGISTRY.register(name='aws')
class AWS(cloud.Cloud):
    NAME = 'aws'
    CAPABILITIES = frozenset({
        cloud.CloudCapability.MULTI_NODE,
        cloud.CloudCapability.SPOT_INSTANCE,
        cloud.CloudCapability.STOP,
        cloud.CloudCapability.AUTOSTOP,
        cloud.CloudCapability.OPEN_PORTS,
        cloud.CloudCapability.STORAGE_MOUNT,
        cloud.CloudCapability.CUSTOM_IMAGE,
        cloud.CloudCapability.HOST_CONTROLLERS,
    })
    # EC2 resource names land in tags; keep parity with the reference's
    # cluster-name truncation behavior.
    MAX_CLUSTER_NAME_LENGTH = 50

    def provision_module(self) -> str:
        return 'skypilot_tpu.provision.aws'

    def make_deploy_variables(self, resources, cluster_name_on_cloud: str,
                              region: str, zone: Optional[str]
                              ) -> Dict[str, object]:
        resources.assert_launchable()
        from skypilot_tpu import config as config_lib
        auth = self.authentication_config()
        variables: Dict[str, object] = {
            'cluster_name_on_cloud': cluster_name_on_cloud,
            'region': region,
            'zone': zone,
            'instance_type': resources.instance_type,
            'use_spot': resources.use_spot,
            'disk_size': resources.disk_size,
            'labels': dict(resources.labels),
            'ports': list(resources.ports or []),
            'vpc_id': config_lib.get_nested(('aws', 'vpc_id')),
            'use_internal_ips': bool(
                config_lib.get_nested(('aws', 'use_internal_ips'),
                                      default=False)),
            'ssh_user': auth.get('ssh_user'),
            'ssh_private_key': auth.get('ssh_private_key'),
            'num_nodes': None,  # filled by the provisioner
        }
        if resources.image_id:
            variables['image_id'] = resources.image_id
        return variables

    def authentication_config(self) -> Dict[str, object]:
        from skypilot_tpu import authentication
        return authentication.authentication_config()

    def check_credentials(self) -> Tuple[bool, Optional[str]]:
        from skypilot_tpu.adaptors import aws as aws_adaptor
        if aws_adaptor.load_credentials() is not None:
            return True, None
        return False, ('AWS credentials not found; set AWS_ACCESS_KEY_ID/'
                       'AWS_SECRET_ACCESS_KEY or populate '
                       '~/.aws/credentials.')

    def probe_credentials(self):
        """Authenticated probe: DescribeRegions with the configured
        keys (reference sky/check.py:53)."""
        ok, reason = self.check_credentials()
        if not ok:
            return ok, reason
        from skypilot_tpu.adaptors import aws as adaptor
        try:
            adaptor.client('us-east-1').call('DescribeRegions')
        except Exception as e:  # noqa: BLE001
            return self._classify_probe_error(e)
        return True, None
